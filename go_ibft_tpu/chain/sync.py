"""Block-sync / catch-up: aggregate-verified finalized-height transfer.

A validator that restarts behind its peers — or observes commit-quorum
evidence for a future height — cannot finish old heights through
consensus (its peers have left them; the reference documents block sync
as the embedder's job, core/ibft.go RunSequence contract).  This module
is that job, done the TPU-native way: a stranded node fetches the missing
``(proposal, committed seals)`` range from any peer and verifies ALL
committed seals across the whole range in ONE batched drain
(``verify_seal_lanes`` — per-lane proposal hashes through the same
recovery ladder as the live COMMIT path, with the
``ResilientBatchVerifier`` breaker ladder as the degraded route).  This
is the light-client primitive ("Practical Light Clients for
Committee-Based Blockchains", PAPERS.md): trust nothing from the peer,
re-derive every height's commit quorum from the seals alone.

One binding is deliberately the embedder's (as in the reference, where
block sync is wholly embedder-owned): committed seals sign
``keccak(raw_proposal, round)`` — the HEIGHT is not covered by the
signature, so the in-protocol check alone cannot catch a peer relabeling
a genuine block at a different height.  Real chains close this in the
proposal content (height/parent-hash inside the block bytes); the chain
runner therefore passes every synced proposal through the embedder's
``is_valid_proposal`` before inserting, which is where that content
check belongs (docs/CHAIN.md).

The peer seam is deliberately as thin as the consensus ``Transport``
(one-method multicast): a :class:`SyncSource` answers ``latest_height``
and ``get_blocks`` — :class:`~go_ibft_tpu.chain.runner.ChainRunner`
implements it from its in-memory chain, :class:`LoopbackSyncNetwork`
wires sources in-process (tests, single-host clusters), and a gRPC/DCN
implementation slots in for multi-host deployments exactly like
``net.GrpcTransport`` does for gossip.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Mapping, Optional, Protocol, Sequence, Tuple

import numpy as np

from ..core.validator_manager import calculate_quorum
from ..crypto.backend import proposal_hash_of
from ..messages.helpers import CommittedSeal
from ..obs import trace
from ..utils import metrics
from .wal import FinalizedBlock

__all__ = [
    "LoopbackSyncNetwork",
    "SyncClient",
    "SyncError",
    "SyncSource",
    "SYNCED_HEIGHTS_KEY",
    "SYNC_DRAINS_KEY",
    "SYNC_CERT_HEIGHTS_KEY",
]

SYNCED_HEIGHTS_KEY = ("go-ibft", "chain", "synced_heights")
SYNC_DRAINS_KEY = ("go-ibft", "chain", "sync_drains")
SYNC_CERT_HEIGHTS_KEY = ("go-ibft", "chain", "sync_cert_heights")


class SyncError(RuntimeError):
    """Catch-up failed: no peer could serve the range, or verification
    rejected the fetched evidence."""


class SyncSource(Protocol):
    """What a peer serves to catch-up requests (the sync seam)."""

    def latest_height(self) -> int: ...

    def get_blocks(self, start: int, end: int) -> List[FinalizedBlock]: ...


class LoopbackSyncNetwork:
    """In-process sync peer registry (the test/single-host fabric).

    Mirrors ``core.LoopbackTransport``'s posture: registration order is
    deterministic, a node never serves itself, and a fault hook lets chaos
    suites drop or truncate responses per (requester, server).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sources: Dict[bytes, SyncSource] = {}
        # Optional fault hook: (requester_id, server_id) -> serve?
        self.should_serve: Callable[[bytes, bytes], bool] = lambda _r, _s: True

    def register(self, node_id: bytes, source: SyncSource) -> None:
        with self._lock:
            self._sources[node_id] = source

    def peers_of(self, node_id: bytes) -> List[Tuple[bytes, SyncSource]]:
        with self._lock:
            return [
                (peer_id, src)
                for peer_id, src in self._sources.items()
                if peer_id != node_id and self.should_serve(node_id, peer_id)
            ]


class SyncClient:
    """Fetch-and-verify catch-up for one node.

    ``verifier`` is any object with ``verify_seal_lanes(lanes, height)``
    (Host/Device/Mesh/Resilient/Adaptive all implement it); verdicts are
    pinned to the sequential host oracle by the conformance tests, so a
    device route can never accept a range the reference semantics would
    reject.  A :class:`~go_ibft_tpu.verify.mesh_batch.MeshBatchVerifier`
    (or an Adaptive ladder carrying one) coalesces a whole multi-height
    range into ONE sharded dispatch — its chunk capacity is ``largest
    lane bucket x device count`` — so catch-up cost scales down with the
    mesh instead of serializing per 2048-lane chunk.
    """

    def __init__(
        self,
        node_id: bytes,
        network: LoopbackSyncNetwork,
        verifier,
        validators_for_height: Callable[[int], Mapping[bytes, int]],
        *,
        cert_verifier=None,
        max_batch_heights: int = 4096,
    ) -> None:
        self.node_id = node_id
        self.network = network
        self.verifier = verifier
        self._validators = validators_for_height
        # Aggregate-certificate route (ISSUE 7/12): blocks served with an
        # AggregateQuorumCertificate instead of per-validator seals verify
        # through this (a BLSCertifier or compatible) — quorum power from
        # the signer bitmap, and the WHOLE range's pairing work in ONE
        # batched multi-pairing dispatch (``verify_many``) — instead of N
        # seal lanes per height through ``verifier``.
        self.cert_verifier = cert_verifier
        self.max_batch_heights = max_batch_heights

    # -- peer observation ----------------------------------------------

    def best_peer_height(self) -> int:
        """Highest finalized height any reachable peer advertises."""
        best = 0
        for _peer_id, source in self.network.peers_of(self.node_id):
            try:
                best = max(best, source.latest_height())
            except Exception:  # noqa: BLE001 - a dead peer is not an error
                continue
        return best

    # -- catch-up -------------------------------------------------------

    def catch_up(self, start: int, target: int) -> List[FinalizedBlock]:
        """Fetch ``[start, target]`` from peers and verify the whole range.

        Peers are tried in registration order; the first one serving a
        non-empty prefix wins (a peer that is itself behind serves what it
        has — the caller loops until caught up).  Raises :class:`SyncError`
        when no peer can serve ``start`` or verification rejects the
        evidence.
        """
        target = min(target, start + self.max_batch_heights - 1)
        blocks: List[FinalizedBlock] = []
        for _peer_id, source in self.network.peers_of(self.node_id):
            try:
                got = source.get_blocks(start, target)
            except Exception:  # noqa: BLE001 - try the next peer
                continue
            if got and got[0].height == start:
                blocks = got
                break
        if not blocks:
            raise SyncError(
                f"no peer could serve heights [{start}, {target}]"
            )
        expected = list(range(start, start + len(blocks)))
        if [b.height for b in blocks] != expected:
            raise SyncError("peer served a non-contiguous height range")
        self.verify_blocks(blocks)
        metrics.inc_counter(SYNCED_HEIGHTS_KEY, len(blocks))
        return blocks

    def verify_blocks(self, blocks: Sequence[FinalizedBlock]) -> None:
        """Verify every fetched block's commit evidence.

        Blocks carrying an aggregate quorum certificate verify on the
        O(1) route: one pairing equation per height-range entry (the
        certificate's proposal hash must match the block's proposal, the
        signer bitmap must reach quorum power — both checked inside the
        cert verifier — so a peer can never relabel a certificate onto a
        different proposal).  Requires ``cert_verifier``; a cert-carrying
        block without one is a :class:`SyncError`, never silently trusted.

        Seal-carrying blocks keep the batched lane route: one
        ``verify_seal_lanes`` drain per validator-set snapshot — with a
        static validator set (the common case) the WHOLE height range is
        a single drain.  Grouping by snapshot keeps the device's
        one-table-per-drain shape exactly as honest as the sequential
        oracle: every lane in a drain shares the validator set its own
        height would select.  After the mask comes back, each height's
        valid signers must reach that height's voting-power quorum.
        """
        cert_blocks = [b for b in blocks if b.cert is not None]
        if cert_blocks:
            self._verify_cert_blocks(cert_blocks)
        blocks = [b for b in blocks if b.cert is None]
        if not blocks:
            return
        groups: Dict[tuple, List[int]] = {}
        snapshots: List[Mapping[bytes, int]] = []
        heights: List[int] = []
        for i, block in enumerate(blocks):
            powers = self._validators(block.height)
            key = tuple(sorted(powers.items()))
            if key not in groups:
                groups[key] = []
            groups[key].append(i)
            snapshots.append(powers)
            heights.append(block.height)

        masks: List[Optional[np.ndarray]] = [None] * len(blocks)
        total_lanes = sum(len(b.seals) for b in blocks)
        with trace.span(
            "chain.sync.verify",
            lanes=total_lanes,
            heights=len(blocks),
            drains=len(groups),
        ):
            for idxs in groups.values():
                lanes: List[Tuple[bytes, CommittedSeal]] = []
                spans: List[Tuple[int, int, int]] = []  # (block idx, lo, hi)
                for i in idxs:
                    block = blocks[i]
                    proposal_hash = proposal_hash_of(block.proposal)
                    lo = len(lanes)
                    lanes.extend(
                        (proposal_hash, seal) for seal in block.seals
                    )
                    spans.append((i, lo, len(lanes)))
                if not lanes:
                    for i in idxs:
                        masks[i] = np.zeros(0, dtype=bool)
                    continue
                # ONE batched drain for the whole snapshot group; the
                # representative height picks the (identical) table.
                mask = np.asarray(
                    self.verifier.verify_seal_lanes(
                        lanes, heights[idxs[-1]]
                    ),
                    dtype=bool,
                )
                metrics.inc_counter(SYNC_DRAINS_KEY)
                for i, lo, hi in spans:
                    masks[i] = mask[lo:hi]

        for block, mask, powers in zip(blocks, masks, snapshots):
            valid_signers = {
                seal.signer
                for seal, ok in zip(block.seals, mask)
                if bool(ok)
            }
            quorum = calculate_quorum(sum(powers.values()))
            got = sum(powers.get(a, 0) for a in valid_signers)
            if got < quorum:
                raise SyncError(
                    f"height {block.height}: committed-seal power {got} < "
                    f"quorum {quorum} ({int(mask.sum())}/{len(block.seals)} "
                    "seals valid)"
                )

    def _verify_cert_blocks(self, blocks: Sequence[FinalizedBlock]) -> None:
        """Batched verification of certificate-carrying blocks.

        Structural gates run per block BEFORE any pairing work; the
        surviving certificates then verify through ONE batched
        multi-pairing dispatch (``cert_verifier.verify_many``, ISSUE 12)
        — a 1000-height catch-up range costs one dispatch instead of
        1000 independent pairing calls.  A verifier without
        ``verify_many`` (a custom embedder seam) keeps the per-height
        route, verdict-identically.
        """
        if self.cert_verifier is None:
            raise SyncError(
                "peer served aggregate-certificate blocks but this client "
                "has no cert_verifier to check them"
            )
        with trace.span(
            "chain.sync.cert_verify", heights=len(blocks)
        ):
            for block in blocks:
                cert = block.cert
                if block.seals:
                    # A cert block carries NO per-validator seals (the WAL
                    # writes them mutually exclusively); a peer serving
                    # both is smuggling seals past verification — this
                    # path checks only the certificate, and the runner
                    # would otherwise insert and re-serve the unchecked
                    # seal list as commit evidence.
                    raise SyncError(
                        f"height {block.height}: certificate block "
                        "carries a seal list (unverifiable evidence mix)"
                    )
                if (
                    cert.height != block.height
                    or cert.proposal_hash != proposal_hash_of(block.proposal)
                ):
                    raise SyncError(
                        f"height {block.height}: certificate does not bind "
                        "the served proposal"
                    )
            verify_many = getattr(self.cert_verifier, "verify_many", None)
            if verify_many is not None:
                mask = np.asarray(
                    verify_many([b.cert for b in blocks]), dtype=bool
                )
            else:
                mask = np.asarray(
                    [self.cert_verifier.verify(b.cert) for b in blocks],
                    dtype=bool,
                )
            for block, ok in zip(blocks, mask):
                if not bool(ok):
                    raise SyncError(
                        f"height {block.height}: aggregate quorum "
                        "certificate failed verification"
                    )
                metrics.inc_counter(SYNC_CERT_HEIGHTS_KEY)
