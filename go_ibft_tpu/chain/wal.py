"""Write-ahead log: crash-durable chain state for one validator node.

go-ibft leaves persistence entirely to the embedder (SURVEY §1 — the
reference's ``Backend.InsertProposal`` is the last it ever hears of a
finalized block).  A continuously-running node needs two durable facts to
restart safely:

* **Finalized heights** — ``(height, proposal, committed seals)``,
  appended with ``fsync`` BEFORE the engine prunes the height's quorum
  evidence from the message store (the finalize -> WAL append -> prune
  ordering enforced in ``core/ibft.py::_insert_block``).  A crash between
  any two steps never loses a finalized height: before the append the
  un-pruned store still carries the commit quorum, after it the height is
  on disk.
* **The in-flight lock** — the prepared certificate pinned when a prepare
  quorum lands (``IBFT.on_lock``).  A validator that sent COMMIT for a
  proposal and then crashed must NOT restart as a blank slate: round 0 of
  a re-run could prepare a *different* proposal for the same height —
  equivocation.  Replaying the lock lets ``ChainRunner.recover()`` re-enter
  the height mid-round with the certificate intact
  (``IBFT.run_sequence(..., restore=)``).

Format: append-only JSONL, one record per line, all message payloads
serialized through the wire codec (:mod:`go_ibft_tpu.messages.wire`) as
hex — a ``Proposal`` / ``PreparedCertificate`` round-trips bit-identically
through ``encode``/``decode``, so a recovered lock carries the exact
signed messages it was built from.  Replay tolerates a torn tail (a crash
mid-append leaves at most one partial final line, which is dropped) but
refuses interior corruption — a damaged middle record means the file is
not the log this code wrote, and silently skipping it could resurrect an
equivocation.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

from ..messages.helpers import CommittedSeal
from ..messages.wire import PreparedCertificate, Proposal
from ..utils import metrics

# Fixed-bucket append latency (fsync included) for the /metrics endpoint;
# recorded only while metrics.enable_fixed_histograms() is on.
WAL_APPEND_MS_KEY = ("go-ibft", "latency", "wal_append_ms")

__all__ = [
    "FinalizedBlock",
    "WalCorruptionError",
    "WalLock",
    "WalState",
    "WriteAheadLog",
]


class WalCorruptionError(ValueError):
    """An interior (non-tail) WAL record failed to parse."""


@dataclass
class FinalizedBlock:
    """One durable chain entry: what ``InsertProposal`` received.

    ``cert`` (an :class:`~go_ibft_tpu.crypto.quorum_cert.
    AggregateQuorumCertificate`) is the O(1) alternative to ``seals``: a
    block finalized — or compressed at persist time — under the
    aggregate-COMMIT mode carries ONE aggregated G2 seal plus a signer
    bitmap instead of N individual seals, and every consumer (WAL replay,
    block-sync verification) re-checks it with ONE pairing equation.
    The two evidence forms are mutually exclusive: ``append_finalize``
    writes an empty seal list whenever a certificate rides, and the sync
    client REJECTS a peer-served block carrying both (a seal list next to
    a certificate would bypass seal verification entirely).
    """

    height: int
    proposal: Proposal
    seals: List[CommittedSeal] = field(default_factory=list)
    cert: Optional[object] = None


@dataclass
class WalLock:
    """The in-flight prepared-certificate lock for an unfinished height."""

    height: int
    round: int
    certificate: Optional[PreparedCertificate] = None


@dataclass
class WalState:
    """Replay result: the durable chain plus the live lock (if any).

    ``checkpoints`` are the epoch checkpoint records (ISSUE 20,
    :class:`~go_ibft_tpu.lightsync.checkpoint.CheckpointRecord`) the
    node built at epoch boundaries — replayed so a restarted node serves
    its skip chain without re-signing history.
    """

    blocks: List[FinalizedBlock] = field(default_factory=list)
    lock: Optional[WalLock] = None
    dropped_tail: bool = False
    checkpoints: List[object] = field(default_factory=list)

    @property
    def next_height(self) -> int:
        """First height NOT finalized in the log (1 for an empty log)."""
        return self.blocks[-1].height + 1 if self.blocks else 1


class WriteAheadLog:
    """Append-only JSONL log with fsync-on-finalize durability.

    Thread-safe (the engine loop appends locks while a sync catch-up may
    append finalized blocks from an executor thread).  ``fsync_locks``
    defaults True — the kill -9 recovery contract covers the mid-round
    lock, not just finalized heights; a deployment that accepts losing the
    lock on power failure (process crash still keeps it via the OS page
    cache) can turn the per-round fsync off.
    """

    def __init__(self, path: str, *, fsync_locks: bool = True) -> None:
        self.path = str(path)
        self._fsync_locks = fsync_locks
        self._lock = threading.Lock()
        self._fh = None
        self._tail_sanitized = False

    # -- appends --------------------------------------------------------

    def _sanitize_tail_locked(self) -> None:
        """Cut any torn final line BEFORE the first append (callers hold
        the lock).  A crash mid-append leaves partial bytes with no
        newline; appending blindly would merge the next record into one
        unparseable INTERIOR line, permanently poisoning the log — and
        nothing forces an embedder to run replay()/recover() (which also
        truncates) before appending."""
        if self._tail_sanitized:
            return
        self._tail_sanitized = True
        if not os.path.exists(self.path):
            return
        with open(self.path, "r+b") as fh:
            data = fh.read()
            if not data or data.endswith(b"\n"):
                return
            keep = data.rfind(b"\n") + 1  # 0 when the only line is torn
            fh.truncate(keep)
            fh.flush()
            os.fsync(fh.fileno())

    def _file(self):
        if self._fh is None or self._fh.closed:
            self._sanitize_tail_locked()
            self._fh = open(self.path, "ab")
        return self._fh

    def _append(self, record: dict, fsync: bool) -> None:
        line = json.dumps(record, separators=(",", ":")) + "\n"
        t0 = (
            time.perf_counter()
            if metrics.fixed_histograms_enabled()
            else None
        )
        with self._lock:
            fh = self._file()
            fh.write(line.encode())
            fh.flush()
            if fsync:
                os.fsync(fh.fileno())
        if t0 is not None:
            metrics.observe_fixed(
                WAL_APPEND_MS_KEY, (time.perf_counter() - t0) * 1e3
            )

    def append_finalize(
        self,
        height: int,
        proposal: Proposal,
        seals: List[CommittedSeal],
        cert=None,
    ) -> None:
        """Durably record one finalized height (fsync before returning).

        ``cert`` (an AggregateQuorumCertificate) replaces the per-seal
        list on disk: the finalize record becomes O(1) in committee size
        — 240 bytes + 1 bitmap bit per validator instead of one 192-byte
        seal each — and replay hands the certificate back for one-pairing
        re-verification instead of N seal lanes.
        """
        record = {
            "kind": "finalize",
            "height": height,
            "proposal": proposal.encode().hex(),
        }
        if cert is not None:
            record["cert"] = cert.encode().hex()
        record["seals"] = (
            []
            if cert is not None
            else [[s.signer.hex(), s.signature.hex()] for s in seals]
        )
        self._append(record, fsync=True)

    def append_lock(
        self, height: int, round_: int, certificate: Optional[PreparedCertificate]
    ) -> None:
        """Record the in-flight prepared-certificate lock for a height."""
        record = {"kind": "lock", "height": height, "round": round_}
        if certificate is not None:
            record["pc"] = certificate.encode().hex()
        self._append(record, fsync=self._fsync_locks)

    def append_checkpoint(self, record) -> None:
        """Durably record one epoch checkpoint (ISSUE 20; fsync — the
        record chains into every later epoch's skip links, so losing it
        would orphan the structure on restart).  ``record`` is a
        :class:`~go_ibft_tpu.lightsync.checkpoint.CheckpointRecord`."""
        self._append(
            {
                "kind": "checkpoint",
                "epoch": record.epoch,
                "height": record.height,
                "rec": record.encode().hex(),
            },
            fsync=True,
        )

    def close(self) -> None:
        with self._lock:
            if self._fh is not None and not self._fh.closed:
                self._fh.close()

    # -- replay ---------------------------------------------------------

    @staticmethod
    def _parse(record: dict):
        kind = record["kind"]
        if kind == "finalize":
            cert_hex = record.get("cert")
            cert = None
            if cert_hex is not None:
                # Lazy import: the certificate codec pulls the BLS stack,
                # which plain ECDSA-seal WALs never need.
                from ..crypto.quorum_cert import AggregateQuorumCertificate

                cert = AggregateQuorumCertificate.decode(
                    bytes.fromhex(cert_hex)
                )
            return FinalizedBlock(
                height=int(record["height"]),
                proposal=Proposal.decode(bytes.fromhex(record["proposal"])),
                seals=[
                    CommittedSeal(
                        signer=bytes.fromhex(signer),
                        signature=bytes.fromhex(signature),
                    )
                    for signer, signature in record.get("seals", ())
                ],
                cert=cert,
            )
        if kind == "lock":
            pc_hex = record.get("pc")
            return WalLock(
                height=int(record["height"]),
                round=int(record["round"]),
                certificate=(
                    PreparedCertificate.decode(bytes.fromhex(pc_hex))
                    if pc_hex is not None
                    else None
                ),
            )
        if kind == "checkpoint":
            # Lazy import, like the certificate codec: checkpoint-less
            # WALs never pay for the lightsync stack.
            from ..lightsync.checkpoint import CheckpointRecord

            return CheckpointRecord.decode(bytes.fromhex(record["rec"]))
        raise ValueError(f"unknown WAL record kind {kind!r}")

    def _truncate_tail(self, data: bytes, torn: bytes) -> None:
        """Cut the torn final line off the file (fsynced, lock-guarded).

        ``torn`` is the last (partial) line of ``data``; everything before
        it is kept.  The file is re-read under the lock and only truncated
        if its tail still matches the snapshot — a concurrent append (which
        sanitizes the tail itself) must never lose fsynced records to a
        stale offset."""
        keep = data.rfind(torn)
        with self._lock:
            if self._fh is not None and not self._fh.closed:
                self._fh.close()
                self._fh = None
            with open(self.path, "r+b") as fh:
                if fh.read() != data:
                    return  # tail already repaired or log moved on
                fh.truncate(keep)
                fh.flush()
                os.fsync(fh.fileno())

    def replay(self) -> WalState:
        """Re-derive the durable state from the log.

        Finalized heights must be non-decreasing (a duplicate height —
        possible when a crash landed between the WAL append and the prune
        and block-sync re-delivered the block — keeps the FIRST, durable,
        record).  The returned lock is the latest lock record for a height
        that was never finalized; locks superseded by a finalize replay to
        nothing.
        """
        state = WalState()
        if not os.path.exists(self.path):
            return state
        with self._lock:
            with open(self.path, "rb") as fh:
                data = fh.read()
        raw_lines = data.split(b"\n")
        # A trailing newline yields one empty tail entry; drop empties at
        # the end but treat interior blank lines as corruption.
        while raw_lines and not raw_lines[-1].strip():
            raw_lines.pop()
        latest_lock: Optional[WalLock] = None
        for i, raw in enumerate(raw_lines):
            try:
                parsed = self._parse(json.loads(raw))
            except Exception as err:  # noqa: BLE001 - classified below
                if i == len(raw_lines) - 1:
                    # Torn tail: the crash interrupted the final append;
                    # everything before it is intact by the append-only
                    # discipline.  TRUNCATE the partial bytes now — left
                    # in place, the next append would merge with them
                    # into one unparseable line, and a later replay would
                    # either drop that line (losing a record whose fsync
                    # succeeded) or refuse the log as interior-corrupt.
                    state.dropped_tail = True
                    self._truncate_tail(data, raw)
                    break
                raise WalCorruptionError(
                    f"WAL record {i} of {self.path} is corrupt: {err}"
                ) from err
            if isinstance(parsed, FinalizedBlock):
                if state.blocks and parsed.height <= state.blocks[-1].height:
                    continue  # duplicate/stale re-append: first write wins
                state.blocks.append(parsed)
            elif isinstance(parsed, WalLock):
                latest_lock = parsed
            else:  # checkpoint record (first write wins, like finalizes)
                if any(c.epoch == parsed.epoch for c in state.checkpoints):
                    continue
                state.checkpoints.append(parsed)
        if latest_lock is not None and (
            not state.blocks or latest_lock.height > state.blocks[-1].height
        ):
            state.lock = latest_lock
        return state
