"""Chain layer: multi-height sequencing, WAL durability, block-sync.

The subsystem that turns the per-height consensus engine
(:mod:`go_ibft_tpu.core`) into a continuously-running validator node:

* :class:`ChainRunner` — persistent height loop with no inter-height
  barrier, measured handoffs, cross-height verify overlap, and
  fall-behind detection (docs/CHAIN.md).
* :class:`WriteAheadLog` — fsync-on-finalize durability for finalized
  heights and the mid-round prepared-certificate lock; crash recovery via
  :meth:`ChainRunner.recover`.
* :class:`SyncClient` / :class:`LoopbackSyncNetwork` — batched
  catch-up: all committed seals of a fetched height range verified in one
  ``verify_seal_lanes`` drain per validator-set snapshot.
"""

from .runner import (
    ChainRunner,
    HANDOFF_MS_KEY,
    HEIGHT_MS_KEY,
    OVERLAP_LANES_KEY,
)
from .sync import LoopbackSyncNetwork, SyncClient, SyncError, SyncSource
from .wal import (
    FinalizedBlock,
    WalCorruptionError,
    WalLock,
    WalState,
    WriteAheadLog,
)

__all__ = [
    "ChainRunner",
    "FinalizedBlock",
    "HANDOFF_MS_KEY",
    "HEIGHT_MS_KEY",
    "LoopbackSyncNetwork",
    "OVERLAP_LANES_KEY",
    "SyncClient",
    "SyncError",
    "SyncSource",
    "WalCorruptionError",
    "WalLock",
    "WalState",
    "WriteAheadLog",
]
