"""ChainRunner: a per-height consensus engine turned continuous node.

go-ibft stops at "one ``run_sequence(height)`` per call" and leaves chain
driving to the embedder (SURVEY §1).  Every embedder so far — including
``examples/minimal_embedder.py`` — drove heights behind a full
``asyncio.gather`` barrier: the FASTEST node of a cluster idles until the
slowest finishes each height, engine tasks are re-spawned per height, and
early traffic for height H+1 sat unexploited while H finished its COMMIT
drain.  :class:`ChainRunner` removes all three costs:

* **No inter-height barrier.**  Each node owns ONE persistent runner task
  that loops heights back-to-back; nodes de-synchronize naturally and
  re-synchronize through consensus itself (a node cannot finalize H+1
  without a quorum at H+1).  The per-height handoff is explicit and
  measured (``chain.handoff`` span + ``("go-ibft","chain","handoff_ms")``).
* **Cross-height verify overlap.**  While H's COMMIT drain is in flight,
  a persistent overlap worker drains the engine's bounded future-height
  buffer and batch-verifies H+1's early envelopes off the event loop
  (device route rides the double-buffered ``verify/pipeline.py`` drains;
  host route releases the GIL in the native verifier), handing verified
  survivors straight into the store (``IBFT.add_verified_messages``) so
  run_sequence(H+1) finds its PREPAREs pre-verified.  Instrumented as
  ``chain.overlap`` spans.
* **Durability + catch-up.**  Finalized heights and the mid-round
  prepared-certificate lock ride the :class:`~go_ibft_tpu.chain.wal.
  WriteAheadLog` (finalize -> WAL append -> prune ordering, see
  ``core/ibft.py::_insert_block``); :meth:`recover` replays it so a
  crashed validator rejoins at the correct height without equivocating.
  A node that falls behind its peers (the sync watcher polls the
  :class:`~go_ibft_tpu.chain.sync.SyncClient` seam) abandons the stale
  sequence and catches up via one batched seal drain per validator-set
  snapshot.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Deque, List, Optional

from ..core.ibft import IBFT, RestoredState
from ..core.state import StateName
from ..messages.helpers import CommittedSeal
from ..messages.wire import PreparedCertificate, Proposal
from ..obs import trace
from ..utils import metrics
from .sync import SyncClient, SyncError
from .wal import FinalizedBlock, WriteAheadLog

__all__ = ["ChainRunner", "HANDOFF_MS_KEY", "HEIGHT_MS_KEY", "OVERLAP_LANES_KEY"]

HANDOFF_MS_KEY = ("go-ibft", "chain", "handoff_ms")
HEIGHT_MS_KEY = ("go-ibft", "chain", "height_ms")
OVERLAP_LANES_KEY = ("go-ibft", "chain", "overlap_lanes")


class ChainRunner:
    """Drives one engine through consecutive heights; implements the
    :class:`~go_ibft_tpu.chain.sync.SyncSource` protocol for peers.

    ``overlap`` enables the cross-height pre-verification worker;
    ``sync`` (a :class:`SyncClient`) enables the fall-behind watcher and
    catch-up.  Both are persistent tasks owned by :meth:`run` — nothing is
    spawned or torn down per height beyond what the engine's own round
    workers require.
    """

    def __init__(
        self,
        engine: IBFT,
        wal: Optional[WriteAheadLog] = None,
        *,
        sync: Optional[SyncClient] = None,
        certifier=None,
        checkpointer=None,
        speculator=None,
        overlap: bool = True,
        overlap_poll_s: float = 0.002,
        max_chain_blocks: int = 8192,
        sync_poll_s: float = 0.05,
        sync_lag: int = 1,
        sync_stall_s: Optional[float] = None,
    ) -> None:
        self.engine = engine
        self.wal = wal
        self.sync = sync
        # Aggregate-COMMIT compression (ISSUE 7): a certifier (a
        # :class:`~go_ibft_tpu.crypto.quorum_cert.BLSCertifier`) makes
        # every finalize record O(1) — a height the engine finalized from
        # an aggregate certificate persists that certificate verbatim;
        # one finalized per-seal is compressed into a certificate at
        # persist time (no pairing: the seals were verified when the
        # quorum formed).  Peers then serve certificate blocks and the
        # sync client re-verifies each height with ONE pairing.
        self.certifier = certifier
        # Epoch checkpointing (ISSUE 20): a checkpointer (a
        # :class:`~go_ibft_tpu.lightsync.checkpoint.Checkpointer`)
        # builds the quorum-sealed skip-chain record at every epoch
        # boundary the runner finalizes, persists it through the WAL
        # (``append_checkpoint``), and serves the ``GET /checkpoints``
        # payload through the proof API.  ``recover()`` restores the
        # durable records so a restarted node never re-signs history.
        self.checkpointer = checkpointer
        # Speculative verification plane (ISSUE 9): attaching a
        # :class:`~go_ibft_tpu.verify.speculate.SpeculativeVerifier`
        # here wires it into the engine — ingress COMMIT seals verify
        # off the event loop as they land (including the future-height
        # COMMITs the overlap worker hands over via
        # ``add_verified_messages``), and the COMMIT drain's early-exit
        # remainder resolves through the same worker.  The engine owns
        # the lifecycle hooks; the runner only surfaces the evidence
        # (``stats()["speculation"]``).
        if speculator is not None:
            engine.speculator = speculator
        self.overlap = overlap
        self._overlap_poll_s = overlap_poll_s
        self._sync_poll_s = sync_poll_s
        # Peers this many heights past OUR current height trigger catch-up
        # (>= 1: a peer that finalized height H+lag can only have done so
        # after a quorum left our height behind).
        self.sync_lag = max(1, sync_lag)
        # Second trigger: a peer that finalized exactly OUR current height
        # is already conclusive evidence we can fetch it — but during
        # normal operation every node sees its peers finish moments before
        # it does, so this trigger additionally requires the current
        # sequence to have been running for ``sync_stall_s`` without
        # finalizing (default: 1.5x the engine's base round timeout — a
        # full round 0 plus slack).  Covers the restarted-mid-round node
        # whose peers finalized its height and then stalled waiting for it
        # at the next one (neither side can make consensus progress;
        # without this trigger that wedge is permanent).
        self.sync_stall_s = sync_stall_s
        self._height_started = time.monotonic()
        # In-memory tail of the finalized chain (contiguous, ascending —
        # consensus appends sequentially and sync fills gaps before the
        # runner advances).  Bounded: run() may drive heights forever;
        # heights evicted from the tail are served to peers from the WAL.
        self.chain: List[FinalizedBlock] = []
        self.max_chain_blocks = max_chain_blocks
        self.height = 1  # next height to run
        self._restore: Optional[RestoredState] = None
        # Readiness half of the supervisor contract (/readyz): flips in
        # recover() — a node with a WAL is not routable before its replay
        # completed, however long warm-start takes.
        self._recovered = False
        self._sync_wake = asyncio.Event() if sync is not None else None
        self._running = False
        # Evidence counters (bench config #7 reads these).
        self.heights_run = 0
        self.synced_heights = 0
        self.overlapped_lanes = 0
        self.overlap_batches = 0
        # Bounded: run() may drive heights forever; the full distribution
        # lives in the metrics histogram (HANDOFF_MS_KEY), this window
        # serves stats()/bench.
        self.handoff_ms: Deque[float] = deque(maxlen=4096)
        try:
            self._track = "chain-" + bytes(engine.backend.id()).hex()[:16]
        except Exception:  # noqa: BLE001 - mocks without a stable id
            self._track = f"chain-{id(self) & 0xFFFF:04x}"
        # Chain hooks: WAL append rides INSIDE the engine's finalize step
        # (between insert_proposal and the store prune — the
        # crash-consistency ordering), locks append at PC-pin time.
        engine.on_finalize = self._on_finalize
        engine.on_lock = self._on_lock

    # -- SyncSource (what this node serves to peers) ---------------------

    def latest_height(self) -> int:
        return self.chain[-1].height if self.chain else 0

    def validators_for_height(self, height: int) -> dict:
        """Per-height validator-set snapshot (the proof-serving seam).

        The serve layer (:mod:`go_ibft_tpu.serve`) builds finality proofs
        from a ``SyncSource`` plus this snapshot source — a
        ``ProofBuilder(runner, runner.validators_for_height)`` mounts a
        running node unchanged, rotation-aware: the engine backend's
        ``get_voting_powers`` is already the height-keyed seam every
        verifier uses."""
        return self.engine.backend.get_voting_powers(height)

    def get_blocks(self, start: int, end: int) -> List[FinalizedBlock]:
        # The in-memory tail is contiguous ascending, so a range request
        # is an index slice, not a scan (peers poll this at sync cadence).
        if self.chain and start >= self.chain[0].height:
            first = self.chain[0].height
            lo = max(0, start - first)
            hi = min(len(self.chain), end - first + 1)
            return self.chain[lo:hi]
        if self.wal is not None:
            # Deep history (evicted from the tail): replay the WAL — the
            # rare path, paid only by peers asking for old heights.
            return [
                b
                for b in self.wal.replay().blocks
                if start <= b.height <= end
            ]
        return []

    def _append_block(self, block: FinalizedBlock) -> None:
        self.chain.append(block)
        if len(self.chain) > self.max_chain_blocks:
            del self.chain[: len(self.chain) - self.max_chain_blocks]

    # -- engine hooks ----------------------------------------------------

    def _on_finalize(
        self, height: int, proposal: Proposal, seals: List[CommittedSeal]
    ) -> None:
        # Prefer the certificate that actually finalized the height
        # (tree-gossip mode) — REGARDLESS of whether this runner carries a
        # certifier: a cert-finalized height's seal list is the synthetic
        # AGG_CERT_SIGNER sentinel, and persisting/serving that as a real
        # seal would hand peers a block their seal-lane verify can never
        # accept.  Persisting the cert itself needs no certifier.
        cert = getattr(self.engine, "finalized_certificate", None)
        if self.certifier is not None:
            # Otherwise compress the verified seal quorum into one.  A
            # failed build (e.g. ECDSA seals a BLS certifier cannot
            # decode) falls back to per-seal evidence — never a lossy
            # record.
            if cert is None:
                try:
                    from ..crypto.backend import proposal_hash_of

                    cert = self.certifier.build(
                        height, proposal.round, proposal_hash_of(proposal), seals
                    )
                except Exception as err:  # noqa: BLE001 - keep per-seal
                    # evidence, but SAY so: a persistently mis-wired
                    # certifier silently producing O(N) records forever
                    # is an operations bug nobody would otherwise see.
                    self.engine.log.error(
                        "certifier failed; falling back to per-seal "
                        "finalize record",
                        height,
                        err,
                    )
                    cert = None
        stored_seals = [] if cert is not None else list(seals)
        if self.wal is not None:
            self.wal.append_finalize(height, proposal, stored_seals, cert=cert)
        self._append_block(
            FinalizedBlock(height, proposal, stored_seals, cert=cert)
        )
        if self.checkpointer is not None:
            from ..crypto.backend import proposal_hash_of

            # Epoch boundary: build the skip-chain record AFTER the
            # finalize record is durable (a checkpoint must never outlive
            # a crash that lost the height it commits to).
            rec = self.checkpointer.on_finalize(
                height, proposal_hash_of(proposal)
            )
            if rec is not None and self.wal is not None:
                self.wal.append_checkpoint(rec)

    def _on_lock(
        self,
        height: int,
        round_: int,
        certificate: PreparedCertificate,
        _proposal: Optional[Proposal],
    ) -> None:
        if self.wal is not None:
            self.wal.append_lock(height, round_, certificate)

    # -- crash recovery --------------------------------------------------

    def recover(self) -> int:
        """Replay the WAL; returns the height the node resumes at.

        Re-inserts every durable block into the embedder backend (the
        chain the old process had built), then restores the in-flight
        prepared-certificate lock so the first ``run_sequence`` re-enters
        its height mid-round instead of starting over — the restarted
        validator can never prepare a different proposal for a height it
        already sent COMMIT for.
        """
        if self.wal is None:
            raise ValueError("recover() needs a WAL")
        state = self.wal.replay()
        for block in state.blocks:
            self.engine.backend.insert_proposal(block.proposal, block.seals)
            self._append_block(block)
        if self.checkpointer is not None and state.checkpoints:
            self.checkpointer.restore(state.checkpoints)
        self.height = state.next_height
        self._restore = None
        self._recovered = True
        if state.lock is not None and state.lock.height >= self.height:
            self.height = state.lock.height
            self._restore = RestoredState(
                height=state.lock.height,
                round=state.lock.round,
                certificate=state.lock.certificate,
            )
        trace.instant(
            "chain.recover",
            track=self._track,
            height=self.height,
            locked=self._restore is not None,
            blocks=len(state.blocks),
        )
        return self.height

    def warm_start(self, **kw):
        """Full warm restore (ISSUE 16): compiled programs + WAL + verdict
        caches, all BEFORE the first round opens.  Thin delegation to
        :func:`go_ibft_tpu.boot.warmstart.warm_start` with this runner —
        keyword arguments (``programs`` / ``manifest`` / ``handle`` /
        ``sig_cache`` / ``warmups`` ...) pass through; returns its
        :class:`~go_ibft_tpu.boot.warmstart.WarmStartReport`.  Lazy import
        so runners that never warm-start pay no boot-package import."""
        from ..boot.warmstart import warm_start as _warm_start

        return _warm_start(self, **kw)

    # -- the height loop -------------------------------------------------

    async def run(
        self,
        heights: Optional[int] = None,
        *,
        until_height: Optional[int] = None,
    ) -> None:
        """Run heights back-to-back until ``until_height`` (inclusive) or
        for ``heights`` more heights; forever when neither is given.

        ONE call owns the node: the height loop, the overlap worker, and
        the sync watcher all live inside it and are torn down on exit or
        cancellation.
        """
        if until_height is not None:
            stop: Optional[int] = until_height
        elif heights is not None:
            stop = self.height + heights - 1
        else:
            stop = None
        if self._running:
            raise RuntimeError("ChainRunner.run is already active")
        self._running = True
        workers: List[asyncio.Task] = []
        if self.overlap:
            workers.append(
                asyncio.create_task(
                    self._overlap_worker(), name="chain-overlap"
                )
            )
        if self.sync is not None:
            workers.append(
                asyncio.create_task(self._sync_watcher(), name="chain-sync")
            )
        try:
            while stop is None or self.height <= stop:
                if self.sync is not None and self._sync_wake.is_set():
                    self._sync_wake.clear()
                    await self._catch_up()
                    continue
                await self._run_one_height()
        finally:
            self._running = False
            for task in workers:
                task.cancel()
            await asyncio.gather(*workers, return_exceptions=True)

    async def _run_one_height(self) -> None:
        height = self.height
        restore, self._restore = self._restore, None
        self._height_started = time.monotonic()
        t0 = time.perf_counter()
        sequence = asyncio.create_task(
            self.engine.run_sequence(height, restore=restore),
            name=f"chain-seq-h{height}",
        )
        interrupted = False
        with trace.span(
            "chain.height",
            track=self._track,
            height=height,
            restored=restore is not None,
        ):
            if self.sync is None:
                await sequence
            else:
                waiter = asyncio.create_task(self._sync_wake.wait())
                try:
                    await asyncio.wait(
                        {sequence, waiter},
                        return_when=asyncio.FIRST_COMPLETED,
                    )
                finally:
                    waiter.cancel()
                    await asyncio.gather(waiter, return_exceptions=True)
                    if not sequence.done():
                        # Either the sync watcher fired (we abandon the
                        # stale height for catch-up) or run() itself is
                        # being cancelled: tear the sequence down cleanly
                        # before leaving — the engine's teardown barrier
                        # runs inside.
                        interrupted = True
                        sequence.cancel()
                        await asyncio.gather(sequence, return_exceptions=True)
            if sequence.done() and not sequence.cancelled():
                sequence.result()  # propagate engine errors
        if interrupted:
            return
        metrics.observe(HEIGHT_MS_KEY, (time.perf_counter() - t0) * 1e3)
        self.heights_run += 1
        t0 = time.perf_counter()
        with trace.span("chain.handoff", track=self._track, height=height):
            self._handoff(height)
        dt_ms = (time.perf_counter() - t0) * 1e3
        metrics.observe(HANDOFF_MS_KEY, dt_ms)
        self.handoff_ms.append(dt_ms)
        self.height = height + 1

    def _handoff(self, height: int) -> None:
        """Between-heights bookkeeping, attributed to its own span.

        The WAL finalize append already ran INSIDE the finalize step (the
        crash-consistency ordering); what remains is rolling the verifier
        caches and pruning the store up to the next height — all
        idempotent with ``run_sequence``'s own start-of-height work, so
        driving the engine directly (without a runner) stays correct.
        """
        engine = self.engine
        engine.messages.prune_by_height(height + 1)
        verifier = engine.batch_verifier
        if hasattr(verifier, "reset_pack_cache"):
            verifier.reset_pack_cache()
        if hasattr(verifier, "note_round"):
            verifier.note_round(0)

    # -- persistent workers ----------------------------------------------

    async def _overlap_worker(self) -> None:
        """Pre-verify next-height ingress while COMMIT is in flight.

        Runs forever at a small poll interval; only acts when the engine
        sits in the COMMIT phase (the window where the current height's
        seal drain is on the device/native path) AND the future buffer
        holds messages for the next height.  Verification runs in an
        executor thread — the engine's event loop keeps draining COMMIT
        wakeups while the envelopes for H+1 verify concurrently; on the
        device route the drain itself is the double-buffered
        ``verify/pipeline.py`` chunk pipeline, and when the engine's
        verifier carries the sharded mesh route
        (:class:`~go_ibft_tpu.verify.mesh_batch.MeshBatchVerifier`, alone
        or as the Adaptive ladder's fast rung) the whole buffered batch
        coalesces into lane-parallel sharded dispatches — the route is the
        verifier's decision, invisible here, exactly like the
        host/device split.
        """
        loop = asyncio.get_running_loop()
        engine = self.engine
        while True:
            await asyncio.sleep(self._overlap_poll_s)
            if engine.state.name != StateName.COMMIT:
                continue
            next_height = engine.state.height + 1
            batch = engine.take_future_messages(next_height)
            if not batch:
                continue
            with trace.span(
                "chain.overlap",
                track=self._track,
                height=next_height,
                lanes=len(batch),
            ):
                verifier = engine.batch_verifier
                try:
                    if verifier is not None:
                        mask = await loop.run_in_executor(
                            None, verifier.verify_senders, batch
                        )
                        accepted = [
                            m for m, ok in zip(batch, mask) if bool(ok)
                        ]
                    else:
                        accepted = await loop.run_in_executor(
                            None,
                            lambda: [
                                m
                                for m in batch
                                if engine.backend.is_valid_validator(m)
                            ],
                        )
                except Exception:  # noqa: BLE001 - degraded path below
                    # A faulted drain must not eat the messages.  Re-
                    # buffering alone is not enough: the engine may have
                    # advanced to the batch's height during the executor
                    # call, and _buffer_future silently rejects heights
                    # that are no longer future.  Anything un-bufferable
                    # goes back through the one-message verified ingress
                    # (each guarded — the verifier just faulted once).
                    for message in batch:
                        if engine._buffer_future(message):
                            continue
                        try:
                            engine.add_message(message)
                        except Exception:  # noqa: BLE001 - still faulting
                            pass
                    continue
                engine.add_verified_messages(accepted)
            self.overlapped_lanes += len(batch)
            self.overlap_batches += 1
            metrics.inc_counter(OVERLAP_LANES_KEY, len(batch))

    async def _sync_watcher(self) -> None:
        """Wake the height loop when peers have demonstrably moved on.

        A peer advertising height >= ours + ``sync_lag`` finalized our
        current height without us — consensus there is over, only block
        sync can rejoin us.  Two consecutive observations are required so
        the normal end-of-height race (a fast peer finishing moments
        before we do) never cancels a sequence that is about to finalize.
        """
        behind_streak = 0
        while True:
            await asyncio.sleep(self._sync_poll_s)
            if self._sync_wake.is_set():
                continue
            try:
                best = self.sync.best_peer_height()
            except Exception:  # noqa: BLE001 - unreachable peers: retry
                continue
            # Fast path: commit-quorum evidence for a FUTURE height in
            # the ingress buffer is conclusive — peers finalized past us
            # (e.g. this node's proposal for the current height was
            # dropped beyond the one-ahead buffer horizon while it was
            # still catching up), so waiting out the stall timer only
            # burns liveness.  Triggers immediately, no streak, as long
            # as a peer can actually serve the gap.
            quorum = self.engine.validator_manager.quorum_size
            if (
                quorum > 0
                and best >= self.height
                and self.engine.future_commit_evidence(self.height + 1)
                >= quorum
            ):
                trace.instant(
                    "chain.sync.behind",
                    track=self._track,
                    height=self.height,
                    best_peer=best,
                    evidence="future-commits",
                )
                self._sync_wake.set()
                continue
            stall_s = (
                self.sync_stall_s
                if self.sync_stall_s is not None
                else 1.5 * self.engine.base_round_timeout
            )
            stalled = (
                best >= self.height
                and time.monotonic() - self._height_started > stall_s
            )
            if best >= self.height + self.sync_lag or stalled:
                behind_streak += 1
                if behind_streak >= 2:
                    behind_streak = 0
                    trace.instant(
                        "chain.sync.behind",
                        track=self._track,
                        height=self.height,
                        best_peer=best,
                        stalled=stalled,
                    )
                    self._sync_wake.set()
            else:
                behind_streak = 0

    async def _catch_up(self) -> None:
        """Fetch and verify the missing range; one drain per snapshot."""
        target = self.sync.best_peer_height()
        if target < self.height:
            return
        loop = asyncio.get_running_loop()
        with trace.span(
            "chain.sync",
            track=self._track,
            start=self.height,
            target=target,
        ):
            try:
                blocks = await loop.run_in_executor(
                    None, self.sync.catch_up, self.height, target
                )
            except SyncError as err:
                self.engine.log.error("block sync failed", err)
                return
        # Embedder content check: committed seals sign (raw_proposal,
        # round) — NOT the height — so in-protocol verification alone
        # cannot catch a peer relabeling a genuine block at a different
        # height.  Height binding lives in the proposal content (real
        # chains embed height/parent-hash in the block and reject it
        # here), exactly as in the reference where block sync is wholly
        # the embedder's job; is_valid_proposal is the seam for it.
        for block in blocks:
            try:
                ok = self.engine.backend.is_valid_proposal(
                    block.proposal.raw_proposal
                )
            except Exception:  # noqa: BLE001 - treat a crash as rejection
                ok = False
            if not ok:
                self.engine.log.error(
                    "block sync: embedder rejected synced proposal",
                    block.height,
                )
                return
        for block in blocks:
            self.engine.backend.insert_proposal(block.proposal, block.seals)
            if self.wal is not None:
                self.wal.append_finalize(
                    block.height, block.proposal, block.seals, cert=block.cert
                )
            self._append_block(block)
            if self.checkpointer is not None:
                from ..crypto.backend import proposal_hash_of

                # A synced epoch boundary checkpoints too — catch-up must
                # not leave holes in the skip chain.
                rec = self.checkpointer.on_finalize(
                    block.height, proposal_hash_of(block.proposal)
                )
                if rec is not None and self.wal is not None:
                    self.wal.append_checkpoint(rec)
        if blocks:
            self.synced_heights += len(blocks)
            self.height = blocks[-1].height + 1
            self._restore = None  # the locked height was finalized by peers

    # -- telemetry plane (live endpoints + per-node trace export) ---------

    def start_telemetry(
        self,
        port: int = 0,
        *,
        host: str = "127.0.0.1",
        wedged_after_s: Optional[float] = None,
        extra_status: Optional[dict] = None,
    ):
        """Mount /metrics, /healthz, /statusz for this node.

        Default-off: nothing calls this unless the embedder (or
        ``examples/minimal_embedder.py --telemetry``) asks — benches are
        unaffected.  Also turns the fixed-bucket latency histograms on
        (they are what /metrics serves).  ``wedged_after_s`` is the
        /healthz liveness bound: a runner that has not advanced a height
        for that long while running reports unhealthy (default: 3x the
        engine's base round timeout + 5s — a full round 0 plus round-
        change slack).  ``extra_status`` maps status keys to zero-arg
        callables merged into /statusz per scrape (mount scheduler or
        proof-server stats here).  Returns the started
        :class:`~go_ibft_tpu.obs.httpd.TelemetryServer` (``.port`` holds
        the bound port).
        """
        from ..obs.httpd import TelemetryServer

        metrics.enable_fixed_histograms()
        self._telemetry_extra = dict(extra_status or {})
        self._wedged_after_s = wedged_after_s
        server = TelemetryServer(
            status_fn=self.telemetry_status,
            health_fn=self.telemetry_health,
            ready_fn=self.telemetry_ready,
            host=host,
            port=port,
        )
        server.start()
        self._telemetry = server
        return server

    def stop_telemetry(self) -> None:
        server = getattr(self, "_telemetry", None)
        if server is not None:
            server.stop()
            self._telemetry = None

    def telemetry_status(self) -> dict:
        """The /statusz payload: one lock-free snapshot of the node."""
        from ..obs import trace

        engine = self.engine
        recorder = trace.recorder()
        verifier = engine.batch_verifier
        breaker = getattr(verifier, "breaker", None)
        speculator = getattr(engine, "speculator", None)
        status = {
            "node": self._track,
            "running": self._running,
            "height": engine.state.height,
            "round": engine.state.round,
            "state": str(getattr(engine.state.name, "name", engine.state.name)),
            "next_height": self.height,
            "chain_height": self.latest_height(),
            "heights_run": self.heights_run,
            "synced_heights": self.synced_heights,
            "overlapped_lanes": self.overlapped_lanes,
            "breaker_level": getattr(breaker, "level", None),
            "speculation": (
                speculator.stats() if speculator is not None else None
            ),
            "ring_dropped": recorder.dropped if recorder is not None else None,
            "handoff_ms_mean": (
                sum(self.handoff_ms) / len(self.handoff_ms)
                if self.handoff_ms
                else None
            ),
        }
        for key, fn in getattr(self, "_telemetry_extra", {}).items():
            try:
                status[key] = fn()
            except Exception as err:  # noqa: BLE001 - a scrape never crashes
                status[key] = {"error": repr(err)}
        return status

    def telemetry_health(self):
        """The /healthz verdict: (ok, payload).

        Unhealthy iff the runner is live but has not started a new height
        within the wedge bound — the restart signal a fleet orchestrator
        polls.  A stopped runner is healthy (it is not wedged, it is
        done); a sequence legitimately waiting out round changes stays
        healthy until the bound, which defaults past a full round 0.
        """
        limit = getattr(self, "_wedged_after_s", None)
        if limit is None:
            limit = 3.0 * self.engine.base_round_timeout + 5.0
        stale_s = time.monotonic() - self._height_started
        wedged = self._running and stale_s > limit
        return not wedged, {
            "ok": not wedged,
            "wedged": wedged,
            "running": self._running,
            "stale_s": round(stale_s, 3),
            "limit_s": limit,
            "height": self.height,
            "chain_height": self.latest_height(),
        }

    def telemetry_ready(self):
        """The /readyz verdict: (ready, payload) — may traffic be routed?

        Distinct from :meth:`telemetry_health` (liveness) on purpose: a
        warm-starting node is alive the whole time ``recover()`` replays
        the WAL, but routing clients to it before the replay lands them
        on a stale (or empty) chain.  Ready iff

        * the WAL, when there is one, has been replayed
          (``recover()`` completed — the supervisor contract from
          ISSUE 19), and
        * at least one height is finalized on the chain tail, so the
          node can actually serve reads (a fresh genesis node becomes
          ready the moment height 1 lands).
        """
        recovered = self.wal is None or self._recovered
        first_height = self.latest_height() >= 1
        ready = recovered and first_height
        return ready, {
            "ready": ready,
            "recovered": recovered,
            "chain_height": self.latest_height(),
            "running": self._running,
        }

    def export_trace(self, path: str) -> int:
        """Per-node flight-recorder export with node identity + clock
        offsets stamped in (the cross-process timeline contract).

        The stamped identity is the ENGINE's track (``node-<id>``), not
        the runner's ``chain-<id>``: peers key their clock-offset
        estimates by the trace-context ``origin``, which is the engine
        track — the timeline tool matches ``otherData.node`` against
        those keys to rebase this file's clock.
        """
        from ..obs.export import write_chrome_trace

        return write_chrome_trace(
            path, node=getattr(self.engine, "_obs_track", self._track)
        )

    # -- evidence ---------------------------------------------------------

    def stats(self) -> dict:
        """Bench/evidence snapshot (config #7 reads this)."""
        n = len(self.handoff_ms)
        speculator = getattr(self.engine, "speculator", None)
        return {
            "heights_run": self.heights_run,
            "synced_heights": self.synced_heights,
            "overlapped_lanes": self.overlapped_lanes,
            "overlap_batches": self.overlap_batches,
            "handoff_ms_mean": (sum(self.handoff_ms) / n) if n else None,
            "handoff_ms_max": max(self.handoff_ms) if n else None,
            "chain_height": self.latest_height(),
            "speculation": (
                speculator.stats() if speculator is not None else None
            ),
        }
