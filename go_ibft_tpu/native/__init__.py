"""ctypes loader for the native C++ runtime components.

Builds ``libibft_native.so`` from source on first use (g++ is part of the
toolchain; there is no pip dependency), then exposes:

* :func:`keccak256` — fast host hashing (also auto-registered as the
  :mod:`go_ibft_tpu.crypto.keccak` fast path via :func:`install`);
* :func:`ecdsa_verify` / :func:`ecdsa_recover` — per-message host crypto;
* :func:`verify_batch_sequential` — the sequential per-message loop used
  as the benchmark baseline denominator (the reference embedder's Go
  crypto/ecdsa shape, go-ibft messages/messages.go:183-198).

Everything degrades gracefully: if no compiler is available the pure
Python paths keep working.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "src", "ibft_native.cc")
_LIB_DIR = os.path.join(os.path.dirname(__file__), "_build")
_LIB = os.path.join(_LIB_DIR, "libibft_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _build() -> Optional[str]:
    os.makedirs(_LIB_DIR, exist_ok=True)
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-o", _LIB, _SRC]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=300
        )
    except (OSError, subprocess.TimeoutExpired) as err:
        return f"{type(err).__name__}: {err}"
    if proc.returncode != 0:
        return proc.stderr[-2000:]
    return None


def load() -> Optional[ctypes.CDLL]:
    """Build (if needed) and load the native library; None if unavailable."""
    global _lib, _build_error
    with _lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            return None
        if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
            err = _build()
            if err is not None:
                _build_error = err
                return None
        lib = ctypes.CDLL(_LIB)
        lib.ibft_keccak256.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ]
        lib.ibft_ecdsa_verify.argtypes = [ctypes.c_char_p] * 3
        lib.ibft_ecdsa_verify.restype = ctypes.c_int
        lib.ibft_ecdsa_recover.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
        ]
        lib.ibft_ecdsa_recover.restype = ctypes.c_int
        lib.ibft_ecdsa_sign.argtypes = [ctypes.c_char_p] * 3
        lib.ibft_ecdsa_sign.restype = ctypes.c_int
        lib.ibft_ecdsa_pubkey.argtypes = [ctypes.c_char_p] * 2
        lib.ibft_ecdsa_pubkey.restype = ctypes.c_int
        lib.ibft_verify_batch_sequential.argtypes = [
            ctypes.c_size_t, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_size_t, ctypes.c_char_p, ctypes.c_void_p,
        ]
        _lib = lib
        return _lib


def build_error() -> Optional[str]:
    return _build_error


def keccak256(data: bytes) -> bytes:
    lib = load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    out = ctypes.create_string_buffer(32)
    lib.ibft_keccak256(data, len(data), out)
    return out.raw


def ecdsa_verify(pub_xy: bytes, digest: bytes, rs: bytes) -> bool:
    """pub_xy = X||Y (64B, big-endian), rs = r||s (64B, big-endian)."""
    lib = load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    return bool(lib.ibft_ecdsa_verify(pub_xy, digest, rs))


def ecdsa_recover(digest: bytes, rs: bytes, v: int) -> Optional[bytes]:
    lib = load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    out = ctypes.create_string_buffer(64)
    if not lib.ibft_ecdsa_recover(digest, rs, v, out):
        return None
    return out.raw


def ecdsa_sign(d_be: bytes, digest: bytes) -> Optional[Tuple[int, int, int]]:
    """Deterministic sign: 32-byte BE private scalar + 32-byte digest ->
    ``(r, s, v)``; None for an out-of-range key."""
    lib = load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    out = ctypes.create_string_buffer(65)
    if not lib.ibft_ecdsa_sign(d_be, digest, out):
        return None
    sig = out.raw
    return (
        int.from_bytes(sig[:32], "big"),
        int.from_bytes(sig[32:64], "big"),
        sig[64],
    )


def ecdsa_pubkey(d_be: bytes) -> Optional[bytes]:
    """32-byte BE private scalar -> 64-byte BE ``X || Y``; None if invalid."""
    lib = load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    out = ctypes.create_string_buffer(64)
    if not lib.ibft_ecdsa_pubkey(d_be, out):
        return None
    return out.raw


def verify_batch_sequential(
    digests: Sequence[bytes],
    sigs: Sequence[bytes],
    claimed: Sequence[bytes],
    table: Sequence[bytes],
) -> np.ndarray:
    """The baseline loop: one recover+address+membership per message."""
    lib = load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_build_error}")
    n = len(digests)
    out = np.zeros(n, dtype=np.uint8)
    lib.ibft_verify_batch_sequential(
        n,
        b"".join(digests),
        b"".join(sigs),
        b"".join(claimed),
        len(table),
        b"".join(table),
        out.ctypes.data_as(ctypes.c_void_p),
    )
    return out.astype(bool)


def install() -> bool:
    """Register the native fast paths (keccak, sign, pubkey, recover).

    All are bit-identical to the pure-Python implementations
    (differential-tested); returns True when the native library is active."""
    lib = load()
    if lib is None:
        return False
    from ..crypto import ecdsa as ecdsa_mod
    from ..crypto import keccak as keccak_mod

    keccak_mod.set_native_impl(keccak256)
    ecdsa_mod.set_native_sign(ecdsa_sign)
    ecdsa_mod.set_native_pubkey(ecdsa_pubkey)
    ecdsa_mod.set_native_recover(ecdsa_recover)
    return True
