// Native runtime components for go_ibft_tpu.
//
// 1. keccak256 — fast host hashing for the wire layer (payload_no_sig
//    digests, proposal hashes, addresses).  The Python fallback is ~100x
//    slower; message ingress hashes on every add_message.
// 2. Sequential secp256k1 ECDSA verify/recover — the per-message host
//    verification path.  This is the honest stand-in for the reference
//    embedder's Go crypto/ecdsa loop (go-ibft calls Verifier once per
//    message, messages/messages.go:183-198): it is the baseline
//    DENOMINATOR for BASELINE.md's >=30x target, and the engine's
//    fallback verifier when no accelerator is attached.
//
// Plain C ABI; loaded from Python via ctypes (go_ibft_tpu/native/__init__.py).
// Build: g++ -O2 -shared -fPIC -o libibft_native.so ibft_native.cc

#include <cstdint>
#include <cstring>
#include <cstddef>

namespace {

// ---------------------------------------------------------------------------
// Keccak-256 (Ethereum flavor: multi-rate padding 0x01 .. 0x80)
// ---------------------------------------------------------------------------

constexpr uint64_t kRC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808AULL,
    0x8000000080008000ULL, 0x000000000000808BULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008AULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000AULL,
    0x000000008000808BULL, 0x800000000000008BULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800AULL, 0x800000008000000AULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL,
};

constexpr int kRot[5][5] = {
    {0, 36, 3, 41, 18},
    {1, 44, 10, 45, 2},
    {62, 6, 43, 15, 61},
    {28, 55, 25, 21, 56},
    {27, 20, 39, 8, 14},
};

inline uint64_t rotl64(uint64_t v, int n) {
  n &= 63;
  if (n == 0) return v;
  return (v << n) | (v >> (64 - n));
}

void keccak_f(uint64_t a[25]) {
  for (int round = 0; round < 24; ++round) {
    uint64_t c[5], d[5];
    for (int x = 0; x < 5; ++x)
      c[x] = a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20];
    for (int x = 0; x < 5; ++x)
      d[x] = c[(x + 4) % 5] ^ rotl64(c[(x + 1) % 5], 1);
    for (int y = 0; y < 5; ++y)
      for (int x = 0; x < 5; ++x) a[x + 5 * y] ^= d[x];
    uint64_t b[25];
    for (int x = 0; x < 5; ++x)
      for (int y = 0; y < 5; ++y)
        b[y + 5 * ((2 * x + 3 * y) % 5)] = rotl64(a[x + 5 * y], kRot[x][y]);
    for (int y = 0; y < 5; ++y)
      for (int x = 0; x < 5; ++x)
        a[x + 5 * y] = b[x + 5 * y] ^ (~b[(x + 1) % 5 + 5 * y] & b[(x + 2) % 5 + 5 * y]);
    a[0] ^= kRC[round];
  }
}

void keccak256(const uint8_t* data, size_t len, uint8_t out[32]) {
  constexpr size_t kRate = 136;
  uint64_t state[25] = {0};
  // absorb full blocks
  while (len >= kRate) {
    for (size_t i = 0; i < kRate / 8; ++i) {
      uint64_t lane;
      std::memcpy(&lane, data + 8 * i, 8);
      state[i] ^= lane;  // little-endian host assumed (x86/arm64)
    }
    keccak_f(state);
    data += kRate;
    len -= kRate;
  }
  // final padded block
  uint8_t block[kRate] = {0};
  std::memcpy(block, data, len);
  block[len] ^= 0x01;
  block[kRate - 1] ^= 0x80;
  for (size_t i = 0; i < kRate / 8; ++i) {
    uint64_t lane;
    std::memcpy(&lane, block + 8 * i, 8);
    state[i] ^= lane;
  }
  keccak_f(state);
  std::memcpy(out, state, 32);
}

// ---------------------------------------------------------------------------
// 256-bit arithmetic (4 x 64-bit little-endian words, __int128 carries)
// ---------------------------------------------------------------------------

struct U256 {
  uint64_t w[4];
};

const U256 kZero = {{0, 0, 0, 0}};

// secp256k1 field prime p = 2^256 - 2^32 - 977 and group order n.
const U256 kP = {{0xFFFFFFFEFFFFFC2FULL, 0xFFFFFFFFFFFFFFFFULL,
                  0xFFFFFFFFFFFFFFFFULL, 0xFFFFFFFFFFFFFFFFULL}};
const U256 kN = {{0xBFD25E8CD0364141ULL, 0xBAAEDCE6AF48A03BULL,
                  0xFFFFFFFFFFFFFFFEULL, 0xFFFFFFFFFFFFFFFFULL}};
// 2^256 mod p = 2^32 + 977; 2^256 mod n (129 bits).
const U256 kCP = {{0x00000001000003D1ULL, 0, 0, 0}};
const U256 kCN = {{0x402DA1732FC9BEBFULL, 0x4551231950B75FC4ULL, 1, 0}};

const U256 kGx = {{0x59F2815B16F81798ULL, 0x029BFCDB2DCE28D9ULL,
                   0x55A06295CE870B07ULL, 0x79BE667EF9DCBBACULL}};
const U256 kGy = {{0x9C47D08FFB10D4B8ULL, 0xFD17B448A6855419ULL,
                   0x5DA4FBFC0E1108A8ULL, 0x483ADA7726A3C465ULL}};

inline int cmp(const U256& a, const U256& b) {
  for (int i = 3; i >= 0; --i) {
    if (a.w[i] != b.w[i]) return a.w[i] < b.w[i] ? -1 : 1;
  }
  return 0;
}

inline bool is_zero(const U256& a) {
  return (a.w[0] | a.w[1] | a.w[2] | a.w[3]) == 0;
}

// returns carry
inline uint64_t add_u(const U256& a, const U256& b, U256* out) {
  unsigned __int128 carry = 0;
  for (int i = 0; i < 4; ++i) {
    carry += (unsigned __int128)a.w[i] + b.w[i];
    out->w[i] = (uint64_t)carry;
    carry >>= 64;
  }
  return (uint64_t)carry;
}

// returns borrow
inline uint64_t sub_u(const U256& a, const U256& b, U256* out) {
  unsigned __int128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    unsigned __int128 d = (unsigned __int128)a.w[i] - b.w[i] - (uint64_t)borrow;
    out->w[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  return (uint64_t)borrow;
}

struct U512 {
  uint64_t w[8];
};

void mul_full(const U256& a, const U256& b, U512* out) {
  std::memset(out->w, 0, sizeof(out->w));
  for (int i = 0; i < 4; ++i) {
    unsigned __int128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      carry += (unsigned __int128)a.w[i] * b.w[j] + out->w[i + j];
      out->w[i + j] = (uint64_t)carry;
      carry >>= 64;
    }
    out->w[i + 4] += (uint64_t)carry;
  }
}

// 5-word product of a 4-word value and kCP (fits: kCP < 2^33).
void fold_mul_cp(const uint64_t hi[4], uint64_t out[5]) {
  unsigned __int128 carry = 0;
  for (int i = 0; i < 4; ++i) {
    carry += (unsigned __int128)hi[i] * kCP.w[0];
    out[i] = (uint64_t)carry;
    carry >>= 64;
  }
  out[4] = (uint64_t)carry;
}

// v mod p for a 512-bit v: two pseudo-Mersenne folds + conditional subtracts.
void reduce_p(const U512& v, U256* out) {
  // fold 1: lo + hi * kCP  (<= 2^256 + 2^289)
  uint64_t prod[5];
  fold_mul_cp(v.w + 4, prod);
  U256 lo = {{v.w[0], v.w[1], v.w[2], v.w[3]}};
  U256 p1 = {{prod[0], prod[1], prod[2], prod[3]}};
  U256 acc;
  uint64_t hi2 = prod[4] + add_u(lo, p1, &acc);  // value = acc + hi2 * 2^256
  // fold 2: hi2 <= 2^34ish
  unsigned __int128 c = (unsigned __int128)hi2 * kCP.w[0];
  unsigned __int128 t = (unsigned __int128)acc.w[0] + (uint64_t)c;
  acc.w[0] = (uint64_t)t;
  unsigned __int128 carry = (t >> 64) + (uint64_t)(c >> 64);
  for (int i = 1; i < 4 && carry; ++i) {
    t = (unsigned __int128)acc.w[i] + (uint64_t)carry;
    acc.w[i] = (uint64_t)t;
    carry = t >> 64;
  }
  if (carry) {  // one more tiny fold
    U256 cp = kCP;
    add_u(acc, cp, &acc);
  }
  while (cmp(acc, kP) >= 0) sub_u(acc, kP, &acc);
  *out = acc;
}

// 7-word product of a 4-word value and kCN (kCN < 2^129 -> 3 words).
void fold_mul_cn(const uint64_t hi[4], uint64_t out[7]) {
  std::memset(out, 0, 7 * sizeof(uint64_t));
  for (int i = 0; i < 4; ++i) {
    unsigned __int128 carry = 0;
    for (int j = 0; j < 3; ++j) {
      carry += (unsigned __int128)hi[i] * kCN.w[j] + out[i + j];
      out[i + j] = (uint64_t)carry;
      carry >>= 64;
    }
    out[i + 3] += (uint64_t)carry;
  }
}

void reduce_n(const U512& v, U256* out) {
  // fold 1: 512 -> ~385 bits
  uint64_t prod[7];
  fold_mul_cn(v.w + 4, prod);
  U512 t1 = {{v.w[0], v.w[1], v.w[2], v.w[3], 0, 0, 0, 0}};
  unsigned __int128 carry = 0;
  for (int i = 0; i < 7; ++i) {
    carry += (unsigned __int128)t1.w[i] + prod[i];
    t1.w[i] = (uint64_t)carry;
    carry >>= 64;
  }
  t1.w[7] = (uint64_t)carry;
  // fold 2: hi is now <= 2^130ish -> product < 2^259
  uint64_t prod2[7];
  fold_mul_cn(t1.w + 4, prod2);
  U512 t2 = {{t1.w[0], t1.w[1], t1.w[2], t1.w[3], 0, 0, 0, 0}};
  carry = 0;
  for (int i = 0; i < 7; ++i) {
    carry += (unsigned __int128)t2.w[i] + prod2[i];
    t2.w[i] = (uint64_t)carry;
    carry >>= 64;
  }
  // fold 3: hi <= small
  uint64_t prod3[7];
  fold_mul_cn(t2.w + 4, prod3);
  U256 acc = {{t2.w[0], t2.w[1], t2.w[2], t2.w[3]}};
  U256 p3 = {{prod3[0], prod3[1], prod3[2], prod3[3]}};
  uint64_t c2 = add_u(acc, p3, &acc);
  if (c2) {
    U256 cn = kCN;
    add_u(acc, cn, &acc);
  }
  while (cmp(acc, kN) >= 0) sub_u(acc, kN, &acc);
  *out = acc;
}

enum Mod { MOD_P, MOD_N };

inline void mulmod(const U256& a, const U256& b, Mod m, U256* out) {
  U512 t;
  mul_full(a, b, &t);
  if (m == MOD_P)
    reduce_p(t, out);
  else
    reduce_n(t, out);
}

inline void addmod(const U256& a, const U256& b, const U256& mod, U256* out) {
  uint64_t carry = add_u(a, b, out);
  if (carry || cmp(*out, mod) >= 0) sub_u(*out, mod, out);
}

inline void submod(const U256& a, const U256& b, const U256& mod, U256* out) {
  if (sub_u(a, b, out)) add_u(*out, mod, out);
}

void powmod(const U256& base, const U256& exp, Mod m, U256* out) {
  U256 acc = {{1, 0, 0, 0}};
  U256 b = base;
  for (int i = 0; i < 256; ++i) {
    int word = i / 64, bit = i % 64;
    if ((exp.w[word] >> bit) & 1) mulmod(acc, b, m, &acc);
    mulmod(b, b, m, &b);
  }
  *out = acc;
}

void invmod(const U256& a, Mod m, U256* out) {
  // Fermat: a^(mod-2)
  U256 e = (m == MOD_P) ? kP : kN;
  U256 two = {{2, 0, 0, 0}};
  sub_u(e, two, &e);
  powmod(a, e, m, out);
}

// ---------------------------------------------------------------------------
// Curve (Jacobian, a = 0)
// ---------------------------------------------------------------------------

struct Jac {
  U256 x, y, z;  // z == 0 => infinity
};

inline bool jac_inf(const Jac& p) { return is_zero(p.z); }

void jac_double(const Jac& p, Jac* out) {
  if (jac_inf(p)) {
    *out = p;
    return;
  }
  U256 a, b, c, d, e, f, t, x3, y3, z3;
  mulmod(p.x, p.x, MOD_P, &a);
  mulmod(p.y, p.y, MOD_P, &b);
  mulmod(b, b, MOD_P, &c);
  addmod(p.x, b, kP, &t);
  mulmod(t, t, MOD_P, &t);
  submod(t, a, kP, &t);
  submod(t, c, kP, &t);
  addmod(t, t, kP, &d);  // D = 2((X+B)^2 - A - C)
  addmod(a, a, kP, &e);
  addmod(e, a, kP, &e);  // E = 3A
  mulmod(e, e, MOD_P, &f);
  submod(f, d, kP, &x3);
  submod(x3, d, kP, &x3);  // X3 = F - 2D
  submod(d, x3, kP, &t);
  mulmod(e, t, MOD_P, &y3);
  U256 c8;
  addmod(c, c, kP, &c8);
  addmod(c8, c8, kP, &c8);
  addmod(c8, c8, kP, &c8);
  submod(y3, c8, kP, &y3);
  mulmod(p.y, p.z, MOD_P, &z3);
  addmod(z3, z3, kP, &z3);
  out->x = x3;
  out->y = y3;
  out->z = z3;
}

void jac_add(const Jac& p, const Jac& q, Jac* out) {
  if (jac_inf(p)) {
    *out = q;
    return;
  }
  if (jac_inf(q)) {
    *out = p;
    return;
  }
  U256 z1s, z2s, u1, u2, s1, s2, t;
  mulmod(p.z, p.z, MOD_P, &z1s);
  mulmod(q.z, q.z, MOD_P, &z2s);
  mulmod(p.x, z2s, MOD_P, &u1);
  mulmod(q.x, z1s, MOD_P, &u2);
  mulmod(z2s, q.z, MOD_P, &t);
  mulmod(p.y, t, MOD_P, &s1);
  mulmod(z1s, p.z, MOD_P, &t);
  mulmod(q.y, t, MOD_P, &s2);
  U256 h, r;
  submod(u2, u1, kP, &h);
  submod(s2, s1, kP, &r);
  if (is_zero(h)) {
    if (is_zero(r)) {
      jac_double(p, out);
      return;
    }
    *out = {kZero, {{1, 0, 0, 0}}, kZero};  // P + (-P) = infinity
    return;
  }
  U256 hs, hc, u1hs, x3, y3, z3;
  mulmod(h, h, MOD_P, &hs);
  mulmod(hs, h, MOD_P, &hc);
  mulmod(u1, hs, MOD_P, &u1hs);
  mulmod(r, r, MOD_P, &x3);
  submod(x3, hc, kP, &x3);
  submod(x3, u1hs, kP, &x3);
  submod(x3, u1hs, kP, &x3);
  submod(u1hs, x3, kP, &t);
  mulmod(r, t, MOD_P, &y3);
  mulmod(s1, hc, MOD_P, &t);
  submod(y3, t, kP, &y3);
  mulmod(p.z, q.z, MOD_P, &z3);
  mulmod(z3, h, MOD_P, &z3);
  out->x = x3;
  out->y = y3;
  out->z = z3;
}

// 4-bit windowed double-scalar multiply: k1*G + k2*Q.
void ecmul2(const U256& k1, const U256& k2, const Jac& q, Jac* out) {
  Jac tg[16], tq[16];
  tg[0] = {kZero, {{1, 0, 0, 0}}, kZero};
  tq[0] = tg[0];
  Jac g = {kGx, kGy, {{1, 0, 0, 0}}};
  tg[1] = g;
  tq[1] = q;
  for (int i = 2; i < 16; ++i) {
    jac_add(tg[i - 1], g, &tg[i]);
    jac_add(tq[i - 1], q, &tq[i]);
  }
  Jac acc = tg[0];
  for (int nib = 63; nib >= 0; --nib) {
    if (nib != 63) {
      jac_double(acc, &acc);
      jac_double(acc, &acc);
      jac_double(acc, &acc);
      jac_double(acc, &acc);
    }
    int word = nib / 16, off = (nib % 16) * 4;
    int d1 = (int)((k1.w[word] >> off) & 0xF);
    int d2 = (int)((k2.w[word] >> off) & 0xF);
    if (d1) jac_add(acc, tg[d1], &acc);
    if (d2) jac_add(acc, tq[d2], &acc);
  }
  *out = acc;
}

void to_affine(const Jac& p, U256* x, U256* y) {
  if (jac_inf(p)) {
    *x = kZero;
    *y = kZero;
    return;
  }
  U256 zi, zi2;
  invmod(p.z, MOD_P, &zi);
  mulmod(zi, zi, MOD_P, &zi2);
  mulmod(p.x, zi2, MOD_P, x);
  mulmod(zi2, zi, MOD_P, &zi2);
  mulmod(p.y, zi2, MOD_P, y);
}

void load_be(const uint8_t* in, U256* out) {
  for (int i = 0; i < 4; ++i) {
    uint64_t w = 0;
    for (int j = 0; j < 8; ++j) w = (w << 8) | in[i * 8 + j];
    out->w[3 - i] = w;
  }
}

void store_be(const U256& in, uint8_t* out) {
  for (int i = 0; i < 4; ++i) {
    uint64_t w = in.w[3 - i];
    for (int j = 7; j >= 0; --j) {
      out[i * 8 + j] = (uint8_t)w;
      w >>= 8;
    }
  }
}

bool on_curve(const U256& x, const U256& y) {
  U256 lhs, rhs, seven = {{7, 0, 0, 0}};
  mulmod(y, y, MOD_P, &lhs);
  mulmod(x, x, MOD_P, &rhs);
  mulmod(rhs, x, MOD_P, &rhs);
  addmod(rhs, seven, kP, &rhs);
  return cmp(lhs, rhs) == 0;
}

bool in_scalar_range(const U256& v) {
  return !is_zero(v) && cmp(v, kN) < 0;
}

bool ecdsa_verify_impl(const U256& qx, const U256& qy, const U256& z,
                       const U256& r, const U256& s) {
  if (!in_scalar_range(r) || !in_scalar_range(s)) return false;
  if (!on_curve(qx, qy)) return false;
  U256 w, u1, u2;
  invmod(s, MOD_N, &w);
  mulmod(z, w, MOD_N, &u1);
  mulmod(r, w, MOD_N, &u2);
  Jac q = {qx, qy, {{1, 0, 0, 0}}}, res;
  ecmul2(u1, u2, q, &res);
  if (jac_inf(res)) return false;
  U256 x, y;
  to_affine(res, &x, &y);
  // x mod n == r  (x < p < 2n: check x == r or x == r + n when r + n < p)
  if (cmp(x, r) == 0) return true;
  U256 rn;
  if (!add_u(r, kN, &rn) && cmp(rn, kP) < 0 && cmp(x, rn) == 0) return true;
  return false;
}

bool ecdsa_recover_impl(const U256& z, const U256& r, const U256& s, int v,
                        U256* qx, U256* qy) {
  if (!in_scalar_range(r) || !in_scalar_range(s)) return false;
  if (v != 0 && v != 1) return false;
  // y^2 = x^3 + 7; y = (x^3+7)^((p+1)/4)
  U256 y2, y, seven = {{7, 0, 0, 0}};
  mulmod(r, r, MOD_P, &y2);
  mulmod(y2, r, MOD_P, &y2);
  addmod(y2, seven, kP, &y2);
  U256 e = kP;  // (p+1)/4: p+1 overflows, but p+1 = p with low bits... compute via shift
  // p + 1 = 2^256 - 2^32 - 976; (p+1)/4 = (p >> 2) + 1 ... derive exactly:
  // p = ...FC2F; p+1 = ...FC30; (p+1)/4 = p/4 rounded: implement as (p+1)>>2
  // with the +1 carried manually (p+1 fits since p < 2^256-1).
  {
    U256 one = {{1, 0, 0, 0}};
    add_u(e, one, &e);  // no carry: p < 2^256 - 1
    // shift right by 2
    for (int i = 0; i < 4; ++i) {
      e.w[i] >>= 2;
      if (i < 3) e.w[i] |= e.w[i + 1] << 62;
    }
  }
  powmod(y2, e, MOD_P, &y);
  U256 chk;
  mulmod(y, y, MOD_P, &chk);
  if (cmp(chk, y2) != 0) return false;
  if ((int)(y.w[0] & 1) != v) submod(kP, y, kP, &y);
  U256 rinv, u1, u2, zneg;
  invmod(r, MOD_N, &rinv);
  submod(kN, z, kN, &zneg);  // -z mod n (z < n)
  mulmod(zneg, rinv, MOD_N, &u1);
  mulmod(s, rinv, MOD_N, &u2);
  Jac rp = {r, y, {{1, 0, 0, 0}}}, res;
  ecmul2(u1, u2, rp, &res);
  if (jac_inf(res)) return false;
  to_affine(res, qx, qy);
  return true;
}

void pubkey_address(const U256& x, const U256& y, uint8_t out[20]) {
  uint8_t buf[64], digest[32];
  store_be(x, buf);
  store_be(y, buf + 32);
  keccak256(buf, 64, digest);
  std::memcpy(out, digest + 12, 20);
}

void digest_to_scalar(const uint8_t digest[32], U256* out) {
  load_be(digest, out);
  if (cmp(*out, kN) >= 0) sub_u(*out, kN, out);
}

// Deterministic ECDSA sign, bit-identical to the Python reference path
// (crypto/ecdsa.py::sign): nonce k = keccak256(d || digest || counter) mod n,
// low-s normalization with the recovery id flipped alongside s.
bool ecdsa_sign_impl(const U256& d, const uint8_t digest[32], U256* r_out,
                     U256* s_out, int* v_out) {
  U256 z;
  digest_to_scalar(digest, &z);
  uint8_t buf[65];
  store_be(d, buf);
  std::memcpy(buf + 32, digest, 32);
  U256 half = kN;  // n >> 1 == n // 2 (n is odd)
  for (int i = 0; i < 4; ++i) {
    half.w[i] >>= 1;
    if (i < 3) half.w[i] |= kN.w[i + 1] << 63;
  }
  // The Python loop is unbounded; 256 nonce retries is unreachable in
  // practice (each retry needs k==0, r==0, or s==0).
  for (int counter = 0; counter < 256; ++counter) {
    buf[64] = (uint8_t)counter;
    uint8_t kd[32];
    keccak256(buf, 65, kd);
    U256 k;
    load_be(kd, &k);
    if (cmp(k, kN) >= 0) sub_u(k, kN, &k);  // k_raw < 2^256 < 2n
    if (is_zero(k)) continue;
    Jac g = {kGx, kGy, {{1, 0, 0, 0}}}, pt;
    ecmul2(k, kZero, g, &pt);
    U256 x, y;
    to_affine(pt, &x, &y);
    U256 r = x;
    if (cmp(r, kN) >= 0) sub_u(r, kN, &r);  // x < p < 2n
    if (is_zero(r)) continue;
    U256 kinv, rd, t, s;
    invmod(k, MOD_N, &kinv);
    mulmod(r, d, MOD_N, &rd);
    addmod(z, rd, kN, &t);
    mulmod(kinv, t, MOD_N, &s);
    if (is_zero(s)) continue;
    int v = (int)(y.w[0] & 1);
    if (cmp(s, half) > 0) {
      submod(kN, s, kN, &s);
      v ^= 1;
    }
    *r_out = r;
    *s_out = s;
    *v_out = v;
    return true;
  }
  return false;
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

void ibft_keccak256(const uint8_t* data, size_t len, uint8_t* out) {
  keccak256(data, len, out);
}

// sig = r(32, BE) || s(32, BE); pub = x(32, BE) || y(32, BE)
int ibft_ecdsa_verify(const uint8_t* pub, const uint8_t* digest,
                      const uint8_t* sig) {
  U256 qx, qy, z, r, s;
  load_be(pub, &qx);
  load_be(pub + 32, &qy);
  digest_to_scalar(digest, &z);
  load_be(sig, &r);
  load_be(sig + 32, &s);
  return ecdsa_verify_impl(qx, qy, z, r, s) ? 1 : 0;
}

// recovers pubkey; returns 1 on success
int ibft_ecdsa_recover(const uint8_t* digest, const uint8_t* sig, int v,
                       uint8_t* pub_out) {
  U256 z, r, s, qx, qy;
  digest_to_scalar(digest, &z);
  load_be(sig, &r);
  load_be(sig + 32, &s);
  if (!ecdsa_recover_impl(z, r, s, v, &qx, &qy)) return 0;
  store_be(qx, pub_out);
  store_be(qy, pub_out + 32);
  return 1;
}

// The sequential baseline loop (the reference's per-message verify shape):
// for each message i: recover(digest_i, sig_i) -> address -> compare with
// claimed address and membership in the validator table.
// digests: n*32, sigs: n*65 (r||s||v), claimed: n*20,
// table: n_validators*20, out: n bytes (0/1)
void ibft_verify_batch_sequential(size_t n, const uint8_t* digests,
                                  const uint8_t* sigs, const uint8_t* claimed,
                                  size_t n_validators, const uint8_t* table,
                                  uint8_t* out) {
  for (size_t i = 0; i < n; ++i) {
    out[i] = 0;
    U256 z, r, s, qx, qy;
    digest_to_scalar(digests + 32 * i, &z);
    load_be(sigs + 65 * i, &r);
    load_be(sigs + 65 * i + 32, &s);
    int v = sigs[65 * i + 64];
    if (!ecdsa_recover_impl(z, r, s, v, &qx, &qy)) continue;
    uint8_t addr[20];
    pubkey_address(qx, qy, addr);
    if (std::memcmp(addr, claimed + 20 * i, 20) != 0) continue;
    bool member = false;
    for (size_t j = 0; j < n_validators && !member; ++j)
      member = std::memcmp(addr, table + 20 * j, 20) == 0;
    out[i] = member ? 1 : 0;
  }
}

// Deterministic sign: d (32B BE) + digest (32B) -> sig r||s||v (65B).
// Returns 1 on success, 0 for an out-of-range private key.
int ibft_ecdsa_sign(const uint8_t* d, const uint8_t* digest,
                    uint8_t* sig_out) {
  U256 dd;
  load_be(d, &dd);
  if (!in_scalar_range(dd)) return 0;
  U256 r, s;
  int v;
  if (!ecdsa_sign_impl(dd, digest, &r, &s, &v)) return 0;
  store_be(r, sig_out);
  store_be(s, sig_out + 32);
  sig_out[64] = (uint8_t)v;
  return 1;
}

// Public-key derivation: d (32B BE) -> x||y (64B BE). Returns 1 on success.
int ibft_ecdsa_pubkey(const uint8_t* d, uint8_t* pub_out) {
  U256 dd;
  load_be(d, &dd);
  if (!in_scalar_range(dd)) return 0;
  Jac g = {kGx, kGy, {{1, 0, 0, 0}}}, pt;
  ecmul2(dd, kZero, g, &pt);
  if (jac_inf(pt)) return 0;
  U256 x, y;
  to_affine(pt, &x, &y);
  store_be(x, pub_out);
  store_be(y, pub_out + 32);
  return 1;
}

}  // extern "C"
