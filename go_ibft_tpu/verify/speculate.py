"""Speculative cross-phase verification: take seal crypto OFF the
phase-ordered critical path.

The engine's phase discipline verifies signatures exactly once — but only
when the *phase* that consumes them opens: a COMMIT seal that arrives
while the PREPARE drain is still running sits unverified in the store
until the prepare quorum lands, and then the whole seal batch is paid on
the commit critical path (PAPERS.md 2302.00418: signature verification
dominates commit latency in exactly this regime).  Signature validity is
*proposal-independent* — the seal signs the proposal hash carried IN the
message — so nothing about it needs the phase to be open.

This module verifies those arrivals as they land, off the event loop:

* :class:`SpeculationCache` — a thread-safe verdict cache whose key is
  the FULL binding ``(owner, height, round, proposal_hash, phase,
  sender, signature)``.  A verdict can never leak across a different
  binding: a speculatively verified COMMIT for proposal hash ``H`` is
  unreachable for ``H'`` at the same height/round, for a different
  round, for a different sender, or for another tenant (``owner``).
  Eviction is round-scoped like the engine's seal-verdict cache (dead
  heights/rounds evict whole before the live view sheds FIFO).  A
  quarantine EVICT hook exists (:meth:`SpeculativeVerifier.
  quarantine_seals`) for embedders that condemn lanes out of band;
  note the binding itself already prevents the stale-verdict hazard —
  a corrected re-send carries different signature bytes and therefore
  a different key, so it can never be served a condemned verdict.

* :class:`SpeculativeVerifier` — a bounded work queue + one daemon
  worker thread that drains queued seal lanes through the engine's OWN
  batch verifier (host native, device, mesh, or a
  :class:`~go_ibft_tpu.sched.scheduler.TenantVerifierHandle` — the
  route is the verifier's decision), storing verdicts into the cache.
  Everything is best-effort: a full queue drops the lane (it simply
  verifies at drain time as before), a worker fault drops the batch,
  and a verdict is only ever a *cache hit* for work the drain would
  have done anyway — speculation can change WHEN a signature verifies,
  never a verdict.

The same worker doubles as the **lazy remainder resolver** for the
early-exit drains (:meth:`~go_ibft_tpu.verify.batch.HostBatchVerifier.
verify_seals_early_exit`): lanes past the quorum cut are submitted here,
resolve off-path, and the next wakeup (or the post-quorum bookkeeping)
sees them as cache hits.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..messages import helpers
from ..messages.wire import IbftMessage, MessageType
from ..obs import trace
from ..utils import metrics

__all__ = [
    "SpeculationCache",
    "SpeculativeVerifier",
    "SPEC_HITS_KEY",
    "SPEC_MISSES_KEY",
    "SPEC_LANES_KEY",
    "SPEC_DROPPED_KEY",
]

SPEC_HITS_KEY = ("go-ibft", "speculate", "hits")
SPEC_MISSES_KEY = ("go-ibft", "speculate", "misses")
SPEC_LANES_KEY = ("go-ibft", "speculate", "lanes")
SPEC_DROPPED_KEY = ("go-ibft", "speculate", "dropped")

# Phase tags for the cache binding.  Only COMMIT seals are speculated
# today (envelopes verify at ingress already), but the phase rides the
# key so an envelope verdict could never alias a seal verdict if a
# future path speculates both.
PHASE_COMMIT_SEAL = "commit-seal"


class SpeculationCache:
    """Thread-safe verdict cache with full-binding keys.

    Buckets are keyed ``(owner, height, round)`` so eviction and the
    engine lifecycle hooks stay scope-exact: ``note_view`` pins the
    owner's live (height, round); on cap pressure every bucket that is
    not some owner's live view evicts whole (oldest (height, round)
    first), and only when nothing dead remains does the oldest live
    bucket shed FIFO — the engine seal-verdict-cache posture (ADVICE
    r5), extended with the owner dimension for multi-tenant sharing.
    """

    def __init__(self, cap: int = 16384):
        self._lock = threading.Lock()
        # (owner, height, round) -> {(phash, phase, sender, sig) -> bool}
        self._buckets: Dict[Tuple[str, int, int], Dict[tuple, bool]] = {}
        self._live: Dict[str, Tuple[int, int]] = {}
        self._count = 0
        self._cap = cap
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        with self._lock:
            return self._count

    # -- lifecycle ------------------------------------------------------

    def note_view(self, height: int, round_: int, owner: str = "") -> None:
        """Pin ``owner``'s live (height, round) and drop its stale
        buckets (anything below the live height — a new sequence can
        never hit them again; higher heights are KEPT: speculation's
        whole point is verifying next-height traffic early)."""
        with self._lock:
            self._live[owner] = (height, round_)
            for key in [
                k
                for k in self._buckets
                if k[0] == owner and k[1] < height
            ]:
                self._count -= len(self._buckets[key])
                del self._buckets[key]

    def clear(self, owner: Optional[str] = None) -> None:
        with self._lock:
            if owner is None:
                self._buckets.clear()
                self._live.clear()
                self._count = 0
                return
            for key in [k for k in self._buckets if k[0] == owner]:
                self._count -= len(self._buckets[key])
                del self._buckets[key]
            self._live.pop(owner, None)

    # -- verdicts -------------------------------------------------------

    def store(
        self,
        height: int,
        round_: int,
        proposal_hash: bytes,
        phase: str,
        sender: bytes,
        signature: bytes,
        verdict: bool,
        owner: str = "",
    ) -> None:
        with self._lock:
            bucket = self._buckets.setdefault((owner, height, round_), {})
            key = (proposal_hash, phase, sender, signature)
            if key not in bucket:
                self._count += 1
            bucket[key] = verdict
            self._evict_locked()

    def lookup(
        self,
        height: int,
        round_: int,
        proposal_hash: bytes,
        phase: str,
        sender: bytes,
        signature: bytes,
        owner: str = "",
    ) -> Optional[bool]:
        """The verdict for EXACTLY this binding, or None.  No partial
        match exists by construction — a different proposal hash, round,
        phase, sender, signature, or owner is a different key."""
        with self._lock:
            bucket = self._buckets.get((owner, height, round_))
            hit = (
                None
                if bucket is None
                else bucket.get((proposal_hash, phase, sender, signature))
            )
            if hit is None:
                self.misses += 1
                metrics.inc_counter(SPEC_MISSES_KEY)
            else:
                self.hits += 1
                metrics.inc_counter(SPEC_HITS_KEY)
            return hit

    def contains(
        self,
        height: int,
        round_: int,
        proposal_hash: bytes,
        phase: str,
        sender: bytes,
        signature: bytes,
        owner: str = "",
    ) -> bool:
        """Hit test WITHOUT touching the hit/miss counters (dedup gate
        for the submit path)."""
        with self._lock:
            bucket = self._buckets.get((owner, height, round_))
            return (
                bucket is not None
                and (proposal_hash, phase, sender, signature) in bucket
            )

    def evict_seal(
        self,
        height: int,
        round_: int,
        proposal_hash: bytes,
        sender: bytes,
        signature: bytes,
        owner: str = "",
    ) -> None:
        """Quarantine hook: a condemned lane's verdict must not outlive
        the quarantine (a corrected re-send re-verifies from bytes)."""
        with self._lock:
            bucket = self._buckets.get((owner, height, round_))
            if bucket is None:
                return
            if bucket.pop(
                (proposal_hash, PHASE_COMMIT_SEAL, sender, signature), None
            ) is not None:
                self._count -= 1
                if not bucket:
                    del self._buckets[(owner, height, round_)]

    def _evict_locked(self) -> None:
        while self._count > self._cap and self._buckets:
            live = set(self._live.items())
            dead = [
                k
                for k in self._buckets
                if (k[0], (k[1], k[2])) not in live
            ]
            pool = dead if dead else list(self._buckets)
            oldest = min(pool, key=lambda k: (k[1], k[2], k[0]))
            bucket = self._buckets[oldest]
            if dead:
                self._count -= len(bucket)
                del self._buckets[oldest]
            else:
                bucket.pop(next(iter(bucket)))
                self._count -= 1
                if not bucket:
                    del self._buckets[oldest]


class _SealJob:
    __slots__ = ("owner", "height", "round", "proposal_hash", "lanes")

    def __init__(self, owner, height, round_, proposal_hash, lanes):
        self.owner = owner
        self.height = height
        self.round = round_
        self.proposal_hash = proposal_hash
        self.lanes = lanes  # [(sender, CommittedSeal), ...]


class SpeculativeVerifier:
    """Background seal verification feeding a :class:`SpeculationCache`.

    ``verifier`` is any object with the seal half of the BatchVerifier
    protocol (``verify_committed_seals(proposal_hash, seals, height)``);
    the engine passes its own batch verifier so speculative verdicts are
    produced by the SAME route (and the same degradation ladder) the
    drain would use.  One daemon worker; the queue is bounded in lanes
    and drops on overflow (best-effort — a dropped lane verifies at
    drain time exactly as without speculation).

    Thread-notes: the worker calls the verifier from its own thread
    concurrently with the event loop's drains.  The host verifier is
    stateless; the device verifier's caches are lock-guarded
    (:class:`~go_ibft_tpu.verify.pipeline.PackCache`) and JAX dispatch
    is thread-safe; a :class:`TenantVerifierHandle` is thread-safe by
    design.  The engine only ever consumes verdicts through the cache,
    so no partially-verified state is observable.
    """

    def __init__(
        self,
        verifier,
        *,
        cache: Optional[SpeculationCache] = None,
        max_queue_lanes: int = 4096,
        owner: str = "",
        batch_lanes: int = 256,
    ):
        self.verifier = verifier
        self.cache = cache if cache is not None else SpeculationCache()
        self.owner = owner
        self.max_queue_lanes = max_queue_lanes
        self.batch_lanes = batch_lanes
        self._queue: "queue.Queue[Optional[_SealJob]]" = queue.Queue()
        self._queued_lanes = 0
        self._lock = threading.Lock()
        self._started = False
        self._stopped = False
        self._idle = threading.Event()
        self._idle.set()
        self._thread: Optional[threading.Thread] = None
        # Evidence counters (bench config #11 reads these).
        self.speculated_lanes = 0
        self.dropped_lanes = 0
        self.batches = 0
        self.faults = 0

    # -- lifecycle -------------------------------------------------------

    def _ensure_worker(self) -> None:
        with self._lock:
            if self._started or self._stopped:
                return
            self._started = True
            self._thread = threading.Thread(
                target=self._worker, name="spec-verify", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            started = self._started
        if started:
            self._queue.put(None)
            if self._thread is not None:
                self._thread.join(timeout=5.0)
                self._thread = None

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Block until the queue is empty and the worker is idle (tests
        and the bench's warm gate).  Returns False on timeout."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout_s:
            with self._lock:
                empty = self._queued_lanes == 0
            if empty and self._idle.wait(timeout=0.05):
                with self._lock:
                    if self._queued_lanes == 0:
                        return True
            time.sleep(0.002)
        return False

    # -- engine lifecycle hooks -----------------------------------------

    def note_view(self, height: int, round_: int) -> None:
        self.cache.note_view(height, round_, owner=self.owner)

    def reset(self) -> None:
        self.cache.clear(owner=self.owner)

    def quarantine_seals(
        self, height: int, round_: int, proposal_hash: bytes, lanes
    ) -> None:
        for sender, seal in lanes:
            self.cache.evict_seal(
                height,
                round_,
                proposal_hash,
                sender,
                seal.signature,
                owner=self.owner,
            )

    # -- submission ------------------------------------------------------

    def submit_commit_messages(self, msgs: Sequence[IbftMessage]) -> int:
        """Queue the COMMIT seals of ``msgs`` for speculative
        verification; non-COMMITs and malformed lanes are skipped.
        Returns the number of lanes queued."""
        jobs: Dict[Tuple[int, int, bytes], List[tuple]] = {}
        for m in msgs:
            if m.view is None or m.type != MessageType.COMMIT:
                continue
            phash = helpers.extract_commit_hash(m)
            seal = helpers.extract_committed_seal(m)
            if phash is None or seal is None or len(phash) != 32:
                continue
            if self.cache.contains(
                m.view.height,
                m.view.round,
                phash,
                PHASE_COMMIT_SEAL,
                m.sender,
                seal.signature,
                owner=self.owner,
            ):
                continue
            jobs.setdefault(
                (m.view.height, m.view.round, phash), []
            ).append((m.sender, seal))
        queued = 0
        for (height, round_, phash), lanes in jobs.items():
            queued += self.submit_seal_lanes(height, round_, phash, lanes)
        return queued

    def submit_seal_lanes(
        self, height: int, round_: int, proposal_hash: bytes, lanes
    ) -> int:
        """Queue raw ``(sender, seal)`` lanes sharing one carried hash —
        the lazy-remainder entry the early-exit drains use."""
        if not lanes:
            return 0
        lanes = list(lanes)
        with self._lock:
            if self._stopped:
                return 0
            room = self.max_queue_lanes - self._queued_lanes
            if room < len(lanes):
                overflow = len(lanes) - max(room, 0)
                self.dropped_lanes += overflow
                metrics.inc_counter(SPEC_DROPPED_KEY, overflow)
                if room <= 0:
                    return 0
                lanes = lanes[:room]
            self._queued_lanes += len(lanes)
        self._queue.put(
            _SealJob(self.owner, height, round_, proposal_hash, lanes)
        )
        self._ensure_worker()
        return len(lanes)

    # -- consumption -----------------------------------------------------

    def lookup_seal(
        self,
        height: int,
        round_: int,
        proposal_hash: bytes,
        sender: bytes,
        signature: bytes,
    ) -> Optional[bool]:
        return self.cache.lookup(
            height,
            round_,
            proposal_hash,
            PHASE_COMMIT_SEAL,
            sender,
            signature,
            owner=self.owner,
        )

    def stats(self) -> dict:
        return {
            "speculated_lanes": self.speculated_lanes,
            "dropped_lanes": self.dropped_lanes,
            "batches": self.batches,
            "faults": self.faults,
            "cache_hits": self.cache.hits,
            "cache_misses": self.cache.misses,
            "cache_entries": len(self.cache),
        }

    # -- the worker ------------------------------------------------------

    def _take_batch(self, first: _SealJob) -> List[_SealJob]:
        batch = [first]
        lanes = len(first.lanes)
        while lanes < self.batch_lanes:
            try:
                job = self._queue.get_nowait()
            except queue.Empty:
                break
            if job is None:
                self._queue.put(None)  # keep the stop sentinel
                break
            batch.append(job)
            lanes += len(job.lanes)
        return batch

    def _worker(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                return
            self._idle.clear()
            try:
                for j in self._take_batch(job):
                    self._run_job(j)
            finally:
                self._idle.set()

    def _run_job(self, job: _SealJob) -> None:
        n = len(job.lanes)
        try:
            with trace.span(
                "verify.speculate",
                lanes=n,
                height=job.height,
                round=job.round,
            ):
                mask = self.verifier.verify_committed_seals(
                    job.proposal_hash,
                    [seal for _sender, seal in job.lanes],
                    job.height,
                )
            for (sender, seal), ok in zip(job.lanes, mask):
                self.cache.store(
                    job.height,
                    job.round,
                    job.proposal_hash,
                    PHASE_COMMIT_SEAL,
                    sender,
                    seal.signature,
                    bool(ok),
                    owner=job.owner,
                )
            self.speculated_lanes += n
            self.batches += 1
            metrics.inc_counter(SPEC_LANES_KEY, n)
        except Exception:  # noqa: BLE001 - best-effort: drop, drain pays
            self.faults += 1
        finally:
            with self._lock:
                self._queued_lanes -= n
