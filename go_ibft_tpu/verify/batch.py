"""BatchVerifier implementations: host oracle + device batch kernels.

The reference verifies one message at a time through embedder predicates
under the store lock (go-ibft messages/messages.go:183-198 calling
core/backend.go:37-56).  Here the same observable semantics — a validity
mask over a message set — are produced by one fixed-shape device batch:

    payload bytes --pack--> keccak blocks --digest--> ecrecover ladder
                 --> pubkey --keccak--> address --compare--> mask

Shapes are static per (batch-bucket, block-bucket, validator-bucket)
triple; each distinct triple compiles once and is cached.  Lanes added by
padding are masked out, so callers see exact-length numpy boolean masks.

Signature format (shared with :mod:`go_ibft_tpu.crypto.backend`):
65 bytes ``r(32, big-endian) || s(32, big-endian) || v(1)``, signing
``keccak256(payload_no_sig)`` for envelopes and the proposal hash directly
for committed seals.
"""

from __future__ import annotations

import time
from contextlib import ExitStack
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.validator_manager import calculate_quorum
from ..crypto import ecdsa as host_ecdsa
from ..obs import ledger as cost_ledger
from ..obs import trace
from ..crypto.keccak import keccak256, keccak256_many
from ..messages.helpers import CommittedSeal
from ..messages.wire import IbftMessage
from ..ops import fields
from ..ops import keccak as dk
from ..ops import quorum
from ..ops import secp256k1 as sec
from ..ops.fields import LIMB_BITS, LIMB_MASK
from ..utils import metrics
from .pipeline import CircuitBreaker, PackCache, SenderPack, VerifyPipeline

SIG_BYTES = 65  # r(32) || s(32) || v(1)

ADDRESS_BYTES = 20


class MalformedLaneError(ValueError):
    """A packer input lane has an invalid length, named by index.

    The vectorized packers build whole-batch ``frombuffer`` views, so a
    single wrong-length signature or address used to surface as an opaque
    numpy reshape error (or worse, silently misaligned lanes).  Length
    validation now runs up front and raises this instead — a ``ValueError``
    subclass, so callers that caught the loop packers' errors still do —
    carrying the offending lane so degraded-mode drains can quarantine
    exactly that lane and verify the rest
    (:class:`ResilientBatchVerifier`).
    """

    def __init__(self, lane: int, field: str, expected: int, got: int):
        self.lane = lane
        self.field = field
        self.expected = expected
        self.got = got
        super().__init__(
            f"lane {lane}: {field} must be {expected} bytes, got {got}"
        )

# Pad-to buckets: batch lanes, keccak blocks per message, validator-set size.
# Every (lane, block, table) triple is a separate XLA program, and the lane x
# table grid drives the expensive EC-ladder compiles, so the block and table
# sets are PRUNED to what the workloads actually hit: envelopes are 1-2
# keccak blocks (mid sizes ride the next bucket — keccak pad lanes are noise
# against the ladder), and table rows only feed the cheap membership
# compare.  Lane buckets stay fine-grained: lane count scales the ladder
# itself, where padding waste is real work.
_BATCH_BUCKETS = (8, 32, 128, 512, 1024, 2048)
_BLOCK_BUCKETS = (2, 8, 32)
_TABLE_BUCKETS = (8, 128, 512, 2048)


def _bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"size {n} exceeds largest bucket {buckets[-1]}")


def _lane_count(n: int, pad_lanes: int = 0) -> int:
    """Static lane dimension for an ``n``-lane pack.

    The packers used to assume the caller's batch fits a bucket —
    ``max(_bucket(n), pad_lanes)`` — which made any explicitly-padded
    shape above the largest bucket (a mesh drain padding to a multiple of
    the device count, e.g. 4096 global lanes on dp=2) an error instead of
    a shape.  An explicit ``pad_lanes >= n`` now PINS the lane dimension
    exactly (the caller owns the padding policy; pad lanes are dead —
    ``live`` False — so no dummy verdict can leak into a quorum count);
    otherwise the next bucket serves as before."""
    if pad_lanes >= max(n, 1):
        return pad_lanes
    return max(_bucket(n, _BATCH_BUCKETS), pad_lanes)


def host_quorum_reached(
    validators_for_height: "ValidatorSource",
    valid_addrs: Iterable[bytes],
    height: int,
    threshold: Optional[int],
) -> bool:
    """Exact host-int voting-power quorum over a drain's valid addresses.

    The ONE host-side quorum reduction shared by
    :class:`AdaptiveBatchVerifier`'s fallback routes and the mesh
    verifier's sharded certify paths (``ops/quorum.py`` ``power_reduce``
    semantics: distinct validators counted once, exact Python ints for any
    power range)."""
    powers = validators_for_height(height)
    thr = (
        calculate_quorum(sum(powers.values()))
        if threshold is None
        else threshold
    )
    if thr <= 0:
        return True
    return sum(powers.get(a, 0) for a in set(valid_addrs)) >= thr


EARLY_EXIT_SKIPPED_KEY = ("go-ibft", "early_exit", "lanes_skipped")
EARLY_EXIT_DRAINS_KEY = ("go-ibft", "early_exit", "drains")

# Fixed-bucket drain-latency family for the live /metrics endpoint: one
# series per route (the key's 4th part renders as the ``tag`` label).
# Recorded only while metrics.enable_fixed_histograms() is on.
VERIFY_DRAIN_MS_KEY = ("go-ibft", "latency", "verify_drain_ms")


@dataclass
class EarlyExitReport:
    """One early-exit seal drain's outcome.

    ``mask`` carries per-lane verdicts — ``False`` both for invalid lanes
    and for lanes the drain never reached; ``verified`` distinguishes
    them (True = the lane has a REAL verdict, bit-identical to the
    sequential oracle's).  ``reached`` is the exact voting-power quorum
    over the verified-valid distinct signers; ``skipped`` counts the
    lanes left unverified (the caller resolves them lazily off-path —
    typically via :class:`~go_ibft_tpu.verify.speculate.
    SpeculativeVerifier` — or synchronously if the early exit
    mispredicted).  Early-exit changes WHEN a lane verifies, never a
    verdict.
    """

    mask: np.ndarray
    verified: np.ndarray
    reached: bool
    skipped: int


class _PowerTally:
    """Exact incremental voting-power quorum (distinct signers counted
    once — the ``has_quorum`` / :func:`host_quorum_reached` semantics,
    fed one verdict at a time)."""

    def __init__(self, powers: Mapping[bytes, int], threshold: int):
        self.powers = powers
        self.threshold = threshold
        self.power = 0
        self._counted: set = set()

    @property
    def reached(self) -> bool:
        return self.power >= self.threshold

    def add(self, signer: bytes) -> bool:
        if signer not in self._counted:
            self._counted.add(signer)
            self.power += self.powers.get(signer, 0)
        return self.reached


def split_signature(sig: bytes) -> Tuple[int, int, int]:
    """65-byte ``r || s || v`` -> ints; raises on wrong length."""
    if len(sig) != SIG_BYTES:
        raise ValueError(f"signature must be {SIG_BYTES} bytes, got {len(sig)}")
    return (
        int.from_bytes(sig[:32], "big"),
        int.from_bytes(sig[32:64], "big"),
        sig[64],
    )


ValidatorSource = Callable[[int], Mapping[bytes, int]]


class HostBatchVerifier:
    """Sequential per-item verification over Python ints.

    Mirrors exactly what the reference does per message — the semantics
    oracle the device path must match, and the honest baseline denominator
    for BASELINE.md's >=30x target.
    """

    def __init__(
        self,
        validators_for_height: ValidatorSource,
        recover_fn: Optional[Callable] = None,
    ):
        self._validators = validators_for_height
        # ``recover_fn`` overrides the ecrecover primitive: the degraded-
        # mode ladder's bottom rung passes ``ecdsa.recover_pure`` so a
        # crashing native library can be routed around entirely.
        self._recover = recover_fn if recover_fn is not None else host_ecdsa.recover

    def _is_member(self, height: int, address: bytes) -> bool:
        return address in self._validators(height)

    def verify_senders(self, msgs: Sequence[IbftMessage]) -> np.ndarray:
        out = np.zeros(len(msgs), dtype=bool)
        t0 = time.perf_counter() if metrics.fixed_histograms_enabled() else None
        with trace.span(
            "verify.drain", kind="senders", route="host", lanes=len(msgs)
        ):
            # The flight-recorder phase structure mirrors the device path
            # (pack -> dispatch -> device-wait -> quorum) so every drain
            # renders the same way regardless of route; on the synchronous
            # host route "dispatch" is the recover loop and the wait is
            # empty by construction.
            with trace.span("verify.pack", lanes=len(msgs)):
                prepared = []
                for i, msg in enumerate(msgs):
                    if msg.view is None or len(msg.sender) != ADDRESS_BYTES:
                        continue
                    if len(msg.signature) != SIG_BYTES:
                        continue
                    r, s, v = split_signature(msg.signature)
                    digest = keccak256(msg.encode(include_signature=False))
                    prepared.append((i, msg, digest, r, s, v))
            with cost_ledger.dispatch_span(
                "ecdsa_recover",
                route="host",
                live=len(prepared),
                padded=len(prepared),
                site="verify/batch.py:HostBatchVerifier.verify_senders",
            ):
                with trace.span(
                    "verify.dispatch", route="host", lanes=len(prepared)
                ):
                    recovered = [
                        (i, msg, self._recover(digest, r, s, v))
                        for i, msg, digest, r, s, v in prepared
                    ]
            with trace.span("verify.device_wait", route="host"):
                pass  # nothing in flight on the synchronous route
            with trace.span("verify.quorum", lanes=len(recovered)):
                for i, msg, pub in recovered:
                    if pub is None:
                        continue
                    out[i] = (
                        host_ecdsa.pubkey_to_address(*pub) == msg.sender
                        and self._is_member(msg.view.height, msg.sender)
                    )
        if t0 is not None:
            metrics.observe_fixed(
                VERIFY_DRAIN_MS_KEY + ("host",),
                (time.perf_counter() - t0) * 1e3,
            )
        return out

    def verify_committed_seals(
        self, proposal_hash: bytes, seals: Sequence[CommittedSeal], height: int
    ) -> np.ndarray:
        out = np.zeros(len(seals), dtype=bool)
        # Same malformed-hash rejection as ECDSABackend.is_valid_committed_seal
        # and the device path (a seal signs a 32-byte keccak hash; the native
        # recover also reads exactly 32 digest bytes).
        if len(proposal_hash) != 32:
            return out
        t0 = time.perf_counter() if metrics.fixed_histograms_enabled() else None
        with trace.span(
            "verify.drain", kind="seals", route="host", lanes=len(seals)
        ):
            with trace.span("verify.pack", lanes=len(seals)):
                prepared = []
                for i, seal in enumerate(seals):
                    if (
                        len(seal.signer) != ADDRESS_BYTES
                        or len(seal.signature) != SIG_BYTES
                    ):
                        continue
                    prepared.append((i, seal, *split_signature(seal.signature)))
            with cost_ledger.dispatch_span(
                "ecdsa_recover",
                route="host",
                live=len(prepared),
                padded=len(prepared),
                site="verify/batch.py:HostBatchVerifier.verify_committed_seals",
            ):
                with trace.span(
                    "verify.dispatch", route="host", lanes=len(prepared)
                ):
                    recovered = [
                        (i, seal, self._recover(proposal_hash, r, s, v))
                        for i, seal, r, s, v in prepared
                    ]
            with trace.span("verify.device_wait", route="host"):
                pass  # nothing in flight on the synchronous route
            with trace.span("verify.quorum", lanes=len(recovered)):
                for i, seal, pub in recovered:
                    if pub is None:
                        continue
                    out[i] = (
                        host_ecdsa.pubkey_to_address(*pub) == seal.signer
                        and self._is_member(height, seal.signer)
                    )
        if t0 is not None:
            metrics.observe_fixed(
                VERIFY_DRAIN_MS_KEY + ("host",),
                (time.perf_counter() - t0) * 1e3,
            )
        return out

    def verify_seal_lanes(
        self, lanes: Sequence[Tuple[bytes, CommittedSeal]], height: int
    ) -> np.ndarray:
        """Per-lane-hash seal verification (the block-sync drain shape).

        Each lane is ``(proposal_hash, seal)`` — sequential per-lane
        recovers against that lane's own hash, membership against
        ``height``'s validator set.  This is the oracle the batched sync
        drain (DeviceBatchVerifier.verify_seal_lanes) is pinned to; the
        caller groups heights so that every lane's own validator set
        equals ``height``'s (chain/sync.py does this by snapshot).
        """
        out = np.zeros(len(lanes), dtype=bool)
        t0 = time.perf_counter() if metrics.fixed_histograms_enabled() else None
        with trace.span(
            "verify.drain", kind="seal_lanes", route="host", lanes=len(lanes)
        ):
            with trace.span("verify.pack", lanes=len(lanes)):
                prepared = []
                for i, (proposal_hash, seal) in enumerate(lanes):
                    if (
                        len(proposal_hash) != 32
                        or len(seal.signer) != ADDRESS_BYTES
                        or len(seal.signature) != SIG_BYTES
                    ):
                        continue
                    prepared.append(
                        (i, proposal_hash, seal, *split_signature(seal.signature))
                    )
            with cost_ledger.dispatch_span(
                "ecdsa_recover",
                route="host",
                live=len(prepared),
                padded=len(prepared),
                site="verify/batch.py:HostBatchVerifier.verify_seal_lanes",
            ):
                with trace.span(
                    "verify.dispatch", route="host", lanes=len(prepared)
                ):
                    recovered = [
                        (i, seal, self._recover(proposal_hash, r, s, v))
                        for i, proposal_hash, seal, r, s, v in prepared
                    ]
            with trace.span("verify.device_wait", route="host"):
                pass  # nothing in flight on the synchronous route
            with trace.span("verify.quorum", lanes=len(recovered)):
                for i, seal, pub in recovered:
                    if pub is None:
                        continue
                    out[i] = (
                        host_ecdsa.pubkey_to_address(*pub) == seal.signer
                        and self._is_member(height, seal.signer)
                    )
        if t0 is not None:
            metrics.observe_fixed(
                VERIFY_DRAIN_MS_KEY + ("host",),
                (time.perf_counter() - t0) * 1e3,
            )
        return out

    def verify_seals_early_exit(
        self,
        proposal_hash: bytes,
        seals: Sequence[CommittedSeal],
        height: int,
        threshold: Optional[int] = None,
    ) -> EarlyExitReport:
        """Arrival-order seal verification that RETURNS at quorum.

        Lanes verify sequentially in the order they arrived; the exact
        voting-power tally (distinct signers once) runs alongside, and
        the loop stops the moment accumulated verified power reaches
        ``threshold`` (the height's quorum when None).  Every verdict
        produced is bit-identical to :meth:`verify_committed_seals`'s for
        that lane; lanes past the cut are reported ``skipped`` for the
        caller to resolve lazily.  Malformed lanes cost no crypto and
        get their (False) verdict immediately, like the full drain.
        """
        n = len(seals)
        mask = np.zeros(n, dtype=bool)
        verified = np.zeros(n, dtype=bool)
        powers = self._validators(height)
        thr = (
            calculate_quorum(sum(powers.values()))
            if threshold is None
            else threshold
        )
        if len(proposal_hash) != 32:
            verified[:] = True  # batch-wide reject: every verdict is False
            return EarlyExitReport(mask, verified, thr <= 0, 0)
        tally = _PowerTally(powers, thr)
        done = 0
        t0 = time.perf_counter() if metrics.fixed_histograms_enabled() else None
        with trace.span(
            "verify.early_exit", route="host", kind="seals", lanes=n
        ):
            for i, seal in enumerate(seals):
                if tally.reached:
                    break
                verified[i] = True
                done = i + 1
                if (
                    len(seal.signer) != ADDRESS_BYTES
                    or len(seal.signature) != SIG_BYTES
                ):
                    continue
                r, s, v = split_signature(seal.signature)
                pub = self._recover(proposal_hash, r, s, v)
                if pub is None:
                    continue
                ok = (
                    host_ecdsa.pubkey_to_address(*pub) == seal.signer
                    and self._is_member(height, seal.signer)
                )
                mask[i] = ok
                if ok:
                    tally.add(seal.signer)
        skipped = n - done
        metrics.inc_counter(EARLY_EXIT_DRAINS_KEY)
        if skipped:
            metrics.inc_counter(EARLY_EXIT_SKIPPED_KEY, skipped)
        # Lane counts are only known at exit (the drain stops at quorum),
        # so the ledger record lands here rather than via a span.
        cost_ledger.record_dispatch(
            "ecdsa_recover", "host", live=done, padded=done
        )
        if t0 is not None:
            metrics.observe_fixed(
                VERIFY_DRAIN_MS_KEY + ("host",),
                (time.perf_counter() - t0) * 1e3,
            )
        return EarlyExitReport(mask, verified, tally.reached, skipped)


# ---------------------------------------------------------------------------
# Device kernels (shape-polymorphic via jit retrace per bucket triple)
# ---------------------------------------------------------------------------


# Two-dispatch pipeline: the digest program recompiles per payload-size
# bucket (cheap keccak scan); the recovery program — the expensive 256-step
# EC ladder — compiles once per lane bucket and serves BOTH envelope senders
# and committed seals.
#
# Buffer donation was evaluated for these kernels and REJECTED: XLA can
# only alias a donated input to an output of matching shape/dtype, and
# every verification program here maps big packed inputs ((B, 20) limb
# vectors, (B, nb, 17, 2) keccak blocks) to tiny boolean masks — nothing
# aliases, so donate_argnums performs no reuse and instead emits a
# "donated buffers were not usable" warning per compile.  The per-call
# inputs are freed by Python refcount right after dispatch regardless.
_digest_kernel = jax.jit(quorum.digest_words)


def _recover_fn(zw, r, s, v, claimed_w, table_w, live):
    ok = quorum.sig_checks_zw(zw, r, s, v, claimed_w, live)
    member = jnp.any(quorum.membership_eq(claimed_w, table_w), axis=-1)
    return ok & member


_recover_kernel = jax.jit(_recover_fn)


def _certify_fn(zw, r, s, v, claimed_w, table_w, live, plo, phi, thr_lo, thr_hi):
    """Fused mask + voting-power quorum in ONE program (the engine's hot
    path): recovery ladder, membership, and the power reduction of
    :func:`go_ibft_tpu.ops.quorum.power_reduce` never leave the device.
    Serves both envelope senders (``zw`` = payload digests) and committed
    seals (``zw`` = broadcast proposal hash), like :func:`_recover_kernel`.
    ``thr_lo``/``thr_hi`` are traced scalars, so per-call thresholds (e.g.
    the prepare-phase proposer credit) do not recompile."""
    ok = quorum.sig_checks_zw(zw, r, s, v, claimed_w, live)
    eq = quorum.membership_eq(claimed_w, table_w)
    ok = ok & jnp.any(eq, axis=-1)
    reached, lo, hi = quorum.power_reduce(ok, eq, plo, phi, thr_lo, thr_hi)
    return ok, reached, lo, hi


_certify_kernel = jax.jit(_certify_fn)


def _round_fn(
    zw, r, s, v, claimed_w, table_w, live, plo, phi, p_lo, p_hi, s_lo, s_hi
):
    """BOTH phases of a round in ONE dispatch (ops.quorum.round_certify
    shape): the first half of the lanes are PREPARE envelopes (payload
    digests), the second half COMMIT seals (broadcast proposal hash); one
    shared recovery ladder, two separate quorum reductions with their own
    thresholds (prepare carries the proposer credit)."""
    ok = quorum.sig_checks_zw(zw, r, s, v, claimed_w, live)
    eq = quorum.membership_eq(claimed_w, table_w)
    ok = ok & jnp.any(eq, axis=-1)
    b = zw.shape[0] // 2
    p_reached, _, _ = quorum.power_reduce(ok[:b], eq[:b], plo, phi, p_lo, p_hi)
    s_reached, _, _ = quorum.power_reduce(ok[b:], eq[b:], plo, phi, s_lo, s_hi)
    return ok, p_reached, s_reached


_round_kernel = jax.jit(_round_fn)


def _pack_scalars(values: List[int], pad_to: int) -> jnp.ndarray:
    """Python-int scalars -> padded limb array (reference packers only; the
    vectorized path limb-splits straight from signature bytes and never
    materializes Python ints)."""
    values = values + [0] * (pad_to - len(values))
    return jnp.asarray(fields.to_limbs(values, sec.FIELD.nlimbs))


def _split_signatures(
    sigs: Sequence[bytes],
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized :func:`split_signature`: N sigs -> value words + v.

    Returns ``(r_words, s_words, v)`` with the words as ``(N, 8)`` uint32
    little-endian value words (the 32 big-endian bytes reversed and viewed
    as uint32) and ``v`` as ``(N,)`` int32.  One C-level join + one
    ``frombuffer`` for the whole batch; raises :class:`MalformedLaneError`
    on any wrong-length signature, naming the lane.
    """
    for i, sig in enumerate(sigs):
        if len(sig) != SIG_BYTES:
            raise MalformedLaneError(i, "signature", SIG_BYTES, len(sig))
    n = len(sigs)
    if n == 0:
        z = np.zeros((0, 8), dtype=np.uint32)
        return z, z.copy(), np.zeros((0,), dtype=np.int32)
    flat = np.frombuffer(b"".join(sigs), dtype=np.uint8).reshape(n, SIG_BYTES)
    r_words = np.ascontiguousarray(flat[:, 31::-1]).view("<u4")
    s_words = np.ascontiguousarray(flat[:, 63:31:-1]).view("<u4")
    return r_words, s_words, flat[:, 64].astype(np.int32)


def _words_to_limbs(words: np.ndarray, nlimbs: int) -> np.ndarray:
    """``(N, nw)`` uint32 LE value words -> ``(N, nlimbs)`` int32 limbs.

    The numpy twin of :func:`go_ibft_tpu.ops.keccak.words_le_to_limbs`
    (same shift schedule), replacing the per-value Python-int loop of
    ``fields.to_limbs`` with ``nlimbs`` whole-batch shift/mask ops.
    """
    nw = words.shape[-1]
    out = np.zeros(words.shape[:-1] + (nlimbs,), dtype=np.int32)
    for k in range(nlimbs):
        lo_bit = LIMB_BITS * k
        j, sh = divmod(lo_bit, 32)
        if j >= nw:
            break
        acc = words[..., j] >> np.uint32(sh)
        if sh + LIMB_BITS > 32 and j + 1 < nw:
            acc = acc | (words[..., j + 1] << np.uint32(32 - sh))
        out[..., k] = (acc & np.uint32(LIMB_MASK)).astype(np.int32)
    return out


def pack_validator_table(addresses: Sequence[bytes], bucket: bool = True) -> np.ndarray:
    """Addresses -> ``(V, 5)`` uint32 words, padded by repeating row 0."""
    addresses = [a for a in addresses if len(a) == ADDRESS_BYTES]
    if not addresses:
        raise ValueError("empty validator set")
    v = _bucket(len(addresses), _TABLE_BUCKETS) if bucket else len(addresses)
    table = np.zeros((v, 5), dtype=np.uint32)
    table[: len(addresses)] = dk.addresses_to_words(addresses)
    table[len(addresses) :] = table[0]  # padding adds no new member
    return table


def pack_sender_batch(
    msgs: Sequence[IbftMessage],
    pad_lanes: int = 0,
    payloads: Optional[List[bytes]] = None,
    cache: Optional[PackCache] = None,
    cache_payloads: Optional[List[bytes]] = None,
    cache_hits: Optional[List[Optional[SenderPack]]] = None,
):
    """Messages -> device-ready arrays for the sender-validity kernel.

    Returns ``(blocks, counts, r, s, v, senders, live)`` as numpy/jax
    arrays padded to bucketed static shapes.  A lane with a wrong-length
    sender or signature raises :class:`MalformedLaneError` naming the lane
    (validated up front — never a numpy reshape crash).  ``payloads``
    overrides the per-message signed bytes (the oversize-payload path
    substitutes empty payloads for lanes whose digest is computed on host).

    Vectorized end to end: signatures split + limbed straight from bytes in
    one shot (:func:`_split_signatures` -> :func:`_words_to_limbs`), sender
    addresses bulk-converted, and the keccak block packing done once for
    the whole batch (``ops/keccak.py::pack_messages``).  Bit-identical to
    :func:`_pack_sender_batch_reference` (tests/test_pack_vectorized.py).

    ``cache`` (a :class:`~go_ibft_tpu.verify.pipeline.PackCache`) reuses a
    message's encoded payload + limb rows from an earlier pack and stores
    fresh ones; ``cache_payloads`` supplies the TRUE payloads for cache
    stores when ``payloads`` carries substituted (oversize-lane) bytes —
    without it, an explicit ``payloads`` disables stores so a substituted
    payload can never poison the cache.  ``cache_hits`` passes lookups a
    caller already performed (``_sender_inputs`` needs them for payload
    sizing) so the hot path pays one lock-guarded lookup per message, not
    two.

    Empty input returns a fully-dead padded batch (all ``live`` False,
    smallest block bucket) instead of raising — an empty drain is a no-op,
    not a crash.
    """
    n = len(msgs)
    # Length validation up front (the whole-batch frombuffer views below
    # would otherwise die in an opaque numpy reshape): the error names the
    # TRUE lane index so degraded-mode drains can quarantine exactly it.
    for i, m in enumerate(msgs):
        if len(m.signature) != SIG_BYTES:
            raise MalformedLaneError(i, "signature", SIG_BYTES, len(m.signature))
        if len(m.sender) != ADDRESS_BYTES:
            raise MalformedLaneError(i, "sender", ADDRESS_BYTES, len(m.sender))
    bb = _lane_count(n, pad_lanes)
    nl = sec.FIELD.nlimbs
    r_limbs = np.zeros((bb, nl), dtype=np.int32)
    s_limbs = np.zeros((bb, nl), dtype=np.int32)
    v = np.zeros((bb,), dtype=np.int32)
    senders = np.zeros((bb, 5), dtype=np.uint32)
    live = np.zeros((bb,), dtype=bool)
    if n == 0:
        blocks = np.zeros((bb, _BLOCK_BUCKETS[0], 17, 2), dtype=np.uint32)
        return blocks, np.ones((bb,), np.int32), r_limbs, s_limbs, v, senders, live

    if cache_hits is not None:
        hits: List[Optional[SenderPack]] = cache_hits
    elif cache is not None:
        hits = [cache.lookup(m) for m in msgs]
    else:
        hits = [None] * n
    own_payloads = payloads is None
    if own_payloads:
        payloads = [
            h.payload if h is not None else m.encode(include_signature=False)
            for h, m in zip(hits, msgs)
        ]
        cache_payloads = payloads

    max_len = max(len(p) for p in payloads)
    nb = _bucket((max_len + 1 + dk.RATE_BYTES - 1) // dk.RATE_BYTES, _BLOCK_BUCKETS)
    blocks = np.zeros((bb, nb, 17, 2), dtype=np.uint32)
    counts = np.ones((bb,), dtype=np.int32)
    pb, pc = dk.pack_messages(payloads, nb)
    blocks[:n] = pb
    counts[:n] = pc

    miss = [i for i, h in enumerate(hits) if h is None]
    if miss:
        rw, sw, vv = _split_signatures([msgs[i].signature for i in miss])
        rl = _words_to_limbs(rw, nl)
        sl = _words_to_limbs(sw, nl)
        aw = dk.addresses_to_words([msgs[i].sender for i in miss])
        idx = np.asarray(miss)
        r_limbs[idx] = rl
        s_limbs[idx] = sl
        v[idx] = vv
        senders[idx] = aw
        if cache is not None and cache_payloads is not None:
            for j, i in enumerate(miss):
                cache.store(
                    msgs[i],
                    SenderPack(
                        payload=cache_payloads[i],
                        r_limbs=rl[j].copy(),
                        s_limbs=sl[j].copy(),
                        v=int(vv[j]),
                        sender_words=aw[j].copy(),
                    ),
                )
    for i, h in enumerate(hits):
        if h is not None:
            r_limbs[i] = h.r_limbs
            s_limbs[i] = h.s_limbs
            v[i] = h.v
            senders[i] = h.sender_words
    live[:n] = True
    return blocks, counts, r_limbs, s_limbs, v, senders, live


def pack_seal_batch(proposal_hash: bytes, seals: Sequence[CommittedSeal], pad_lanes: int = 0):
    """Seals -> device-ready arrays for the seal-validity kernel.

    Returns ``(hash_words, r, s, v, signers, live)``; the proposal hash is
    broadcast to every lane as little-endian value words.  Vectorized like
    :func:`pack_sender_batch`; an empty seal sequence returns a fully-dead
    padded batch.  Lengths are validated up front
    (:class:`MalformedLaneError` names the bad lane; a non-32-byte proposal
    hash is a plain ``ValueError`` — it is batch-wide, not a lane).
    """
    if len(proposal_hash) != 32:
        raise ValueError(
            f"proposal hash must be 32 bytes, got {len(proposal_hash)}"
        )
    for i, s in enumerate(seals):
        if len(s.signature) != SIG_BYTES:
            raise MalformedLaneError(i, "signature", SIG_BYTES, len(s.signature))
        if len(s.signer) != ADDRESS_BYTES:
            raise MalformedLaneError(i, "signer", ADDRESS_BYTES, len(s.signer))
    n = len(seals)
    bb = _lane_count(n, pad_lanes)
    hw = np.frombuffer(proposal_hash, ">u4")[::-1].astype(np.uint32)  # LE words
    hash_zw = np.broadcast_to(hw, (bb, 8)).copy()
    nl = sec.FIELD.nlimbs
    r_limbs = np.zeros((bb, nl), dtype=np.int32)
    s_limbs = np.zeros((bb, nl), dtype=np.int32)
    v = np.zeros((bb,), dtype=np.int32)
    signers = np.zeros((bb, 5), dtype=np.uint32)
    live = np.zeros((bb,), dtype=bool)
    if n:
        rw, sw, vv = _split_signatures([s.signature for s in seals])
        r_limbs[:n] = _words_to_limbs(rw, nl)
        s_limbs[:n] = _words_to_limbs(sw, nl)
        v[:n] = vv
        signers[:n] = dk.addresses_to_words([s.signer for s in seals])
        live[:n] = True
    return hash_zw, r_limbs, s_limbs, v, signers, live


def validate_seal_lanes(lanes: Sequence[Tuple[bytes, CommittedSeal]]) -> None:
    """Shape-validate (proposal_hash, seal) lanes, naming the bad lane.

    The ONE definition of what a well-formed sync lane is — shared by the
    per-lane packer and the resilient fallback rung so their
    :class:`MalformedLaneError` quarantine semantics can never drift."""
    for i, (proposal_hash, seal) in enumerate(lanes):
        if len(proposal_hash) != 32:
            raise MalformedLaneError(i, "proposal_hash", 32, len(proposal_hash))
        if len(seal.signature) != SIG_BYTES:
            raise MalformedLaneError(i, "signature", SIG_BYTES, len(seal.signature))
        if len(seal.signer) != ADDRESS_BYTES:
            raise MalformedLaneError(i, "signer", ADDRESS_BYTES, len(seal.signer))


def pack_seal_lanes(
    lanes: Sequence[Tuple[bytes, CommittedSeal]], pad_lanes: int = 0
):
    """(proposal_hash, seal) lanes -> device arrays with PER-LANE hashes.

    The block-sync drain verifies committed seals across a whole height
    RANGE at once — every height signs its own proposal hash, so unlike
    :func:`pack_seal_batch` (one hash broadcast to all lanes) each lane
    here carries its own 32-byte hash.  The device kernel already takes
    per-lane hash words (``hash_zw`` rows); only the packers assumed one
    hash per drain.  Returns the same ``(hash_words, r, s, v, signers,
    live)`` tuple; lengths are validated up front with
    :class:`MalformedLaneError` naming the lane (a bad per-lane hash IS a
    lane fault here, not a batch-wide error).
    """
    validate_seal_lanes(lanes)
    n = len(lanes)
    bb = _lane_count(n, pad_lanes)
    nl = sec.FIELD.nlimbs
    hash_zw = np.zeros((bb, 8), dtype=np.uint32)
    r_limbs = np.zeros((bb, nl), dtype=np.int32)
    s_limbs = np.zeros((bb, nl), dtype=np.int32)
    v = np.zeros((bb,), dtype=np.int32)
    signers = np.zeros((bb, 5), dtype=np.uint32)
    live = np.zeros((bb,), dtype=bool)
    if n:
        # Same word layout as pack_seal_batch: 8 big-endian u32 words per
        # hash, reversed to little-endian value order — vectorized over
        # all lanes in one frombuffer.
        hw = np.frombuffer(
            b"".join(h for h, _ in lanes), ">u4"
        ).reshape(n, 8)[:, ::-1]
        hash_zw[:n] = hw.astype(np.uint32)
        rw, sw, vv = _split_signatures([s.signature for _, s in lanes])
        r_limbs[:n] = _words_to_limbs(rw, nl)
        s_limbs[:n] = _words_to_limbs(sw, nl)
        v[:n] = vv
        signers[:n] = dk.addresses_to_words([s.signer for _, s in lanes])
        live[:n] = True
    return hash_zw, r_limbs, s_limbs, v, signers, live


# -- reference loop packers (parity oracles) ---------------------------------
# The original per-message implementations, kept verbatim so the vectorized
# packers above have bit-identity references to diff against
# (tests/test_pack_vectorized.py); not hot paths.


def _pack_sender_batch_reference(
    msgs: Sequence[IbftMessage],
    pad_lanes: int = 0,
    payloads: Optional[List[bytes]] = None,
):
    """Per-message loop twin of :func:`pack_sender_batch`."""
    n = len(msgs)
    bb = _lane_count(n, pad_lanes)
    if payloads is None:
        payloads = [m.encode(include_signature=False) for m in msgs]
    max_len = max(len(p) for p in payloads)
    nb = _bucket((max_len + 1 + dk.RATE_BYTES - 1) // dk.RATE_BYTES, _BLOCK_BUCKETS)
    blocks = np.zeros((bb, nb, 17, 2), dtype=np.uint32)
    counts = np.ones((bb,), dtype=np.int32)
    pb, pc = dk._pack_messages_reference(payloads, nb)
    blocks[:n] = pb
    counts[:n] = pc
    rs, ss, vs = [], [], []
    senders = np.zeros((bb, 5), dtype=np.uint32)
    for i, m in enumerate(msgs):
        r, s, v = split_signature(m.signature)
        rs.append(r)
        ss.append(s)
        vs.append(v)
        senders[i] = dk.address_to_words(m.sender)
    live = np.zeros((bb,), dtype=bool)
    live[:n] = True
    return (
        blocks,
        counts,
        np.asarray(_pack_scalars(rs, bb)),
        np.asarray(_pack_scalars(ss, bb)),
        np.pad(np.asarray(vs, np.int32), (0, bb - n)),
        senders,
        live,
    )


def _pack_seal_batch_reference(
    proposal_hash: bytes, seals: Sequence[CommittedSeal], pad_lanes: int = 0
):
    """Per-message loop twin of :func:`pack_seal_batch`."""
    n = len(seals)
    bb = _lane_count(n, pad_lanes)
    hw = np.frombuffer(proposal_hash, ">u4")[::-1].astype(np.uint32)  # LE words
    hash_zw = np.broadcast_to(hw, (bb, 8)).copy()
    rs, ss, vs = [], [], []
    signers = np.zeros((bb, 5), dtype=np.uint32)
    for i, seal in enumerate(seals):
        r, s, v = split_signature(seal.signature)
        rs.append(r)
        ss.append(s)
        vs.append(v)
        signers[i] = dk.address_to_words(seal.signer)
    live = np.zeros((bb,), dtype=bool)
    live[:n] = True
    return (
        hash_zw,
        np.asarray(_pack_scalars(rs, bb)),
        np.asarray(_pack_scalars(ss, bb)),
        np.pad(np.asarray(vs, np.int32), (0, bb - n)),
        signers,
        live,
    )


# Largest payload the device digest path can absorb; one byte is reserved
# for keccak padding in the last block.
MAX_DEVICE_PAYLOAD = _BLOCK_BUCKETS[-1] * dk.RATE_BYTES - 1


def pack_sender_digest_rows(
    msgs: Sequence[IbftMessage],
    *,
    cache=None,
    hits: Optional[list] = None,
    pad_lanes: int = 0,
):
    """The device sender-route pack sequence: cache-hit reuse, oversize
    payloads digested on host, everything else on the device digest
    kernel.

    A payload above the largest keccak block bucket (a PREPREPARE
    carrying a round-change certificate easily is) must NOT crash the
    packer — r05 observed exactly that taking a cluster down when a
    round change produced a 57-block proposal.  Such lanes get their
    digest from the (native) host keccak, injected into the ``zw`` rows;
    the expensive part — the recovery ladder — still runs on device for
    every lane.

    ONE implementation serves both the single-tenant plane
    (:meth:`DeviceBatchVerifier._sender_inputs_impl`) and the
    multi-tenant coalesced dispatcher (``sched/dispatch.py``), so a fix
    to the oversize/cache path can never apply to one and silently miss
    the other.  ``cache`` is the store target for fresh packs (a
    :class:`PackCache`, or the scheduler's per-tenant routing shim);
    ``hits`` supplies pre-routed lookups (computed from ``cache`` when
    omitted).  Returns ``(zw, r, s, v, senders, live)``.
    """
    if hits is None:
        hits = (
            [cache.lookup(m) for m in msgs]
            if cache is not None
            else [None] * len(msgs)
        )
    payloads = [
        h.payload if h is not None else m.encode(include_signature=False)
        for h, m in zip(hits, msgs)
    ]
    big = [i for i, p in enumerate(payloads) if len(p) > MAX_DEVICE_PAYLOAD]
    if big:
        device_payloads = list(payloads)
        for i in big:
            device_payloads[i] = b""
    else:
        device_payloads = payloads
    blocks, counts, r, s, v, senders, live = pack_sender_batch(
        msgs,
        pad_lanes=pad_lanes,
        payloads=device_payloads,
        cache=cache,
        cache_payloads=payloads,
        cache_hits=hits,
    )
    with cost_ledger.dispatch_span(
        "digest_words",
        route="device",
        live_mask=live,
        kernels=(("digest_words", _digest_kernel),),
        block=False,
        site="verify/batch.py:pack_sender_digest_rows",
    ):
        zw = _digest_kernel(jnp.asarray(blocks), jnp.asarray(counts))
    if big:
        zw = np.array(zw)  # writable host copy (np.asarray can be RO)
        digests = keccak256_many([payloads[i] for i in big])
        for i, digest in zip(big, digests):
            zw[i] = np.frombuffer(digest, ">u4")[::-1].astype(np.uint32)
    return zw, r, s, v, senders, live


class DeviceBatchVerifier:
    """One ``jit`` batch per phase on the active JAX backend.

    ``validators_for_height`` supplies the voting-power map (the engine's
    ``ValidatorBackend.get_voting_powers`` works directly); validator
    address tables are packed to device arrays once per height and cached.
    """

    def __init__(self, validators_for_height: ValidatorSource, cache_heights: int = 4):
        from ..utils.jaxcache import enable_persistent_cache

        enable_persistent_cache()
        self._validators = validators_for_height
        # One full dispatch's lane capacity: floods above it chunk into
        # multiple dispatches riding the double-buffered pipeline.  The
        # mesh subclass raises it to ``largest bucket x dp`` so a multi-
        # height drain coalesces into ONE sharded dispatch instead of dp
        # sequential single-device ones.
        self._dispatch_cap = _BATCH_BUCKETS[-1]
        # Obs route label: the mesh subclass overrides to "mesh" so every
        # span a drain emits names the route that actually served it.
        self._route = "device"
        self._tables: Dict[int, Tuple[np.ndarray, List[bytes]]] = {}
        # Device-resident twins of the packed tables/power vectors: uploaded
        # once per height and reused by every dispatch of that height
        # (re-uploading per call was a host->device copy of data that never
        # changes within a height).
        self._tables_dev: Dict[int, jnp.ndarray] = {}
        self._quorum_packs: Dict[
            int, Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, int]]
        ] = {}
        self._quorum_dev: Dict[int, Tuple[jnp.ndarray, jnp.ndarray]] = {}
        self._cache_heights = cache_heights
        # Per-message pack cache (round-scoped, like the engine's
        # seal-verdict cache): engine wakeups that re-verify the same
        # messages (certificate validation re-runs per wakeup) skip the
        # re-encode + re-limb entirely.
        self._pack_cache = PackCache()

    def note_round(self, round_: int) -> None:
        """Engine hook: tag pack-cache entries with the live round (round
        advances drive the cache's oldest-round-first eviction)."""
        self._pack_cache.note_round(round_)

    def reset_pack_cache(self) -> None:
        """Engine hook: new sequence -> drop all cached packs."""
        self._pack_cache.clear()

    def _pack_caches(self) -> List["PackCache"]:
        """The lifecycle-scoped caches this verifier owns (EngineScope)."""
        return [self._pack_cache]

    def scoped(self, owner: str) -> "EngineScope":
        """A per-engine lifecycle facade for SHARING this verifier across
        engines: see :class:`EngineScope`."""
        return EngineScope(self, owner)

    def quarantine(self, msgs: Sequence[IbftMessage]) -> None:
        """Degraded-mode hook: lanes condemned by a quarantining drain.

        Evicts the lanes' cached packs so a corrected re-send (or a retry
        after a transient device fault) re-packs from the live bytes
        instead of being served the lane that was just condemned."""
        for m in msgs:
            self._pack_cache.evict(m)

    def warmup(
        self,
        lanes: Sequence[int] = (8,),
        blocks: Sequence[int] = (2, 8),
        table_rows: int = 8,
    ) -> None:
        """Pre-compile the kernels for the given shape buckets.

        A consensus engine must never stall mid-round on an XLA compile
        (the round timer would expire and tear the round down); call this
        once at node startup.  With the persistent cache, repeat processes
        pay only a cache load.
        """
        for bb in lanes:
            # route="warmup": startup compiles must not pollute the
            # production routes' occupancy, but their compile events ARE
            # the cost the ledger exists to measure (the AOT-manifest
            # baseline of ROADMAP item 5).
            with cost_ledger.dispatch_span(
                "ecdsa_recover",
                route="warmup",
                padded=bb,
                kernels=(("ecdsa_recover", _recover_kernel),),
                site="verify/batch.py:warmup",
            ):
                _recover_kernel(
                    jnp.zeros((bb, 8), jnp.uint32),
                    jnp.zeros((bb, 20), jnp.int32),
                    jnp.zeros((bb, 20), jnp.int32),
                    jnp.zeros((bb,), jnp.int32),
                    jnp.zeros((bb, 5), jnp.uint32),
                    jnp.zeros((table_rows, 5), jnp.uint32),
                    jnp.zeros((bb,), bool),
                ).block_until_ready()
            with cost_ledger.dispatch_span(
                "quorum_certify",
                route="warmup",
                padded=bb,
                kernels=(("quorum_certify", _certify_kernel),),
                site="verify/batch.py:warmup",
            ):
                jax.block_until_ready(
                    _certify_kernel(
                        jnp.zeros((bb, 8), jnp.uint32),
                        jnp.zeros((bb, 20), jnp.int32),
                        jnp.zeros((bb, 20), jnp.int32),
                        jnp.zeros((bb,), jnp.int32),
                        jnp.zeros((bb, 5), jnp.uint32),
                        jnp.zeros((table_rows, 5), jnp.uint32),
                        jnp.zeros((bb,), bool),
                        jnp.zeros((table_rows,), jnp.int32),
                        jnp.zeros((table_rows,), jnp.int32),
                        jnp.int32(1),
                        jnp.int32(0),
                    )
                )
            for nb in blocks:
                with cost_ledger.dispatch_span(
                    "digest_words",
                    route="warmup",
                    padded=bb,
                    kernels=(("digest_words", _digest_kernel),),
                    site="verify/batch.py:warmup",
                ):
                    _digest_kernel(
                        jnp.zeros((bb, nb, 17, 2), jnp.uint32),
                        jnp.ones((bb,), jnp.int32),
                    ).block_until_ready()

    # -- validator table management ------------------------------------

    def _table_and_addrs(self, height: int) -> Tuple[np.ndarray, List[bytes]]:
        """Packed address table + the filtered address list its rows follow
        (one build + one cache for both the mask and fused-quorum paths)."""
        hit = self._tables.get(height)
        if hit is not None:
            return hit
        addrs = [
            a for a in self._validators(height) if len(a) == ADDRESS_BYTES
        ]
        table = pack_validator_table(addrs)
        self._tables[height] = (table, addrs)
        if len(self._tables) > self._cache_heights:
            evicted = min(self._tables)
            self._tables.pop(evicted)
            self._tables_dev.pop(evicted, None)
        return table, addrs

    def _table(self, height: int) -> np.ndarray:
        return self._table_and_addrs(height)[0]

    def _table_dev(self, height: int) -> jnp.ndarray:
        """Device-resident packed table (uploaded once per height)."""
        hit = self._tables_dev.get(height)
        if hit is None:
            hit = jnp.asarray(self._table(height))
            self._tables_dev[height] = hit
        return hit

    def _quorum_powers_dev(
        self, height: int, plo: np.ndarray, phi: np.ndarray
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Device-resident power vectors for the fused-quorum kernels."""
        hit = self._quorum_dev.get(height)
        if hit is None:
            hit = (jnp.asarray(plo), jnp.asarray(phi))
            self._quorum_dev[height] = hit
            if len(self._quorum_dev) > self._cache_heights:
                self._quorum_dev.pop(min(self._quorum_dev))
        return hit

    def _quorum_pack(
        self, height: int
    ) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, int]]:
        """Per-height fused-quorum arrays: (table, powers_lo, powers_hi,
        quorum), or None when the device quorum path cannot represent the
        set exactly (power >= 2**31, total >= 2**31, or set larger than the
        biggest table bucket) — callers then fall back to host big-int
        quorum (the exactness contract of ops/quorum.py)."""
        if height in self._quorum_packs:
            return self._quorum_packs[height]
        powers_map = self._validators(height)
        pack: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, int]] = None
        try:
            table, addrs = self._table_and_addrs(height)
        except ValueError:  # empty validator set
            addrs = []
        # Quorum must match the host ValidatorManager exactly: the total is
        # over the FULL voting-power map (a malformed address can never
        # match a sender, but its power still raises the threshold).
        total = sum(powers_map.values())
        if (
            addrs
            and 0 < total < (1 << 31)
            and all(0 <= p < (1 << 31) for p in powers_map.values())
        ):
            v = table.shape[0]
            plo = np.zeros(v, dtype=np.int32)
            phi = np.zeros(v, dtype=np.int32)
            for i, a in enumerate(addrs):
                plo[i], phi[i] = quorum.split_power(powers_map[a])
            pack = (table, plo, phi, calculate_quorum(total))
        self._quorum_packs[height] = pack
        if len(self._quorum_packs) > self._cache_heights:
            self._quorum_packs.pop(min(self._quorum_packs))
        return pack

    def supports_fused(self, height: int) -> bool:
        """True when the fused mask+quorum device path is exact for this
        height's validator set."""
        return self._quorum_pack(height) is not None

    # -- shared pack/dispatch scaffolding -------------------------------
    # One implementation of the idxs-filter -> pack -> kernel -> unpack ->
    # metrics pipeline serves all four public entry points, so the fused
    # and non-fused masks can never drift apart.

    @staticmethod
    def _well_formed_sender(m: IbftMessage, height: Optional[int]) -> bool:
        return (
            m.view is not None
            and (height is None or m.view.height == height)
            and len(m.sender) == ADDRESS_BYTES
            and len(m.signature) == SIG_BYTES
        )

    @staticmethod
    def _well_formed_seal(seal: CommittedSeal) -> bool:
        return (
            len(seal.signer) == ADDRESS_BYTES
            and len(seal.signature) == SIG_BYTES
        )

    def _pad_lanes(self, n: int) -> int:
        """Minimum packed lane count for an ``n``-lane dispatch.

        0 on a single device (the packers bucket freely); the mesh
        subclass returns the smallest bucket-aligned multiple of the
        device count so every shard gets an identical local shape."""
        return 0

    def _program_of(self, quorum_args) -> str:
        """Cost-ledger program identity for one dispatch (the
        compile-budget family names — the mesh subclass renames the
        mask-only program to its sharded twin)."""
        return "ecdsa_recover" if quorum_args is None else "quorum_certify"

    def _dispatch_async(self, inputs, table, quorum_args):
        """Queue the recover (mask-only) or certify (mask+quorum) kernel.

        ``inputs`` = (zw, r, s, v, claimed, live) numpy/jax arrays;
        ``quorum_args`` = None for the plain mask, or (plo, phi, thr).
        Returns ``(mask_dev, reached_dev_or_None)`` device futures WITHOUT
        blocking — JAX async dispatch lets the caller pack the next batch
        while this one executes (:mod:`go_ibft_tpu.verify.pipeline`).
        """
        kernel = _recover_kernel if quorum_args is None else _certify_kernel
        with cost_ledger.dispatch_span(
            self._program_of(quorum_args),
            route=self._route,
            live_mask=inputs[5],
            kernels=((self._program_of(quorum_args), kernel),),
            block=False,
            site="verify/batch.py:_dispatch_async",
        ):
            with trace.span("verify.dispatch", route="device"):
                zw, r, s, v, claimed, live = (jnp.asarray(a) for a in inputs)
                if quorum_args is None:
                    return (
                        _recover_kernel(
                            zw, r, s, v, claimed, jnp.asarray(table), live
                        ),
                        None,
                    )
                plo, phi, thr = quorum_args
                mask, reached_dev, _, _ = _certify_kernel(
                    zw,
                    r,
                    s,
                    v,
                    claimed,
                    jnp.asarray(table),
                    live,
                    jnp.asarray(plo),
                    jnp.asarray(phi),
                    jnp.int32(max(thr, 0) & 0xFFFF),
                    jnp.int32(max(thr, 0) >> 16),
                )
                return mask, reached_dev

    @staticmethod
    def _readback(handle) -> Tuple[np.ndarray, Optional[bool]]:
        """Block on one :meth:`_dispatch_async` handle -> host results."""
        mask_dev, reached_dev = handle
        with trace.span("verify.device_wait", route="device"):
            mask = np.asarray(mask_dev)
            reached = (
                None if reached_dev is None else bool(np.asarray(reached_dev))
            )
        return mask, reached

    def _dispatch(self, inputs, table, quorum_args, metric: str):
        """Synchronous pack->kernel->readback (single-batch callers)."""
        t0 = time.perf_counter()
        mask, reached = self._readback(
            self._dispatch_async(inputs, table, quorum_args)
        )
        dt_ms = (time.perf_counter() - t0) * 1e3
        metrics.observe(("go-ibft", "device", metric), dt_ms)
        metrics.observe_fixed(VERIFY_DRAIN_MS_KEY + ("device",), dt_ms)
        # The dispatch record itself landed in _dispatch_async (block=False
        # — queue time only); the synchronous path knows the full
        # block-until-ready wall, so attribute it here.
        cost_ledger.add_device_ms(
            self._program_of(quorum_args), self._route, dt_ms
        )
        return mask, reached

    # Largest payload the device digest path can absorb; one byte is
    # reserved for keccak padding in the last block.
    _MAX_DEVICE_PAYLOAD = MAX_DEVICE_PAYLOAD

    def _sender_inputs(self, msgs: List[IbftMessage], pad_lanes: int = 0):
        pad_lanes = max(pad_lanes, self._pad_lanes(len(msgs)))
        with trace.span("verify.pack", kind="senders", lanes=len(msgs)):
            return self._sender_inputs_impl(msgs, pad_lanes)

    def _sender_inputs_impl(self, msgs: List[IbftMessage], pad_lanes: int = 0):
        """Pack envelopes; digest on device, oversize payloads on host.

        Payload encodings and limb rows come from the pack cache when this
        engine already packed the message (certificate re-validation runs
        per round-change wakeup over the same envelopes); fresh lanes pack
        vectorized and store back.  Serves both the per-phase dispatches
        and (via ``pad_lanes``) the single-dispatch ``certify_round``
        packing; the sequence itself lives in
        :func:`pack_sender_digest_rows` (shared with the multi-tenant
        coalesced dispatcher).
        """
        cache = self._pack_cache
        return pack_sender_digest_rows(
            msgs,
            cache=cache,
            hits=[cache.lookup(m) for m in msgs],
            pad_lanes=pad_lanes,
        )

    def _seal_inputs(
        self, proposal_hash: bytes, seals: List[CommittedSeal], pad_lanes: int = 0
    ):
        pad_lanes = max(pad_lanes, self._pad_lanes(len(seals)))
        with trace.span("verify.pack", kind="seals", lanes=len(seals)):
            return pack_seal_batch(proposal_hash, seals, pad_lanes=pad_lanes)

    # -- fused mask + quorum (the engine's phase hot path) --------------

    def _fused_pack(self, height: int, threshold: Optional[int]):
        pack = self._quorum_pack(height)
        if pack is None:
            raise ValueError(f"fused quorum unsupported for height {height}")
        table, plo, phi, quorum_size = pack
        thr = quorum_size if threshold is None else threshold
        # Device-resident handles: the table and power vectors upload once
        # per height; jnp.asarray at the dispatch edge is then a no-op.
        plo_dev, phi_dev = self._quorum_powers_dev(height, plo, phi)
        return self._table_dev(height), (plo_dev, phi_dev, thr), thr

    def certify_senders(
        self, msgs: Sequence[IbftMessage], height: int, threshold: Optional[int] = None
    ) -> Tuple[np.ndarray, bool]:
        """One device program: envelope recovery + membership + voting-power
        quorum (ops/quorum.py ``quorum_certify`` semantics).  All messages
        must share ``height``.  ``threshold`` overrides the quorum size
        (the engine passes ``quorum - proposer_power`` for the prepare
        phase's proposer credit); ``None`` means the height's quorum.

        Returns ``(mask, reached)``; requires :meth:`supports_fused`.
        """
        table, qargs, thr = self._fused_pack(height, threshold)
        out = np.zeros(len(msgs), dtype=bool)
        idxs = [
            i
            for i, m in enumerate(msgs)
            if self._well_formed_sender(m, height)
        ]
        if not idxs:
            return out, thr <= 0
        with trace.span(
            "verify.drain", route="device", kind="certify_senders", lanes=len(idxs)
        ):
            mask, reached = self._dispatch(
                self._sender_inputs([msgs[i] for i in idxs]),
                table,
                qargs,
                "certify_senders_ms",
            )
            with trace.span("verify.quorum", route="device-fused"):
                out[np.asarray(idxs)] = mask[: len(idxs)]
        return out, reached

    def certify_seals(
        self,
        proposal_hash: bytes,
        seals: Sequence[CommittedSeal],
        height: int,
        threshold: Optional[int] = None,
    ) -> Tuple[np.ndarray, bool]:
        """Fused COMMIT-phase check: seal recovery + membership + quorum in
        one device program (ops/quorum.py ``seal_quorum_certify``
        semantics).  Returns ``(mask, reached)``."""
        table, qargs, thr = self._fused_pack(height, threshold)
        out = np.zeros(len(seals), dtype=bool)
        idxs = [i for i, s in enumerate(seals) if self._well_formed_seal(s)]
        if not idxs or len(proposal_hash) != 32:
            return out, thr <= 0
        with trace.span(
            "verify.drain", route="device", kind="certify_seals", lanes=len(idxs)
        ):
            mask, reached = self._dispatch(
                self._seal_inputs(proposal_hash, [seals[i] for i in idxs]),
                table,
                qargs,
                "certify_seals_ms",
            )
            with trace.span("verify.quorum", route="device-fused"):
                out[np.asarray(idxs)] = mask[: len(idxs)]
        return out, reached

    def certify_round(
        self,
        msgs: Sequence[IbftMessage],
        proposal_hash: bytes,
        seals: Sequence[CommittedSeal],
        height: int,
        prepare_threshold: Optional[int] = None,
    ) -> Tuple[np.ndarray, bool, np.ndarray, bool]:
        """Certify BOTH phases of a round in ONE device dispatch.

        PREPARE envelopes and COMMIT seals share the recovery ladder, so
        their lanes are concatenated (padded to one common bucket) and run
        as a single program with two quorum reductions — the whole-round
        certification shape (validating a prepared certificate plus its
        committed seals at once; reference core/ibft.go:1161-1231).

        Returns ``(sender_mask, prepare_reached, seal_mask, commit_reached)``.
        Requires :meth:`supports_fused`.
        """
        table, (plo, phi, seal_thr), _ = self._fused_pack(height, None)
        p_thr = seal_thr if prepare_threshold is None else prepare_threshold
        sender_mask = np.zeros(len(msgs), dtype=bool)
        seal_mask = np.zeros(len(seals), dtype=bool)
        midx = [
            i for i, m in enumerate(msgs) if self._well_formed_sender(m, height)
        ]
        sidx = [i for i, s in enumerate(seals) if self._well_formed_seal(s)]
        if not midx or not sidx or len(proposal_hash) != 32:
            # Degenerate rounds fall back to the per-phase paths (an empty
            # half would break the kernel's split-at-half contract).
            if midx:
                sm, pr = self.certify_senders(
                    msgs, height, threshold=prepare_threshold
                )
                sender_mask, p_ok = sm, pr
            else:
                p_ok = p_thr <= 0
            if sidx:
                cm, cr = self.certify_seals(proposal_hash, seals, height)
                seal_mask, s_ok = cm, cr
            else:
                s_ok = seal_thr <= 0
            return sender_mask, p_ok, seal_mask, s_ok

        # Pack both halves to ONE common lane bucket so the kernel can
        # split at half statically.
        lanes = max(
            _bucket(len(midx), _BATCH_BUCKETS), _bucket(len(sidx), _BATCH_BUCKETS)
        )
        t0 = time.perf_counter()
        with trace.span(
            "verify.drain", route="device", kind="certify_round", lanes=lanes
        ):
            zw1, r1, s1, v1, senders, live1 = self._sender_inputs(
                [msgs[i] for i in midx], pad_lanes=lanes
            )
            with trace.span("verify.pack", kind="seals", lanes=len(sidx)):
                hz, r2, s2, v2, signers, live2 = pack_seal_batch(
                    proposal_hash, [seals[i] for i in sidx], pad_lanes=lanes
                )
            with cost_ledger.dispatch_span(
                "round_certify",
                route=self._route,
                live=len(midx) + len(sidx),
                padded=2 * lanes,
                kernels=(("round_certify", _round_kernel),),
                site="verify/batch.py:certify_round",
            ):
                with trace.span("verify.dispatch", route="device"):
                    mask, p_reached, s_reached = _round_kernel(
                        jnp.concatenate([jnp.asarray(zw1), jnp.asarray(hz)], axis=0),
                        jnp.concatenate([jnp.asarray(r1), jnp.asarray(r2)], axis=0),
                        jnp.concatenate([jnp.asarray(s1), jnp.asarray(s2)], axis=0),
                        jnp.concatenate([jnp.asarray(v1), jnp.asarray(v2)], axis=0),
                        jnp.concatenate(
                            [jnp.asarray(senders), jnp.asarray(signers)], axis=0
                        ),
                        jnp.asarray(table),
                        jnp.concatenate(
                            [jnp.asarray(live1), jnp.asarray(live2)], axis=0
                        ),
                        jnp.asarray(plo),
                        jnp.asarray(phi),
                        jnp.int32(max(p_thr, 0) & 0xFFFF),
                        jnp.int32(max(p_thr, 0) >> 16),
                        jnp.int32(max(seal_thr, 0) & 0xFFFF),
                        jnp.int32(max(seal_thr, 0) >> 16),
                    )
                with trace.span("verify.device_wait", route="device"):
                    mask = np.asarray(mask)
            with trace.span("verify.quorum", route="device-fused"):
                sender_mask[np.asarray(midx)] = mask[: len(midx)]
                seal_mask[np.asarray(sidx)] = mask[lanes : lanes + len(sidx)]
                p_ok = bool(np.asarray(p_reached))
                s_ok = bool(np.asarray(s_reached))
        metrics.observe(
            ("go-ibft", "device", "certify_round_ms"),
            (time.perf_counter() - t0) * 1e3,
        )
        return sender_mask, p_ok, seal_mask, s_ok

    # -- BatchVerifier protocol ----------------------------------------

    def _run_chunk_pipeline(self, items, pack, metric: str):
        """Pipeline (pack -> async dispatch -> readback) over chunk items.

        ``items`` are opaque chunk descriptors; ``pack(item)`` returns
        ``(item, inputs, table_dev)``.  Host packing of chunk N+1 overlaps
        device execution of chunk N (double buffered) — a flood above the
        largest lane bucket no longer serializes pack/dispatch/readback
        per chunk.  Returns ``[(item, mask), ...]`` in item order.
        """
        t0 = time.perf_counter()
        # ledger_key: the pipeline attributes each chunk's readback wait
        # to the mask program (the dispatch records landed per chunk in
        # _dispatch_async; the wait is the only timing the async path
        # cannot observe itself).
        report = VerifyPipeline(
            depth=2, ledger_key=(self._program_of(None), self._route)
        ).run(
            items,
            pack,
            dispatch=lambda p: (p[0], self._dispatch_async(p[1], p[2], None)),
            readback=lambda h: (h[0], self._readback(h[1])[0]),
        )
        metrics.observe(
            ("go-ibft", "device", metric), (time.perf_counter() - t0) * 1e3
        )
        return report.results

    def verify_senders(self, msgs: Sequence[IbftMessage]) -> np.ndarray:
        if not msgs:
            return np.zeros(0, dtype=bool)
        out = np.zeros(len(msgs), dtype=bool)
        by_height: Dict[int, List[int]] = {}
        for i, m in enumerate(msgs):
            if self._well_formed_sender(m, None):
                by_height.setdefault(m.view.height, []).append(i)
        # Floods above the largest lane bucket run as multiple full
        # dispatches — a 2049-message burst costs two kernel launches, not
        # 2049 sequential host recovers (VERDICT r04 weak #6) — and the
        # chunks ride the double-buffered pipeline: chunk N+1 packs on host
        # while chunk N executes.
        items = [
            (height, idxs[start : start + self._dispatch_cap])
            for height, idxs in by_height.items()
            for start in range(0, len(idxs), self._dispatch_cap)
        ]
        if not items:
            return out

        def pack(item):
            height, chunk = item
            return (
                item,
                self._sender_inputs([msgs[i] for i in chunk]),
                self._table_dev(height),
            )

        with trace.span(
            "verify.drain", route=self._route, kind="senders", chunks=len(items)
        ):
            results = self._run_chunk_pipeline(items, pack, "verify_senders_ms")
            # Mask-only drain: the voting-power reduction proper runs in
            # the caller (engine exact ints); this phase is the per-lane
            # verdict assembly.
            with trace.span("verify.quorum", route="mask"):
                for (_, chunk), mask in results:
                    out[np.asarray(chunk)] = mask[: len(chunk)]
        return out

    def verify_sender_rows(
        self,
        height: int,
        zw: np.ndarray,
        r: np.ndarray,
        s: np.ndarray,
        v: np.ndarray,
        claimed: np.ndarray,
        live: np.ndarray,
    ) -> np.ndarray:
        """Pre-digested rows -> per-lane sender-validity mask.

        The ICI tick drain (:meth:`go_ibft_tpu.net.ici
        .IciLockstepTransport.step`): the tick program already computed
        the payload digests on-device and gathered the
        signature/claimed-address rows, so this is ONE recover dispatch
        per call — no decode→re-encode→re-pack round trip.  ``zw`` is
        ``(n, 8)`` little-endian digest words; the remaining arrays
        follow :func:`pack_sender_batch` row layout."""
        n = int(zw.shape[0])
        if n == 0:
            return np.zeros(0, dtype=bool)
        bb = _lane_count(n, self._pad_lanes(n))
        if bb > n:
            pad = bb - n
            zw = np.concatenate([zw, np.zeros((pad,) + zw.shape[1:], zw.dtype)])
            r = np.concatenate([r, np.zeros((pad,) + r.shape[1:], r.dtype)])
            s = np.concatenate([s, np.zeros((pad,) + s.shape[1:], s.dtype)])
            v = np.concatenate([v, np.zeros((pad,), v.dtype)])
            claimed = np.concatenate(
                [claimed, np.zeros((pad,) + claimed.shape[1:], claimed.dtype)]
            )
            live = np.concatenate([live, np.zeros((pad,), dtype=bool)])
        mask, _ = self._dispatch(
            (zw, r, s, v, claimed, live),
            self._table_dev(height),
            None,
            "verify_sender_rows_ms",
        )
        return np.asarray(mask[:n], dtype=bool)

    def verify_committed_seals(
        self, proposal_hash: bytes, seals: Sequence[CommittedSeal], height: int
    ) -> np.ndarray:
        out = np.zeros(len(seals), dtype=bool)
        idxs = [i for i, s in enumerate(seals) if self._well_formed_seal(s)]
        if not idxs or len(proposal_hash) != 32:
            return out
        items = [
            idxs[start : start + self._dispatch_cap]
            for start in range(0, len(idxs), self._dispatch_cap)
        ]

        def pack(chunk):
            return (
                chunk,
                self._seal_inputs(proposal_hash, [seals[i] for i in chunk]),
                self._table_dev(height),
            )

        with trace.span(
            "verify.drain", route=self._route, kind="seals", chunks=len(items)
        ):
            results = self._run_chunk_pipeline(items, pack, "verify_seals_ms")
            with trace.span("verify.quorum", route="mask"):
                for chunk, mask in results:
                    out[np.asarray(chunk)] = mask[: len(chunk)]
        return out

    def verify_seal_lanes(
        self, lanes: Sequence[Tuple[bytes, CommittedSeal]], height: int
    ) -> np.ndarray:
        """Cross-height batched seal drain: per-lane proposal hashes.

        The block-sync catch-up path verifies EVERY committed seal of a
        fetched height range in one drain — each height signs its own
        proposal hash, so lanes carry their own hash words
        (:func:`pack_seal_lanes`); the recovery ladder and membership
        check are the same program as the single-hash drain.  All lanes
        are checked against ``height``'s validator table (callers group
        ranges by validator-set snapshot).  Chunks above the largest lane
        bucket ride the double-buffered pipeline like every other flood.
        """
        out = np.zeros(len(lanes), dtype=bool)
        idxs = [
            i
            for i, (proposal_hash, seal) in enumerate(lanes)
            if len(proposal_hash) == 32 and self._well_formed_seal(seal)
        ]
        if not idxs:
            return out
        items = [
            idxs[start : start + self._dispatch_cap]
            for start in range(0, len(idxs), self._dispatch_cap)
        ]

        def pack(chunk):
            with trace.span("verify.pack", kind="seal_lanes", lanes=len(chunk)):
                inputs = pack_seal_lanes(
                    [lanes[i] for i in chunk],
                    pad_lanes=self._pad_lanes(len(chunk)),
                )
            return chunk, inputs, self._table_dev(height)

        with trace.span(
            "verify.drain",
            route=self._route,
            kind="seal_lanes",
            chunks=len(items),
        ):
            results = self._run_chunk_pipeline(
                items, pack, "verify_seal_lanes_ms"
            )
            with trace.span("verify.quorum", route="mask"):
                for chunk, mask in results:
                    out[np.asarray(chunk)] = mask[: len(chunk)]
        return out

    def verify_seals_early_exit(
        self,
        proposal_hash: bytes,
        seals: Sequence[CommittedSeal],
        height: int,
        threshold: Optional[int] = None,
    ) -> EarlyExitReport:
        """Power-ordered chunked seal drain that STOPS DISPATCHING at
        quorum.

        Lanes are ordered by claimed signer power (descending, stable)
        so the fewest chunks cover the threshold; the first chunk is the
        smallest lane bucket covering the claimed-power quorum prefix
        (optimistic: every lane valid), subsequent chunks double.  After
        each readback the exact host-int tally updates and the loop
        exits before the next dispatch once quorum is certain —
        remaining lanes are reported ``skipped``.  Verdicts for
        dispatched lanes are the kernel's usual mask, bit-identical to
        the sequential oracle; the mesh subclass shards each chunk like
        any other drain.
        """
        n = len(seals)
        mask = np.zeros(n, dtype=bool)
        verified = np.zeros(n, dtype=bool)
        powers = self._validators(height)
        thr = (
            calculate_quorum(sum(powers.values()))
            if threshold is None
            else threshold
        )
        well_formed = [i for i, s in enumerate(seals) if self._well_formed_seal(s)]
        if len(proposal_hash) != 32:
            verified[:] = True
            return EarlyExitReport(mask, verified, thr <= 0, 0)
        # Malformed lanes have their (False) verdict without crypto.
        malformed = set(range(n)) - set(well_formed)
        if malformed:
            verified[np.asarray(sorted(malformed))] = True
        # Power-ordered, stable: arrival order breaks ties so equal-power
        # sets (the common 1-power-each committee) drain in arrival order.
        order = sorted(
            well_formed, key=lambda i: -powers.get(seals[i].signer, 0)
        )
        # First chunk: the claimed-power quorum prefix, bucket-padded —
        # the extra bucket lanes are verified for free (they pad anyway).
        claimed = _PowerTally(powers, thr)
        prefix = 0
        for i in order:
            prefix += 1
            if claimed.add(seals[i].signer):
                break
        chunk = (
            min(_bucket(max(prefix, 1), _BATCH_BUCKETS), self._dispatch_cap)
            if order
            else 0
        )
        tally = _PowerTally(powers, thr)
        pos = 0
        with trace.span(
            "verify.early_exit",
            route=self._route,
            kind="seals",
            lanes=n,
        ):
            while pos < len(order) and not tally.reached:
                take = order[pos : pos + chunk]
                cmask, _ = self._dispatch(
                    self._seal_inputs(
                        proposal_hash, [seals[i] for i in take]
                    ),
                    self._table_dev(height),
                    None,
                    "early_exit_ms",
                )
                for j, i in enumerate(take):
                    verified[i] = True
                    if cmask[j]:
                        mask[i] = True
                        tally.add(seals[i].signer)
                pos += len(take)
                chunk = min(chunk * 2, self._dispatch_cap)
        skipped = len(order) - pos
        metrics.inc_counter(EARLY_EXIT_DRAINS_KEY)
        if skipped:
            metrics.inc_counter(EARLY_EXIT_SKIPPED_KEY, skipped)
        return EarlyExitReport(mask, verified, tally.reached, skipped)

    def verify_round_chunked(
        self,
        msgs: Sequence[IbftMessage],
        proposal_hash: bytes,
        seals: Sequence[CommittedSeal],
        height: int,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """BOTH phases' drains through ONE pipeline (oversize rounds).

        PREPARE-envelope chunks and COMMIT-seal chunks share the in-flight
        window, so the seal packing overlaps the tail envelope dispatches —
        the multi-phase drain shape ``AdaptiveBatchVerifier.certify_round``
        routes floods above the fused-dispatch bucket through.  Masks only;
        the quorum reduction stays with the caller (exact host ints).
        Envelopes are height-gated like the certify paths.
        """
        sender_mask = np.zeros(len(msgs), dtype=bool)
        seal_mask = np.zeros(len(seals), dtype=bool)
        cap = self._dispatch_cap
        midx = [
            i for i, m in enumerate(msgs) if self._well_formed_sender(m, height)
        ]
        sidx = (
            [i for i, s in enumerate(seals) if self._well_formed_seal(s)]
            if len(proposal_hash) == 32
            else []
        )
        items = [
            ("sender", midx[start : start + cap])
            for start in range(0, len(midx), cap)
        ] + [
            ("seal", sidx[start : start + cap])
            for start in range(0, len(sidx), cap)
        ]
        if not items:
            return sender_mask, seal_mask

        def pack(item):
            kind, chunk = item
            if kind == "sender":
                inputs = self._sender_inputs([msgs[i] for i in chunk])
            else:
                inputs = self._seal_inputs(
                    proposal_hash, [seals[i] for i in chunk]
                )
            return item, inputs, self._table_dev(height)

        with trace.span(
            "verify.drain",
            route=self._route,
            kind="round_chunked",
            chunks=len(items),
        ):
            results = self._run_chunk_pipeline(items, pack, "round_drain_ms")
            with trace.span("verify.quorum", route="mask"):
                for (kind, chunk), mask in results:
                    target = sender_mask if kind == "sender" else seal_mask
                    target[np.asarray(chunk)] = mask[: len(chunk)]
        return sender_mask, seal_mask


QUARANTINED_LANES_KEY = ("go-ibft", "resilient", "quarantined_lanes")
DRAIN_FAULTS_KEY = ("go-ibft", "resilient", "drain_faults")

# Below this many lanes a sharded dispatch loses to one single-device
# dispatch: the mesh pads every drain to ``bucket x dp`` lanes and pays a
# multi-device launch, which only amortizes once the per-lane ladder work
# dominates.  Default = half the largest single-device bucket (a drain
# that nearly fills one device's biggest program is worth sharding);
# callers with a measured crossover pass their own.
MESH_CUTOVER_LANES = _BATCH_BUCKETS[-1] // 2


class ResilientBatchVerifier:
    """Degraded-mode drain: quarantine poison lanes, demote dead rungs.

    Implements the :class:`~go_ibft_tpu.core.backend.BatchVerifier`
    protocol over a fastest-first ladder of rungs — by default
    ``device -> host (native) -> pure Python``, with an optional
    ``mesh`` rung on top (lane-sharded drains; a mesh failure demotes to
    single-device exactly like a device failure demotes to host, and
    drains below ``mesh_cutover_lanes`` enter at the device rung
    directly) — governed by a
    :class:`~go_ibft_tpu.verify.pipeline.CircuitBreaker`:

    * **Poison batches never propagate.**  A drain whose rung raises
      (a device-side XLA ``RuntimeError``, a native verifier crash, a lane
      whose packing blows up) is bisected: halves re-verify independently,
      a single lane that still raises at this rung is retried one rung
      down, and only a lane no rung can process is condemned (mask False).
      :class:`MalformedLaneError` short-circuits the bisection — the
      packer already named the lane, so it quarantines immediately and the
      rest of the batch re-verifies in one piece.
    * **Circuit breaker.**  ``k`` consecutive faulted drains at a rung
      demote all traffic one rung down; after ``cooldown_s`` the breaker
      probes the faster rung with one live drain and climbs back on
      success.  Every transition is counted in
      :mod:`go_ibft_tpu.utils.metrics` (``("go-ibft", "breaker", ...)``).
    * **Quarantine eviction.**  Condemned sender lanes are reported to the
      fast rung's ``quarantine`` hook (when present), which evicts their
      :class:`~go_ibft_tpu.verify.pipeline.PackCache` entries so a
      corrected re-send is never served a stale packed lane.

    A drain therefore ALWAYS returns a verdict per lane and never raises —
    the liveness contract the chaos suites pin (ISSUE 3).
    """

    def __init__(
        self,
        device,
        host: Optional[HostBatchVerifier] = None,
        python: Optional[HostBatchVerifier] = None,
        *,
        mesh=None,
        mesh_cutover_lanes: Optional[int] = None,
        validators_for_height: Optional[ValidatorSource] = None,
        breaker: Optional["CircuitBreaker"] = None,
    ):
        if host is None or python is None:
            if validators_for_height is None:
                raise ValueError(
                    "validators_for_height required when host/python rungs "
                    "are not supplied"
                )
        if host is None:
            host = HostBatchVerifier(validators_for_height)
        if python is None:
            python = HostBatchVerifier(
                validators_for_height or host._validators,
                recover_fn=host_ecdsa.recover_pure,
            )
        # ``mesh`` (a MeshBatchVerifier or compatible) prepends a fourth,
        # fastest rung: a mesh fault demotes to single-device exactly like
        # a device fault demotes to host.  Drains below the lane cutover
        # enter at the device rung directly — sharding a handful of lanes
        # pays padding + multi-device launch for nothing.
        self._rungs = [("device", device), ("host", host), ("python", python)]
        self.mesh = mesh
        if mesh is not None:
            self._rungs.insert(0, ("mesh", mesh))
        self.mesh_cutover = (
            mesh_cutover_lanes
            if mesh_cutover_lanes is not None
            else MESH_CUTOVER_LANES
        )
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            tuple(name for name, _ in self._rungs)
        )
        self.device = device
        self.host = host

    # -- engine hooks (forwarded to the fast rungs when they have them) --
    # The mesh and device rungs each own a PackCache and table cache, so
    # lifecycle hooks fan out to both.

    def _fast_rungs(self):
        return [self.device] if self.mesh is None else [self.mesh, self.device]

    def warmup(self, **kw) -> None:
        for rung in self._fast_rungs():
            if hasattr(rung, "warmup"):
                rung.warmup(**kw)

    def note_round(self, round_: int) -> None:
        for rung in self._fast_rungs():
            if hasattr(rung, "note_round"):
                rung.note_round(round_)

    def reset_pack_cache(self) -> None:
        for rung in self._fast_rungs():
            if hasattr(rung, "reset_pack_cache"):
                rung.reset_pack_cache()

    def _pack_caches(self) -> List["PackCache"]:
        return [
            cache
            for rung in self._fast_rungs()
            if hasattr(rung, "_pack_caches")
            for cache in rung._pack_caches()
        ]

    def scoped(self, owner: str) -> "EngineScope":
        """Per-engine lifecycle facade over the shared ladder (the whole
        rung stack stays shared; only round/sequence state splits)."""
        return EngineScope(self, owner)

    # -- BatchVerifier ---------------------------------------------------

    def verify_senders(self, msgs: Sequence[IbftMessage]) -> np.ndarray:
        msgs = list(msgs)
        return self._drain(
            msgs,
            lambda rung, idxs: rung.verify_senders([msgs[i] for i in idxs]),
            quarantinable=msgs,
        )

    def verify_committed_seals(
        self, proposal_hash: bytes, seals: Sequence[CommittedSeal], height: int
    ) -> np.ndarray:
        seals = list(seals)
        return self._drain(
            seals,
            lambda rung, idxs: rung.verify_committed_seals(
                proposal_hash, [seals[i] for i in idxs], height
            ),
        )

    def verify_seal_lanes(
        self, lanes: Sequence[Tuple[bytes, CommittedSeal]], height: int
    ) -> np.ndarray:
        """Cross-height sync drain through the degradation ladder: poison
        lanes quarantine by bisection, a faulting device demotes to the
        host rungs — the block-sync catch-up path's fallback route."""
        lanes = list(lanes)
        return self._drain(
            lanes,
            lambda rung, idxs: self._run_seal_lanes(
                rung, [lanes[i] for i in idxs], height
            ),
        )

    def verify_seals_early_exit(
        self,
        proposal_hash: bytes,
        seals: Sequence[CommittedSeal],
        height: int,
        threshold: Optional[int] = None,
    ) -> EarlyExitReport:
        """Early-exit drain through the degradation ladder.

        The breaker's active rung serves the early-exit shape when it
        has one (mesh/device/host all do); a rung fault — or a malformed
        lane, which the early-exit packers cannot bisect around — falls
        back to the FULL resilient drain (quarantine + breaker
        accounting intact), reported with ``skipped=0``.  Early-exit
        never weakens the ladder's liveness contract: a verdict per lane
        is always available, it just may arrive via the full drain.
        """
        seals = list(seals)
        level, probe = self.breaker.acquire()
        if self.mesh is not None and level == 0 and len(seals) < self.mesh_cutover:
            # Same lane-count cutover as _drain: small drains skip the
            # padded multi-device launch; a pending mesh probe cannot be
            # answered by a drain that will not run the mesh.
            if probe:
                self.breaker.abort_probe(level)
                probe = False
            level = 1
        rung = self._rungs[level][1]
        fn = getattr(rung, "verify_seals_early_exit", None)
        if fn is not None:
            try:
                report = fn(
                    proposal_hash, seals, height, threshold=threshold
                )
            except MalformedLaneError:
                # Input poison, not a rung fault: release a pending
                # probe; the full drain below quarantines the lane.
                self.breaker.abort_probe(level)
            except Exception:
                # ONE breaker fault per underlying failure: the
                # full-drain fallback below re-acquires this rung and
                # its own accounting records the fault (and the
                # DRAIN_FAULTS counter) exactly once.  A pending PROBE
                # is the exception — the probed rung genuinely ran and
                # failed, and leaving it unanswered would wedge the
                # breaker's single-probe slot forever.
                if probe:
                    self.breaker.record_fault(level)
            else:
                self.breaker.record_success(level)
                return report
        elif probe:
            self.breaker.abort_probe(level)
        # Full-resilient fallback: bisection/quarantine semantics, every
        # lane verified (no skip), exact host-int quorum over the valid
        # signers.
        mask = self.verify_committed_seals(proposal_hash, seals, height)
        reached = host_quorum_reached(
            self.host._validators,
            [s.signer for s, ok in zip(seals, mask) if ok],
            height,
            threshold,
        )
        return EarlyExitReport(
            mask, np.ones(len(seals), dtype=bool), reached, 0
        )

    @staticmethod
    def _run_seal_lanes(rung, lanes, height) -> np.ndarray:
        if hasattr(rung, "verify_seal_lanes"):
            return rung.verify_seal_lanes(lanes, height)
        # Rung without the per-lane-hash entry point (a bare BatchVerifier
        # protocol implementer): validate lane shapes FIRST so malformed
        # lanes raise with the drain-relative index the bisection expects,
        # then group by hash and reuse the single-hash drain per group.
        validate_seal_lanes(lanes)
        out = np.zeros(len(lanes), dtype=bool)
        groups: Dict[bytes, List[int]] = {}
        for i, (proposal_hash, _seal) in enumerate(lanes):
            groups.setdefault(proposal_hash, []).append(i)
        for proposal_hash, idxs in groups.items():
            mask = np.asarray(
                rung.verify_committed_seals(
                    proposal_hash, [lanes[i][1] for i in idxs], height
                ),
                dtype=bool,
            )
            out[np.asarray(idxs)] = mask[: len(idxs)]
        return out

    # -- drain machinery -------------------------------------------------

    def _drain(self, items, run, quarantinable=None) -> np.ndarray:
        n = len(items)
        out = np.zeros(n, dtype=bool)
        if n == 0:
            return out
        level, probe = self.breaker.acquire()
        if self.mesh is not None and level == 0 and n < self.mesh_cutover:
            # Lane-count cutover: small drains skip the mesh rung (padding
            # to a dp multiple + a multi-device launch loses below it).  A
            # pending mesh probe cannot be answered by a drain that will
            # not run the mesh — release it so the next big drain gets it.
            # KNOWN TRADE-OFF: faults recorded at this forced device level
            # are no-ops while the breaker sits at the mesh level (the
            # breaker counts consecutive faults at its ACTIVE level only),
            # so a dead device rung under a healthy mesh never demotes for
            # small drains — each one pays the exception + bisection to
            # host, verdicts intact.  Accepted because the rungs share
            # hardware: a faulting single-device dispatch with a HEALTHY
            # mesh on the same devices is a corner (mesh faults demote 0->1
            # first, after which device faults count normally); per-rung
            # fault counters would be a CircuitBreaker redesign.
            if probe:
                self.breaker.abort_probe(level)
            level = 1
        quarantined: List[int] = []
        faulted = [False]
        self._verify(level, list(range(n)), run, out, quarantined, faulted)
        if faulted[0]:
            metrics.inc_counter(DRAIN_FAULTS_KEY)
            self.breaker.record_fault(level)
        else:
            self.breaker.record_success(level)
        if quarantined:
            metrics.inc_counter(QUARANTINED_LANES_KEY, len(quarantined))
            if quarantinable is not None:
                condemned = [quarantinable[i] for i in quarantined]
                for rung in self._fast_rungs():
                    if hasattr(rung, "quarantine"):
                        rung.quarantine(condemned)
        return out

    def _verify(self, level, idxs, run, out, quarantined, faulted) -> None:
        """Verify ``idxs`` at rung ``level``, bisecting around failures.

        Writes verdicts into ``out``; lanes no rung can process land in
        ``quarantined`` (verdict stays False).  ``faulted`` records whether
        THIS drain hit any non-malformed rung failure — one breaker fault
        per drain, no matter how many bisection steps it took.
        """
        while idxs:
            try:
                mask = np.asarray(run(self._rungs[level][1], idxs), dtype=bool)
                out[np.asarray(idxs)] = mask[: len(idxs)]
                return
            except MalformedLaneError as err:
                # The packer named the lane: condemn it, retry the rest in
                # one piece (no bisection needed, no breaker fault — the
                # rung is healthy, the input was not).
                if not 0 <= err.lane < len(idxs):
                    quarantined.extend(idxs)
                    return
                quarantined.append(idxs[err.lane])
                idxs = idxs[: err.lane] + idxs[err.lane + 1 :]
            except Exception:
                faulted[0] = True
                if len(idxs) == 1:
                    if level + 1 < len(self._rungs):
                        self._verify(
                            level + 1, idxs, run, out, quarantined, faulted
                        )
                    else:
                        quarantined.extend(idxs)
                    return
                mid = len(idxs) // 2
                self._verify(level, idxs[:mid], run, out, quarantined, faulted)
                idxs = idxs[mid:]


class AdaptiveBatchVerifier:
    """Host/device router: tiny batches on host, large ones on device.

    SURVEY.md §7 hard part (d): a device round-trip has a fixed dispatch
    latency floor that dwarfs a handful of native per-message recovers, so
    a 4-validator cluster should never pay it — while a 100-validator
    quorum drain absolutely should.  Batches with fewer than
    ``cutover_lanes`` items run the sequential host path (native C++
    ecrecover); everything else dispatches the fused device kernels.  Both
    paths produce identical accept-sets (the differential suites pin this),
    so the route is invisible to the engine.

    Implements BOTH engine protocols (BatchVerifier + FusedBatchVerifier);
    the host fallback computes the voting-power quorum with exact Python
    ints, mirroring ops/quorum.py ``power_reduce`` semantics (distinct
    validators counted once).

    An optional ``mesh`` route (a
    :class:`~go_ibft_tpu.verify.mesh_batch.MeshBatchVerifier`) adds a
    second, upper lane-count cutover: drains at or above
    ``mesh_cutover_lanes`` dispatch lane-sharded across the device mesh
    first, with the single-device routes as their breaker-accounted
    fallback (ladder ``mesh -> device -> host -> python``).

    Device-routed drains ride a :class:`ResilientBatchVerifier` ladder: a
    poison batch (device raising mid-dispatch, a lane whose packing blows
    up) is bisected/quarantined instead of crashing the drain, and the
    shared circuit breaker demotes to the host rungs after repeated device
    faults (restoring after cooldown).  The fused certify paths fall back
    to the exact host-int route on any device exception — counted under
    ``("go-ibft", "resilient", "certify_fallback")`` — so a consensus
    phase never loses its verdict to a device fault.
    """

    def __init__(
        self,
        validators_for_height: ValidatorSource,
        cutover_lanes: Optional[int] = None,
        device: Optional[DeviceBatchVerifier] = None,
        host: Optional[HostBatchVerifier] = None,
        breaker: Optional[CircuitBreaker] = None,
        *,
        mesh=None,
        mesh_cutover_lanes: Optional[int] = None,
    ):
        from ..utils import calibration

        self._validators = validators_for_height
        if cutover_lanes is None:
            # Measurement first (bench.py persists the device-dispatch
            # floor vs host per-verify crossover for THIS platform), static
            # conservative default only when no measurement exists.
            cutover_lanes = (
                calibration.measured_cutover()
                or calibration.DEFAULT_CUTOVER_LANES
            )
        self.cutover = cutover_lanes
        self.device = device if device is not None else DeviceBatchVerifier(validators_for_height)
        self.host = host if host is not None else HostBatchVerifier(validators_for_height)
        # Optional mesh route (a MeshBatchVerifier): drains at or above
        # ``mesh_cutover_lanes`` try the sharded rung first; the resilient
        # ladder below becomes mesh -> device -> host -> python, so a mesh
        # failure demotes to single-device before host.  Deliberately NOT
        # auto-constructed — sharding is an explicit deployment decision
        # (embedders/bench opt in), and a surprise shard_map compile must
        # never land in a default engine.
        self._mesh = mesh
        self._resilient = ResilientBatchVerifier(
            self.device,
            host=self.host,
            mesh=mesh,
            mesh_cutover_lanes=mesh_cutover_lanes,
            validators_for_height=validators_for_height,
            breaker=breaker,
        )
        self.mesh_cutover = self._resilient.mesh_cutover
        # The single-device rung's breaker level: 0 without a mesh, 1 with
        # one (the mesh occupies level 0).
        self._device_level = 0 if mesh is None else 1
        self.breaker = self._resilient.breaker

    def warmup(self, **kw) -> None:
        self._resilient.warmup(**kw)

    def note_round(self, round_: int) -> None:
        """Engine hook: forward round advances to the fast-rung pack caches."""
        self._resilient.note_round(round_)

    def reset_pack_cache(self) -> None:
        self._resilient.reset_pack_cache()

    def _pack_caches(self) -> List["PackCache"]:
        return self._resilient._pack_caches()

    def scoped(self, owner: str) -> "EngineScope":
        """Per-engine lifecycle facade over the shared adaptive router."""
        return EngineScope(self, owner)

    # -- host-side quorum (exact big ints) ------------------------------

    def _host_reached(
        self, valid_addrs: Iterable[bytes], height: int, threshold: Optional[int]
    ) -> bool:
        with trace.span("verify.quorum", route="host-int"):
            return host_quorum_reached(
                self._validators, valid_addrs, height, threshold
            )

    # -- BatchVerifier ---------------------------------------------------

    def _host_sized(self, n: int) -> bool:
        # Below the cutover the device dispatch floor loses to a handful
        # of native host recovers.  There is NO upper bound: floods above
        # the largest lane bucket stay on device as chunked full-bucket
        # dispatches (DeviceBatchVerifier.verify_senders) — 2049 messages
        # cost two launches, not ~0.7s of sequential host verifies
        # (VERDICT r04 weak #6).
        return n < self.cutover

    def verify_senders(self, msgs: Sequence[IbftMessage]) -> np.ndarray:
        if self._host_sized(len(msgs)):
            return self.host.verify_senders(msgs)
        # Device route rides the degradation ladder: poison batches
        # quarantine instead of raising, device faults demote to host.
        return self._resilient.verify_senders(msgs)

    def verify_committed_seals(
        self, proposal_hash: bytes, seals: Sequence[CommittedSeal], height: int
    ) -> np.ndarray:
        if self._host_sized(len(seals)):
            return self.host.verify_committed_seals(proposal_hash, seals, height)
        return self._resilient.verify_committed_seals(proposal_hash, seals, height)

    def verify_seal_lanes(
        self, lanes: Sequence[Tuple[bytes, CommittedSeal]], height: int
    ) -> np.ndarray:
        """Cross-height sync drain, routed like any other seal drain: tiny
        ranges on the sequential host path, everything else through the
        device ladder (the block-sync catch-up's normal route)."""
        if self._host_sized(len(lanes)):
            return self.host.verify_seal_lanes(lanes, height)
        return self._resilient.verify_seal_lanes(lanes, height)

    def verify_seals_early_exit(
        self,
        proposal_hash: bytes,
        seals: Sequence[CommittedSeal],
        height: int,
        threshold: Optional[int] = None,
    ) -> EarlyExitReport:
        """Early-exit seal drain, routed like every other seal drain:
        tiny batches take the sequential host early-exit (arrival-order
        stop-at-quorum), larger ones the ladder's power-ordered chunked
        route (mesh/device with full breaker accounting)."""
        if self._host_sized(len(seals)):
            return self.host.verify_seals_early_exit(
                proposal_hash, seals, height, threshold=threshold
            )
        return self._resilient.verify_seals_early_exit(
            proposal_hash, seals, height, threshold=threshold
        )

    # -- FusedBatchVerifier ---------------------------------------------

    def supports_fused(self, height: int) -> bool:
        """Always true: batches the device range cannot represent exactly
        (powers >= 2**31) are routed to the host big-int path instead."""
        return True

    def _route_device(self, n: int, height: int) -> bool:
        # Single fused dispatch (mask + quorum in one program) fits one
        # lane bucket; larger floods use chunked device crypto with the
        # quorum reduced on host ints (_chunked route below).
        return (
            self.cutover <= n <= _BATCH_BUCKETS[-1]
            and self.device.supports_fused(height)
        )

    def _breaker_gate(self) -> Tuple[bool, Optional[int]]:
        """Consult the breaker before a fused single-device dispatch.

        Returns ``(use_device, acquired_level)``: when the ladder is
        demoted below the device rung the fused dispatch is suppressed and
        the caller's fallback serves the call.  An acquisition that does
        not end up running the device MUST be released with
        ``breaker.abort_probe(acquired_level)`` once the call completes —
        never answered with success for a rung that did not run (the
        ladder would restore on no evidence), and a pending probe must
        not leak (``_probing`` would wedge and no probe would ever be
        offered again).  With a mesh rung present the device sits at
        level 1; an active-or-probed mesh level is NOT consumable by a
        single-device dispatch — a mesh probe stays pending through the
        ladder fallback (same deferred-release discipline as a demoted
        level), while a healthy mesh level simply lets the device run
        without recording evidence against the mesh rung."""
        level, probe = self.breaker.acquire()
        if level == self._device_level:
            # Plain dispatch at the device rung, or the device rung's own
            # cooldown probe — either way success/fault at
            # ``self._device_level`` is the correct answer.
            return True, None
        if level < self._device_level:
            if probe:
                return False, level
            return True, None
        return False, level

    def _device_faulted(self) -> None:
        metrics.inc_counter(("go-ibft", "resilient", "certify_fallback"))
        self.breaker.record_fault(self._device_level)

    def _mesh_gate(self, n: int) -> bool:
        """Route a certify call to the sharded mesh rung?  True only when
        a mesh exists, the drain clears the lane cutover, and the breaker
        has not demoted the mesh."""
        return self._mesh is not None and n >= self.mesh_cutover

    def _try_mesh(self, n: int, call):
        """One fused dispatch on the mesh rung, breaker-accounted.

        Returns the call's result, or ``None`` when the mesh route was
        unavailable (breaker demoted), faulted (recorded; the caller's
        single-device/ladder fallback serves the drain), or the input was
        poison (probe released; the ladder fallback quarantines)."""
        if not self._mesh_gate(n):
            return None
        level, probe = self.breaker.acquire()
        if level != 0:
            if probe:
                # A probe for a SLOWER rung (device/host) cannot be
                # answered by a mesh dispatch that will not run: release
                # it immediately — the cooldown has elapsed, so the very
                # next gate (the single-device route below, or the
                # resilient fallback) re-acquires and runs it with real
                # evidence.
                self.breaker.abort_probe(level)
            return None
        try:
            result = call(self._mesh)
        except MalformedLaneError:
            # Input poison, not a mesh fault: release a pending probe and
            # let the ladder-aware fallback quarantine the lane.
            self.breaker.abort_probe(0)
            return None
        except Exception:  # noqa: BLE001 - demote mesh -> device
            metrics.inc_counter(("go-ibft", "resilient", "certify_fallback"))
            self.breaker.record_fault(0)
            return None
        self.breaker.record_success(0)
        return result

    def _chunked_device(self, n: int, height: int) -> bool:
        # No supports_fused gate: the chunked route never touches the
        # device quorum pack (mask from verify_*, quorum from host ints),
        # so it is exact for ANY voting-power range.
        return n > _BATCH_BUCKETS[-1]

    def certify_senders(
        self, msgs: Sequence[IbftMessage], height: int, threshold: Optional[int] = None
    ) -> Tuple[np.ndarray, bool]:
        # Sharded route first: big drains go to the mesh rung (its quorum
        # reduce runs on exact host ints, so it is exact for any power
        # range); a mesh fault falls through to the single-device routes.
        result = self._try_mesh(
            len(msgs), lambda m: m.certify_senders(msgs, height, threshold)
        )
        if result is not None:
            return result
        fallback_level = None
        device_route = self._route_device(len(msgs), height)
        if device_route:
            use_device, fallback_level = self._breaker_gate()
            if use_device:
                try:
                    result = self.device.certify_senders(msgs, height, threshold)
                    self.breaker.record_success(self._device_level)
                    return result
                except MalformedLaneError:
                    # Input poison, not a device fault: the rung is
                    # healthy (same rule as the resilient drain), so no
                    # breaker fault — a pending probe is released, not
                    # failed, and the ladder-aware fallback below
                    # quarantines the lane.
                    self.breaker.abort_probe(self._device_level)
                except Exception:
                    # Device fault mid-phase: the fallback below still
                    # produces the verdict (no exception escapes a
                    # certify call).
                    self._device_faulted()
        if device_route or self._chunked_device(len(msgs), height):
            # Ladder-aware fallback: quarantines poison lanes, respects
            # the breaker's demotion, never raises, and carries its own
            # breaker accounting (oversize floods keep crypto on device
            # in full-bucket chunks; only the quorum reduction moves to
            # exact host ints).
            mask = self._resilient.verify_senders(msgs)
        else:
            mask = self.host.verify_senders(msgs)
        # Same height gate as the device path (certify is per-view).
        for i, m in enumerate(msgs):
            if m.view is None or m.view.height != height:
                mask[i] = False
        valid = [m.sender for m, ok in zip(msgs, mask) if ok]
        if fallback_level is not None:
            # The gate's acquisition did not run the device: release it
            # (a pending probe must neither leak nor count as evidence).
            self.breaker.abort_probe(fallback_level)
        return mask, self._host_reached(valid, height, threshold)

    def certify_seals(
        self,
        proposal_hash: bytes,
        seals: Sequence[CommittedSeal],
        height: int,
        threshold: Optional[int] = None,
    ) -> Tuple[np.ndarray, bool]:
        result = self._try_mesh(
            len(seals),
            lambda m: m.certify_seals(proposal_hash, seals, height, threshold),
        )
        if result is not None:
            return result
        fallback_level = None
        device_route = self._route_device(len(seals), height)
        if device_route:
            use_device, fallback_level = self._breaker_gate()
            if use_device:
                try:
                    result = self.device.certify_seals(
                        proposal_hash, seals, height, threshold
                    )
                    self.breaker.record_success(self._device_level)
                    return result
                except MalformedLaneError:
                    self.breaker.abort_probe(self._device_level)
                except Exception:
                    self._device_faulted()
        if device_route or self._chunked_device(len(seals), height):
            mask = self._resilient.verify_committed_seals(
                proposal_hash, seals, height
            )
        else:
            mask = self.host.verify_committed_seals(proposal_hash, seals, height)
        valid = [s.signer for s, ok in zip(seals, mask) if ok]
        if fallback_level is not None:
            self.breaker.abort_probe(fallback_level)
        return mask, self._host_reached(valid, height, threshold)

    def certify_round(
        self,
        msgs: Sequence[IbftMessage],
        proposal_hash: bytes,
        seals: Sequence[CommittedSeal],
        height: int,
        prepare_threshold: Optional[int] = None,
    ) -> Tuple[np.ndarray, bool, np.ndarray, bool]:
        if msgs and seals and len(proposal_hash) == 32:
            result = self._try_mesh(
                max(len(msgs), len(seals)),
                lambda m: m.certify_round(
                    msgs, proposal_hash, seals, height, prepare_threshold
                ),
            )
            if result is not None:
                return result
        fallback_level = None
        if (
            self._route_device(max(len(msgs), len(seals)), height)
            and msgs
            and seals
        ):
            use_device, fallback_level = self._breaker_gate()
            if use_device:
                try:
                    result = self.device.certify_round(
                        msgs, proposal_hash, seals, height, prepare_threshold
                    )
                    self.breaker.record_success(self._device_level)
                    return result
                except MalformedLaneError:
                    self.breaker.abort_probe(self._device_level)
                except Exception:
                    # Fall through to the per-phase routes, which carry
                    # their own breaker accounting and ladder fallbacks.
                    self._device_faulted()
        if (
            msgs
            and seals
            and len(proposal_hash) == 32
            and self._chunked_device(max(len(msgs), len(seals)), height)
            and min(len(msgs), len(seals)) >= self.cutover
            # injected device stubs (tests, embedders) may predate the
            # cross-phase drain; fall back to the per-phase routes then
            and hasattr(self.device, "verify_round_chunked")
        ):
            # Oversize round: BOTH phases drain through one device pipeline
            # (seal packing overlaps the tail envelope dispatches); quorum
            # reduces on exact host ints like every chunked route.
            try:
                sender_mask, seal_mask = self.device.verify_round_chunked(
                    msgs, proposal_hash, seals, height
                )
            except Exception:
                # Cross-phase pipeline faulted: the per-phase resilient
                # drains below still produce both verdicts.
                self._device_faulted()
            else:
                p_ok = self._host_reached(
                    [m.sender for m, ok in zip(msgs, sender_mask) if ok],
                    height,
                    prepare_threshold,
                )
                s_ok = self._host_reached(
                    [s.signer for s, ok in zip(seals, seal_mask) if ok],
                    height,
                    None,
                )
                if fallback_level is not None:
                    self.breaker.abort_probe(fallback_level)
                return sender_mask, p_ok, seal_mask, s_ok
        sender_mask, p_ok = self.certify_senders(
            msgs, height, threshold=prepare_threshold
        )
        seal_mask, s_ok = self.certify_seals(proposal_hash, seals, height)
        if fallback_level is not None:
            # Released AFTER the per-phase routes: their own gates see the
            # probe as still pending and cannot double-acquire it.
            self.breaker.abort_probe(fallback_level)
        return sender_mask, p_ok, seal_mask, s_ok


class EngineScope:
    """Per-engine lifecycle facade over a SHARED verifier ladder.

    N engines (one per chain/tenant) may share one verifier so their
    drains land on one device data plane, but the engine lifecycle hooks
    carry per-sequence/per-round state: before this scope existed the
    ladder-wide reset assumed a single engine — engine A's
    ``reset_pack_cache()`` (sequence start) wiped engine B's live packs,
    and A's ``note_round(0)`` retagged the shared cache's live round out
    from under B's entries, demoting them to dead-round eviction fodder
    mid-round (ISSUE 8 satellite).

    ``ladder.scoped("chain-a")`` returns a drop-in ``BatchVerifier``
    whose verify calls attribute their pack-cache stores to the owner
    (:meth:`PackCache.owned`) and whose ``note_round`` /
    ``reset_pack_cache`` rotate/drop ONLY the owner's entries; every
    other attribute (``quarantine`` — already per-message — ``warmup``,
    the certify surface, breaker state) delegates to the shared parent.
    The :class:`~go_ibft_tpu.sched.TenantScheduler`'s handles are the
    fully-managed version of this (per-tenant queues, fairness and
    backpressure on top); a bare shared ladder with scopes is the
    minimal-correct one.
    """

    def __init__(self, parent, owner: str):
        if not owner:
            raise ValueError("EngineScope requires a non-empty owner label")
        self._parent = parent
        self._owner = owner

    @property
    def owner(self) -> str:
        return self._owner

    def __getattr__(self, name: str):
        return getattr(self._parent, name)

    def _caches(self) -> List["PackCache"]:
        fn = getattr(self._parent, "_pack_caches", None)
        return fn() if fn is not None else []

    def _owned(self) -> ExitStack:
        stack = ExitStack()
        for cache in self._caches():
            stack.enter_context(cache.owned(self._owner))
        return stack

    # -- owner-scoped engine lifecycle hooks -----------------------------

    def note_round(self, round_: int) -> None:
        for cache in self._caches():
            cache.note_round(round_, owner=self._owner)

    def reset_pack_cache(self) -> None:
        for cache in self._caches():
            cache.clear(owner=self._owner)

    # -- BatchVerifier (stores attributed to the owner) ------------------

    def verify_senders(self, msgs: Sequence[IbftMessage]) -> np.ndarray:
        with self._owned():
            return self._parent.verify_senders(msgs)

    def verify_committed_seals(
        self, proposal_hash: bytes, seals: Sequence[CommittedSeal], height: int
    ) -> np.ndarray:
        with self._owned():
            return self._parent.verify_committed_seals(
                proposal_hash, seals, height
            )

    def verify_seal_lanes(
        self, lanes: Sequence[Tuple[bytes, CommittedSeal]], height: int
    ) -> np.ndarray:
        with self._owned():
            return self._parent.verify_seal_lanes(lanes, height)
