"""Device-resident BLS aggregation + batched multi-pairing verification.

ISSUE 12 tentpole.  PR 7 made a COMMIT quorum O(1) on the wire and ONE
pairing to verify — but *building* the aggregate was still a host-side
sequential ``g2_add`` loop, and every consumer (certifier, block-sync,
proof serving) verified one pairing per call, so a 1000-height catch-up
was 1000 independent pairing dispatches.  This module closes both gaps:

* **Vmapped merge trees** (:class:`G2MergeTree`,
  :func:`aggregate_signatures`, :func:`aggregate_pubkeys`): point
  aggregation routes through the scanned log-depth masked tree kernels
  (:func:`go_ibft_tpu.ops.bls12_381.g2_merge_tree` /
  ``g1_merge_tree``) — one dispatch merges a whole committee, and the
  batched form merges MANY disjoint groups per dispatch (the
  aggregation-tree pump's per-sweep combine).  The host loop
  (:func:`go_ibft_tpu.crypto.bls.aggregate_signatures`) remains the
  bit-parity oracle and the small-input / degraded route.

* **Batched multi-pairing** (:func:`multi_aggregate_check`,
  :class:`MultiPairVerifier`): MANY aggregate equations
  ``e(G1, S_i) == e(sum(pk), H_i)`` verify together instead of one
  pairing per call.  Three routes, every verdict pinned to the per-lane
  :func:`~go_ibft_tpu.verify.bls.aggregate_check` oracle:

  - ``device``: ONE staged dispatch
    (:func:`go_ibft_tpu.ops.bls12_381.multi_pairing_check`) — all 2N
    Miller loops ride one batched scan, one final exponentiation per
    lane through the SAME scan-staged hard part the single-certificate
    pipeline compiled;
  - ``mesh``: the device kernel dp-sharded over a
    :func:`~go_ibft_tpu.parallel.mesh.mesh_context` mesh with masked
    lane padding to ``bucket x dp`` (the PR-6 seam) — lanes are
    independent, so the shard_map needs NO collectives;
  - ``host``: the small-exponents batch test (Bellare-Garay-Rabin) on
    the pure-Python oracle tower — each lane's ratio
    ``miller(S_i, G1) * miller(H_i, -PK_i)`` is raised to a 64-bit
    exponent derived from verifier-private fresh randomness plus the
    whole lane set, the products combine, and ONE final exponentiation
    (the ~90% term of a host pairing) covers the whole batch.  A
    failing batch bisects (the
    :class:`~go_ibft_tpu.verify.bls.BLSAggregateVerifier` posture) down
    to per-lane oracle checks, so k bad lanes cost O(k log n) product
    equations and verdicts stay EXACT.

  Batch-soundness note (host route): the exponents mix per-batch
  ``os.urandom`` (unpredictable to the adversary — forging is an
  online 2^-64 gamble, never an offline grind) with a hash of the
  ENTIRE lane set (a compromised RNG degrades to the Fiat-Shamir
  bound, not to fixed exponents).  The device route checks every
  lane's equation individually (vmapped) and needs no randomization.
  Either way a *rejected* batch resolves through the per-lane oracle,
  so no accept/reject verdict ever depends on the batching shortcut
  alone beyond the 2^-64 host-batch term.

Degradation (:class:`MultiPairVerifier`): mesh -> device -> host-batch ->
per-lane python, demoting on faults with the transition counted — the
:class:`~go_ibft_tpu.verify.batch.ResilientBatchVerifier` ladder applied
to pairing work.
"""

from __future__ import annotations

import threading
from typing import List, Sequence, Tuple

import numpy as np

from ..crypto import bls as hbls
from ..crypto.keccak import keccak256
from ..obs import ledger as cost_ledger
from ..obs import trace
from ..utils import metrics
from .bls import PAIRING_EQS_KEY, aggregate_check, encode_seal

__all__ = [
    "G2MergeTree",
    "MultiPairVerifier",
    "aggregate_pubkeys",
    "aggregate_signatures",
    "multi_aggregate_check",
    "MERGE_DISPATCHES_KEY",
    "MERGE_POINTS_KEY",
    "MULTIPAIR_DISPATCHES_KEY",
    "MULTIPAIR_LANES_KEY",
]

# One count per batched merge dispatch / merged point (device route).
MERGE_DISPATCHES_KEY = ("go-ibft", "verify", "merge_dispatches")
MERGE_POINTS_KEY = ("go-ibft", "verify", "merge_points")
# One count per multi-pairing entry call + its lane total: the
# lanes-per-dispatch evidence obs/gates.py regression-gates (a batching
# regression shows up as dispatches growing against lanes).
MULTIPAIR_DISPATCHES_KEY = ("go-ibft", "verify", "multipair_dispatches")
MULTIPAIR_LANES_KEY = ("go-ibft", "verify", "multipair_lanes")

# Pad-to buckets: point-axis buckets for the merge trees (committee
# sizes), lane buckets for the multi-pairing kernel, group buckets for
# the batched pump combine.  Power-of-two ladders keep the compiled-shape
# set small across the mega-committee sweep (100 -> 128, 300 -> 512,
# 1000 -> 1024 validators; 8/64/256-lane multi-pairings per ISSUE 12).
MERGE_BUCKETS = (2, 8, 32, 128, 512, 1024)
MULTIPAIR_BUCKETS = (2, 8, 64, 256, 1024)
GROUP_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)

# Below this many points a device merge dispatch costs more than the host
# adds it replaces (a g2_add is ~0.5 ms host; a dispatch floor is ~1 ms).
DEVICE_MERGE_CUTOVER = 8

# Lane type shared with verify.bls.aggregate_check: (proposal_hash,
# seal points, pubkeys).  A lane verifies True iff the aggregate of its
# points passes the ONE-equation check against the aggregate of its
# pubkeys over H2(proposal_hash).
Lane = Tuple[bytes, Sequence["hbls.PointG2"], Sequence["hbls.PointG1"]]


def _bucket(n: int, ladder: Sequence[int]) -> int:
    """Smallest ladder bucket >= n; beyond the ladder, the next power of
    two (no silent truncation — a 2000-lane call pads to 2048, it never
    drops lanes)."""
    for b in ladder:
        if n <= b:
            return b
    b = ladder[-1]
    while b < n:
        b *= 2
    return b


# -- merge trees ------------------------------------------------------------


def aggregate_signatures(
    points: Sequence["hbls.PointG2"], *, device: bool = False
) -> "hbls.PointG2":
    """Drop-in for :func:`crypto.bls.aggregate_signatures` with a device
    merge-tree route (``device=True``, above the dispatch cutover)."""
    if not device or len(points) < DEVICE_MERGE_CUTOVER:
        return hbls.aggregate_signatures(points)
    return _merge_g2_groups_device([list(points)])[0]


def aggregate_pubkeys(
    pks: Sequence["hbls.PointG1"], *, device: bool = False
) -> "hbls.PointG1":
    """Drop-in for :func:`crypto.bls.aggregate_pubkeys`, device-routable."""
    if not device or len(pks) < DEVICE_MERGE_CUTOVER:
        return hbls.aggregate_pubkeys(pks)
    return _merge_g1_groups_device([list(pks)])[0]


def _merge_g2_groups_device(groups: List[list]) -> list:
    """One vmapped merge-tree dispatch over many disjoint G2 groups."""
    import jax.numpy as jnp

    from ..ops import bls12_381 as dev

    g = _bucket(len(groups), GROUP_BUCKETS)
    v = _bucket(max((len(grp) for grp in groups), default=1), MERGE_BUCKETS)
    packed = []
    live = np.zeros((g, v), dtype=bool)
    for gi in range(g):
        grp = groups[gi] if gi < len(groups) else []
        pts = [p for p in grp]
        live[gi, : len(pts)] = [p is not None for p in pts]
        packed.append(dev.pack_g2_points(pts + [None] * (v - len(pts))))
    args = [
        jnp.asarray(np.stack([p[c] for p in packed])) for c in range(4)
    ]
    metrics.inc_counter(MERGE_DISPATCHES_KEY)
    metrics.inc_counter(MERGE_POINTS_KEY, int(live.sum()))
    # Ledger occupancy over point SLOTS (g x v): merge padding wastes
    # both dead groups and dead points within a group.
    with cost_ledger.dispatch_span(
        "bls_g2_merge_tree",
        route="device",
        live_mask=live,
        kernels=(("bls_g2_merge_tree", dev.g2_merge_tree),),
        site="verify/aggregate.py:_merge_g2_groups_device",
    ):
        limbs, inf = dev.g2_merge_tree(*args, jnp.asarray(live))
        return dev.unpack_g2_points(np.asarray(limbs), np.asarray(inf))[
            : len(groups)
        ]


def _merge_g1_groups_device(groups: List[list]) -> list:
    import jax.numpy as jnp

    from ..ops import bls12_381 as dev

    from ..ops import bls_fp

    g = _bucket(len(groups), GROUP_BUCKETS)
    v = _bucket(max((len(grp) for grp in groups), default=1), MERGE_BUCKETS)
    px = np.zeros((g, v, bls_fp.L), dtype=np.int32)
    py = np.zeros((g, v, bls_fp.L), dtype=np.int32)
    live = np.zeros((g, v), dtype=bool)
    for gi in range(g):
        grp = groups[gi] if gi < len(groups) else []
        if grp:
            x, y = dev.pack_g1_points(list(grp) + [None] * (v - len(grp)))
            px[gi], py[gi] = x, y
            live[gi, : len(grp)] = [p is not None for p in grp]
    metrics.inc_counter(MERGE_DISPATCHES_KEY)
    metrics.inc_counter(MERGE_POINTS_KEY, int(live.sum()))
    with cost_ledger.dispatch_span(
        "bls_g1_merge_tree",
        route="device",
        live_mask=live,
        kernels=(("bls_g1_merge_tree", dev.g1_merge_tree),),
        site="verify/aggregate.py:_merge_g1_groups_device",
    ):
        limbs, inf = dev.g1_merge_tree(
            jnp.asarray(px), jnp.asarray(py), jnp.asarray(live)
        )
        return dev.unpack_g1_points(np.asarray(limbs), np.asarray(inf))[
            : len(groups)
        ]


class G2MergeTree:
    """Batched G2 aggregation with transparent host degradation.

    ``merge_groups`` merges MANY disjoint point sets in ONE vmapped
    device dispatch (the aggregation-tree pump seam: every node's
    per-sweep slot merge becomes one combine per tree level instead of
    per-child Python adds).  Below ``cutover_points`` total points — or
    after a device fault (the breaker posture: demote, never raise) —
    groups merge through the host oracle loop, bit-identically.
    """

    def __init__(
        self,
        *,
        device: bool = True,
        cutover_points: int = DEVICE_MERGE_CUTOVER,
        logger=None,
    ) -> None:
        self._device = device
        self.cutover_points = cutover_points
        self._log = logger
        self._lock = threading.Lock()
        self.device_merges = 0
        self.host_merges = 0
        self.faults = 0

    @property
    def demoted(self) -> bool:
        return not self._device

    def merge(self, points: Sequence["hbls.PointG2"]) -> "hbls.PointG2":
        return self.merge_groups([list(points)])[0]

    def merge_groups(self, groups: Sequence[Sequence["hbls.PointG2"]]) -> list:
        """One merged point (or None for an empty/cancelled group) per
        group; device route when live and worth a dispatch."""
        groups = [list(g) for g in groups]
        total = sum(len(g) for g in groups)
        if not groups:
            return []
        with trace.span(
            "verify.merge", groups=len(groups), points=total
        ):
            if self._device and total >= self.cutover_points:
                try:
                    out = _merge_g2_groups_device(groups)
                    with self._lock:
                        self.device_merges += 1
                    return out
                except Exception as err:  # noqa: BLE001 - demote, never raise
                    with self._lock:
                        self.faults += 1
                        self._device = False
                    if self._log:
                        self._log.error(
                            "G2 merge tree demoted to host", err
                        )
                    trace.instant("verify.merge_demoted")
            with self._lock:
                self.host_merges += 1
            return [hbls.aggregate_signatures(g) for g in groups]

    def stats(self) -> dict:
        with self._lock:
            return {
                "device": self._device,
                "device_merges": self.device_merges,
                "host_merges": self.host_merges,
                "faults": self.faults,
            }


# -- batched multi-pairing --------------------------------------------------


def _lane_aggregates(lane: Lane):
    """(agg signature point, agg pubkey point) or None when the lane is
    vacuously False under the oracle semantics (no points / no pubkeys /
    cancelled-to-infinity aggregate).  Deliberately NO proposal-hash
    length gate: the python oracle (``aggregate_check`` ->
    ``hash_to_g2``) accepts any message bytes, and route verdicts must
    not diverge — 32-byte enforcement is the certifier's job
    (``BLSCertifier._lane_of``)."""
    phash, points, pubkeys = lane
    if not points or not pubkeys:
        return None
    agg = hbls.aggregate_signatures(list(points))
    if agg is None:
        return None
    pk = hbls.aggregate_pubkeys(list(pubkeys))
    if pk is None:
        return None
    return agg, pk


def _fs_exponents(
    lanes: Sequence[Lane], aggs: Sequence[tuple], salt: bytes
) -> List[int]:
    """64-bit batch exponents: verifier-private ``salt`` + whole-lane-set
    binding.

    The small-exponents test is only sound when the adversary cannot
    predict the exponents while crafting the statements.  ``salt`` is
    fresh ``os.urandom`` per batch (drawn by the caller), so every
    forgery attempt is an online 2^-64 gamble — content-only derivation
    would let an attacker grind lane tweaks offline until the per-lane
    errors cancel in the product.  The lane content still feeds the
    digest (belt and braces: even a compromised RNG degrades to the
    Fiat-Shamir bound, not to a fixed exponent set; ``None`` pubkeys are
    identity elements and contribute nothing, matching the oracle
    fold)."""
    digest = keccak256(
        b"go-ibft-multipair-fs-v2"
        + salt
        + b"".join(
            bytes(lane[0])
            + encode_seal(agg)
            + b"".join(
                hbls.pubkey_bytes(pk) for pk in lane[2] if pk is not None
            )
            for lane, (agg, _pk) in zip(lanes, aggs)
        )
    )
    out = []
    for i in range(len(lanes)):
        r = int.from_bytes(
            keccak256(digest + i.to_bytes(4, "big"))[:8], "big"
        )
        out.append(r | 1)  # never zero
    return out


# -- fast host Miller loop --------------------------------------------------
# The oracle Miller (crypto/bls.py::miller_raw) untwists into Fp12 and
# pays a full Fp12 inversion per line — deliberately slow-but-sure.  The
# batch route runs MANY Millers against ONE shared final exponentiation,
# so the Miller becomes the bottleneck; this is the device kernel's
# sparse-line Jacobian formulas (ops/bls12_381.py::_dbl_step/_add_step)
# ported to exact host ints: no inversions, lines land in w-basis slots
# (0, 3, 5), values differ from the oracle Miller only by Fp2-subfield
# scalings that the final exponentiation kills — pinned by
# tests/test_aggregate.py (final_exp(fast) == final_exp(oracle raw)).

_X_BITS_HOST = [int(b) for b in bin(hbls.BLS_X)[3:]]


def _f2_smul(a, k: int):
    """Fp2 element times an Fp integer scalar."""
    return (a[0] * k % hbls.P, a[1] * k % hbls.P)


def _line12(e0, e3, e5) -> "hbls.Fp12T":
    """Sparse w-basis line (slots 0, 3, 5) as a host Fp12 tuple."""
    return ((e0, hbls.F2_ZERO, hbls.F2_ZERO), (hbls.F2_ZERO, e3, e5))


def _host_dbl_step(T, px: int, py: int):
    """Tangent line at Jacobian T evaluated at (px, py), plus 2T."""
    X, Y, Z = T
    z2 = hbls.f2_sqr(Z)
    z3 = hbls.f2_mul(z2, Z)
    yz3 = hbls.f2_mul(Y, z3)
    e0 = hbls.f2_neg(hbls.f2_muli(hbls.f2_mul_xi(_f2_smul(yz3, py)), 2))
    y2 = hbls.f2_sqr(Y)
    x2 = hbls.f2_sqr(X)
    x3 = hbls.f2_mul(x2, X)
    e3 = hbls.f2_sub(hbls.f2_muli(y2, 2), hbls.f2_muli(x3, 3))
    e5 = hbls.f2_muli(_f2_smul(hbls.f2_mul(x2, z2), px), 3)
    a = x2
    b = y2
    c = hbls.f2_sqr(b)
    t = hbls.f2_sqr(hbls.f2_add(X, b))
    d = hbls.f2_muli(hbls.f2_sub(hbls.f2_sub(t, a), c), 2)
    e = hbls.f2_muli(a, 3)
    ff = hbls.f2_sqr(e)
    x3n = hbls.f2_sub(ff, hbls.f2_muli(d, 2))
    y3n = hbls.f2_sub(
        hbls.f2_mul(e, hbls.f2_sub(d, x3n)), hbls.f2_muli(c, 8)
    )
    z3n = hbls.f2_muli(hbls.f2_mul(Y, Z), 2)
    return _line12(e0, e3, e5), (x3n, y3n, z3n)


def _host_add_step(T, qx, qy, px: int, py: int):
    """Chord line through T and the affine twist point Q at (px, py),
    plus T + Q (mixed addition)."""
    X, Y, Z = T
    z2 = hbls.f2_sqr(Z)
    z3 = hbls.f2_mul(z2, Z)
    hh = hbls.f2_sub(hbls.f2_mul(qx, z2), X)
    r = hbls.f2_sub(hbls.f2_mul(qy, z3), Y)
    zh = hbls.f2_mul(Z, hh)
    e0 = hbls.f2_neg(hbls.f2_mul_xi(_f2_smul(zh, py)))
    e3 = hbls.f2_sub(hbls.f2_mul(qy, zh), hbls.f2_mul(r, qx))
    e5 = _f2_smul(r, px)
    hs = hbls.f2_sqr(hh)
    hc = hbls.f2_mul(hs, hh)
    v = hbls.f2_mul(X, hs)
    x3n = hbls.f2_sub(
        hbls.f2_sub(hbls.f2_sqr(r), hc), hbls.f2_muli(v, 2)
    )
    y3n = hbls.f2_sub(
        hbls.f2_mul(r, hbls.f2_sub(v, x3n)), hbls.f2_mul(Y, hc)
    )
    z3n = hbls.f2_mul(Z, hh)
    return _line12(e0, e3, e5), (x3n, y3n, z3n)


def fast_miller(q: "hbls.PointG2", p: "hbls.PointG1") -> "hbls.Fp12T":
    """f_{|x|, q}(p) up to Fp2-subfield line scalings (final-exp-legal).

    ~20x the oracle Miller's speed (no per-line Fp12 inversion); only
    valid for r-torsion ``q`` (the ate ladder then never meets an
    exceptional case), which every caller guarantees via decode_seal /
    hash_to_g2.
    """
    qx, qy = q
    T = (qx, qy, hbls.F2_ONE)
    f = hbls.F12_ONE
    for bit in _X_BITS_HOST:
        line, T = _host_dbl_step(T, p[0], p[1])
        f = hbls.f12_mul(hbls.f12_sqr(f), line)
        if bit:
            line, T = _host_add_step(T, qx, qy, p[0], p[1])
            f = hbls.f12_mul(f, line)
    return f


def _host_ratio(agg, pk, phash) -> "hbls.Fp12T":
    """miller(S, G1) * miller(H, -PK): the lane's pre-final-exp ratio
    (line-scaled; the scalings die under the shared final exp)."""
    h = hbls.hash_to_g2(bytes(phash))
    return hbls.f12_mul(
        fast_miller(agg, hbls.G1_GEN),
        fast_miller(h, hbls.g1_neg(pk)),
    )


def _host_batch_group(
    entries: List[Tuple[int, "hbls.Fp12T"]],
    exps: List[int],
    lanes: Sequence[Lane],
    out: np.ndarray,
) -> None:
    """Check one product equation over ``entries``; bisect on failure.

    ``entries`` carries (lane index, precomputed ratio); singletons fall
    through to the per-lane oracle (exact verdicts, same as
    BLSAggregateVerifier's bisect floor)."""
    if not entries:
        return
    if len(entries) == 1:
        i, _ratio = entries[0]
        phash, points, pubkeys = lanes[i]
        out[i] = aggregate_check(phash, points, pubkeys)
        return
    acc = hbls.F12_ONE
    for (i, ratio), r in zip(entries, exps):
        acc = hbls.f12_mul(acc, hbls.f12_pow(ratio, r))
    metrics.inc_counter(PAIRING_EQS_KEY)
    # fe(x) == 1 iff fe(inv(x)) == 1, so the negative-parameter
    # inversion the oracle pairing performs is unnecessary here.
    if hbls.final_exponentiation(acc) == hbls.F12_ONE:
        for i, _ratio in entries:
            out[i] = True
        return
    mid = len(entries) // 2
    _host_batch_group(entries[:mid], exps[:mid], lanes, out)
    _host_batch_group(entries[mid:], exps[mid:], lanes, out)


def _host_batch_check(lanes: Sequence[Lane]) -> np.ndarray:
    """Shared-final-exponentiation batch verification on the host tower."""
    out = np.zeros(len(lanes), dtype=bool)
    entries: List[Tuple[int, "hbls.Fp12T"]] = []
    aggs = []
    live_lanes = []
    for i, lane in enumerate(lanes):
        pair = _lane_aggregates(lane)
        if pair is None:
            continue  # oracle semantics: vacuous lane -> False
        aggs.append(pair)
        live_lanes.append(lane)
        entries.append((i, _host_ratio(pair[0], pair[1], lane[0])))
    if not entries:
        return out
    import os

    exps = _fs_exponents(live_lanes, aggs, os.urandom(32))
    _host_batch_group(entries, exps, lanes, out)
    return out


def _pack_lanes_device(lanes: Sequence[Lane], *, dp: int = 1):
    """Pack live lanes for the device kernel; returns (args, live index
    list) — vacuous lanes are excluded (verdict False host-side).

    ``dp``: the mesh's data-parallel extent — the lane bucket is raised
    to at least ``dp`` so the padded lane axis always shards cleanly
    (both are powers of two, so max() is the lcm)."""
    import jax.numpy as jnp

    from ..ops import bls12_381 as dev

    live_idx = []
    sig_pts = []
    h_pts = []
    pk_lists = []
    for i, lane in enumerate(lanes):
        phash, points, pubkeys = lane
        # Vacuity gates only (no hash-length gate — the oracle accepts
        # any message bytes) — the per-lane PUBKEY fold is the kernel's
        # job (_multi_g1_neg_aggregate_stage also derives the
        # cancelled-to-infinity flag, which masks the verdict False
        # exactly like the oracle's pk_agg-is-None case); re-folding it
        # here would serialize ~lanes x committee host G1 adds in front
        # of the one batched dispatch.
        if not points or not pubkeys:
            continue
        pks = [pk for pk in pubkeys if pk is not None]
        if not pks:
            continue
        agg = hbls.aggregate_signatures(list(points))
        if agg is None:
            continue
        live_idx.append(i)
        sig_pts.append(agg)
        h_pts.append(hbls.hash_to_g2(bytes(phash)))
        pk_lists.append(pks)
    if not live_idx:
        return None, []
    from ..ops import bls_fp

    b = max(_bucket(len(live_idx), MULTIPAIR_BUCKETS), dp)
    v = _bucket(max(len(p) for p in pk_lists), MERGE_BUCKETS)
    pad = b - len(live_idx)
    sx = dev.pack_g2_points(sig_pts + [None] * pad)
    hx = dev.pack_g2_points(h_pts + [None] * pad)
    pk_x = np.zeros((b, v, bls_fp.L), dtype=np.int32)
    pk_y = np.zeros((b, v, bls_fp.L), dtype=np.int32)
    pk_live = np.zeros((b, v), dtype=bool)
    for li, pks in enumerate(pk_lists):
        x, y = dev.pack_g1_points(pks + [None] * (v - len(pks)))
        pk_x[li], pk_y[li] = x, y
        pk_live[li, : len(pks)] = True
    lane_live = np.zeros(b, dtype=bool)
    lane_live[: len(live_idx)] = True
    args = (
        jnp.asarray(sx[0]),
        jnp.asarray(sx[1]),
        jnp.asarray(sx[2]),
        jnp.asarray(sx[3]),
        jnp.asarray(hx[0]),
        jnp.asarray(hx[1]),
        jnp.asarray(hx[2]),
        jnp.asarray(hx[3]),
        jnp.asarray(pk_x),
        jnp.asarray(pk_y),
        jnp.asarray(pk_live),
        jnp.asarray(lane_live),
    )
    return args, live_idx


def _device_batch_check(lanes: Sequence[Lane], mesh=None) -> np.ndarray:
    """ONE staged batched dispatch (optionally dp-sharded over ``mesh``)."""
    from ..ops import bls12_381 as dev

    out = np.zeros(len(lanes), dtype=bool)
    dp = mesh.shape["dp"] if mesh is not None else 1
    args, live_idx = _pack_lanes_device(lanes, dp=dp)
    if not live_idx:
        return out
    # args[-1] is the padded lane-live mask — its length is the bucket
    # the dispatch actually compiled for (occupancy denominator).
    with cost_ledger.dispatch_span(
        "bls_multipair_miller",
        route="mesh" if mesh is not None else "device",
        live=len(live_idx),
        padded=int(np.shape(args[-1])[0]),
        site="verify/aggregate.py:_device_batch_check",
    ):
        if mesh is not None:
            ok = _mesh_multi_pairing(mesh)(*args)
        else:
            ok = dev.multi_pairing_check(*args)
        metrics.inc_counter(PAIRING_EQS_KEY, len(live_idx))
        mask = np.asarray(ok, dtype=bool)
    for j, i in enumerate(live_idx):
        out[i] = mask[j]
    return out


# Weak-keyed: a retired mesh (device fault, topology resize) must not be
# pinned for process life by its cached compiled program.
_MESH_MULTIPAIR_CACHE = None


def _mesh_multi_pairing(mesh):
    """dp-sharded multi-pairing: the PR-6 masked-padding seam applied to
    pairing lanes.  Lanes are independent, so the shard_map needs no
    collectives — every input shards on its lane axis, the pubkey table
    rides with its lane, and the verdict vector shards back out.  The
    caller (``_pack_lanes_device(dp=...)``) raises the lane bucket to at
    least dp, so the padded lane axis always shards cleanly."""
    global _MESH_MULTIPAIR_CACHE
    if _MESH_MULTIPAIR_CACHE is None:
        import weakref

        _MESH_MULTIPAIR_CACHE = weakref.WeakKeyDictionary()
    hit = _MESH_MULTIPAIR_CACHE.get(mesh)
    if hit is not None:
        return hit

    import jax
    from jax.sharding import PartitionSpec as P

    from ..ops import bls12_381 as dev
    from ..parallel.mesh import shard_map

    lane = P("dp")

    def step(*args):
        return dev.multi_pairing_check(*args)

    fn = jax.jit(
        shard_map(
            step,
            mesh=mesh,
            in_specs=(lane,) * 12,
            out_specs=lane,
            check_vma=False,
        )
    )
    _MESH_MULTIPAIR_CACHE[mesh] = fn
    return fn


def multi_aggregate_check(
    lanes: Sequence[Lane], *, route: str = "host", mesh=None
) -> np.ndarray:
    """Verify MANY aggregate equations as one batched operation.

    One logical multi-pairing dispatch per call (the dispatch-count
    contract block-sync pins: a whole catch-up range is ONE call);
    ``route`` picks the engine:

    * ``"python"`` — the per-lane :func:`aggregate_check` oracle loop
      (the semantics source of truth, one pairing equation per lane);
    * ``"host"`` — small-exponents batch on the host tower: ONE final
      exponentiation per batch, bisect-to-oracle on failure;
    * ``"device"`` — the staged batched kernel, one verdict per lane;
    * ``"mesh"`` — the device kernel dp-sharded over ``mesh``.

    Returns per-lane verdicts bit-identical to the python oracle (the
    host route's 2^-64 batch term resolves through the oracle on any
    rejection — see the module docstring).
    """
    lanes = list(lanes)
    metrics.inc_counter(MULTIPAIR_DISPATCHES_KEY)
    metrics.inc_counter(MULTIPAIR_LANES_KEY, len(lanes))
    with trace.span("verify.multipair", lanes=len(lanes), route=route):
        if not lanes:
            return np.zeros(0, dtype=bool)
        # Host/python lanes are never padded, so occupancy is 1.0 by
        # construction; the device/mesh routes record inside
        # _device_batch_check where the padded bucket is known.  ONE
        # ledger program family either way — the route says which engine
        # served the lanes, the program keys the attribution.
        if route == "python":
            with cost_ledger.dispatch_span(
                "bls_multipair_miller",
                route="python",
                live=len(lanes),
                padded=len(lanes),
                site="verify/aggregate.py:multi_aggregate_check",
            ):
                return np.asarray(
                    [
                        aggregate_check(phash, points, pubkeys)
                        for phash, points, pubkeys in lanes
                    ],
                    dtype=bool,
                )
        if route == "host":
            with cost_ledger.dispatch_span(
                "bls_multipair_miller",
                route="host",
                live=len(lanes),
                padded=len(lanes),
                site="verify/aggregate.py:multi_aggregate_check",
            ):
                return _host_batch_check(lanes)
        if route == "device":
            return _device_batch_check(lanes)
        if route == "mesh":
            if mesh is None:
                raise ValueError("route='mesh' requires a mesh")
            return _device_batch_check(lanes, mesh=mesh)
        raise ValueError(f"unknown multi-pairing route {route!r}")


class MultiPairVerifier:
    """Route-laddered multi-pairing with breaker-style degradation.

    Preference order: ``mesh`` (when a mesh was given) -> ``device``
    (when ``device=True``) -> ``host`` (batched) -> ``python`` (the
    per-lane oracle).  A fault on any rung demotes PAST it for the rest
    of the verifier's life (the
    :class:`~go_ibft_tpu.verify.batch.ResilientBatchVerifier` posture:
    verdicts never change across rungs, only cost does), with the
    transition counted and traced.
    """

    _LADDER = ("mesh", "device", "host", "python")

    def __init__(
        self,
        *,
        device: bool = False,
        mesh=None,
        host_batch: bool = True,
        logger=None,
    ) -> None:
        self.mesh = mesh
        self._log = logger
        self._lock = threading.Lock()
        rungs = []
        if mesh is not None:
            # An explicitly-attached mesh IS the request for the sharded
            # route — it must not silently depend on the device flag.
            rungs.append("mesh")
        if device:
            rungs.append("device")
        if host_batch:
            rungs.append("host")
        rungs.append("python")
        self._rungs = tuple(rungs)
        self._level = 0
        self.dispatches = 0
        self.lanes = 0
        self.demotions = 0

    @property
    def route(self) -> str:
        return self._rungs[self._level]

    def check(self, lanes: Sequence[Lane]) -> np.ndarray:
        """Per-lane verdicts through the highest live rung; a rung fault
        demotes and re-verifies on the next one (never raises past the
        python oracle, which cannot fault)."""
        lanes = list(lanes)
        with self._lock:
            self.dispatches += 1
            self.lanes += len(lanes)
            level = self._level
        while True:
            route = self._rungs[level]
            try:
                return multi_aggregate_check(
                    lanes, route=route, mesh=self.mesh
                )
            except Exception as err:  # noqa: BLE001 - demote, retry below
                if route == "python":
                    raise
                with self._lock:
                    level = max(level + 1, self._level + 1)
                    level = min(level, len(self._rungs) - 1)
                    self._level = level
                    self.demotions += 1
                if self._log:
                    self._log.error(
                        f"multi-pairing rung {route!r} demoted to "
                        f"{self._rungs[level]!r}",
                        err,
                    )
                trace.instant(
                    "verify.multipair_demoted", to=self._rungs[level]
                )

    def stats(self) -> dict:
        with self._lock:
            return {
                "route": self._rungs[self._level],
                "rungs": self._rungs,
                "dispatches": self.dispatches,
                "lanes": self.lanes,
                "demotions": self.demotions,
                "lanes_per_dispatch": (
                    round(self.lanes / self.dispatches, 2)
                    if self.dispatches
                    else None
                ),
            }
