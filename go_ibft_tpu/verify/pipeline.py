"""Pipelined verification data plane: overlap host packing with device work.

JAX dispatch is asynchronous: a jitted call returns device futures
immediately and the host only blocks when it *reads* a result.  The
sequential shape ``pack -> dispatch -> read -> pack -> ...`` throws that
away — the host sits idle while the device runs, then the device sits idle
while the host packs the next batch.  :class:`VerifyPipeline` is the
double-buffered executor that keeps both sides busy: it packs item ``N+1``
on the host while the device executes item ``N``, reading results back only
when the in-flight window (``depth``, default 2 = classic double buffering)
is full.  The same executor drives a thread-pool "device" for the
host-routed benchmark variants (the native C++ verifier releases the GIL,
so host packing genuinely overlaps native verification).

Buffer discipline (measured, not assumed):

* **Host zero-copy packing.**  The packers build each batch in one flat
  staging buffer with ``frombuffer`` views and vectorized padding — no
  per-message bytearray churn (see ``ops/keccak.py::pack_messages``).
* **Device-resident validator tables.**  ``DeviceBatchVerifier`` pins each
  height's packed table (and quorum-power vectors) on device once and
  reuses the handle across every dispatch of the height — re-uploading
  them per call was a per-dispatch host->device copy for data that never
  changes within a height.
* **Buffer donation stays REJECTED** for the verification kernels (the
  PR-1 finding holds for the pipelined path too): XLA only aliases a
  donated input to an output of matching shape/dtype, and these programs
  map large packed inputs — ``(B, nb, 17, 2)`` keccak blocks, ``(B, 20)``
  limb vectors — to tiny ``(B,)`` masks.  Nothing aliases, so
  ``donate_argnums`` would perform no reuse and emit a warning per
  compile; the per-item inputs are freed by refcount right after dispatch
  regardless.

:class:`PackCache` is the second half of the data plane: a per-message
pack cache (message identity -> packed sender lane) with round-scoped
oldest-round-first eviction, mirroring the engine's seal-verdict cache, so
engine wakeups that re-verify the same messages (certificate validation
re-runs per round-change wakeup) never re-encode or re-limb a message they
already packed.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import ledger as cost_ledger
from ..obs import trace
from ..utils import metrics

__all__ = [
    "PACK_MS_KEY",
    "READBACK_WAIT_MS_KEY",
    "OVERLAP_EFFICIENCY_KEY",
    "BREAKER_LEVEL_KEY",
    "BREAKER_TRANSITIONS_KEY",
    "CircuitBreaker",
    "PipelineReport",
    "VerifyPipeline",
    "SenderPack",
    "PackCache",
    "observe_overlap_efficiency",
]

# First-class packing-attribution metric keys (satellite: pack_ms and
# overlap efficiency are round evidence, not debug prints).
PACK_MS_KEY = ("go-ibft", "pipeline", "pack_ms")
READBACK_WAIT_MS_KEY = ("go-ibft", "pipeline", "readback_wait_ms")
OVERLAP_EFFICIENCY_KEY = ("go-ibft", "pipeline", "overlap_efficiency")

# Degradation-ladder metric keys: the breaker's active level as a gauge and
# every transition as a histogram sample (value = the level transitioned TO),
# so ``metrics.summarize(BREAKER_TRANSITIONS_KEY)`` shows transition counts
# without a scrape sink.  Per-edge counters ride
# ``("go-ibft", "breaker", <demote|restore|probe|probe_failed>)``.
BREAKER_LEVEL_KEY = ("go-ibft", "breaker", "level")
BREAKER_TRANSITIONS_KEY = ("go-ibft", "breaker", "transitions")


class CircuitBreaker:
    """K-consecutive-fault demotion ladder with cooldown re-probe.

    ``levels`` names the rungs fastest-first (e.g. ``("device", "host",
    "python")``); traffic starts at level 0.  After ``k`` consecutive
    recorded faults at the active level the breaker demotes one rung; after
    ``cooldown_s`` seconds at a demoted level :meth:`acquire` offers the
    next-faster rung once as a *probe* — a successful probe restores one
    rung, a failed probe restarts the cooldown.  Restoration is therefore
    stepwise: a ladder that fell two rungs climbs back one cooldown at a
    time, each step proven by live traffic.

    Thread-safe; ``clock`` is injectable so tests control the cooldown.
    Every transition is counted in :mod:`go_ibft_tpu.utils.metrics`.
    """

    def __init__(
        self,
        levels: Sequence[str] = ("device", "host", "python"),
        *,
        k: int = 3,
        cooldown_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if not levels:
            raise ValueError("breaker needs at least one level")
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.levels = tuple(levels)
        self.k = k
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._level = 0
        self._faults = 0
        self._demoted_at: Optional[float] = None
        self._probing = False

    @property
    def level(self) -> int:
        with self._lock:
            return self._level

    @property
    def level_name(self) -> str:
        return self.levels[self.level]

    def acquire(self) -> Tuple[int, bool]:
        """Pick the level for one drain: ``(level, is_probe)``.

        At a demoted level past its cooldown, returns the next-faster level
        with ``is_probe=True`` (exactly one in-flight probe at a time; the
        caller MUST answer with :meth:`record_success` or
        :meth:`record_fault` for that level).
        """
        with self._lock:
            if (
                self._level > 0
                and not self._probing
                and self._demoted_at is not None
                and self._clock() - self._demoted_at >= self.cooldown_s
            ):
                self._probing = True
                metrics.inc_counter(("go-ibft", "breaker", "probe"))
                return self._level - 1, True
            return self._level, False

    def record_success(self, level: int) -> None:
        """A drain at ``level`` completed without a fault."""
        with self._lock:
            if self._probing and level == self._level - 1:
                self._probing = False
                self._transition(level, "restore")
            elif level == self._level:
                self._faults = 0

    def abort_probe(self, level: int) -> None:
        """Release a probe whose drain never exercised the probed rung
        (input poison aborted it pre-dispatch, or the work was routed to a
        different rung): the ladder stays demoted, no fault is recorded,
        and — the cooldown having already elapsed — the next drain is
        offered a fresh probe.  Recording success instead would restore
        the ladder on no evidence; recording a fault would punish a rung
        that never ran.  No-op unless ``level`` is the pending probe."""
        with self._lock:
            if self._probing and level == self._level - 1:
                self._probing = False

    def record_fault(self, level: int) -> bool:
        """A drain at ``level`` faulted; returns True when this demoted."""
        with self._lock:
            if self._probing and level == self._level - 1:
                # Probe failed: stay demoted, restart the cooldown clock.
                self._probing = False
                self._demoted_at = self._clock()
                metrics.inc_counter(("go-ibft", "breaker", "probe_failed"))
                return False
            if level != self._level:
                return False
            self._faults += 1
            if self._faults >= self.k and self._level + 1 < len(self.levels):
                self._transition(self._level + 1, "demote")
                return True
            return False

    def _transition(self, new_level: int, kind: str) -> None:
        # Callers hold self._lock.
        self._level = new_level
        self._faults = 0
        self._demoted_at = self._clock() if new_level > 0 else None
        metrics.inc_counter(("go-ibft", "breaker", kind))
        metrics.observe(BREAKER_TRANSITIONS_KEY, float(new_level))
        metrics.set_gauge(BREAKER_LEVEL_KEY, float(new_level))
        trace.instant(
            "breaker.transition", kind=kind, level=self.levels[new_level]
        )


def observe_overlap_efficiency(serial_s: float, pipelined_s: float) -> float:
    """Record and return the overlap efficiency of a pipelined run.

    ``1 - pipelined/serial`` — the fraction of the serial wall-clock the
    pipeline hid by overlapping host packing with device execution
    (0 = no overlap, 0.5 = packing fully hidden behind an equally-long
    device leg).  Clamped at 0 so measurement noise never reports a
    negative efficiency.
    """
    eff = 0.0 if serial_s <= 0 else max(0.0, 1.0 - pipelined_s / serial_s)
    metrics.observe(OVERLAP_EFFICIENCY_KEY, eff)
    return eff


@dataclass
class PipelineReport:
    """One pipelined run's results + host-side time attribution.

    ``pack_s``/``dispatch_s``/``wait_s`` partition the host thread's time:
    packing, (asynchronous) dispatch calls, and blocking on device results.
    Overlap shows up as ``wait_s`` shrinking — device time hidden behind
    packing never blocks the host.  ``wall_s`` is end-to-end.
    """

    results: List[Any]
    pack_s: float
    dispatch_s: float
    wait_s: float
    wall_s: float


class VerifyPipeline:
    """Double-buffered pack/dispatch executor over an async device.

    ``depth`` bounds the number of dispatched-but-unread items (2 = double
    buffering: while item N executes, item N+1 packs and dispatches; N is
    read back only when N+2 wants its slot).  The executor is agnostic to
    what "dispatch" means — a jitted JAX call (returns device futures), a
    ``ThreadPoolExecutor.submit`` (host-routed bench variants), or a test
    stub — as long as it returns quickly and ``readback`` blocks until the
    handle's work is done.
    """

    def __init__(
        self,
        depth: int = 2,
        ledger_key: Optional[Tuple[str, str]] = None,
    ):
        if depth < 1:
            raise ValueError(f"pipeline depth must be >= 1, got {depth}")
        self.depth = depth
        # (program, route) for the cost ledger: the async dispatch seam
        # records the launch but cannot see its block-until-ready wall —
        # the pipeline owns the readback wait, so it attributes that leg
        # (no-op while the ledger is disabled).
        self.ledger_key = ledger_key

    def run(
        self,
        items: Sequence[Any],
        pack: Callable[[Any], Any],
        dispatch: Callable[[Any], Any],
        readback: Callable[[Any], Any],
    ) -> PipelineReport:
        """Run ``readback(dispatch(pack(item)))`` for every item, pipelined.

        Results are returned in item order.  Exceptions propagate after all
        in-flight work is drained (a dispatched batch is never abandoned
        mid-flight — device buffers must be consumed).
        """
        results: List[Any] = [None] * len(items)
        inflight: Deque[Tuple[int, Any]] = deque()
        pack_s = dispatch_s = wait_s = 0.0
        t_start = time.perf_counter()

        def _finish_oldest() -> None:
            nonlocal wait_s
            idx, handle = inflight.popleft()
            t0 = time.perf_counter()
            with trace.span("pipeline.readback", item=idx):
                results[idx] = readback(handle)
            dt = time.perf_counter() - t0
            wait_s += dt
            metrics.observe(READBACK_WAIT_MS_KEY, dt * 1e3)
            if self.ledger_key is not None:
                cost_ledger.add_device_ms(
                    self.ledger_key[0], self.ledger_key[1], dt * 1e3
                )

        try:
            for i, item in enumerate(items):
                t0 = time.perf_counter()
                with trace.span("pipeline.pack", item=i):
                    packed = pack(item)
                dt = time.perf_counter() - t0
                pack_s += dt
                metrics.observe(PACK_MS_KEY, dt * 1e3)

                t0 = time.perf_counter()
                with trace.span("pipeline.dispatch", item=i):
                    inflight.append((i, dispatch(packed)))
                dispatch_s += time.perf_counter() - t0

                while len(inflight) >= self.depth:
                    _finish_oldest()
        finally:
            while inflight:
                _finish_oldest()
        return PipelineReport(
            results=results,
            pack_s=pack_s,
            dispatch_s=dispatch_s,
            wait_s=wait_s,
            wall_s=time.perf_counter() - t_start,
        )


@dataclass
class SenderPack:
    """One message's packed sender lane (everything per-message about it).

    ``payload`` is the canonical ``payload_no_sig`` encoding; the limb rows
    and word vectors are exactly the lane the batch packers would rebuild.
    """

    payload: bytes
    r_limbs: np.ndarray  # (nlimbs,) int32
    s_limbs: np.ndarray  # (nlimbs,) int32
    v: int
    sender_words: np.ndarray  # (5,) uint32


class PackCache:
    """Message identity -> :class:`SenderPack`, round-scoped eviction.

    Keyed on the message *object* (``id`` + a weak reference so a dead
    object's recycled id can never alias a stale entry) and guarded by a
    ``(sender, signature)`` token so in-place mutation of either field
    (tests and Byzantine harnesses do this) turns the entry into a miss.
    The payload itself is NOT re-checked on hit — the cache contract is the
    message-store contract: stored messages are replaced, never mutated
    (``messages/store.py`` dedup is last-write-wins on whole objects), and
    any same-object payload mutation also breaks the signature it was
    packed with, which ingress already verified.

    Eviction mirrors the engine's seal-verdict cache: entries are tagged
    with the round current at pack time (``note_round``); on cap pressure
    whole dead rounds evict before any live round gives up anything, and
    within a live round eviction is FIFO.  ``clear()`` runs per sequence.
    Thread-safe (ingress may pack from transport threads).

    **Owner scoping (ISSUE 8 satellite).**  A cache shared by several
    engines (one ladder serving N chains) must not let one engine's
    lifecycle rotate or reset another's live state: every entry is tagged
    with an *owner* — the thread-local label installed by
    :meth:`owned` while a scoped verify call packs — and ``note_round``
    / ``clear`` take an optional ``owner`` so a rotation retags, and a
    sequence reset drops, ONLY that owner's entries.  The legacy
    single-engine calls (no owner) keep their process-wide meaning: the
    default owner is ``""`` for ``note_round``, and an ownerless
    ``clear()`` still wipes everything (the sole-owner posture).  Each
    owner's live round is protected from cap-pressure eviction
    independently; dead rounds of any owner evict first, oldest round
    first.
    """

    def __init__(self, cap: int = 8192):
        self._lock = threading.RLock()
        # (owner, round) -> {id(msg) -> (weakref, token, pack)}
        self._by_round: Dict[
            Tuple[str, int],
            Dict[int, Tuple[Any, Tuple[bytes, bytes], SenderPack]],
        ] = {}
        self._index: Dict[int, Tuple[str, int]] = {}  # id(msg) -> tag
        self._count = 0
        self._rounds: Dict[str, int] = {"": 0}  # owner -> live round
        self._tl = threading.local()
        self._cap = cap
        self.hits = 0
        self.misses = 0

    @property
    def _round(self) -> int:
        """Default owner's live round (single-engine posture)."""
        with self._lock:
            return self._rounds.get("", 0)

    @contextmanager
    def owned(self, owner: str):
        """Attribute stores on THIS thread to ``owner`` while active (the
        :class:`~go_ibft_tpu.verify.batch.EngineScope` verify wrapper)."""
        prev = getattr(self._tl, "owner", "")
        self._tl.owner = owner
        try:
            yield self
        finally:
            self._tl.owner = prev

    def note_round(self, round_: int, owner: str = "") -> None:
        """Tag ``owner``'s subsequent stores with ``round_`` (engine round
        advances).  Only that owner's eviction ordering moves."""
        with self._lock:
            self._rounds[owner] = round_

    def clear(self, owner: Optional[str] = None) -> None:
        """Drop cached packs: all of them (``owner=None`` — the
        single-engine sequence reset) or one owner's only."""
        with self._lock:
            if owner is None:
                self._by_round.clear()
                self._index.clear()
                self._count = 0
                self._rounds = {"": 0}
                return
            for tag in [t for t in self._by_round if t[0] == owner]:
                bucket = self._by_round.pop(tag)
                for mid in bucket:
                    del self._index[mid]
                self._count -= len(bucket)
            self._rounds.pop(owner, None)
            self._rounds.setdefault("", 0)

    def __len__(self) -> int:
        with self._lock:
            return self._count

    @property
    def cap(self) -> int:
        return self._cap

    def lookup(self, msg) -> Optional[SenderPack]:
        mid = id(msg)
        with self._lock:
            tag = self._index.get(mid)
            if tag is None:
                self.misses += 1
                return None
            wref, token, pack = self._by_round[tag][mid]
        if wref() is not msg or token != (msg.sender, msg.signature):
            with self._lock:
                self.misses += 1
            return None
        with self._lock:
            self.hits += 1
        return pack

    def store(self, msg, pack: SenderPack) -> None:
        mid = id(msg)
        try:
            wref = weakref.ref(msg, lambda _r, mid=mid: self._drop(mid))
        except TypeError:  # not weak-referenceable; skip caching
            return
        with self._lock:
            self._remove(mid)
            owner = getattr(self._tl, "owner", "")
            self._rounds.setdefault(owner, 0)
            tag = (owner, self._rounds[owner])
            self._by_round.setdefault(tag, {})[mid] = (
                wref,
                (msg.sender, msg.signature),
                pack,
            )
            self._index[mid] = tag
            self._count += 1
            self._evict()

    def evict(self, msg) -> None:
        """Drop a message's cached pack (degraded-mode quarantine hook).

        A quarantined lane's pack must not outlive the quarantine: if the
        sender corrects and re-sends, the verifier must re-pack from the
        fresh bytes rather than be served the lane that was just condemned.
        No-op for messages never cached."""
        with self._lock:
            self._remove(id(msg))

    # -- internals ------------------------------------------------------

    def _drop(self, mid: int) -> None:
        """Weakref death callback: the object is gone, so its id may be
        recycled — the entry must go with it."""
        with self._lock:
            self._remove(mid)

    def _remove(self, mid: int) -> None:
        tag = self._index.pop(mid, None)
        if tag is None:
            return
        bucket = self._by_round.get(tag)
        if bucket is not None and bucket.pop(mid, None) is not None:
            self._count -= 1
            if not bucket:
                del self._by_round[tag]

    def _evict(self) -> None:
        while self._count > self._cap and self._by_round:
            # EVERY owner's live round is protected equally: dead rounds
            # (any owner, oldest round first) evict whole; only when no
            # dead round remains does the oldest live round shed FIFO.
            live = {(o, r) for o, r in self._rounds.items()}
            dead = [t for t in self._by_round if t not in live]
            pool = dead if dead else list(self._by_round)
            oldest = min(pool, key=lambda t: (t[1], t[0]))
            bucket = self._by_round[oldest]
            if not dead:
                mid = next(iter(bucket))
                del bucket[mid]
                del self._index[mid]
                self._count -= 1
                if not bucket:
                    del self._by_round[oldest]
            else:
                for mid in bucket:
                    del self._index[mid]
                self._count -= len(bucket)
                del self._by_round[oldest]
