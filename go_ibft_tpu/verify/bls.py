"""BLS12-381 aggregate committed-seal verification (BatchVerifier-shaped).

BASELINE.md config #4: instead of one ECDSA recovery per COMMIT seal, the
whole quorum is certified with ONE pairing equation —
``e(G1, sum(sig_i)) == e(sum(pk_i), H2(proposal_hash))`` — so the COMMIT
phase cost is two masked point aggregations plus a validator-count-
independent pairing check.

Shape of the integration (same seam as the ECDSA path,
:class:`go_ibft_tpu.core.backend.BatchVerifier`): ``verify_committed_seals``
returns a per-seal boolean mask.  Aggregate verification is all-or-nothing,
so the fast path answers "all valid"; on failure the batch is BISECTED —
halves re-aggregate-verify independently, so ``k`` Byzantine seals in an
``n``-seal quorum cost ``O(k log n)`` pairing equations instead of ``n``
(the same quarantine posture as
:class:`~go_ibft_tpu.verify.batch.ResilientBatchVerifier`'s poison-batch
bisection, applied to cryptographic rather than operational faults).

Seal wire format: 192 bytes ``x0 || x1 || y0 || y1`` (uncompressed G2,
48-byte big-endian field elements).  Validator registry maps the 20-byte
consensus address to the BLS G1 public key.

Security posture (ISSUE 7 satellite): :func:`decode_seal` rejects G2
points outside the r-torsion subgroup — the twist's full group order is
``r * h2`` with a composite cofactor, so an on-curve check alone admits
small-subgroup points whose contribution to an aggregate is confined to a
tiny group (a classic malleability / key-leak primitive).  The check is
``[r]P == O`` (the subgroup definition), LRU-cached by seal bytes because
the same 192 bytes recur across drains and rounds.
"""

from __future__ import annotations

import time
from functools import lru_cache
from typing import Callable, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..crypto import bls as hbls
from ..messages.helpers import CommittedSeal
from ..obs import ledger as cost_ledger
from ..utils import metrics

BLS_SEAL_BYTES = 192
_FE = 48  # bytes per Fp element

# One count per aggregate pairing EQUATION checked (host or device route);
# the bench's config #9 reads this to report what a drain actually cost.
PAIRING_EQS_KEY = ("go-ibft", "bls", "pairing_equations")

BLSKeySource = Callable[[int], Mapping[bytes, "hbls.PointG1"]]


def encode_seal(point: "hbls.PointG2") -> bytes:
    """G2 point -> 192-byte seal (x0 || x1 || y0 || y1, big-endian)."""
    if point is None:
        raise ValueError("cannot encode the point at infinity as a seal")
    (x0, x1), (y0, y1) = point
    return b"".join(v.to_bytes(_FE, "big") for v in (x0, x1, y0, y1))


@lru_cache(maxsize=8192)
def _decode_seal_cached(blob: bytes) -> Optional["hbls.PointG2"]:
    x0, x1, y0, y1 = (
        int.from_bytes(blob[i * _FE : (i + 1) * _FE], "big") for i in range(4)
    )
    if max(x0, x1, y0, y1) >= hbls.P:
        return None
    pt = ((x0, x1), (y0, y1))
    if not hbls.g2_on_curve(pt):
        return None
    # r-torsion membership: the on-curve check admits points of any order
    # dividing #E'(Fp2) = r * h2; a seal living in the h2 part would pass
    # curve validation yet aggregate maliciously.  [r]P == O is the
    # definition of membership (a ~255-bit ladder, ~10 ms host — absorbed
    # by this cache since seal bytes recur across drains).
    if hbls.g2_mul(hbls.R, pt) is not None:
        return None
    return pt


def decode_seal(blob: bytes) -> Optional["hbls.PointG2"]:
    """192-byte seal -> G2 point in the r-torsion subgroup, else None.

    Rejects: wrong length, non-canonical field elements, off-curve
    points, and on-curve points outside the r-order subgroup.
    """
    if len(blob) != BLS_SEAL_BYTES:
        return None
    return _decode_seal_cached(bytes(blob))


def aggregate_check(
    proposal_hash: bytes,
    points: Sequence["hbls.PointG2"],
    pubkeys: Sequence["hbls.PointG1"],
    *,
    device: bool = False,
) -> bool:
    """ONE pairing equation over a seal set (host oracle or device route).

    Shared by :class:`BLSAggregateVerifier`, the quorum-certificate
    verifier (:mod:`go_ibft_tpu.crypto.quorum_cert`) and the bench, so
    every aggregate consumer counts pairings through the same metric and
    can never drift in accept-set semantics.
    """
    metrics.inc_counter(PAIRING_EQS_KEY)
    if device:
        return _aggregate_check_device(proposal_hash, points, pubkeys)
    agg = hbls.aggregate_signatures(points)
    return hbls.aggregate_verify(list(pubkeys), proposal_hash, agg)


def _aggregate_check_device(proposal_hash, points, pubkeys) -> bool:
    import jax.numpy as jnp

    from ..ops import bls12_381 as dev

    n = len(points)
    v = 1
    while v < n:
        v *= 2
    v = max(v, 2)
    pk_x, pk_y = dev.pack_g1_points(list(pubkeys) + [None] * (v - n))
    sx0, sx1, sy0, sy1 = dev.pack_g2_points(list(points) + [None] * (v - n))
    h = hbls.hash_to_g2(proposal_hash)
    hx0, hx1, hy0, hy1 = dev.pack_g2_points([h])
    live = np.zeros(v, dtype=bool)
    live[:n] = True
    t0 = time.perf_counter()
    ok = dev.aggregate_verify_commit(
        jnp.asarray(pk_x),
        jnp.asarray(pk_y),
        jnp.asarray(sx0),
        jnp.asarray(sx1),
        jnp.asarray(sy0),
        jnp.asarray(sy1),
        jnp.asarray(hx0[0]),
        jnp.asarray(hx1[0]),
        jnp.asarray(hy0[0]),
        jnp.asarray(hy1[0]),
        jnp.asarray(live),
    )
    out = bool(np.asarray(ok))
    dt_ms = (time.perf_counter() - t0) * 1e3
    metrics.observe(("go-ibft", "device", "bls_aggregate_ms"), dt_ms)
    # The dispatch record landed inside aggregate_verify_commit
    # (block=False — it returns a device future); THIS is the seam that
    # blocks on the verdict, so it attributes the pairing's wall time.
    cost_ledger.add_device_ms("bls_aggregate_verify", "device", dt_ms)
    return out


class BLSAggregateVerifier:
    """Aggregate-first committed-seal verifier.

    ``bls_keys_for_height`` maps height -> {consensus address: G1 pubkey}.
    The device path (:func:`go_ibft_tpu.ops.bls12_381.aggregate_verify_commit`)
    runs when ``device=True``; the host oracle pairing runs otherwise —
    identical accept-sets either way (conformance tests assert it).

    Unhappy path: aggregate-then-bisect.  A failing aggregate splits in
    half and each half re-verifies as its own aggregate; a single seal
    that still fails is condemned.  ``k`` bad seals therefore cost
    ``O(k log n)`` pairing equations — the byzantine-free round stays ONE.
    """

    def __init__(self, bls_keys_for_height: BLSKeySource, device: bool = True):
        self._keys = bls_keys_for_height
        self._device = device

    # -- the one-pairing happy path ------------------------------------

    def _aggregate_check(
        self,
        proposal_hash: bytes,
        points: Sequence["hbls.PointG2"],
        pubkeys: Sequence["hbls.PointG1"],
    ) -> bool:
        return aggregate_check(
            proposal_hash, points, pubkeys, device=self._device
        )

    # -- the bisect unhappy path ---------------------------------------

    def _bisect(
        self,
        proposal_hash: bytes,
        decoded: List[Tuple[int, "hbls.PointG2", "hbls.PointG1"]],
        out: np.ndarray,
    ) -> None:
        """Pinpoint bad seals by recursive aggregate halving.

        Called AFTER the whole-set aggregate failed, so the set is known
        to contain at least one bad seal.  Verdicts land in ``out``;
        sub-aggregates that pass mark their whole half True in one
        equation.

        Soundness note: "True" means *member of a verifying aggregate* —
        the same statement the happy path proves for the full set.  Two
        colluding signers whose seal errors cancel verify jointly at
        EVERY granularity their seals share a sub-aggregate (including
        the happy path itself); this is inherent to aggregate signatures
        and quorum-sound, because each claimed signer's registered (PoP-
        checked) pubkey participates in the equation.  For non-colluding
        corruption (bit flips, wrong-hash seals) the verdicts are
        bit-identical to the per-seal oracle, which the conformance
        tests pin.
        """
        if len(decoded) == 1:
            i, pt, pk = decoded[0]
            out[i] = aggregate_check(
                proposal_hash, [pt], [pk], device=self._device
            )
            return
        mid = len(decoded) // 2
        for half in (decoded[:mid], decoded[mid:]):
            if len(half) == 1:
                # one equation suffices; a failed pre-check would only be
                # re-checked by the recursion
                self._bisect(proposal_hash, half, out)
            elif self._aggregate_check(
                proposal_hash, [p for _, p, _ in half], [k for _, _, k in half]
            ):
                out[np.asarray([i for i, _, _ in half])] = True
            else:
                self._bisect(proposal_hash, half, out)

    # -- BatchVerifier-shaped seal interface ---------------------------

    def verify_committed_seals(
        self, proposal_hash: bytes, seals: Sequence[CommittedSeal], height: int
    ) -> np.ndarray:
        out = np.zeros(len(seals), dtype=bool)
        if not seals or len(proposal_hash) != 32:
            return out
        keys = self._keys(height)
        decoded: list[Tuple[int, "hbls.PointG2", "hbls.PointG1"]] = []
        for i, seal in enumerate(seals):
            pk = keys.get(seal.signer)
            if pk is None:
                continue  # not a validator at this height
            pt = decode_seal(seal.signature)
            if pt is None:
                continue  # malformed / off-curve / small-subgroup
            decoded.append((i, pt, pk))
        if not decoded:
            return out
        idxs = [i for i, _, _ in decoded]
        points = [p for _, p, _ in decoded]
        pks = [k for _, _, k in decoded]
        if self._aggregate_check(proposal_hash, points, pks):
            out[np.asarray(idxs)] = True
            return out
        # Unhappy path (requires an actively byzantine signer inside the
        # candidate set): aggregate-then-bisect — O(k log n) equations for
        # k bad seals instead of n per-seal pairings.
        self._bisect(proposal_hash, decoded, out)
        return out
