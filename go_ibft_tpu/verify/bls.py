"""BLS12-381 aggregate committed-seal verification (BatchVerifier-shaped).

BASELINE.md config #4: instead of one ECDSA recovery per COMMIT seal, the
whole quorum is certified with ONE pairing equation —
``e(G1, sum(sig_i)) == e(sum(pk_i), H2(proposal_hash))`` — so the COMMIT
phase cost is two masked point aggregations plus a validator-count-
independent pairing check.

Shape of the integration (same seam as the ECDSA path,
:class:`go_ibft_tpu.core.backend.BatchVerifier`): ``verify_committed_seals``
returns a per-seal boolean mask.  Aggregate verification is all-or-nothing,
so the fast path answers "all valid"; on failure it falls back to
per-seal host verification to pinpoint the bad lanes (the standard
aggregate-then-bisect trade: the happy path — byzantine-free rounds — is
one pairing).

Seal wire format: 192 bytes ``x0 || x1 || y0 || y1`` (uncompressed G2,
48-byte big-endian field elements).  Validator registry maps the 20-byte
consensus address to the BLS G1 public key.
"""

from __future__ import annotations

import time
from typing import Callable, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..crypto import bls as hbls
from ..messages.helpers import CommittedSeal
from ..utils import metrics

BLS_SEAL_BYTES = 192
_FE = 48  # bytes per Fp element

BLSKeySource = Callable[[int], Mapping[bytes, "hbls.PointG1"]]


def encode_seal(point: "hbls.PointG2") -> bytes:
    """G2 point -> 192-byte seal (x0 || x1 || y0 || y1, big-endian)."""
    if point is None:
        raise ValueError("cannot encode the point at infinity as a seal")
    (x0, x1), (y0, y1) = point
    return b"".join(v.to_bytes(_FE, "big") for v in (x0, x1, y0, y1))


def decode_seal(blob: bytes) -> Optional["hbls.PointG2"]:
    """192-byte seal -> G2 point, or None when malformed / off-curve."""
    if len(blob) != BLS_SEAL_BYTES:
        return None
    x0, x1, y0, y1 = (
        int.from_bytes(blob[i * _FE : (i + 1) * _FE], "big") for i in range(4)
    )
    if max(x0, x1, y0, y1) >= hbls.P:
        return None
    pt = ((x0, x1), (y0, y1))
    if not hbls.g2_on_curve(pt):
        return None
    return pt


class BLSAggregateVerifier:
    """Aggregate-first committed-seal verifier.

    ``bls_keys_for_height`` maps height -> {consensus address: G1 pubkey}.
    The device path (:func:`go_ibft_tpu.ops.bls12_381.aggregate_verify_commit`)
    runs when ``device=True``; the host oracle pairing runs otherwise —
    identical accept-sets either way (conformance tests assert it).
    """

    def __init__(self, bls_keys_for_height: BLSKeySource, device: bool = True):
        self._keys = bls_keys_for_height
        self._device = device

    # -- the one-pairing happy path ------------------------------------

    def _aggregate_check(
        self,
        proposal_hash: bytes,
        points: Sequence["hbls.PointG2"],
        pubkeys: Sequence["hbls.PointG1"],
    ) -> bool:
        if self._device:
            return self._aggregate_check_device(proposal_hash, points, pubkeys)
        agg = hbls.aggregate_signatures(points)
        return hbls.aggregate_verify(list(pubkeys), proposal_hash, agg)

    def _aggregate_check_device(
        self, proposal_hash, points, pubkeys
    ) -> bool:
        import jax.numpy as jnp

        from ..ops import bls12_381 as dev

        n = len(points)
        v = 1
        while v < n:
            v *= 2
        v = max(v, 2)
        pk_x, pk_y = dev.pack_g1_points(list(pubkeys) + [None] * (v - n))
        sx0, sx1, sy0, sy1 = dev.pack_g2_points(
            list(points) + [None] * (v - n)
        )
        h = hbls.hash_to_g2(proposal_hash)
        hx0, hx1, hy0, hy1 = dev.pack_g2_points([h])
        live = np.zeros(v, dtype=bool)
        live[:n] = True
        t0 = time.perf_counter()
        ok = dev.aggregate_verify_commit(
            jnp.asarray(pk_x),
            jnp.asarray(pk_y),
            jnp.asarray(sx0),
            jnp.asarray(sx1),
            jnp.asarray(sy0),
            jnp.asarray(sy1),
            jnp.asarray(hx0[0]),
            jnp.asarray(hx1[0]),
            jnp.asarray(hy0[0]),
            jnp.asarray(hy1[0]),
            jnp.asarray(live),
        )
        out = bool(np.asarray(ok))
        metrics.observe(
            ("go-ibft", "device", "bls_aggregate_ms"),
            (time.perf_counter() - t0) * 1e3,
        )
        return out

    # -- BatchVerifier-shaped seal interface ---------------------------

    def verify_committed_seals(
        self, proposal_hash: bytes, seals: Sequence[CommittedSeal], height: int
    ) -> np.ndarray:
        out = np.zeros(len(seals), dtype=bool)
        if not seals or len(proposal_hash) != 32:
            return out
        keys = self._keys(height)
        decoded: list[Tuple[int, "hbls.PointG2", "hbls.PointG1"]] = []
        for i, seal in enumerate(seals):
            pk = keys.get(seal.signer)
            if pk is None:
                continue  # not a validator at this height
            pt = decode_seal(seal.signature)
            if pt is None:
                continue  # malformed / off-curve
            decoded.append((i, pt, pk))
        if not decoded:
            return out
        idxs = [i for i, _, _ in decoded]
        points = [p for _, p, _ in decoded]
        pks = [k for _, _, k in decoded]
        if self._aggregate_check(proposal_hash, points, pks):
            out[np.asarray(idxs)] = True
            return out
        # Unhappy path: pinpoint bad seals one by one on host (rare —
        # requires an actively byzantine signer inside the candidate set).
        for i, pt, pk in decoded:
            out[i] = hbls.verify(pk, proposal_hash, pt)
        return out
