"""Batched signature verification backends.

Three interchangeable implementations of the
:class:`go_ibft_tpu.core.backend.BatchVerifier` protocol (SURVEY.md §7
stage 4):

* :class:`HostBatchVerifier` — sequential Python ints; the reference
  semantics oracle and the CI stand-in when no accelerator exists.
* :class:`DeviceBatchVerifier` — one ``jit`` batch per phase on whatever
  JAX backend is active (TPU in production, CPU in tests); the framework's
  headline capability.

Both return identical boolean masks for identical inputs — determinism
across backends is part of the conformance suite.
"""

from .batch import DeviceBatchVerifier, HostBatchVerifier, SIG_BYTES

__all__ = ["DeviceBatchVerifier", "HostBatchVerifier", "SIG_BYTES"]
