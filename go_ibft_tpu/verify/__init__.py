"""Batched signature verification backends.

Interchangeable implementations of the
:class:`go_ibft_tpu.core.backend.BatchVerifier` protocol (SURVEY.md §7
stage 4):

* :class:`HostBatchVerifier` — sequential per-message verification (native
  C++ ecrecover when available, pure Python otherwise); the reference
  semantics oracle and the CI stand-in when no accelerator exists.
* :class:`DeviceBatchVerifier` — one ``jit`` batch per phase on whatever
  JAX backend is active (TPU in production, CPU in tests); the framework's
  headline capability.
* :class:`MeshBatchVerifier` — the same drains sharded lane-parallel
  across the device mesh (shard_map, host-side quorum reduce); degrades
  transparently to :class:`DeviceBatchVerifier` on a 1-device host.
* :class:`AdaptiveBatchVerifier` — routes tiny batches to the host path
  and big ones to the device kernels (the dispatch-latency floor makes
  device batching a loss below ~a dozen lanes).
* :class:`ResilientBatchVerifier` — the degraded-mode drain: quarantines
  poison lanes by bisection and demotes a faulting device down the
  ``device -> host (native) -> pure Python`` ladder via a
  :class:`CircuitBreaker`, restoring after cooldown (docs/ROBUSTNESS.md).

All return identical boolean masks for identical inputs — determinism
across backends is part of the conformance suite.

Two cross-cutting latency planes ride on top (ISSUE 9):

* every rung serves ``verify_seals_early_exit`` — a seal drain that
  stops at the exact voting-power quorum and reports the unverified
  remainder (:class:`EarlyExitReport`) for lazy off-path resolution;
* :class:`SpeculativeVerifier` + :class:`SpeculationCache`
  (:mod:`go_ibft_tpu.verify.speculate`) verify cross-phase arrivals as
  they land, hash-bound so a verdict can never leak across a different
  (height, round, proposal hash, phase, sender, signature) binding.
"""

from .aggregate import G2MergeTree, MultiPairVerifier, multi_aggregate_check
from .batch import (
    AdaptiveBatchVerifier,
    DeviceBatchVerifier,
    EarlyExitReport,
    EngineScope,
    HostBatchVerifier,
    MalformedLaneError,
    ResilientBatchVerifier,
    SIG_BYTES,
)
from .mesh_batch import MeshBatchVerifier
from .pipeline import CircuitBreaker, PackCache, VerifyPipeline
from .speculate import SpeculationCache, SpeculativeVerifier

__all__ = [
    "AdaptiveBatchVerifier",
    "CircuitBreaker",
    "DeviceBatchVerifier",
    "EarlyExitReport",
    "EngineScope",
    "G2MergeTree",
    "HostBatchVerifier",
    "MalformedLaneError",
    "MeshBatchVerifier",
    "MultiPairVerifier",
    "PackCache",
    "ResilientBatchVerifier",
    "SpeculationCache",
    "SpeculativeVerifier",
    "VerifyPipeline",
    "SIG_BYTES",
    "multi_aggregate_check",
]
