"""MeshBatchVerifier: the verify data plane sharded across the device mesh.

Every production drain so far — engine quorum certification, the pipeline's
double-buffered chunks, chain/sync seal verification — executed on ONE
device, while ``parallel/mesh.py`` proved an 8-device shard_map
quorum-certify program and nothing routed traffic through it.  This module
closes that gap: :class:`MeshBatchVerifier` is a
:class:`~go_ibft_tpu.verify.batch.DeviceBatchVerifier` whose dispatches
place packed lanes across a ``(dp, vp)`` mesh, with

* **lane-parallel sharding** — the lane axis is the data-parallel dim
  (``in_specs=P("dp")`` per lane array, validator table replicated); each
  device runs the UNCHANGED single-chip recovery ladder on its local lane
  slice, so the sharded program stays a thin shell around the single-chip
  one (the compile-budget pins enforce this per dp);
* **masked dummy-lane padding** — lane counts pad to ``bucket x dp`` so
  every shard gets an identical local shape; pad lanes are dead (``live``
  False) end to end, so no dummy verdict can leak into a quorum count
  (``tests/test_mesh_batch.py`` pins bit-identity to the sequential oracle
  at uneven remainders);
* **coalesced multi-drain dispatch** — the chunk capacity rises to
  ``largest bucket x dp``, so a multi-height sync range (or several
  chains' lanes) that used to cost dp sequential single-device dispatches
  is ONE sharded launch, still riding the double-buffered
  :class:`~go_ibft_tpu.verify.pipeline.VerifyPipeline`;
* **host-side quorum reduce** — the certify entry points compute the
  voting-power quorum from the sharded mask on exact host ints
  (:func:`~go_ibft_tpu.verify.batch.host_quorum_reached`), keeping the
  sharded program collective-free AND exact for any power range (no
  ``supports_fused`` representability gate);
* **transparent 1-device degradation** — when
  :func:`~go_ibft_tpu.parallel.mesh.mesh_context` finds a single device
  (or a dead backend) the instance behaves exactly as its
  ``DeviceBatchVerifier`` base: no shard_map program is ever built, no
  behavior changes.

Sharding choices mirror the SNIPPETS.md compile-plan harness: the jit
wrapper carries *explicit* ``in_shardings``/``out_shardings``
(``NamedSharding`` per ``in_specs``) so array placement is stated, not
inferred.  ``donate_argnums`` was re-evaluated for the sharded programs
and stays REJECTED, per the PR-1/PR-2 analysis which holds per shard: XLA
only aliases a donated input to an output of matching shape/dtype, and
these programs map ``(B, 20)`` limb vectors to a ``(B,)`` boolean mask —
nothing aliases, donation would emit a warning per compile and reuse
nothing.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..messages.helpers import CommittedSeal
from ..messages.wire import IbftMessage
from ..obs import ledger as cost_ledger
from ..obs import trace
from ..ops import quorum
from ..ops import secp256k1 as sec
from ..parallel.mesh import mesh_context, shard_map
from ..utils import metrics
from .batch import (
    _BATCH_BUCKETS,
    _bucket,
    DeviceBatchVerifier,
    ValidatorSource,
    host_quorum_reached,
)

__all__ = ["MeshBatchVerifier", "mesh_verify_mask", "REDUCE_MS_KEY"]

# Host-side quorum-reduce cost per sharded certify (the "reduce" leg the
# bench evidence reports as reduce_ms).
REDUCE_MS_KEY = ("go-ibft", "mesh", "reduce_ms")


def _mask_fn(zw, r, s, v, claimed, table, live):
    """Per-shard verification mask: the single-chip recovery ladder +
    membership compare, identical to ``batch._recover_fn`` — kept
    collective-free so the sharded program is embarrassingly parallel
    (quorum reduction happens on host)."""
    ok = quorum.sig_checks_zw(zw, r, s, v, claimed, live)
    member = jnp.any(quorum.membership_eq(claimed, table), axis=-1)
    return ok & member


# One compiled sharded-mask program per mesh (tests and the bench share
# meshes, so they share compiles; jit itself caches per input shape).
_MASK_KERNELS: Dict[Mesh, object] = {}


def mesh_verify_mask(mesh: Mesh):
    """Build (or reuse) the lane-sharded verification-mask program.

    ``shard_map`` over the mesh's ``dp`` axis: lane arrays shard on dim 0,
    the validator table replicates, the mask comes back lane-sharded.  The
    jit wrapper pins explicit ``in_shardings``/``out_shardings`` (the
    SNIPPETS.md compile-plan posture) so host numpy inputs are placed
    deterministically at the dispatch edge.
    """
    hit = _MASK_KERNELS.get(mesh)
    if hit is not None:
        return hit
    lane = P("dp")
    rep = P()
    in_specs = (lane, lane, lane, lane, lane, rep, lane)
    fn = shard_map(
        _mask_fn, mesh=mesh, in_specs=in_specs, out_specs=lane, check_vma=False
    )
    kernel = jax.jit(
        fn,
        in_shardings=tuple(NamedSharding(mesh, s) for s in in_specs),
        out_shardings=NamedSharding(mesh, lane),
        # donate_argnums deliberately empty: nothing aliases (see module
        # docstring) — stated explicitly so the decision is visible at the
        # compile plan, not implied by omission.
        donate_argnums=(),
    )
    _MASK_KERNELS[mesh] = kernel
    return kernel


class MeshBatchVerifier(DeviceBatchVerifier):
    """Lane-parallel sharded drain over the device mesh.

    Drop-in wherever a :class:`DeviceBatchVerifier` goes: the
    ``BatchVerifier`` protocol entry points (``verify_senders``,
    ``verify_committed_seals``, ``verify_seal_lanes``,
    ``verify_round_chunked``) inherit the parent's chunking/pipeline
    machinery and only the dispatch seam changes; the fused certify entry
    points compute their quorum on host ints from the sharded mask.

    ``mesh`` wins when given; otherwise :func:`mesh_context` enumerates
    devices (``dp``/``devices`` forwarded).  With one visible device the
    instance IS a ``DeviceBatchVerifier`` in behavior — ``self.mesh`` is
    ``None``, ``sharded`` False, and no shard_map program is built.
    """

    def __init__(
        self,
        validators_for_height: ValidatorSource,
        *,
        mesh: Optional[Mesh] = None,
        dp: Optional[int] = None,
        devices=None,
        cache_heights: int = 4,
    ):
        super().__init__(validators_for_height, cache_heights=cache_heights)
        if mesh is None:
            mesh = mesh_context(dp, devices=devices)
        if mesh is not None and mesh.devices.size < 2:
            mesh = None
        self.mesh = mesh
        self.dp = int(np.prod(mesh.devices.shape)) if mesh is not None else 1
        if mesh is not None:
            self._mask_kernel = mesh_verify_mask(mesh)
            self._dispatch_cap = _BATCH_BUCKETS[-1] * self.dp
            self._route = "mesh"

    @property
    def sharded(self) -> bool:
        return self.mesh is not None

    # -- pad/placement seams --------------------------------------------

    def _pad_lanes(self, n: int) -> int:
        """Smallest ``bucket x dp`` lane count holding ``n`` lanes.

        The per-shard shape is the bucket of ``ceil(n / dp)``, so the
        local program compiles at the same lane buckets as the
        single-device kernels; the global pad lanes are dead (``live``
        False) and their verdicts are sliced off before any caller sees
        them."""
        if self.mesh is None or n == 0:
            return 0
        return _bucket((n + self.dp - 1) // self.dp, _BATCH_BUCKETS) * self.dp

    def _table_dev(self, height: int) -> jnp.ndarray:
        """Validator table replicated across the mesh (uploaded once per
        height, like the parent's single-device pin)."""
        if self.mesh is None:
            return super()._table_dev(height)
        hit = self._tables_dev.get(height)
        if hit is None:
            hit = jax.device_put(
                self._table(height), NamedSharding(self.mesh, P())
            )
            self._tables_dev[height] = hit
        return hit

    # -- dispatch -------------------------------------------------------

    def _program_of(self, quorum_args) -> str:
        """The sharded mask program has its own compile-budget family
        (``mesh_verify_mask_8l_dp*`` pins); fused dispatches delegate to
        the parent's single-chip names."""
        if self.mesh is not None and quorum_args is None:
            return "mesh_verify_mask"
        return super()._program_of(quorum_args)

    def _dispatch_async(self, inputs, table, quorum_args):
        """Queue one sharded mask dispatch (mask-only route).

        The fused single-device kernels (``quorum_args`` set) never run
        here — the certify overrides below reduce quorum on host — but the
        seam stays delegating for safety if a caller reaches it.
        """
        if self.mesh is None or quorum_args is not None:
            return super()._dispatch_async(inputs, table, quorum_args)
        zw, r, s, v, claimed, live = inputs
        lanes = int(np.shape(live)[0])
        with cost_ledger.dispatch_span(
            "mesh_verify_mask",
            route=self._route,
            live_mask=live,
            kernels=(("mesh_verify_mask", self._mask_kernel),),
            block=False,
            site="verify/mesh_batch.py:_dispatch_async",
        ):
            with trace.span(
                "verify.shard",
                devices=self.dp,
                lanes=lanes,
                lanes_per_device=lanes // self.dp,
            ):
                with trace.span("verify.dispatch", route="mesh"):
                    mask = self._mask_kernel(
                        jnp.asarray(zw),
                        jnp.asarray(r),
                        jnp.asarray(s),
                        jnp.asarray(v),
                        jnp.asarray(claimed),
                        table,
                        jnp.asarray(live),
                    )
        return mask, None

    def warmup(
        self,
        lanes: Sequence[int] = (8,),
        blocks: Sequence[int] = (2, 8),
        table_rows: int = 8,
    ) -> None:
        """Pre-compile the single-device kernels AND the sharded mask
        program at ``bucket x dp`` global shapes (a consensus engine must
        never stall mid-round on a shard_map compile)."""
        super().warmup(lanes=lanes, blocks=blocks, table_rows=table_rows)
        if self.mesh is None:
            return
        nl = sec.FIELD.nlimbs
        for bb in lanes:
            g = _bucket(bb, _BATCH_BUCKETS) * self.dp
            with cost_ledger.dispatch_span(
                "mesh_verify_mask",
                route="warmup",
                padded=g,
                kernels=(("mesh_verify_mask", self._mask_kernel),),
                site="verify/mesh_batch.py:warmup",
            ):
                self._mask_kernel(
                    jnp.zeros((g, 8), jnp.uint32),
                    jnp.zeros((g, nl), jnp.int32),
                    jnp.zeros((g, nl), jnp.int32),
                    jnp.zeros((g,), jnp.int32),
                    jnp.zeros((g, 5), jnp.uint32),
                    jax.device_put(
                        np.zeros((table_rows, 5), np.uint32),
                        NamedSharding(self.mesh, P()),
                    ),
                    jnp.zeros((g,), bool),
                ).block_until_ready()

    # -- fused certify: sharded mask + host-int quorum reduce ------------

    def supports_fused(self, height: int) -> bool:
        """Always true on the sharded route: the quorum reduction runs on
        exact host ints, so there is no device-representability gate."""
        if self.mesh is None:
            return super().supports_fused(height)
        return True

    def _reduce(
        self, valid_addrs, height: int, threshold: Optional[int]
    ) -> bool:
        t0 = time.perf_counter()
        with trace.span("verify.quorum", route="host-int", shard="reduce"):
            reached = host_quorum_reached(
                self._validators, valid_addrs, height, threshold
            )
        metrics.observe(REDUCE_MS_KEY, (time.perf_counter() - t0) * 1e3)
        return reached

    def certify_senders(
        self,
        msgs: Sequence[IbftMessage],
        height: int,
        threshold: Optional[int] = None,
    ) -> Tuple[np.ndarray, bool]:
        if self.mesh is None:
            return super().certify_senders(msgs, height, threshold)
        out = np.zeros(len(msgs), dtype=bool)
        idxs = [
            i for i, m in enumerate(msgs) if self._well_formed_sender(m, height)
        ]
        if not idxs:
            return out, self._reduce((), height, threshold)
        sub = [msgs[i] for i in idxs]
        mask = self.verify_senders(sub)
        out[np.asarray(idxs)] = mask[: len(idxs)]
        reached = self._reduce(
            [m.sender for m, ok in zip(sub, mask) if ok], height, threshold
        )
        return out, reached

    def certify_seals(
        self,
        proposal_hash: bytes,
        seals: Sequence[CommittedSeal],
        height: int,
        threshold: Optional[int] = None,
    ) -> Tuple[np.ndarray, bool]:
        if self.mesh is None:
            return super().certify_seals(proposal_hash, seals, height, threshold)
        mask = self.verify_committed_seals(proposal_hash, seals, height)
        reached = self._reduce(
            [s.signer for s, ok in zip(seals, mask) if ok], height, threshold
        )
        return mask, reached

    def certify_round(
        self,
        msgs: Sequence[IbftMessage],
        proposal_hash: bytes,
        seals: Sequence[CommittedSeal],
        height: int,
        prepare_threshold: Optional[int] = None,
    ) -> Tuple[np.ndarray, bool, np.ndarray, bool]:
        if self.mesh is None:
            return super().certify_round(
                msgs, proposal_hash, seals, height, prepare_threshold
            )
        # Both phases drain through ONE pipeline of sharded dispatches
        # (seal packing overlaps the tail envelope dispatches); each
        # phase's quorum reduces on host ints.
        sender_mask, seal_mask = self.verify_round_chunked(
            msgs, proposal_hash, seals, height
        )
        p_ok = self._reduce(
            [m.sender for m, ok in zip(msgs, sender_mask) if ok],
            height,
            prepare_threshold,
        )
        s_ok = self._reduce(
            [s.signer for s, ok in zip(seals, seal_mask) if ok], height, None
        )
        return sender_mask, p_ok, seal_mask, s_ok
