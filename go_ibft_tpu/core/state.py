"""Per-sequence mutable consensus state.

Re-design of the reference's ``state`` struct (core/state.go:10-221).  The
engine is asyncio-single-owner, but the embedder may read state from other
threads (e.g. metrics scrapers), so mutations stay behind an RLock exactly as
the reference guards them with an RWMutex.
"""

from __future__ import annotations

import enum
import threading
from typing import Optional

from ..messages import helpers
from ..messages.helpers import CommittedSeal
from ..messages.wire import IbftMessage, PreparedCertificate, Proposal, View


class StateName(enum.IntEnum):
    """State machine phases (reference core/state.go:10-32)."""

    NEW_ROUND = 0
    PREPARE = 1
    COMMIT = 2
    FIN = 3

    def __str__(self) -> str:  # parity with stateType.String()
        return {
            StateName.NEW_ROUND: "new round",
            StateName.PREPARE: "prepare",
            StateName.COMMIT: "commit",
            StateName.FIN: "fin",
        }[self]


class SequenceState:
    """Mutex-guarded per-height state (reference core/state.go:34-57)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._view = View(height=0, round=0)
        self._latest_pc: Optional[PreparedCertificate] = None
        self._latest_prepared_proposal: Optional[Proposal] = None
        self._proposal_message: Optional[IbftMessage] = None
        self._seals: list[CommittedSeal] = []
        self._round_started = False
        self._name = StateName.NEW_ROUND

    # -- views --------------------------------------------------------------

    @property
    def view(self) -> View:
        """Copy of the current view (reference core/state.go:59-67)."""
        with self._lock:
            return self._view.copy()

    @property
    def height(self) -> int:
        with self._lock:
            return self._view.height

    @property
    def round(self) -> int:
        with self._lock:
            return self._view.round

    def set_view(self, view: View) -> None:
        with self._lock:
            self._view = view

    # -- lifecycle ----------------------------------------------------------

    def reset(self, height: int) -> None:
        """Wipe per-height state (reference core/state.go:69-84)."""
        with self._lock:
            self._seals = []
            self._round_started = False
            self._name = StateName.NEW_ROUND
            self._proposal_message = None
            self._latest_pc = None
            self._latest_prepared_proposal = None
            self._view = View(height=height, round=0)

    def new_round(self) -> None:
        """Kick off the round once (idempotent; reference core/state.go:198-207)."""
        with self._lock:
            if not self._round_started:
                self._name = StateName.NEW_ROUND
                self._round_started = True

    def finalize_prepare(
        self, certificate: PreparedCertificate, latest_ppb: Optional[Proposal]
    ) -> None:
        """Pin the PC and move to commit (reference core/state.go:209-221)."""
        with self._lock:
            self._latest_pc = certificate
            self._latest_prepared_proposal = latest_ppb
            self._name = StateName.COMMIT

    # -- accessors ----------------------------------------------------------

    @property
    def latest_pc(self) -> Optional[PreparedCertificate]:
        with self._lock:
            return self._latest_pc

    @property
    def latest_prepared_proposal(self) -> Optional[Proposal]:
        with self._lock:
            return self._latest_prepared_proposal

    @property
    def proposal_message(self) -> Optional[IbftMessage]:
        with self._lock:
            return self._proposal_message

    def set_proposal_message(self, message: Optional[IbftMessage]) -> None:
        with self._lock:
            self._proposal_message = message

    @property
    def proposal_hash(self) -> Optional[bytes]:
        """Hash of the accepted proposal (reference core/state.go:107-112)."""
        with self._lock:
            if self._proposal_message is None:
                return None
            return helpers.extract_proposal_hash(self._proposal_message)

    @property
    def proposal(self) -> Optional[Proposal]:
        """Accepted proposal, if any (reference core/state.go:135-144)."""
        with self._lock:
            if self._proposal_message is None:
                return None
            return helpers.extract_proposal(self._proposal_message)

    @property
    def raw_proposal(self) -> Optional[bytes]:
        """Raw bytes of the accepted proposal (reference core/state.go:146-154)."""
        proposal = self.proposal
        return proposal.raw_proposal if proposal is not None else None

    @property
    def committed_seals(self) -> list[CommittedSeal]:
        with self._lock:
            return list(self._seals)

    def set_committed_seals(self, seals: list[CommittedSeal]) -> None:
        with self._lock:
            self._seals = list(seals)

    @property
    def name(self) -> StateName:
        with self._lock:
            return self._name

    def change_state(self, name: StateName) -> None:
        with self._lock:
            self._name = name

    @property
    def round_started(self) -> bool:
        with self._lock:
            return self._round_started

    def set_round_started(self, started: bool) -> None:
        with self._lock:
            self._round_started = started
