"""Voting-power quorum math.

Re-design of the reference's ValidatorManager
(core/validator_manager.go:23-155).  Voting powers are arbitrary-precision
Python ints (parity with Go's big.Int); quorum = floor(2·total/3) + 1.

TPU note: alongside the host-side dict the manager maintains a *packed
voting-power vector* (validator index -> weight, float64 ndarray) so the batch
verifier can fuse the quorum reduction into device code: a quorum check over a
verification mask becomes ``(weights @ mask) >= quorum``.  The host path below
remains the source of truth for exact big-int arithmetic.
"""

from __future__ import annotations

import threading
from typing import Iterable, Mapping, Optional, Protocol, Sequence

import numpy as np

from ..messages.wire import IbftMessage
from .state import StateName


class Logger(Protocol):
    """3-method logger injected by the embedder (reference core/ibft.go:16-20)."""

    def info(self, msg: str, *args) -> None: ...

    def debug(self, msg: str, *args) -> None: ...

    def error(self, msg: str, *args) -> None: ...


class ValidatorBackend(Protocol):
    """Voting-power source (reference core/validator_manager.go:17-20)."""

    def get_voting_powers(self, height: int) -> Mapping[bytes, int]:
        """Map of validator address -> voting power for ``height``."""
        ...


class VotingPowerError(ValueError):
    """Total voting power is zero or less (reference validator_manager.go:13)."""


def calculate_quorum(total_voting_power: int) -> int:
    """floor(2·total/3) + 1 (reference core/validator_manager.go:129-135)."""
    return (2 * total_voting_power) // 3 + 1


class ValidatorManager:
    """Per-height voting power and quorum (reference core/validator_manager.go:23-47)."""

    def __init__(self, backend: ValidatorBackend, logger: Logger) -> None:
        self._backend = backend
        self._log = logger
        self._lock = threading.RLock()
        self._quorum_size: int = 0
        self._voting_power: Optional[dict[bytes, int]] = None
        # Packed mirror for device-side fused quorum checks.
        self._index_of: dict[bytes, int] = {}
        self._weights: Optional[np.ndarray] = None

    def init(self, height: int) -> None:
        """Load voting powers for a height (reference validator_manager.go:50-57).

        Raises VotingPowerError when the total voting power is not positive.
        """
        voting_power = dict(self._backend.get_voting_powers(height))
        self._set_current_voting_power(voting_power)

    def _set_current_voting_power(self, voting_power: dict[bytes, int]) -> None:
        total = sum(voting_power.values())
        if total <= 0:
            raise VotingPowerError("total voting power is zero or less")
        with self._lock:
            self._voting_power = voting_power
            self._quorum_size = calculate_quorum(total)
            # Deterministic packed order: sorted by address.
            addrs = sorted(voting_power)
            self._index_of = {a: i for i, a in enumerate(addrs)}
            self._weights = np.array(
                [float(voting_power[a]) for a in addrs], dtype=np.float64
            )

    @property
    def quorum_size(self) -> int:
        with self._lock:
            return self._quorum_size

    def power_of(self, address: bytes) -> int:
        """Voting power of one validator (0 for unknowns / before init)."""
        with self._lock:
            if self._voting_power is None:
                return 0
            return self._voting_power.get(address, 0)

    def has_quorum(self, sender_addresses: Iterable[bytes]) -> bool:
        """True when the senders' combined power reaches quorum
        (reference core/validator_manager.go:77-96).

        Unknown senders contribute zero.  Returns False before ``init``.
        """
        with self._lock:
            if self._voting_power is None:
                return False
            power = sum(
                self._voting_power.get(addr, 0) for addr in set(sender_addresses)
            )
            return power >= self._quorum_size

    def has_prepare_quorum(
        self,
        state_name: StateName,
        proposal_message: Optional[IbftMessage],
        msgs: Sequence[IbftMessage],
    ) -> bool:
        """Prepare-phase quorum rule (reference core/validator_manager.go:99-127).

        The proposer is counted via its proposal message; the proposer sending
        its own PREPARE is a protocol violation and voids the quorum.
        """
        if proposal_message is None:
            # Valid scenario unless we are already in the prepare state
            # (e.g. a PREPARE arrived before the proposal for the same view).
            if state_name == StateName.PREPARE:
                self._log.error("has_prepare_quorum: proposal message is not set")
            return False

        proposer = proposal_message.sender
        senders = {proposer}
        for message in msgs:
            if message.sender == proposer:
                self._log.error(
                    "has_prepare_quorum: proposer is among prepare signers"
                )
                return False
            senders.add(message.sender)

        return self.has_quorum(senders)

    # -- device mirror ------------------------------------------------------

    def packed_weights(self) -> tuple[np.ndarray, dict[bytes, int], float]:
        """(weights vector, address->index map, quorum) for device-side fusion.

        The float64 mirror is exact for voting powers below 2^53; consumers
        must fall back to the host big-int path for larger powers.
        """
        with self._lock:
            if self._weights is None:
                return np.zeros(0, dtype=np.float64), {}, float("inf")
            return self._weights, dict(self._index_of), float(self._quorum_size)


def senders_of(messages: Iterable[IbftMessage]) -> set[bytes]:
    """Messages -> unique sender set (reference validator_manager.go:147-155)."""
    return {m.sender for m in messages}
