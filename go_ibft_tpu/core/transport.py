"""Transport seam: multicast out, add_message in.

The reference's entire comm layer is a one-method interface
(core/transport.go:7-10); gossip lives in the embedder.  This build keeps the
seam; :class:`LoopbackTransport` below is the in-process fan-out for tests and
single-host clusters (the reference's test harness pattern,
core/helpers_test.go:227-231).  Further backends per SURVEY.md §5 — a
gRPC/DCN transport for multi-host deployments and the ICI lock-step
collective transport (multicast as an all_gather of fixed-size message
tensors) — plug into the same ``Transport`` protocol.
"""

from __future__ import annotations

from typing import Callable, Protocol

from ..messages.wire import IbftMessage


class Transport(Protocol):
    """Fire-and-forget multicast (reference core/transport.go:7-10).

    Self-delivery is expected: nodes receive their own messages through the
    same path as everyone else's.
    """

    def multicast(self, message: IbftMessage) -> None: ...


class LoopbackTransport:
    """In-process multicast: deliver to every registered node, self included.

    Mirrors the reference test clusters' gossip closure
    (core/mock_test.go:546-550, core/helpers_test.go:227-231).  Delivery is
    synchronous and in registration order; a delivery hook lets fault tests
    drop or mutate messages per (sender, receiver).
    """

    def __init__(self) -> None:
        self._receivers: list[Callable[[IbftMessage], None]] = []
        # Optional fault hook: (message, receiver_index) -> deliver?
        self.should_deliver: Callable[[IbftMessage, int], bool] = lambda m, i: True

    def register(self, add_message: Callable[[IbftMessage], None]) -> None:
        self._receivers.append(add_message)

    def multicast(self, message: IbftMessage) -> None:
        for idx, deliver in enumerate(self._receivers):
            if self.should_deliver(message, idx):
                deliver(message)
