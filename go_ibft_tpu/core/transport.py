"""Transport seam: multicast out, add_message in.

The reference's entire comm layer is a one-method interface
(core/transport.go:7-10); gossip lives in the embedder.  This build keeps the
seam; :class:`LoopbackTransport` below is the in-process fan-out for tests and
single-host clusters (the reference's test harness pattern,
core/helpers_test.go:227-231).  Further backends per SURVEY.md §5 — a
gRPC/DCN transport for multi-host deployments and the ICI lock-step
collective transport (multicast as an all_gather of fixed-size message
tensors) — plug into the same ``Transport`` protocol.
"""

from __future__ import annotations

import asyncio
from typing import Callable, Optional, Protocol, Sequence

from ..messages.wire import IbftMessage


class Transport(Protocol):
    """Fire-and-forget multicast (reference core/transport.go:7-10).

    Self-delivery is expected: nodes receive their own messages through the
    same path as everyone else's.
    """

    def multicast(self, message: IbftMessage) -> None: ...


class LoopbackTransport:
    """In-process multicast: deliver to every registered node, self included.

    Mirrors the reference test clusters' gossip closure
    (core/mock_test.go:546-550, core/helpers_test.go:227-231).  Delivery is
    synchronous and in registration order; a delivery hook lets fault tests
    drop or mutate messages per (sender, receiver).
    """

    def __init__(self) -> None:
        self._receivers: list[Callable[[IbftMessage], None]] = []
        # Optional fault hook: (message, receiver_index) -> deliver?
        self.should_deliver: Callable[[IbftMessage, int], bool] = lambda m, i: True

    def register(self, add_message: Callable[[IbftMessage], None]) -> None:
        self._receivers.append(add_message)

    def multicast(self, message: IbftMessage) -> None:
        for idx, deliver in enumerate(self._receivers):
            if self.should_deliver(message, idx):
                deliver(message)


class BatchingIngress:
    """Inbound micro-batcher: the TPU-native ingress shape.

    Gossip delivers messages one at a time; verifying each eagerly costs one
    device launch (or one host recover) per message — the reference's
    sequential AddMessage shape (core/ibft.go:1101-1123).  This collects a
    burst and flushes it through :meth:`IBFT.add_messages`, so sender
    signatures for the whole burst are verified in ONE device batch.

    Flushes when ``max_batch`` messages accumulate or ``max_delay`` seconds
    after the first buffered message, whichever comes first.  Event-loop
    affine (call :meth:`submit` from the loop thread); ``flush`` may be
    called directly for deterministic tests.
    """

    def __init__(
        self,
        add_messages: Callable[[Sequence[IbftMessage]], None],
        *,
        max_batch: int = 256,
        max_delay: float = 0.002,
    ) -> None:
        self._add_messages = add_messages
        self._buffer: list[IbftMessage] = []
        self._handle: Optional[asyncio.TimerHandle] = None
        self.max_batch = max_batch
        self.max_delay = max_delay

    def submit(self, message: IbftMessage) -> None:
        self._buffer.append(message)
        if len(self._buffer) >= self.max_batch:
            self.flush()
        elif self._handle is None:
            self._handle = asyncio.get_running_loop().call_later(
                self.max_delay, self.flush
            )

    def flush(self) -> None:
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
        if not self._buffer:
            return
        batch, self._buffer = self._buffer, []
        self._add_messages(batch)

    def close(self) -> None:
        """Drop buffered messages and cancel the pending flush timer."""
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
        self._buffer.clear()
