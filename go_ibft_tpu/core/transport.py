"""Transport seam: multicast out, add_message in.

The reference's entire comm layer is a one-method interface
(core/transport.go:7-10); gossip lives in the embedder.  This build keeps the
seam; :class:`LoopbackTransport` below is the in-process fan-out for tests and
single-host clusters (the reference's test harness pattern,
core/helpers_test.go:227-231).  Further backends per SURVEY.md §5 — a
gRPC/DCN transport for multi-host deployments and the ICI lock-step
collective transport (multicast as an all_gather of fixed-size message
tensors) — plug into the same ``Transport`` protocol.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Callable, Optional, Protocol, Sequence

from ..messages.wire import IbftMessage
from ..obs import trace


class Transport(Protocol):
    """Fire-and-forget multicast (reference core/transport.go:7-10).

    Self-delivery is expected: nodes receive their own messages through the
    same path as everyone else's.
    """

    def multicast(self, message: IbftMessage) -> None: ...


class LoopbackTransport:
    """In-process multicast: deliver to every registered node, self included.

    Mirrors the reference test clusters' gossip closure
    (core/mock_test.go:546-550, core/helpers_test.go:227-231).  Delivery is
    synchronous and in registration order; a delivery hook lets fault tests
    drop or mutate messages per (sender, receiver).

    Telemetry: loopback dispatch hands the SAME stamped message object to
    every receiver, and each receiving engine records its own ``net.recv``
    instant at ingress (``IBFT._record_recv``) — the loopback delivery
    callback IS the engine ingress, so the trace context needs no wire
    framing here and the shared process clock makes every clock offset
    exactly zero.
    """

    def __init__(self) -> None:
        self._receivers: list[Callable[[IbftMessage], None]] = []
        # Optional fault hook: (message, receiver_index) -> deliver?
        self.should_deliver: Callable[[IbftMessage, int], bool] = lambda m, i: True

    def register(self, add_message: Callable[[IbftMessage], None]) -> None:
        self._receivers.append(add_message)

    def multicast(self, message: IbftMessage) -> None:
        for idx, deliver in enumerate(self._receivers):
            if self.should_deliver(message, idx):
                deliver(message)


class BatchingIngress:
    """Inbound micro-batcher: the TPU-native ingress shape.

    Gossip delivers messages one at a time; verifying each eagerly costs one
    device launch (or one host recover) per message — the reference's
    sequential AddMessage shape (core/ibft.go:1101-1123).  This collects a
    burst and flushes it through :meth:`IBFT.add_messages`, so sender
    signatures for the whole burst are verified in ONE device batch.

    Flushes when ``max_batch`` messages accumulate or at the end of the
    current event-loop tick / after ``max_delay`` seconds, whichever the
    adaptive window picks (below).  Event-loop affine (call :meth:`submit`
    from the loop thread); ``flush`` may be called directly for
    deterministic tests.

    **Adaptive window.**  The wall-clock window only earns its latency when
    the resulting batch is big enough to take the device route; below the
    adaptive verifier's cutover the batch is host-verified one message at a
    time anyway, so waiting ``max_delay`` for company is pure added latency
    — it put the 4-validator happy path ~2 ms/phase behind the sequential
    baseline (BENCH_r05: 0.86x).  Small flows therefore flush with
    ``call_soon``: every message delivered in the same event-loop tick (a
    loopback multicast, a burst drained from one socket read) still lands
    in ONE batch, but the flush costs zero wall-clock.  The timed window
    engages when the flow is device-sized: either one flush carried
    ``>= eager_cutover`` messages, or the flushes of the last ``max_delay``
    of wall-clock add up to that many (a sustained flood arriving a few
    messages per tick — without the accumulation signal, sub-cutover eager
    flushes could never bootstrap into batching).  The window is a true
    sliding window: counts older than ``max_delay`` fall out, so a steady
    sub-cutover trickle never chains itself over the threshold, and any
    idle gap drops straight back to eager.

    **Arrival calibration (ISSUE 9).**  When the timed window engages it
    is no longer the fixed ``max_delay``: an
    :class:`~go_ibft_tpu.utils.calibration.ArrivalCalibrator` tracks the
    stream's EWMA inter-arrival gap and the wait becomes the PROJECTED
    time for the remaining ``max_batch`` lanes to arrive — a flood pays
    microseconds instead of the full 2 ms tail, and a stream measured too
    slow to fill the batch inside the ceiling flushes eagerly instead of
    idling.  ``max_delay`` stays the hard ceiling; pass
    ``calibrate=False`` for the fixed legacy window.
    """

    def __init__(
        self,
        add_messages: Callable[[Sequence[IbftMessage]], None],
        *,
        max_batch: int = 256,
        max_delay: float = 0.002,
        eager_cutover: Optional[int] = None,
        calibrate: bool = True,
    ) -> None:
        from ..utils import calibration

        if eager_cutover is None:
            eager_cutover = (
                calibration.measured_cutover() or calibration.DEFAULT_CUTOVER_LANES
            )
        self._add_messages = add_messages
        self._buffer: list[IbftMessage] = []
        self._handle: Optional[asyncio.Handle] = None
        self.max_batch = max_batch
        self.max_delay = max_delay
        self.eager_cutover = eager_cutover
        self.calibrator = (
            calibration.ArrivalCalibrator(max_window_s=max_delay)
            if calibrate
            else None
        )
        # Sliding window of recent flushes [(monotonic t, n), ...] whose
        # total within the trailing ``max_delay`` is the device-sized-flow
        # detector.  A true window, not a chained sum: flushes spaced just
        # under ``max_delay`` apart must NOT accumulate forever (a slow
        # steady trickle would eventually cross the cutover and pay the
        # timed window for nothing).
        self._recent: deque = deque()
        self._recent_n = 0

    def _trim_recent(self, now: float) -> None:
        while self._recent and now - self._recent[0][0] > self.max_delay:
            self._recent_n -= self._recent.popleft()[1]

    def _window(self) -> float:
        """The timed-window wait: calibrated projection, ceiling-clamped."""
        if self.calibrator is None:
            return self.max_delay
        window = self.calibrator.window(len(self._buffer), self.max_batch)
        trace.instant(
            "ingress.calibrate",
            window_us=round(window * 1e6, 1),
            pending=len(self._buffer),
        )
        return window

    def submit(self, message: IbftMessage) -> None:
        self._buffer.append(message)
        if self.calibrator is not None:
            self.calibrator.observe()
        if len(self._buffer) >= self.max_batch:
            self.flush()
        elif self._handle is None:
            loop = asyncio.get_running_loop()
            self._trim_recent(time.monotonic())
            if self._recent_n + len(self._buffer) >= self.eager_cutover:
                window = self._window()
                if window > 0:
                    self._handle = loop.call_later(window, self.flush)
                else:
                    self._handle = loop.call_soon(self.flush)
            else:
                self._handle = loop.call_soon(self.flush)

    def flush(self) -> None:
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
        if not self._buffer:
            return
        batch, self._buffer = self._buffer, []
        now = time.monotonic()
        self._recent.append((now, len(batch)))
        self._recent_n += len(batch)
        self._trim_recent(now)
        self._add_messages(batch)

    def close(self) -> None:
        """Drop buffered messages and cancel the pending flush timer."""
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
        self._buffer.clear()
