"""The IBFT 2.0 consensus engine.

Re-design of the reference's state machine (core/ibft.go:59-1315) on asyncio.
One :class:`IBFT` instance drives one validator; a cluster of instances
multicasting to each other reaches agreement on one proposal per *height*,
possibly across multiple *rounds* with rotating proposer and exponentially
growing timeouts.

Control flow stays on host (it is branchy and latency-bound); the O(N)
per-phase data plane — signature and seal verification — is delegated to a
:class:`~go_ibft_tpu.core.backend.BatchVerifier` when the backend provides
one, draining each phase's message store in one device batch (SURVEY.md §7).

Concurrency model (mirrors reference core/ibft.go:323-394 exactly):
every round spawns four workers — round timer, future-proposal watcher,
round-change-certificate watcher, and the state machine — whose first
completed signal wins the round arbitration; teardown cancels and awaits all
workers (the reference's WaitGroup barrier) before the next round starts.
"""

from __future__ import annotations

import asyncio
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from ..messages import helpers
from ..messages.events import SubscriptionDetails
from ..messages.helpers import CommittedSeal
from ..messages.store import MessageStore
from ..messages.wire import (
    IbftMessage,
    MessageType,
    PreparedCertificate,
    Proposal,
    RoundChangeCertificate,
    TraceContext,
    View,
)
from ..obs import trace
from ..utils import metrics
from ..utils.metrics import set_gauge
from .backend import Backend, BatchVerifier
from .state import SequenceState, StateName
from .transport import Transport
from .validator_manager import Logger, ValidatorManager, senders_of

# Default base round (round 0) timeout, seconds (reference core/ibft.go:49-50).
DEFAULT_BASE_ROUND_TIMEOUT = 10.0

# Fixed-bucket latency family (telemetry plane): proposal-accept ->
# finalize, the per-height number the /metrics endpoint and the SLO soak
# gates read.  Recorded only while ``metrics.enable_fixed_histograms()``
# is on — the same one-predicate disabled posture as the tracer.
ACCEPT_FINALIZE_MS_KEY = ("go-ibft", "latency", "accept_finalize_ms")

_ROUND_FACTOR_BASE = 2.0

# Exponent cap for the round-timeout formula: 2.0**round_ raises
# OverflowError past round ~1023, so a long-stalled sequence (or a
# Byzantine-driven round jump) would CRASH the timer worker instead of
# timing out.  10s * 2^62 is ~1.5e12 years — indistinguishable from
# "forever" while staying finite, monotone, and arithmetic-safe.
MAX_TIMEOUT_EXPONENT = 62


def get_round_timeout(
    base_round_timeout: float, additional_timeout: float, round_: int
) -> float:
    """Exponential round timeout: base·2^round + additional
    (reference core/ibft.go:1300-1315).  The exponent saturates at
    ``MAX_TIMEOUT_EXPONENT`` so arbitrarily high rounds return a finite
    timeout instead of raising ``OverflowError`` (the reference's Go
    ``time.Duration`` shift overflows silently there; we saturate)."""
    exponent = min(round_, MAX_TIMEOUT_EXPONENT)
    return base_round_timeout * (_ROUND_FACTOR_BASE**exponent) + additional_timeout


@dataclass
class RestoredState:
    """Crash-recovered in-flight state for one height (chain/wal.py lock
    records).  ``run_sequence(height, restore=...)`` re-enters the height
    at ``round`` with the prepared-certificate lock intact, so a restarted
    validator that had already sent COMMIT for a proposal can never
    prepare a different one for the same height (the equivocation the WAL
    exists to prevent)."""

    height: int
    round: int
    certificate: Optional[PreparedCertificate] = None


class _NewProposalEvent:
    """A valid proposal for a higher round (reference core/ibft.go:195-198)."""

    __slots__ = ("proposal_message", "round")

    def __init__(self, proposal_message: IbftMessage, round_: int) -> None:
        self.proposal_message = proposal_message
        self.round = round_


class _RoundSignals:
    """Per-round-iteration signal slots.

    The reference uses unbuffered channels selected against ctx.Done
    (core/ibft.go:77-94,170-207); futures owned by a single round iteration
    give the same no-stale-events guarantee — they are dropped wholesale at
    teardown.
    """

    def __init__(self) -> None:
        self.new_proposal: asyncio.Future = asyncio.get_running_loop().create_future()
        self.round_certificate: asyncio.Future = (
            asyncio.get_running_loop().create_future()
        )
        self.round_expired: asyncio.Future = asyncio.get_running_loop().create_future()
        self.round_done: asyncio.Future = asyncio.get_running_loop().create_future()
        # Process-fatal BaseException surfaced by a worker (a simulated
        # kill -9, KeyboardInterrupt, ...): unlike an ordinary worker crash
        # (logged at teardown; the round retries via its timer) this must
        # END the sequence — a worker replaced next round would let a
        # "dead" node keep participating.
        self.fatal: asyncio.Future = asyncio.get_running_loop().create_future()

    def all(self) -> list[asyncio.Future]:
        return [
            self.new_proposal,
            self.round_certificate,
            self.round_expired,
            self.round_done,
            self.fatal,
        ]

    @staticmethod
    def fire(fut: asyncio.Future, value=None) -> None:
        if not fut.done():
            fut.set_result(value)


class IBFT:
    """A single IBFT consensus state machine instance (reference core/ibft.go:59-136)."""

    def __init__(
        self,
        logger: Logger,
        backend: Backend,
        transport: Transport,
        *,
        message_store: Optional[MessageStore] = None,
        batch_verifier: Optional[BatchVerifier] = None,
        cert_verifier=None,
        speculator=None,
        commit_early_exit: bool = True,
    ) -> None:
        self.log = logger
        self.backend = backend
        self.transport = transport
        self.messages = message_store if message_store is not None else MessageStore()
        self.state = SequenceState()
        self.validator_manager = ValidatorManager(backend, logger)
        self.base_round_timeout = DEFAULT_BASE_ROUND_TIMEOUT
        self.additional_timeout = 0.0
        # Explicit batch verifier wins; otherwise use the backend when it
        # implements the BatchVerifier protocol.
        if batch_verifier is not None:
            self.batch_verifier: Optional[BatchVerifier] = batch_verifier
        elif isinstance(backend, BatchVerifier):
            self.batch_verifier = backend
        else:
            self.batch_verifier = None
        self._signals: Optional[_RoundSignals] = None
        # Committed-seal verdict cache, scoped per round: round -> {(sender,
        # proposal hash, seal bytes) -> bool}.  Every signature is verified
        # EXACTLY ONCE: envelopes at ingress (add_message/add_messages),
        # seals at first sight here, certificate innards when the carrying
        # message validates.  Phase wakeups after that are pure exact-int
        # arithmetic — re-dispatching crypto per wakeup made the phase loop
        # O(n^2) in signatures (VERDICT r04 weak #2).  The key carries the
        # proposal hash the seal was verified AGAINST (ADVICE r5): a cached
        # True can never validate a seal against a hash it did not sign,
        # even if a future path re-set the accepted proposal mid-round.
        # Cleared per sequence (run_sequence -> state.reset) and bounded
        # round-first (``_evict_seal_verdicts``): a Byzantine seal-rewrite
        # flood mints a fresh key per delivery, and must compete with dead
        # rounds' verdicts before it can evict the live view's (ADVICE r5).
        self._seal_verdicts: dict[int, dict[tuple, bool]] = {}
        self._seal_verdict_count = 0
        self._seal_verdict_cap = 16384
        # accept -> finalize latency anchor (set by _accept_proposal,
        # consumed by _insert_block into ACCEPT_FINALIZE_MS_KEY).
        self._accept_ts: Optional[float] = None
        # Memoized is_valid_proposal_hash verdicts for the ACCEPTED proposal
        # (cleared whenever it changes): a prepare/commit drain checks the
        # carried hash once per message per wakeup, and the backend call
        # re-hashes the proposal each time — at 4 validators that keccak was
        # a measurable slice of the happy-path phase loop.  Each distinct
        # carried hash now costs one backend call per round.
        self._hash_memo: dict[bytes, bool] = {}
        self._hash_memo_cap = 1024
        # Bounded future-height ingress buffer: messages for height H+1
        # arriving while H is still finalizing are held here (dedup by
        # (type, height, round, sender), the store's slot rule) instead of
        # flowing straight into the main store — the pre-chain gate let ANY
        # future height in, an unbounded spam surface.  Flushed through the
        # verified ingress path by run_sequence(H+1), or pre-verified early
        # by the chain runner's overlap worker (take_future_messages).
        # Signatures are NOT verified at buffer time, so both a per-sender
        # and a total cap bound what a sender-forging spammer can pin.
        self._future_lock = threading.Lock()
        self._future_buffer: dict[bytes, dict[tuple, IbftMessage]] = {}
        self._future_count = 0
        self.future_cap_per_sender = 16
        self.future_cap_total = 4096
        # PREPREPAREs get a longer buffer horizon than the flood-prone
        # types: a proposal is one message per (height, proposer) by the
        # dedup key, so holding a few heights' worth is still strictly
        # bounded — and dropping one wedges a lagging node permanently
        # (proposers never re-send; a node that missed the proposal for
        # height H while catching up can neither run H nor, if it is H+1's
        # proposer, let anyone else proceed).
        self.future_proposal_horizon = 4
        # Aggregate-COMMIT path (ISSUE 7): ``cert_verifier`` (a
        # BLSCertifier or compatible — one ``verify(cert)`` doing ONE
        # pairing equation + exact quorum power over the cert's signer
        # bitmap) enables finalizing a height straight from an
        # AggregateQuorumCertificate delivered by the aggregation-tree
        # gossip transport (net/aggtree.py) — the engine never needs a
        # quorum of INDIVIDUAL COMMITs when a verified certificate proves
        # one existed.  Certificates land in a tiny pending slot keyed by
        # height (latest wins; one live height + one ahead, same bounded
        # posture as the future-message buffer) and are consumed by the
        # COMMIT drain on the event loop, where the accepted proposal is
        # stable.  ``finalized_certificate`` records the cert that
        # finalized the CURRENT height (None for per-seal finalization);
        # the chain runner persists it as the height's O(1) WAL record.
        self.cert_verifier = cert_verifier
        self._cert_lock = threading.Lock()
        self._pending_certs: dict[int, object] = {}
        self.finalized_certificate = None
        # Speculative cross-phase verification (ISSUE 9): ``speculator``
        # (a :class:`~go_ibft_tpu.verify.speculate.SpeculativeVerifier`)
        # verifies COMMIT seals OFF the event loop as they land at
        # ingress — seal validity is proposal-independent (the digest is
        # the hash carried IN the message), so nothing about it needs
        # the COMMIT phase to be open.  The drain then consults the
        # speculation cache (full binding: height, round, carried hash,
        # sender, signature — a verdict can never leak across a
        # different binding) before dispatching fresh crypto.  Opt-in,
        # like cert_verifier: the embedder decides whether a background
        # verify thread exists.
        self.speculator = speculator
        # Incremental quorum early-exit (ISSUE 9): the COMMIT drain
        # stops verifying at the exact voting-power quorum and hands the
        # unverified remainder to the speculator for lazy off-path
        # resolution (or resolves it synchronously if the early exit
        # mispredicted — liveness never depends on a deferred lane).
        self.commit_early_exit = commit_early_exit
        # Chain-layer hooks (go_ibft_tpu.chain): on_lock fires when a
        # prepare quorum pins the PC (the WAL's in-flight lock record);
        # on_finalize fires after insert_proposal and BEFORE the store
        # prune — the crash-consistent finalize -> WAL append -> prune
        # ordering the chain WAL relies on.
        self.on_lock: Optional[
            Callable[[int, int, PreparedCertificate, Optional[Proposal]], None]
        ] = None
        self.on_finalize: Optional[
            Callable[[int, Proposal, list[CommittedSeal]], None]
        ] = None
        # Flight-recorder track: one timeline row per node, so a 6-node
        # height renders as six labeled rows (obs/export.py).  Named after
        # the validator identity when the backend provides one.
        try:
            self._obs_track = "node-" + bytes(backend.id()).hex()[:16]
        except Exception:  # noqa: BLE001 - mocks without a stable id
            self._obs_track = f"node-{id(self) & 0xFFFF:04x}"

    # -- configuration (reference core/ibft.go:1151-1159) -------------------

    def extend_round_timeout(self, amount: float) -> None:
        """Extend each round's timer by ``amount`` seconds."""
        self.additional_timeout = amount

    def set_base_round_timeout(self, base: float) -> None:
        """Set the base (round 0) timeout in seconds."""
        self.base_round_timeout = base

    # ------------------------------------------------------------------
    # sequence driver (reference core/ibft.go:304-395)
    # ------------------------------------------------------------------

    async def run_sequence(
        self, height: int, *, restore: Optional[RestoredState] = None
    ) -> None:
        """Run the IBFT sequence for ``height`` until a proposal is finalized.

        Cancel the surrounding task to abort; the backend's
        ``sequence_cancelled`` callback fires and CancelledError propagates.

        ``restore`` re-enters the height mid-round with a crash-recovered
        prepared-certificate lock (chain/wal.py): the state machine resumes
        in COMMIT for the restored round and re-announces its COMMIT for
        the locked proposal instead of starting the height from scratch.
        """
        start_time = time.monotonic()

        self.state.reset(height)
        self._seal_verdicts.clear()
        self._seal_verdict_count = 0
        self._hash_memo.clear()
        self._accept_ts = None
        self.finalized_certificate = None
        with self._cert_lock:
            for h in [h for h in self._pending_certs if h < height]:
                del self._pending_certs[h]
        # New sequence: drop the verifier's per-message pack cache (same
        # lifecycle as the seal-verdict cache) and tag round 0.
        bv = self.batch_verifier
        if hasattr(bv, "reset_pack_cache"):
            bv.reset_pack_cache()
        if hasattr(bv, "note_round"):
            bv.note_round(0)
        if self.speculator is not None:
            # Pin the live view; verdicts speculated for FUTURE heights
            # survive (that early traffic is the whole point), stale
            # heights drop.
            self.speculator.note_view(height, 0)

        try:
            self.validator_manager.init(height)
        except Exception as err:  # noqa: BLE001 - parity: the reference logs
            # and aborts on any init failure (ibft.go:310-314)
            self.log.error(
                "failed to run sequence - validator manager init",
                height,
                err,
            )
            return

        self.messages.prune_by_height(height)
        # Early traffic for THIS height that arrived while the previous
        # height was finalizing: flush it through the verified ingress path
        # (unless the chain runner's overlap worker already did).
        self._flush_future(height)
        if restore is not None and restore.height == height:
            self._apply_restore(restore)

        self.log.info("sequence started", height)
        trace.instant("sequence.start", track=self._obs_track, height=height)
        try:
            while True:
                view = self.state.view

                try:
                    self.backend.round_starts(view)
                except Exception as err:  # noqa: BLE001 - callback is advisory
                    self.log.error(
                        "failed to handle start round callback on backend", view, err
                    )

                self.log.info("round started", view.round)
                trace.instant(
                    "round.start",
                    track=self._obs_track,
                    height=height,
                    round=view.round,
                )

                current_round = view.round
                signals = _RoundSignals()
                self._signals = signals
                workers = [
                    asyncio.create_task(
                        self._guard_worker(
                            self._start_round_timer(signals, current_round),
                            signals,
                        ),
                        name=f"ibft-timer-h{height}-r{current_round}",
                    ),
                    asyncio.create_task(
                        self._guard_worker(
                            self._watch_for_future_proposal(signals), signals
                        ),
                        name=f"ibft-future-proposal-h{height}-r{current_round}",
                    ),
                    asyncio.create_task(
                        self._guard_worker(
                            self._watch_for_round_change_certificates(signals),
                            signals,
                        ),
                        name=f"ibft-rcc-watch-h{height}-r{current_round}",
                    ),
                    asyncio.create_task(
                        self._guard_worker(self._start_round(signals), signals),
                        name=f"ibft-round-h{height}-r{current_round}",
                    ),
                ]

                async def teardown() -> None:
                    # The reference's cancelRound(); wg.Wait() barrier
                    # (core/ibft.go:349-352): all workers exit before the
                    # next round may start.
                    for task in workers:
                        task.cancel()
                    results = await asyncio.gather(*workers, return_exceptions=True)
                    for task, result in zip(workers, results):
                        if isinstance(result, Exception) and not isinstance(
                            result, asyncio.CancelledError
                        ):
                            self.log.error(
                                "round worker crashed", task.get_name(), result
                            )

                try:
                    await asyncio.wait(
                        signals.all(), return_when=asyncio.FIRST_COMPLETED
                    )
                except asyncio.CancelledError:
                    # ctx cancelled by the embedder (core/ibft.go:383-392)
                    await teardown()
                    try:
                        self.backend.sequence_cancelled(view)
                    except Exception as err:  # noqa: BLE001
                        self.log.error(
                            "failed to handle sequence cancelled callback", view, err
                        )
                    self.log.debug("sequence cancelled")
                    raise

                # Arbitration order: the reference's Go select picks randomly
                # among ready channels (core/ibft.go:354-393), so no signal is
                # ever systematically starved.  Deterministic asyncio must pick
                # an order; round_done goes FIRST: if consensus finished while
                # the loop was busy (e.g. a verifier compile stalled it past the
                # round timer), finishing beats a moot round change — the
                # liveness-safe resolution of the tie the reference leaves to
                # chance.
                if signals.fatal.done():
                    # A worker hit a process-fatal BaseException (simulated
                    # kill -9, KeyboardInterrupt): the sequence ENDS —
                    # letting the round timer replace the dead worker would
                    # keep a "dead" node participating in consensus.
                    await teardown()
                    raise signals.fatal.result()
                elif signals.round_done.done():
                    # Consensus for this height is finished (ibft.go:376-382).
                    await teardown()
                    self._insert_block()
                    return
                elif signals.new_proposal.done():
                    ev: _NewProposalEvent = signals.new_proposal.result()
                    await teardown()
                    self.log.info("received future proposal", ev.round)
                    self._move_to_new_round(ev.round)
                    self._accept_proposal(ev.proposal_message)
                    self.state.set_round_started(True)
                    # NOTE: the reference multicasts this PREPARE with the
                    # view captured at round start, not ev.round
                    # (core/ibft.go:355-362); mirrored bit-for-bit.
                    self._send_prepare_message(view)
                elif signals.round_certificate.done():
                    round_ = signals.round_certificate.result()
                    await teardown()
                    self.log.info("received future RCC", round_)
                    self._move_to_new_round(round_)
                elif signals.round_expired.done():
                    await teardown()
                    self.log.info("round timeout expired", current_round)
                    new_round = current_round + 1
                    self._move_to_new_round(new_round)
                    self._send_round_change_message(height, new_round)
        finally:
            self._signals = None
            set_gauge(("go-ibft", "sequence", "duration"), time.monotonic() - start_time)
            trace.instant("sequence.done", track=self._obs_track, height=height)
            self.log.info("sequence done", height)

    # -- round workers ------------------------------------------------------

    async def _guard_worker(self, coro, signals: _RoundSignals) -> None:
        """Surface process-fatal worker deaths to the round arbitration.

        Ordinary ``Exception`` crashes keep today's semantics (logged at
        teardown; the round retries through its timer).  A non-Exception
        ``BaseException`` — a simulated kill -9 from the chaos harness, a
        KeyboardInterrupt — fires the ``fatal`` signal so ``run_sequence``
        tears the round down immediately and re-raises it, instead of the
        next round silently spawning a replacement worker."""
        try:
            await coro
        except asyncio.CancelledError:
            raise
        except Exception:
            raise
        except BaseException as err:
            # The exception IS the signal value; run_sequence re-raises it.
            signals.fire(signals.fatal, err)
            raise

    async def _start_round_timer(self, signals: _RoundSignals, round_: int) -> None:
        """Exponential round timer worker (reference core/ibft.go:145-165)."""
        start_time = time.monotonic()
        timeout = get_round_timeout(
            self.base_round_timeout, self.additional_timeout, round_
        )
        try:
            await asyncio.sleep(timeout)
            trace.instant(
                "round.timeout",
                track=self._obs_track,
                round=round_,
                timeout_s=timeout,
            )
            signals.fire(signals.round_expired)
        finally:
            set_gauge(("go-ibft", "round", "duration"), time.monotonic() - start_time)

    async def _watch_for_future_proposal(self, signals: _RoundSignals) -> None:
        """Jump rounds on valid proposals for higher rounds
        (reference core/ibft.go:211-253)."""
        view = self.state.view
        height, next_round = view.height, view.round + 1

        sub = self._subscribe(
            SubscriptionDetails(
                message_type=MessageType.PREPREPARE,
                view=View(height=height, round=next_round),
                has_min_round=True,
            )
        )
        try:
            while True:
                round_ = await sub.wait()
                if round_ is None:
                    return
                proposal = self._handle_preprepare(View(height=height, round=round_))
                if proposal is None:
                    continue
                signals.fire(
                    signals.new_proposal, _NewProposalEvent(proposal, round_)
                )
                return
        finally:
            self.messages.unsubscribe(sub.id)

    async def _watch_for_round_change_certificates(
        self, signals: _RoundSignals
    ) -> None:
        """Jump rounds on valid RCCs for higher rounds
        (reference core/ibft.go:258-301)."""
        view = self.state.view
        height, round_ = view.height, view.round

        sub = self._subscribe(
            SubscriptionDetails(
                message_type=MessageType.ROUND_CHANGE,
                view=View(height=height, round=round_ + 1),  # only higher rounds
                has_min_round=True,
            )
        )
        try:
            while True:
                wake = await sub.wait()
                if wake is None:
                    return
                rcc = self._handle_round_change_message(
                    View(height=height, round=round_)
                )
                if rcc is None:
                    continue
                new_round = rcc.round_change_messages[0].view.round
                signals.fire(signals.round_certificate, new_round)
                return
        finally:
            self.messages.unsubscribe(sub.id)

    async def _start_round(self, signals: _RoundSignals) -> None:
        """The per-round state machine worker (reference core/ibft.go:398-429)."""
        self.state.new_round()

        validator_id = self.backend.id()
        view = self.state.view

        if (
            self.backend.is_proposer(validator_id, view.height, view.round)
            and self.state.proposal_message is None
        ):
            # The proposal_message guard covers crash recovery: a restored
            # lock re-enters the round with its proposal already accepted,
            # and re-proposing over it would tear the lock down.
            self.log.info("we are the proposer")

            proposal_message = await self._build_proposal(view)
            if proposal_message is None:
                self.log.error("unable to build proposal")
                return

            self._accept_proposal(proposal_message)
            self.log.debug("block proposal accepted")

            self._send_preprepare_message(proposal_message)
            self.log.debug("pre-prepare message multicasted")

        await self._run_states(signals)

    # -- state machine loop (reference core/ibft.go:554-576) ----------------

    async def _run_states(self, signals: _RoundSignals) -> None:
        while True:
            name = self.state.name
            if name == StateName.NEW_ROUND:
                done = await self._run_new_round()
            elif name == StateName.PREPARE:
                done = await self._run_prepare()
            elif name == StateName.COMMIT:
                done = await self._run_commit()
            else:  # FIN
                signals.fire(signals.round_done)
                return
            if done:
                # Subscription closed from under us (store shut down) — the
                # asyncio analogue of the reference's errTimeoutExpired exit.
                return

    async def _run_new_round(self) -> bool:
        """Wait for and validate a proposal (reference core/ibft.go:579-625).

        Returns True when the engine should stop running states.
        """
        self.log.debug("enter: new round state")
        view = self.state.view
        sub = self._subscribe(
            SubscriptionDetails(message_type=MessageType.PREPREPARE, view=view)
        )
        try:
            while True:
                wake = await sub.wait()
                if wake is None:
                    return True
                with trace.span(
                    "proposal.drain", track=self._obs_track, round=view.round
                ):
                    proposal_message = self._handle_preprepare(view)
                if proposal_message is None:
                    continue

                self._hash_memo.clear()
                self.state.set_proposal_message(proposal_message)
                # Non-proposer accept point: the accept -> finalize
                # latency anchor (the proposer's is _accept_proposal).
                self._accept_ts = time.perf_counter()
                self._send_prepare_message(view)
                self.log.debug("prepare message multicasted")
                self.state.change_state(StateName.PREPARE)
                return False
        finally:
            self.messages.unsubscribe(sub.id)
            self.log.debug("exit: new round state")

    async def _run_prepare(self) -> bool:
        """Wait for a prepare quorum (reference core/ibft.go:816-851)."""
        self.log.debug("enter: prepare state")
        view = self.state.view
        sub = self._subscribe(
            SubscriptionDetails(message_type=MessageType.PREPARE, view=view)
        )
        try:
            while True:
                wake = await sub.wait()
                if wake is None:
                    return True
                # Batched drain arbitration: wakeups queued behind this one
                # are covered by the store re-read below — coalesce them
                # instead of re-draining the phase once per signal.
                sub.drain_pending()
                with trace.span(
                    "prepare.drain", track=self._obs_track, round=view.round
                ):
                    quorum = self._handle_prepare(view)
                if not quorum:
                    continue
                return False
        finally:
            self.messages.unsubscribe(sub.id)
            self.log.debug("exit: prepare state")

    async def _run_commit(self) -> bool:
        """Wait for a commit quorum (reference core/ibft.go:892-927)."""
        self.log.debug("enter: commit state")
        view = self.state.view
        sub = self._subscribe(
            SubscriptionDetails(message_type=MessageType.COMMIT, view=view)
        )
        try:
            while True:
                wake = await sub.wait()
                if wake is None:
                    return True
                # Same coalescing as the prepare drain: the commit drain
                # snapshots the whole view, so stale queued signals only
                # repeat it (each repeat is crypto-free thanks to the seal
                # verdict cache, but still walks the store).
                sub.drain_pending()
                with trace.span(
                    "commit.drain", track=self._obs_track, round=view.round
                ):
                    quorum = self._handle_commit(view)
                if not quorum:
                    continue
                return False
        finally:
            self.messages.unsubscribe(sub.id)
            self.log.debug("exit: commit state")

    # -- message handling ---------------------------------------------------

    def _handle_preprepare(self, view: View) -> Optional[IbftMessage]:
        """Fetch-and-validate proposals for a view (reference core/ibft.go:792-813)."""

        def is_valid_preprepare(message: IbftMessage) -> bool:
            if view.round == 0:
                return self._validate_proposal_0(message, view)
            return self._validate_proposal(message, view)

        msgs = self.messages.get_valid_messages(
            view, MessageType.PREPREPARE, is_valid_preprepare
        )
        return msgs[0] if msgs else None

    def _validate_proposal_common(self, msg: IbftMessage, view: View) -> bool:
        """Validations shared by all rounds (reference core/ibft.go:629-655)."""
        proposal = helpers.extract_proposal(msg)
        proposal_hash = helpers.extract_proposal_hash(msg)

        if proposal is None:
            return False
        # round matches
        if proposal.round != view.round:
            return False
        # sender is the proposer for this view
        if not self.backend.is_proposer(msg.sender, view.height, view.round):
            return False
        # hash matches keccak(proposal)
        if not self.backend.is_valid_proposal_hash(proposal, proposal_hash or b""):
            return False
        # the embedder accepts the proposal body
        return self.backend.is_valid_proposal(proposal.raw_proposal)

    def _validate_proposal_0(self, msg: IbftMessage, view: View) -> bool:
        """Round-0 proposal validation (reference core/ibft.go:658-680)."""
        if msg.view is None or msg.view.round != 0:
            return False
        if not self._validate_proposal_common(msg, view):
            return False
        # we must not be the proposer ourselves
        return not self.backend.is_proposer(self.backend.id(), view.height, view.round)

    def _validate_proposal(self, msg: IbftMessage, view: View) -> bool:
        """Round-N proposal validation with RCC (reference core/ibft.go:683-788)."""
        height, round_ = view.height, view.round
        proposal = helpers.extract_proposal(msg)
        rcc = helpers.extract_round_change_certificate(msg)

        if not self._validate_proposal_common(msg, view):
            return False
        if rcc is None:
            return False
        if not helpers.has_unique_senders(rcc.round_change_messages):
            return False
        if not self._has_quorum_by_msg_type(
            rcc.round_change_messages, MessageType.ROUND_CHANGE
        ):
            return False
        if self.backend.is_proposer(self.backend.id(), height, round_):
            return False

        # Structural checks on every RCC member.
        for rc in rcc.round_change_messages:
            if rc.type != MessageType.ROUND_CHANGE:
                return False
            if rc.view is None or rc.view.height != height:
                return False
            if rc.view.round != round_:
                return False

        # Sender validity: one device batch when available, else per-message
        # (reference loops IsValidValidator per message, ibft.go:718-738).
        if not self._all_senders_valid(rcc.round_change_messages):
            return False

        # maxRound re-proposal rule (reference ibft.go:740-788): among the
        # valid PCs inside the RCC, the proposal must hash-match the prepared
        # proposal of the highest prepared round.
        max_round: Optional[int] = None
        expected_hash: Optional[bytes] = None
        for rc_message in rcc.round_change_messages:
            cert = helpers.extract_latest_pc(rc_message)
            if cert is None or not self._valid_pc(cert, msg.view.round, height):
                continue
            assert cert.proposal_message is not None  # _valid_pc guarantees
            cert_round = cert.proposal_message.view.round
            cert_hash = helpers.extract_proposal_hash(cert.proposal_message)
            if max_round is None or cert_round >= max_round:
                max_round = cert_round
                expected_hash = cert_hash

        if max_round is None:
            return True

        assert proposal is not None  # _validate_proposal_common guarantees
        return self.backend.is_valid_proposal_hash(
            Proposal(raw_proposal=proposal.raw_proposal, round=max_round),
            expected_hash or b"",
        )

    def _handle_prepare(self, view: View) -> bool:
        """Drain PREPAREs; move to commit on quorum (reference core/ibft.go:855-889).

        NO cryptography here, by design: every stored PREPARE already had
        its envelope signature recovered and membership-checked at ingress
        (``add_message``/``add_messages`` — the device-batched path at
        scale), so the phase check is a cheap host predicate (proposal-hash
        equality) plus the exact big-int prepare quorum.  Re-verifying the
        envelopes per wakeup — the r02-r04 "fused phase" design — burned
        one full batch of signature recoveries on EVERY prepare arrival;
        the fused device programs (``ops/quorum``) remain the data plane
        for ingress floods and certificate validation, where the
        signatures genuinely have not been seen before."""

        def is_valid_prepare(message: IbftMessage) -> bool:
            proposal = self.state.proposal
            if proposal is None:
                return False
            return self._proposal_hash_ok(
                proposal, helpers.extract_prepare_hash(message) or b""
            )

        prepare_messages = self.messages.get_valid_messages(
            view, MessageType.PREPARE, is_valid_prepare
        )

        if not self._has_quorum_by_msg_type(prepare_messages, MessageType.PREPARE):
            return False

        certificate = PreparedCertificate(
            proposal_message=self.state.proposal_message,
            prepare_messages=prepare_messages,
        )
        proposal = self.state.proposal
        self.state.finalize_prepare(certificate, proposal)
        if self.on_lock is not None:
            # The WAL's in-flight lock record, made durable BEFORE the
            # commit multicast below: once a COMMIT for this proposal can
            # exist on the network, a crash-and-restart of this node must
            # find the lock and can never prepare a different proposal for
            # the height (the reference orders send-then-state in memory,
            # ibft.go:855-889; with persistence in the loop the lock has
            # to lead).  A FAILED append therefore withholds the COMMIT —
            # the node stays locked in memory and still finalizes from its
            # peers' commits, it just contributes no commit of its own
            # this round (safety over one node's liveness share; sending
            # anyway would re-open the equivocation window the ordering
            # exists to close).
            try:
                self.on_lock(view.height, view.round, certificate, proposal)
            except Exception as err:  # noqa: BLE001 - degrade, don't equivocate
                self.log.error(
                    "lock hook failed; commit withheld", view, err
                )
                return True
        self._send_commit_message(view)
        self.log.debug("commit message multicasted")
        return True

    def _handle_commit(self, view: View) -> bool:
        """Drain COMMITs; move to fin on quorum (reference core/ibft.go:931-967).

        With a batch verifier this is the seal hot path: committed seals
        are NEW cryptographic material (not covered by the ingress envelope
        check), verified in batches at first sight and cached by identity
        (``_seal_verdicts``), so each seal costs exactly one recover no
        matter how many wakeups the phase takes.  The quorum reduction is
        exact host ints over the cached-valid set.

        Aggregate short-circuit: a pending quorum certificate for this
        height that hash-matches the accepted proposal and verifies (ONE
        pairing, quorum power from the signer bitmap) finalizes the
        height immediately — no per-sender COMMIT quorum needed, which is
        what makes tree-aggregated dissemination O(1) wire per node.
        """
        if self._certificate_finalizes(view):
            return True
        commit_messages = self._drain_valid_commits(view)
        if not self._has_quorum_by_msg_type(commit_messages, MessageType.COMMIT):
            return False

        try:
            commit_seals = helpers.extract_committed_seals(commit_messages)
        except helpers.WrongCommitMessageTypeError as err:  # safe check
            self.log.error("failed to extract committed seals", err)
            return False

        self.state.set_committed_seals(commit_seals)
        self.state.change_state(StateName.FIN)
        return True

    def _drain_valid_commits(self, view: View) -> list[IbftMessage]:
        """Validity-filtered COMMIT drain — batched when possible."""
        proposal = self.state.proposal

        if self.batch_verifier is None or proposal is None:
            # Reference path: per-message predicates inside the store lock.
            def is_valid_commit(message: IbftMessage) -> bool:
                proposal_hash = helpers.extract_commit_hash(message)
                committed_seal = helpers.extract_committed_seal(message)
                if proposal is None or committed_seal is None:
                    return False
                if not self._proposal_hash_ok(proposal, proposal_hash or b""):
                    return False
                return self.backend.is_valid_committed_seal(
                    proposal_hash or b"", committed_seal, view.height
                )

            return self.messages.get_valid_messages(
                view, MessageType.COMMIT, is_valid_commit
            )

        # Batched path: snapshot, one host pass for the (cheap) hash
        # equality, then verification of the seals this engine has never
        # verified before — repeat wakeups in the same phase re-verify
        # nothing (the verdict cache keys on the seal bytes themselves, so
        # a store-evicting rewrite from the same sender re-verifies).
        # Fresh seals first consult the SPECULATION cache (verdicts the
        # off-path worker produced while the phase was closed — ISSUE 9;
        # the lookup binds height, round, carried hash, sender and
        # signature, so a speculated verdict for proposal H can never
        # certify H' at the same height/round), then drain with quorum
        # EARLY-EXIT when the verifier supports it: verification stops at
        # the exact voting-power quorum and the unverified remainder
        # resolves lazily off-path.  Deferred lanes are neither valid nor
        # invalid this wakeup — they stay in the store untouched.
        candidates, invalid = self._collect_commit_candidates(view, proposal)
        valid_messages: list[IbftMessage] = []
        if candidates:
            round_cache = self._seal_verdicts.setdefault(view.round, {})
            keys = [
                (m.sender, phash, seal.signature)
                for m, phash, seal in candidates
            ]
            verdicts = {k: round_cache[k] for k in keys if k in round_cache}
            fresh = [i for i, k in enumerate(keys) if k not in verdicts]
            stored = 0

            def note(i: int, ok: bool) -> None:
                nonlocal stored
                verdicts[keys[i]] = ok
                round_cache[keys[i]] = ok
                stored += 1

            if fresh and self.speculator is not None:
                missed = []
                for i in fresh:
                    hit = self.speculator.lookup_seal(
                        view.height,
                        view.round,
                        keys[i][1],
                        keys[i][0],
                        keys[i][2],
                    )
                    if hit is None:
                        missed.append(i)
                    else:
                        note(i, bool(hit))
                fresh = missed
            deferred: list[int] = []
            if fresh:
                deferred = self._verify_fresh_seals(
                    view, candidates, keys, fresh, verdicts, note
                )
            mask = [verdicts.get(k) for k in keys]
            for (message, _, _), ok in zip(candidates, mask):
                if ok is None:
                    continue  # deferred: not valid, not pruned
                if ok:
                    valid_messages.append(message)
                else:
                    invalid.append(message)
            if deferred and not self._has_quorum_by_msg_type(
                valid_messages, MessageType.COMMIT
            ):
                # Early-exit misprediction (the incremental tally and the
                # exact quorum check disagreed): resolve the remainder NOW
                # — liveness must never wait on an off-path worker, since
                # no further wakeup is guaranteed.
                fresh_mask = self.batch_verifier.verify_committed_seals(
                    candidates[0][1],
                    [candidates[i][2] for i in deferred],
                    view.height,
                )
                for i, ok in zip(deferred, fresh_mask):
                    note(i, bool(ok))
                    if bool(ok):
                        valid_messages.append(candidates[i][0])
                    else:
                        invalid.append(candidates[i][0])
                deferred = []
            elif deferred and self.speculator is not None:
                # Quorum certified without them: the remainder resolves
                # lazily off-path and a later wakeup (or nothing at all)
                # sees the verdicts as cache hits.
                self.speculator.submit_seal_lanes(
                    view.height,
                    view.round,
                    candidates[0][1],
                    [
                        (candidates[i][0].sender, candidates[i][2])
                        for i in deferred
                    ],
                )
            if stored:
                self._seal_verdict_count += stored
                self._evict_seal_verdicts(view.round)

        if invalid:
            self.messages.remove_messages(view, MessageType.COMMIT, invalid)
        return valid_messages

    def _verify_fresh_seals(
        self, view: View, candidates, keys, fresh, verdicts, note
    ) -> list[int]:
        """Verify the fresh commit-seal lanes, early-exiting at quorum.

        Returns the lanes left unverified (deferred).  Without an
        early-exit-capable verifier — or with ``commit_early_exit``
        off — this is the original one-batch drain and nothing defers.
        """
        early = (
            getattr(self.batch_verifier, "verify_seals_early_exit", None)
            if self.commit_early_exit
            else None
        )
        if early is None:
            fresh_mask = self.batch_verifier.verify_committed_seals(
                candidates[0][1],
                [candidates[i][2] for i in fresh],
                view.height,
            )
            for i, ok in zip(fresh, fresh_mask):
                note(i, bool(ok))
            return []
        # Power already certified by cached/speculated verdicts shrinks
        # the drain's stop threshold (distinct senders: the store holds
        # one slot per sender, so candidate senders never repeat).
        certified = sum(
            self.validator_manager.power_of(candidates[i][0].sender)
            for i, k in enumerate(keys)
            if verdicts.get(k)
        )
        remaining = max(0, self.validator_manager.quorum_size - certified)
        report = early(
            candidates[0][1],
            [candidates[i][2] for i in fresh],
            view.height,
            threshold=remaining,
        )
        deferred: list[int] = []
        for j, i in enumerate(fresh):
            if report.verified[j]:
                note(i, bool(report.mask[j]))
            else:
                deferred.append(i)
        return deferred

    def _evict_seal_verdicts(self, current_round: int) -> None:
        """Oldest-round-first seal-verdict eviction (ADVICE r5).

        A Byzantine seal-rewrite flood (fresh seal bytes per delivery mint
        fresh cache keys) competes first with verdicts from rounds the
        engine has already left behind; only when the live round is all
        that remains does it evict within itself (FIFO there — insertion
        order is verification order)."""
        while self._seal_verdict_count > self._seal_verdict_cap:
            oldest = min(self._seal_verdicts)
            bucket = self._seal_verdicts[oldest]
            if oldest == current_round:
                bucket.pop(next(iter(bucket)))
                self._seal_verdict_count -= 1
                if not bucket:
                    del self._seal_verdicts[oldest]
            else:
                self._seal_verdict_count -= len(bucket)
                del self._seal_verdicts[oldest]

    # -- aggregate quorum certificates (ISSUE 7) ------------------------

    def add_quorum_certificate(self, cert) -> bool:
        """Feed an aggregate COMMIT certificate into the engine (thread-
        safe; the aggregation-tree transport's delivery seam).

        The certificate is NOT verified here — verification (one pairing
        equation) runs in the COMMIT drain on the event loop, where the
        accepted proposal is stable and the cost is attributed to the
        phase span.  Bounded exactly like the future-message buffer: one
        pending slot for the live height and one for the next (latest
        certificate wins a slot; anything staler or further ahead drops).
        Returns True when the certificate was buffered.
        """
        if self.cert_verifier is None or cert is None:
            return False
        height = getattr(cert, "height", None)
        if not isinstance(height, int):
            return False
        state_height = self.state.height
        if not state_height <= height <= state_height + 1:
            return False
        with self._cert_lock:
            self._pending_certs[height] = cert
        # Wake the COMMIT drain; the subscription re-checks the store AND
        # the pending slot, so a cert arriving before the engine enters
        # COMMIT is found by the phase's subscribe-then-recheck.
        self.messages.signal_event(MessageType.COMMIT, self.state.view)
        return True

    def _take_pending_cert(self, height: int):
        with self._cert_lock:
            return self._pending_certs.pop(height, None)

    def _certificate_finalizes(self, view: View) -> bool:
        """Try to finalize the view from a pending aggregate certificate.

        Acceptance requires: a verifier is configured, the certificate's
        proposal hash matches the ACCEPTED proposal (so a certificate can
        never finalize a proposal this node did not validate), and the
        certificate verifies — signer bitmap resolves inside the height's
        validator set, combined voting power reaches quorum, and the one
        pairing equation holds.  A failing certificate is dropped (the
        normal per-seal path continues; a fresh certificate can arrive).
        """
        cert = self._take_pending_cert(view.height)
        if cert is None or self.cert_verifier is None:
            return False
        proposal = self.state.proposal
        accepted_hash = self.state.proposal_hash
        if (
            proposal is None
            or accepted_hash is None
            or getattr(cert, "proposal_hash", None) != accepted_hash
        ):
            # Not consumable YET — re-buffer instead of dropping: the hub
            # broadcasts a certified key exactly once, and under tree
            # dissemination the certificate may be this node's ONLY
            # commit evidence.  An equivocation victim that accepted P'
            # while the quorum certified P re-finds the certificate here
            # after the round change lands it on P (a newer certificate
            # arriving meanwhile wins the slot — never overwrite it with
            # a stale one).  The re-check per wakeup is a bytes compare.
            with self._cert_lock:
                self._pending_certs.setdefault(view.height, cert)
            return False
        with trace.span(
            "commit.cert_verify", track=self._obs_track, round=view.round
        ):
            try:
                ok = bool(self.cert_verifier.verify(cert))
            except Exception as err:  # noqa: BLE001 - a bad cert must not
                # take down the round; per-seal COMMITs still finalize it
                self.log.error("quorum certificate verification crashed", err)
                ok = False
        if not ok:
            self.log.debug("quorum certificate rejected")
            return False
        trace.instant(
            "commit.cert_finalize",
            track=self._obs_track,
            height=view.height,
            signers=len(cert.signer_indices())
            if hasattr(cert, "signer_indices")
            else None,
        )
        self.finalized_certificate = cert
        self.state.set_committed_seals([cert.to_seal()])
        self.state.change_state(StateName.FIN)
        return True

    def _proposal_hash_ok(self, proposal: Proposal, hash_: bytes) -> bool:
        """Memoized ``backend.is_valid_proposal_hash`` against the accepted
        proposal.  The accepted proposal is fixed until the round moves (the
        memo is cleared at every point that changes it), so each distinct
        carried hash costs ONE backend keccak per round instead of one per
        message per wakeup.  Bounded: a flood of distinct bogus hashes
        clears the memo rather than growing it."""
        hit = self._hash_memo.get(hash_)
        if hit is None:
            if len(self._hash_memo) >= self._hash_memo_cap:
                self._hash_memo.clear()
            hit = self.backend.is_valid_proposal_hash(proposal, hash_)
            self._hash_memo[hash_] = hit
        return hit

    def _collect_commit_candidates(
        self, view: View, proposal: Optional[Proposal]
    ) -> tuple[list[tuple[IbftMessage, bytes, CommittedSeal]], list[IbftMessage]]:
        """Snapshot the view's COMMITs and split into hash-valid candidates
        (message, hash, seal) vs invalid messages (shared by the batched and
        fused drains so their pruning semantics cannot diverge)."""
        candidates: list[tuple[IbftMessage, bytes, CommittedSeal]] = []
        invalid: list[IbftMessage] = []
        for message in self.messages.snapshot_view(view, MessageType.COMMIT):
            proposal_hash = helpers.extract_commit_hash(message)
            committed_seal = helpers.extract_committed_seal(message)
            if (
                committed_seal is None
                or not self._proposal_hash_ok(proposal, proposal_hash or b"")
            ):
                invalid.append(message)
                continue
            candidates.append((message, proposal_hash or b"", committed_seal))
        return candidates, invalid

    def _all_senders_valid(self, msgs: Sequence[IbftMessage]) -> bool:
        """IsValidValidator over a message set — batched when possible."""
        if not msgs:
            return True
        if self.batch_verifier is not None:
            mask = self.batch_verifier.verify_senders(list(msgs))
            return bool(all(bool(x) for x in mask))
        return all(self.backend.is_valid_validator(m) for m in msgs)

    # -- round change / certificates ----------------------------------------

    async def _wait_for_rcc(
        self, height: int, round_: int
    ) -> Optional[RoundChangeCertificate]:
        """Block until a valid RCC materializes (reference core/ibft.go:432-466)."""
        view = View(height=height, round=round_)
        sub = self._subscribe(
            SubscriptionDetails(message_type=MessageType.ROUND_CHANGE, view=view)
        )
        try:
            while True:
                wake = await sub.wait()
                if wake is None:
                    return None
                rcc = self._handle_round_change_message(view)
                if rcc is None:
                    continue
                return rcc
        finally:
            self.messages.unsubscribe(sub.id)

    def _handle_round_change_message(
        self, view: View
    ) -> Optional[RoundChangeCertificate]:
        """Validate RC messages and build an RCC (reference core/ibft.go:470-512)."""
        height = view.height
        has_accepted_proposal = self.state.proposal is not None

        def is_valid_msg(msg: IbftMessage) -> bool:
            proposal = helpers.extract_last_prepared_proposal(msg)
            certificate = helpers.extract_latest_pc(msg)
            if msg.view is None:
                return False
            if not self._valid_pc(certificate, msg.view.round, height):
                return False
            return self._proposal_matches_certificate(proposal, certificate)

        def is_valid_rcc(round_: int, msgs: list[IbftMessage]) -> bool:
            # Accept an RCC for our own round only if we have not accepted a
            # proposal in it (reference ibft.go:489-497).
            if round_ == view.round and has_accepted_proposal:
                return False
            return self._has_quorum_by_msg_type(msgs, MessageType.ROUND_CHANGE)

        extended_rcc = self.messages.get_extended_rcc(
            height, is_valid_msg, is_valid_rcc
        )
        if not extended_rcc:
            return None
        return RoundChangeCertificate(round_change_messages=list(extended_rcc))

    def _proposal_matches_certificate(
        self,
        proposal: Optional[Proposal],
        certificate: Optional[PreparedCertificate],
    ) -> bool:
        """PC must accompany — and hash-match — a prepared proposal
        (reference core/ibft.go:516-551)."""
        if proposal is None and certificate is None:
            return True
        if certificate is None:
            return False
        # NOTE: proposal may be None here with a set certificate; like the
        # reference we defer to the hash check (IsValidProposalHash(nil, ..)).
        hashes: list[bytes] = [
            helpers.extract_proposal_hash(certificate.proposal_message) or b""
            if certificate.proposal_message is not None
            else b""
        ]
        for msg in certificate.prepare_messages or ():
            hashes.append(helpers.extract_prepare_hash(msg) or b"")

        return all(
            self.backend.is_valid_proposal_hash(
                proposal if proposal is not None else Proposal(), h
            )
            for h in hashes
        )

    def _valid_pc(
        self,
        certificate: Optional[PreparedCertificate],
        round_limit: int,
        height: int,
    ) -> bool:
        """Prepared-certificate validity (reference core/ibft.go:1161-1231)."""
        if certificate is None:
            # PCs that are not set are valid by default.
            return True

        if certificate.proposal_message is None or certificate.prepare_messages is None:
            return False

        all_messages = [certificate.proposal_message, *certificate.prepare_messages]

        # Quorum over PP+P senders (mixed types: use HasQuorum directly).
        if not self.validator_manager.has_quorum(senders_of(all_messages)):
            return False

        if certificate.proposal_message.type != MessageType.PREPREPARE:
            return False
        if any(
            m.type != MessageType.PREPARE for m in certificate.prepare_messages
        ):
            return False

        # Same height/round/hash, unique senders.
        if not helpers.are_valid_pc_messages(all_messages, height, round_limit):
            return False

        proposal_msg = certificate.proposal_message
        if proposal_msg.view is None:
            return False
        if not self.backend.is_proposer(
            proposal_msg.sender, proposal_msg.view.height, proposal_msg.view.round
        ):
            return False

        # Sender signatures: proposal + each prepare (batched when possible).
        if not self._all_senders_valid(all_messages):
            return False

        # Prepare messages must come from validators that are NOT the
        # proposer for their view.
        for message in certificate.prepare_messages:
            if message.view is None:
                return False
            if self.backend.is_proposer(
                message.sender, message.view.height, message.view.round
            ):
                return False

        return True

    # -- proposal building (reference core/ibft.go:1005-1091) ---------------

    async def _build_proposal(self, view: View) -> Optional[IbftMessage]:
        height, round_ = view.height, view.round

        if round_ == 0:
            raw_proposal = self.backend.build_proposal(View(height=height, round=round_))
            return self.backend.build_preprepare_message(
                raw_proposal, None, View(height=height, round=round_)
            )

        # round > 0 needs an RCC
        rcc = await self._wait_for_rcc(height, round_)
        if rcc is None:
            return None  # store shut down

        # Re-propose the prepared proposal of the highest prepared round
        # carried inside the RCC, if any (maxRound rule, ibft.go:1036-1063).
        previous_proposal: Optional[bytes] = None
        max_round = 0
        for msg in rcc.round_change_messages:
            latest_pc = helpers.extract_latest_pc(msg)
            if latest_pc is None or latest_pc.proposal_message is None:
                continue
            proposal = helpers.extract_proposal(latest_pc.proposal_message)
            if proposal is None:
                continue
            cert_round = proposal.round
            if previous_proposal is not None and cert_round <= max_round:
                continue
            last_pb = helpers.extract_last_prepared_proposal(msg)
            if last_pb is None:
                continue
            previous_proposal = last_pb.raw_proposal
            max_round = cert_round

        if previous_proposal is None:
            raw_proposal = self.backend.build_proposal(View(height=height, round=round_))
            return self.backend.build_preprepare_message(
                raw_proposal, rcc, View(height=height, round=round_)
            )

        return self.backend.build_preprepare_message(
            previous_proposal, rcc, View(height=height, round=round_)
        )

    # -- inbound path (reference core/ibft.go:1101-1149) --------------------

    def _record_recv(self, message: IbftMessage) -> None:
        """``net.recv`` instant for a delivered traced message.

        Called from the ingress paths with tracing already known enabled.
        Loopback dispatch hands the SAME message object to every engine,
        so the context is never mutated here — each receiver records its
        own instant on its own track; a socket transport that already
        recorded at the wire boundary sets ``ctx.recorded`` and is
        skipped.  A message re-entering ingress via the future-buffer
        flush may record twice on one node; the timeline tool keys on
        first arrival per (node, origin), so duplicates are harmless
        (chaos duplication produces them anyway).
        """
        ctx = getattr(message, "trace_ctx", None)
        if ctx is None or ctx.recorded:
            return
        trace.instant(
            "net.recv",
            track=self._obs_track,
            origin=ctx.origin,
            height=ctx.height,
            round=ctx.round,
            type=int(message.type),
            span=ctx.span_id,
            sent_us=ctx.sent_us,
        )

    def add_message(self, message: Optional[IbftMessage]) -> None:
        """Feed one message into the engine (thread-safe).

        Validates the sender signature eagerly, stores, and signals
        subscribers when the view's message set became quorum-capable.
        """
        if message is None:
            return
        if trace.enabled():
            self._record_recv(message)
        if not self._is_acceptable_message(message):
            self._buffer_future(message)
            return
        self.messages.add_message(message)
        self._speculate([message])
        self._signal_if_quorum(message.view, message.type)

    def add_messages(self, batch: Sequence[IbftMessage]) -> None:
        """Batched inbound path — the TPU-native ingress.

        Sender signatures for the whole batch are verified in one device call
        (when a batch verifier is present), then each message passes the same
        height/round acceptance gate as ``add_message``.  Observable semantics
        match N calls to ``add_message``; cost is one kernel launch.
        """
        if not batch:
            return
        if trace.enabled():
            for m in batch:
                self._record_recv(m)
        with trace.span(
            "ingress.batch", track=self._obs_track, lanes=len(batch)
        ):
            gated = []
            for m in batch:
                if self._gate_height_round(m):
                    gated.append(m)
                else:
                    self._buffer_future(m)
            if self.batch_verifier is not None:
                mask = self.batch_verifier.verify_senders(gated)
                accepted = [m for m, ok in zip(gated, mask) if bool(ok)]
            else:
                accepted = [
                    m for m in gated if self.backend.is_valid_validator(m)
                ]

        # Store everything first, then signal once per (view, type) key —
        # signaling mid-batch could find quorum incomplete and never re-check.
        to_signal: dict[tuple[int, int, int], tuple[View, object]] = {}
        for message in accepted:
            self.messages.add_message(message)
            if message.view is not None:
                key = (message.view.height, message.view.round, int(message.type))
                to_signal.setdefault(key, (message.view, message.type))
        self._speculate(accepted)
        for view, message_type in to_signal.values():
            self._signal_if_quorum(view, message_type)

    def add_verified_messages(self, batch: Sequence[IbftMessage]) -> None:
        """Store messages whose envelope signatures the caller has ALREADY
        verified through this engine's own verifier.

        The chain runner's cross-height overlap worker uses this: it
        drains the future-height buffer and batch-verifies the envelopes
        while the previous height's COMMIT drain is still in flight, then
        hands the survivors over here — no re-verification, no height gate
        (the messages are for a height the engine has not reached yet; the
        store keys them by their own view and ``run_sequence`` finds them
        via subscribe-then-recheck).  NEVER feed this from an unverified
        source: the store's last-write-wins dedup would let a forged
        sender evict a genuine message.
        """
        to_signal: dict[tuple[int, int, int], tuple[View, object]] = {}
        stored: list[IbftMessage] = []
        for message in batch:
            if message.view is None or not isinstance(message.type, MessageType):
                continue
            self.messages.add_message(message)
            stored.append(message)
            key = (message.view.height, message.view.round, int(message.type))
            to_signal.setdefault(key, (message.view, message.type))
        self._speculate(stored)
        for view, message_type in to_signal.values():
            self._signal_if_quorum(view, message_type)

    def _speculate(self, msgs: Sequence[IbftMessage]) -> None:
        """Queue stored COMMITs' seals for off-path speculative
        verification (no-op without a speculator).  Runs AFTER the store
        insert so a verdict can never exist for a message the store
        rejected; the cache key binds the carried proposal hash, so the
        verdict is only ever a hit when the drain's accepted proposal
        matches."""
        if self.speculator is None or not msgs:
            return
        try:
            self.speculator.submit_commit_messages(msgs)
        except Exception as err:  # noqa: BLE001 - speculation is advisory
            self.log.debug("speculative submit failed", err)

    # -- future-height buffer (chain handoff support) -----------------------

    def _buffer_future(self, message: Optional[IbftMessage]) -> bool:
        """Hold a message ONE height ahead (bounded, deduped).

        Anything further ahead is dropped: consensus only ever needs the
        NEXT height's early traffic, and an unbounded horizon is an
        unbounded spam surface.  Dedup key (type, height, round, sender)
        matches the store's slot rule with last-write-wins.  PREPREPAREs
        alone get ``future_proposal_horizon`` heights (see __init__: one
        proposal per height per sender, and a dropped one is a liveness
        wedge)."""
        if message is None or message.view is None:
            return False
        if not isinstance(message.type, MessageType):
            return False
        view = message.view
        horizon = (
            self.future_proposal_horizon
            if message.type == MessageType.PREPREPARE
            else 1
        )
        if not self.state.height < view.height <= self.state.height + horizon:
            return False
        # Membership pre-filter on the CLAIMED sender (no signature work):
        # without it, forged identities fill future_cap_total for free and
        # starve genuine validators' early traffic every height.  A
        # non-member claim can never verify at flush anyway; when the
        # embedder cannot answer for a future height, fall through — the
        # caps still bound the buffer.
        try:
            if message.sender not in self.backend.get_voting_powers(
                view.height
            ):
                return False
        except Exception:  # noqa: BLE001 - unknown future set: caps bound it
            pass
        key = (int(message.type), view.height, view.round, message.sender)
        with self._future_lock:
            per_sender = self._future_buffer.setdefault(message.sender, {})
            slot = per_sender.get(key)
            if slot is not None:
                # Each slot keeps the FIRST and the LATEST candidate.  The
                # buffer holds UNVERIFIED messages, so plain last-write-
                # wins would let a forged-sender message evict a genuine
                # buffered one (and first-write-wins would let a forgery
                # that raced ahead pin the slot).  With both ends kept,
                # the genuine message survives either arrival order; the
                # flush verifies all candidates and the store's own
                # (verified) last-write-wins dedup settles the slot.
                if len(slot) == 1:
                    slot.append(message)
                    self._future_count += 1
                else:
                    slot[1] = message
                return True
            if (
                len(per_sender) >= self.future_cap_per_sender
                or self._future_count >= self.future_cap_total
            ):
                if not per_sender:
                    del self._future_buffer[message.sender]
                return False
            per_sender[key] = [message]
            self._future_count += 1
        return True

    def take_future_messages(self, height: int) -> list[IbftMessage]:
        """Pop every buffered message for ``height``; drop anything staler.

        Called by ``run_sequence(height)`` at height start (the default
        flush) and by the chain runner's overlap worker, which pre-verifies
        the batch off the critical path and re-inserts the survivors via
        :meth:`add_verified_messages`."""
        out: list[IbftMessage] = []
        with self._future_lock:
            for sender in list(self._future_buffer):
                per_sender = self._future_buffer[sender]
                for key in list(per_sender):
                    if key[1] <= height:
                        slot = per_sender.pop(key)
                        self._future_count -= len(slot)
                        if key[1] == height:
                            out.extend(slot)
                if not per_sender:
                    del self._future_buffer[sender]
        return out

    @property
    def future_buffered(self) -> int:
        with self._future_lock:
            return self._future_count

    def future_commit_evidence(self, height: int) -> int:
        """Combined voting power of the distinct senders with buffered
        COMMITs for ``height`` — in the same units as
        ``validator_manager.quorum_size``, so weighted validator sets
        compare correctly (a raw sender count never reaches a
        power-denominated quorum).

        The chain layer's fall-behind tripwire: a quorum's worth of
        COMMITs for a FUTURE height means peers are finalizing past this
        node — consensus here cannot catch up, only block sync can.  The
        senders are not signature-verified yet (the buffer holds raw
        ingress; unknown senders weigh zero), so callers treat the value
        as a hint: the sync path re-verifies every fetched block against
        real quorums, making a spoofed trigger a wasted poll, never a
        wrong chain."""
        commit = int(MessageType.COMMIT)
        with self._future_lock:
            senders = [
                sender
                for sender, per_sender in self._future_buffer.items()
                if any(
                    key[0] == commit and key[1] == height
                    for key in per_sender
                )
            ]
        return sum(self.validator_manager.power_of(s) for s in senders)

    def _flush_future(self, height: int) -> None:
        batch = self.take_future_messages(height)
        if batch:
            self.add_messages(batch)

    def _apply_restore(self, restore: RestoredState) -> None:
        """Re-enter a height mid-round from a WAL lock record.

        The restored engine resumes in COMMIT for the locked round with
        the PC pinned (``latest_pc``), so its ROUND_CHANGE messages carry
        the certificate and it can never prepare a different proposal for
        this height — the no-equivocation recovery invariant.  It also
        re-announces its COMMIT: the seal is rebuilt from the same key
        over the same proposal hash, which peers dedup by sender."""
        certificate = restore.certificate
        self.state.set_view(View(height=restore.height, round=restore.round))
        if hasattr(self.batch_verifier, "note_round"):
            self.batch_verifier.note_round(restore.round)
        if certificate is not None and certificate.proposal_message is not None:
            proposal = helpers.extract_proposal(certificate.proposal_message)
            self.state.set_proposal_message(certificate.proposal_message)
            self.state.finalize_prepare(certificate, proposal)
            self.state.set_round_started(True)
            self._send_commit_message(self.state.view)
        trace.instant(
            "sequence.restore",
            track=self._obs_track,
            height=restore.height,
            round=restore.round,
            locked=certificate is not None,
        )

    def _signal_if_quorum(self, view: Optional[View], message_type) -> None:
        """Signal subscribers when quorum became possible
        (reference core/ibft.go:1111-1121)."""
        if view is None or view.height != self.state.height:
            return
        msgs = self.messages.get_valid_messages(view, message_type, lambda _m: True)
        if self._has_quorum_by_msg_type(msgs, message_type):
            self.messages.signal_event(message_type, view)

    def _is_acceptable_message(self, message: IbftMessage) -> bool:
        """Inbound acceptance gate (reference core/ibft.go:1126-1149).

        Signature verification is NEVER deferred past the store: the store
        dedups by (type, height, round, sender) with last-write-wins, so an
        unverified message with a forged ``sender`` field could evict a
        validator's genuine stored message and break round liveness.  Batch
        ingress (:meth:`add_messages`) keeps the same gate, just amortized
        over one device call per burst.
        """
        if not self._gate_height_round(message):
            return False
        # sender signature + validator-set membership (embedder crypto)
        return self.backend.is_valid_validator(message)

    def _gate_height_round(self, message: IbftMessage) -> bool:
        if message.view is None:
            return False
        # Unknown open-enum types preserved by the wire codec are not
        # consensus messages: reject at the ingress gate so the signal path
        # never consults the store with a type it has no key for.
        if not isinstance(message.type, MessageType):
            return False
        state_height = self.state.height
        if state_height > message.view.height:
            return False
        if state_height == message.view.height:
            return message.view.round >= self.state.round
        # Future heights never enter the store through the gate: height+1
        # goes through the bounded dedup buffer (the ingress paths call
        # _buffer_future on gate failure), anything further is dropped —
        # the old "accept any future height" rule let one spammer grow the
        # store without bound.
        return False

    # -- quorum dispatch (reference core/ibft.go:1272-1284) -----------------

    def _has_quorum_by_msg_type(
        self, msgs: Sequence[IbftMessage], message_type
    ) -> bool:
        if message_type == MessageType.PREPREPARE:
            return len(msgs) >= 1
        if message_type == MessageType.PREPARE:
            return self.validator_manager.has_prepare_quorum(
                self.state.name, self.state.proposal_message, msgs
            )
        if message_type in (MessageType.ROUND_CHANGE, MessageType.COMMIT):
            return self.validator_manager.has_quorum(senders_of(msgs))
        return False

    def _subscribe(self, details: SubscriptionDetails):
        """Subscribe-then-recheck (closes the missed-message race;
        reference core/ibft.go:1286-1298).  A pending aggregate quorum
        certificate counts as a COMMIT wake condition — under tree-
        aggregated dissemination the certificate may be the ONLY commit
        evidence this node ever receives, so missing it would stall the
        phase forever."""
        subscription = self.messages.subscribe(details)
        msgs = self.messages.get_valid_messages(
            details.view, details.message_type, lambda _m: True
        )
        if self._has_quorum_by_msg_type(msgs, details.message_type):
            self.messages.signal_event(details.message_type, details.view)
        elif (
            details.message_type == MessageType.COMMIT
            and self.cert_verifier is not None
        ):
            with self._cert_lock:
                pending = details.view.height in self._pending_certs
            if pending:
                self.messages.signal_event(details.message_type, details.view)
        return subscription

    # -- state helpers ------------------------------------------------------

    def _move_to_new_round(self, round_: int) -> None:
        """(reference core/ibft.go:994-1003)"""
        trace.instant("round.change", track=self._obs_track, round=round_)
        self._hash_memo.clear()
        # Round advance drives the pack cache's oldest-round-first eviction
        # (entries packed for dead rounds yield before the live round's).
        if hasattr(self.batch_verifier, "note_round"):
            self.batch_verifier.note_round(round_)
        if self.speculator is not None:
            self.speculator.note_view(self.state.height, round_)
        self.state.set_view(View(height=self.state.height, round=round_))
        self.state.set_round_started(False)
        self.state.set_proposal_message(None)
        self.state.change_state(StateName.NEW_ROUND)

    def _accept_proposal(self, proposal_message: IbftMessage) -> None:
        """Accept a proposal and move to PREPARE (reference core/ibft.go:1094-1098)."""
        trace.instant(
            "proposal.accept", track=self._obs_track, round=self.state.round
        )
        # accept -> finalize latency anchor (one clock read per proposal;
        # the histogram itself records only when fixed histograms are on).
        self._accept_ts = time.perf_counter()
        self._hash_memo.clear()
        self.state.set_proposal_message(proposal_message)
        self.state.change_state(StateName.PREPARE)

    def _insert_block(self) -> None:
        """Insert the finalized block and GC (reference core/ibft.go:978-991).

        The step order is the chain layer's crash-consistency contract:
        finalize (insert_proposal) -> on_finalize (the WAL's fsynced
        append) -> prune.  A crash between any two steps never loses a
        finalized height — before the WAL append the store still holds the
        commit-quorum evidence (nothing pruned yet), after it the height
        is durable.  on_finalize is deliberately NOT exception-guarded: a
        WAL that cannot append must stop the height from pruning the only
        other copy of its evidence (chaos kill-point test pins this)."""
        height = self.state.height
        proposal = Proposal(
            raw_proposal=self.state.raw_proposal or b"",
            round=self.state.round,
        )
        seals = self.state.committed_seals
        if self._accept_ts is not None:
            metrics.observe_fixed(
                ACCEPT_FINALIZE_MS_KEY,
                (time.perf_counter() - self._accept_ts) * 1e3,
            )
            self._accept_ts = None
        self.backend.insert_proposal(proposal, seals)
        if self.on_finalize is not None:
            self.on_finalize(height, proposal, seals)
        self.messages.prune_by_height(height)

    # -- outbound (reference core/ibft.go:1234-1270) ------------------------

    def _multicast(self, message: IbftMessage) -> None:
        """Stamp + multicast: the telemetry plane's outbound seam.

        When tracing is enabled every outbound message gains a
        :class:`~go_ibft_tpu.messages.wire.TraceContext` (origin track,
        view, monotonic send µs, fresh span id) and a ``net.send``
        instant; receivers record the matching ``net.recv`` at ingress,
        so N nodes' flight recorders hold causally-linked records the
        timeline tool (:mod:`go_ibft_tpu.obs.timeline`) can merge.  The
        context rides OUTSIDE the signed bytes — object attribute on
        loopback, :func:`~go_ibft_tpu.messages.wire.encode_traced` frame
        on socket transports — so signatures are unaffected.  Disabled
        tracing keeps this a single predicate check.
        """
        if trace.enabled():
            view = message.view
            ctx = TraceContext(
                origin=self._obs_track,
                height=view.height if view is not None else self.state.height,
                round=view.round if view is not None else self.state.round,
                sent_us=time.perf_counter_ns() // 1000,
                span_id=trace.next_span_id(),
            )
            message.trace_ctx = ctx
            trace.instant(
                "net.send",
                track=self._obs_track,
                height=ctx.height,
                round=ctx.round,
                type=int(message.type),
                span=ctx.span_id,
            )
        self.transport.multicast(message)

    def _send_preprepare_message(self, message: IbftMessage) -> None:
        self._multicast(message)

    def _send_round_change_message(self, height: int, new_round: int) -> None:
        self._multicast(
            self.backend.build_round_change_message(
                self.state.latest_prepared_proposal,
                self.state.latest_pc,
                View(height=height, round=new_round),
            )
        )

    def _send_prepare_message(self, view: View) -> None:
        self._multicast(
            self.backend.build_prepare_message(self.state.proposal_hash or b"", view)
        )

    def _send_commit_message(self, view: View) -> None:
        self._multicast(
            self.backend.build_commit_message(self.state.proposal_hash or b"", view)
        )
