"""Embedder contract: message construction, verification, notifications.

Re-design of the reference's Backend interface split
(core/backend.go:12-85).  The shape is preserved — the engine owns consensus,
the embedder owns blocks, crypto and networking — with one TPU-native
addition: :class:`BatchVerifier`, which lets the engine drain a whole round's
message store in one fixed-shape device batch instead of per-message
sequential verifies (SURVEY.md §2 #10, BASELINE.md north star).
"""

from __future__ import annotations

from typing import Mapping, Optional, Protocol, Sequence, runtime_checkable

import numpy as np

from ..messages.helpers import CommittedSeal
from ..messages.wire import (
    IbftMessage,
    PreparedCertificate,
    Proposal,
    RoundChangeCertificate,
    View,
)


class MessageConstructor(Protocol):
    """Builds signed consensus messages (reference core/backend.go:12-34)."""

    def build_preprepare_message(
        self,
        raw_proposal: bytes,
        certificate: Optional[RoundChangeCertificate],
        view: View,
    ) -> IbftMessage: ...

    def build_prepare_message(self, proposal_hash: bytes, view: View) -> IbftMessage: ...

    def build_commit_message(self, proposal_hash: bytes, view: View) -> IbftMessage:
        """Must create a committed seal for the proposal hash."""
        ...

    def build_round_change_message(
        self,
        proposal: Optional[Proposal],
        certificate: Optional[PreparedCertificate],
        view: View,
    ) -> IbftMessage: ...


class Verifier(Protocol):
    """Expensive predicates injected by the embedder (reference core/backend.go:37-56)."""

    def is_valid_proposal(self, raw_proposal: bytes) -> bool: ...

    def is_valid_validator(self, msg: IbftMessage) -> bool:
        """Signature recovers to ``msg.sender`` AND the sender is a validator."""
        ...

    def is_proposer(self, validator_id: bytes, height: int, round_: int) -> bool: ...

    def is_valid_proposal_hash(self, proposal: Proposal, hash_: bytes) -> bool: ...

    def is_valid_committed_seal(
        self,
        proposal_hash: bytes,
        committed_seal: CommittedSeal,
        height: Optional[int] = None,
    ) -> bool:
        """Seal signature recovers to ``committed_seal.signer``.

        The engine always passes ``height`` (the height being finalized) so
        implementations can ALSO enforce validator-set membership — keeping
        the accept-set identical to the batched verifiers
        (:meth:`BatchVerifier.verify_committed_seals`).  The reference's
        two-argument shape (core/backend.go:50-55) is the ``height=None``
        case.
        """
        ...


class Notifier(Protocol):
    """Consensus execution callbacks (reference core/backend.go:59-65)."""

    def round_starts(self, view: View) -> None: ...

    def sequence_cancelled(self, view: View) -> None: ...


class ValidatorBackend(Protocol):
    """Voting-power source (reference core/validator_manager.go:17-20)."""

    def get_voting_powers(self, height: int) -> Mapping[bytes, int]: ...


@runtime_checkable
class BatchVerifier(Protocol):
    """TPU-native batched verification — the new capability of this build.

    A backend additionally implementing this protocol lets the engine replace
    the reference's per-message predicate loop (core/ibft.go:931-944 calling
    Verifier once per message under the store lock) with one device batch per
    phase.  Implementations return boolean masks aligned with the input
    order; the engine prunes exactly the ``False`` entries, preserving the
    observable semantics of GetValidMessages
    (reference messages/messages.go:169-199).
    """

    def verify_senders(self, msgs: Sequence[IbftMessage]) -> np.ndarray:
        """Mask of IsValidValidator over a message batch."""
        ...

    def verify_committed_seals(
        self,
        proposal_hash: bytes,
        seals: Sequence[CommittedSeal],
        height: int,
    ) -> np.ndarray:
        """Mask of IsValidCommittedSeal over a seal batch for one hash.

        ``height`` selects the validator set the signers must belong to.
        """
        ...


@runtime_checkable
class FusedBatchVerifier(BatchVerifier, Protocol):
    """BatchVerifier that can ALSO certify quorum on device.

    The flagship fusion (SURVEY.md §2 #2/#3, ops/quorum.py): one compiled
    program per phase returns both the validity mask and the voting-power
    quorum verdict, so the reduction never leaves the device.  The engine
    uses these for its PREPARE/COMMIT hot path when
    :meth:`supports_fused` says the height's powers fit the device's exact
    integer range; otherwise it falls back to mask-on-device +
    big-int-quorum-on-host.
    """

    def supports_fused(self, height: int) -> bool: ...

    def certify_senders(
        self,
        msgs: Sequence[IbftMessage],
        height: int,
        threshold: Optional[int] = None,
    ) -> tuple[np.ndarray, bool]:
        """(validity mask, quorum reached) for one view's envelopes.

        ``threshold`` overrides the height's quorum size — the engine
        passes ``quorum - proposer_power`` to credit the proposer's
        proposal in the prepare phase (reference
        core/validator_manager.go:99-127)."""
        ...

    def certify_seals(
        self,
        proposal_hash: bytes,
        seals: Sequence[CommittedSeal],
        height: int,
        threshold: Optional[int] = None,
    ) -> tuple[np.ndarray, bool]:
        """(validity mask, quorum reached) for one view's committed seals."""
        ...


class Backend(
    MessageConstructor, Verifier, ValidatorBackend, Notifier, Protocol
):
    """Composite embedder interface (reference core/backend.go:69-85)."""

    def build_proposal(self, view: View) -> bytes: ...

    def insert_proposal(
        self, proposal: Proposal, committed_seals: Sequence[CommittedSeal]
    ) -> None:
        """Insert a finalized proposal.  ``proposal.round`` matters: each
        committed seal signed the tuple (raw_proposal, round)."""
        ...

    def id(self) -> bytes:
        """This validator's address."""
        ...
