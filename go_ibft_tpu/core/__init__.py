"""Consensus core: engine, state, quorum math, embedder contracts.

TPU-native re-design of the reference's L3+L4 (core/ package): see
SURVEY.md §1.  Control flow is asyncio on host; expensive verification is
delegated to a BatchVerifier draining device batches.
"""

from .backend import (
    Backend,
    BatchVerifier,
    FusedBatchVerifier,
    MessageConstructor,
    Notifier,
    ValidatorBackend,
    Verifier,
)
from .ibft import (
    DEFAULT_BASE_ROUND_TIMEOUT,
    IBFT,
    RestoredState,
    get_round_timeout,
)
from .state import SequenceState, StateName
from .transport import BatchingIngress, LoopbackTransport, Transport
from .validator_manager import (
    Logger,
    ValidatorManager,
    VotingPowerError,
    calculate_quorum,
    senders_of,
)

__all__ = [
    "Backend",
    "BatchingIngress",
    "BatchVerifier",
    "DEFAULT_BASE_ROUND_TIMEOUT",
    "FusedBatchVerifier",
    "IBFT",
    "Logger",
    "LoopbackTransport",
    "MessageConstructor",
    "Notifier",
    "SequenceState",
    "StateName",
    "Transport",
    "ValidatorBackend",
    "ValidatorManager",
    "Verifier",
    "VotingPowerError",
    "calculate_quorum",
    "get_round_timeout",
    "senders_of",
]
