"""``python -m go_ibft_tpu.node --config node.toml`` — run one validator.

Exit codes: 0 clean run/drain, 1 crash, 2 bad config.  The process
prints exactly two JSON lines on stdout — a boot line (bound ports,
resumed height) and a final drain report — so supervisors and the fleet
harness (:mod:`go_ibft_tpu.sim.fleet`) parse state instead of scraping
logs.  ``--check`` validates the config and exits without binding
anything (the supervisor pre-flight).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m go_ibft_tpu.node", description=__doc__
    )
    parser.add_argument("--config", required=True, help="path to node.toml")
    parser.add_argument(
        "--check",
        action="store_true",
        help="validate the config and exit (no sockets, no chain)",
    )
    args = parser.parse_args(argv)

    from .config import NodeConfigError, load_config

    try:
        config = load_config(args.config)
    except (OSError, NodeConfigError) as err:
        print(json.dumps({"config_error": str(err)}), flush=True)
        return 2
    if args.check:
        print(
            json.dumps(
                {
                    "config_ok": True,
                    "node": config.node_id,
                    "validators": len(config.validators),
                    "peers": len(config.consensus.peers),
                }
            ),
            flush=True,
        )
        return 0

    from .node import ValidatorNode

    try:
        node = ValidatorNode(config)
    except NodeConfigError as err:
        print(json.dumps({"config_error": str(err)}), flush=True)
        return 2
    try:
        report = asyncio.run(node.run())
    except Exception as err:  # noqa: BLE001 - the report line IS the contract
        print(json.dumps({"node_error": repr(err)}), flush=True)
        return 1
    print(json.dumps(report), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
