"""The proof API: the serve plane's first wire transport (ISSUE 19).

Serves :class:`~go_ibft_tpu.serve.ProofServer` finality proofs to
**untrusted** clients over plain HTTP/1.1 + JSON — the wire format is
``serve/proof.py``'s existing codec (``FinalityProof.to_wire()``,
``PROOF_WIRE_VERSION``), so any light client that already speaks the
in-process codec speaks the socket one for free (docs/SERVING.md).

Endpoints::

    GET /head                          -> {"head": H}
    GET /proof?checkpoint=C[&target=T] -> {"version": 1, "head": H,
                                           "proof": <FinalityProof wire>}

Hostile-client posture — the reason this is NOT another
``ThreadingHTTPServer`` mount like :mod:`go_ibft_tpu.obs.httpd`:

* **one IO thread, N sockets**: a ``selectors`` event loop owns every
  connection, so 1k-10k concurrent clients cost file descriptors, not
  threads — the fleet-harness acceptance shape (and the reason a
  slowloris army cannot exhaust a thread pool that does not exist);
* **bounded requests**: request line + headers are capped at
  ``max_request_bytes`` (431 + close past it) and only ``GET`` with no
  body is accepted (request smuggling surface: zero);
* **per-connection limits**: at ``max_connections`` open sockets new
  arrivals get an immediate 503 + close; a connection holding an
  INCOMPLETE request past ``header_timeout_s`` (the slowloris
  signature: bytes trickling forever) is cut; an idle keep-alive
  connection past ``idle_timeout_s`` is closed like any production
  front-end would;
* **isolated proof builds**: ``get_proof`` (chain reads + self-check
  crypto) runs on a small worker pool, never on the IO thread — a slow
  build delays its own client, not accepts/reads/timeout sweeps.

The consensus plane is untouched: this server only reads through the
``ProofServer``'s coalesced read tier (QoS: the TenantScheduler's
``read`` class), so a proof flood cannot starve a live round.
"""

from __future__ import annotations

import collections
import json
import selectors
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, Optional, Tuple

from ..obs import trace
from ..serve.proof import PROOF_WIRE_VERSION, ProofError
from ..utils import metrics

__all__ = ["ProofApiServer"]

REQUESTS_KEY = ("go-ibft", "node", "proof_api_requests")
REJECTED_CONN_KEY = ("go-ibft", "node", "proof_api_rejected_conns")
SLOW_CLOSE_KEY = ("go-ibft", "node", "proof_api_slow_closes")
IDLE_CLOSE_KEY = ("go-ibft", "node", "proof_api_idle_closes")
OVERSIZE_KEY = ("go-ibft", "node", "proof_api_oversize")

_MAX_HEADER_LINES = 64


class _Conn:
    """Per-socket state owned by the IO thread."""

    __slots__ = (
        "sock",
        "addr",
        "buf",
        "out",
        "last_activity",
        "request_started",
        "close_after_write",
        "inflight",
    )

    def __init__(self, sock: socket.socket, addr) -> None:
        self.sock = sock
        self.addr = addr
        self.buf = b""
        self.out = b""
        self.last_activity = time.monotonic()
        # Set while a PARTIAL request sits in ``buf`` (the slowloris
        # clock); cleared when a full request parses or the buf drains.
        self.request_started: Optional[float] = None
        self.close_after_write = False
        # A request is being built on the worker pool: reads pause (one
        # request in flight per connection; no pipelining).
        self.inflight = False


class ProofApiServer:
    """Bounded HTTP/1.1 JSON front-end over a :class:`ProofServer`.

    ``head_fn`` returns the latest finalized height (the runner's
    ``latest_height``); ``ready_fn``, when given, gates ``/proof`` with
    503 until the node is routable (the /readyz condition) so a
    warm-starting node never serves a stale chain to a client that
    found it before the load balancer did.

    ``checkpoints_fn`` (ISSUE 20) serves ``GET /checkpoints`` — the
    epoch skip-chain payload a
    :class:`~go_ibft_tpu.lightsync.client.CheckpointClient` anchors on.
    Wire a :class:`~go_ibft_tpu.lightsync.checkpoint.Checkpointer`'s
    ``wire_payload`` here; without one the route answers 404.  Query
    params: ``epoch=<N>`` descends the skip path to epoch N instead of
    the latest, ``all=1`` serves the full linear epoch list (the
    measured baseline shape).  Builds run on the worker pool — lazy
    signing may pay pure-Python G2 work, never on the IO thread.
    """

    def __init__(
        self,
        proof_server,
        head_fn: Callable[[], int],
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        max_connections: int = 1024,
        max_request_bytes: int = 8192,
        header_timeout_s: float = 5.0,
        idle_timeout_s: float = 30.0,
        workers: int = 2,
        ready_fn: Optional[Callable[[], Tuple[bool, dict]]] = None,
        checkpoints_fn: Optional[Callable[..., dict]] = None,
    ) -> None:
        self._proofs = proof_server
        self._head_fn = head_fn
        self._ready_fn = ready_fn
        self._checkpoints_fn = checkpoints_fn
        self._host = host
        self._want_port = port
        self.max_connections = max_connections
        self.max_request_bytes = max_request_bytes
        self.header_timeout_s = header_timeout_s
        self.idle_timeout_s = idle_timeout_s
        self._n_workers = max(1, workers)
        self.port: Optional[int] = None
        self._listener: Optional[socket.socket] = None
        self._selector: Optional[selectors.BaseSelector] = None
        self._thread: Optional[threading.Thread] = None
        self._pool: Optional[ThreadPoolExecutor] = None
        self._conns: Dict[socket.socket, _Conn] = {}
        # Worker -> IO thread handoff: finished responses queue here and
        # the socketpair write wakes the selector.
        self._done: collections.deque = collections.deque()
        self._wake_r: Optional[socket.socket] = None
        self._wake_w: Optional[socket.socket] = None
        self._stopping = threading.Event()
        self._stats_lock = threading.Lock()
        self.stats_counters = {
            "connections_total": 0,
            "requests": 0,
            "proofs_served": 0,
            "rejected_connections": 0,
            "slow_client_closes": 0,
            "idle_closes": 0,
            "oversize_requests": 0,
            "bad_requests": 0,
            "not_ready": 0,
            "checkpoints_served": 0,
        }

    # -- lifecycle ------------------------------------------------------

    def start(self) -> int:
        if self._thread is not None:
            raise RuntimeError("ProofApiServer already started")
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((self._host, self._want_port))
        listener.listen(min(1024, socket.SOMAXCONN * 4))
        listener.setblocking(False)
        self.port = listener.getsockname()[1]
        self._listener = listener
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._selector = selectors.DefaultSelector()
        self._selector.register(listener, selectors.EVENT_READ, "accept")
        self._selector.register(self._wake_r, selectors.EVENT_READ, "wake")
        self._pool = ThreadPoolExecutor(
            max_workers=self._n_workers, thread_name_prefix="proof-api"
        )
        self._stopping.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"proof-api-{self.port}", daemon=True
        )
        self._thread.start()
        return self.port

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def stop(self) -> None:
        """Close the listener first (no new clients), then drain out."""
        if self._thread is None:
            return
        self._stopping.set()
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass
        self._thread.join(timeout=10.0)
        self._thread = None
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def stats(self) -> dict:
        with self._stats_lock:
            out = dict(self.stats_counters)
        out["open_connections"] = len(self._conns)
        out["max_connections"] = self.max_connections
        return out

    def _count(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self.stats_counters[key] += n

    # -- IO loop --------------------------------------------------------

    def _loop(self) -> None:
        try:
            while not self._stopping.is_set():
                events = self._selector.select(timeout=0.05)
                for key, mask in events:
                    what = key.data
                    if what == "accept":
                        self._accept()
                    elif what == "wake":
                        try:
                            self._wake_r.recv(4096)
                        except OSError:
                            pass
                    else:  # a connection
                        conn = what
                        if mask & selectors.EVENT_READ:
                            self._readable(conn)
                        if (
                            mask & selectors.EVENT_WRITE
                            and conn.sock in self._conns
                        ):
                            self._writable(conn)
                self._drain_done()
                self._sweep_timeouts()
        finally:
            for conn in list(self._conns.values()):
                self._close(conn)
            for sock in (self._listener, self._wake_r, self._wake_w):
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
            self._selector.close()

    def _accept(self) -> None:
        for _ in range(64):  # accept in batches, never starve the loop
            try:
                sock, addr = self._listener.accept()
            except (BlockingIOError, OSError):
                return
            self._count("connections_total")
            if len(self._conns) >= self.max_connections:
                # Over the cap: tell the client it is load, not protocol.
                self._count("rejected_connections")
                metrics.inc_counter(REJECTED_CONN_KEY)
                try:
                    sock.send(
                        b"HTTP/1.1 503 Service Unavailable\r\n"
                        b"Content-Length: 0\r\nConnection: close\r\n\r\n"
                    )
                except OSError:
                    pass
                # Drain whatever request bytes already arrived: closing
                # with unread data RSTs the connection, and the RST can
                # destroy the 503 in the client's receive buffer before
                # it is read.
                try:
                    sock.setblocking(False)
                    while sock.recv(4096):
                        pass
                except OSError:
                    pass
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            sock.setblocking(False)
            conn = _Conn(sock, addr)
            self._conns[sock] = conn
            self._selector.register(sock, selectors.EVENT_READ, conn)

    def _close(self, conn: _Conn) -> None:
        if conn.sock in self._conns:
            del self._conns[conn.sock]
            try:
                self._selector.unregister(conn.sock)
            except (KeyError, ValueError):
                pass
        try:
            conn.sock.close()
        except OSError:
            pass

    def _readable(self, conn: _Conn) -> None:
        try:
            chunk = conn.sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close(conn)
            return
        if not chunk:
            self._close(conn)
            return
        now = time.monotonic()
        conn.last_activity = now
        conn.buf += chunk
        if conn.inflight:
            # One request at a time; extra bytes wait in buf — but a
            # client that floods while we build is shedding, not waiting.
            if len(conn.buf) > self.max_request_bytes:
                self._count("oversize_requests")
                metrics.inc_counter(OVERSIZE_KEY)
                self._close(conn)
            return
        if len(conn.buf) > self.max_request_bytes:
            self._count("oversize_requests")
            metrics.inc_counter(OVERSIZE_KEY)
            self._respond(
                conn,
                431,
                {"error": "request too large"},
                close=True,
            )
            return
        if conn.request_started is None:
            conn.request_started = now
        head, sep, rest = conn.buf.partition(b"\r\n\r\n")
        if not sep:
            return  # incomplete: the slowloris clock is running
        conn.buf = rest
        conn.request_started = None
        self._dispatch(conn, head)

    def _writable(self, conn: _Conn) -> None:
        try:
            sent = conn.sock.send(conn.out)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close(conn)
            return
        conn.out = conn.out[sent:]
        conn.last_activity = time.monotonic()
        if conn.out:
            return
        if conn.close_after_write:
            self._close(conn)
            return
        self._selector.modify(conn.sock, selectors.EVENT_READ, conn)
        if conn.buf:
            # A pipelined follow-up arrived while we served: handle it.
            self._readable_buffered(conn)

    def _readable_buffered(self, conn: _Conn) -> None:
        head, sep, rest = conn.buf.partition(b"\r\n\r\n")
        if not sep:
            if conn.buf:
                conn.request_started = time.monotonic()
            return
        conn.buf = rest
        conn.request_started = None
        self._dispatch(conn, head)

    def _sweep_timeouts(self) -> None:
        now = time.monotonic()
        for conn in list(self._conns.values()):
            if conn.inflight:
                continue
            if conn.out:
                # Slow-read mirror of slowloris: a client that never
                # drains its response holds a socket hostage.
                if now - conn.last_activity > self.idle_timeout_s:
                    self._count("idle_closes")
                    metrics.inc_counter(IDLE_CLOSE_KEY)
                    self._close(conn)
                continue
            if (
                conn.request_started is not None
                and now - conn.request_started > self.header_timeout_s
            ):
                # Slowloris: a request that trickles header bytes forever.
                self._count("slow_client_closes")
                metrics.inc_counter(SLOW_CLOSE_KEY)
                trace.instant("node.proof_api.slow_close")
                self._respond(
                    conn, 408, {"error": "request header timeout"}, close=True
                )
            elif (
                conn.request_started is None
                and now - conn.last_activity > self.idle_timeout_s
            ):
                self._count("idle_closes")
                metrics.inc_counter(IDLE_CLOSE_KEY)
                self._close(conn)

    # -- request handling ------------------------------------------------

    def _dispatch(self, conn: _Conn, head: bytes) -> None:
        self._count("requests")
        metrics.inc_counter(REQUESTS_KEY)
        lines = head.split(b"\r\n")
        if len(lines) > _MAX_HEADER_LINES:
            self._count("bad_requests")
            self._respond(conn, 431, {"error": "too many headers"}, close=True)
            return
        parts = lines[0].split()
        if len(parts) != 3:
            self._count("bad_requests")
            self._respond(conn, 400, {"error": "bad request line"}, close=True)
            return
        method, target, _version = parts
        keep_alive = True
        has_body = False
        for line in lines[1:]:
            lowered = line.lower()
            if lowered.startswith(b"connection:") and b"close" in lowered:
                keep_alive = False
            if lowered.startswith((b"content-length:", b"transfer-encoding:")):
                has_body = True
        if method != b"GET":
            self._count("bad_requests")
            self._respond(
                conn, 405, {"error": "only GET"}, close=not keep_alive
            )
            return
        if has_body:
            # GET with a body is a smuggling vector, not a client.
            self._count("bad_requests")
            self._respond(conn, 400, {"error": "GET takes no body"}, close=True)
            return
        conn.close_after_write = not keep_alive
        try:
            path, _, query = target.decode("ascii").partition("?")
        except UnicodeDecodeError:
            self._count("bad_requests")
            self._respond(conn, 400, {"error": "bad target"}, close=True)
            return
        if path == "/head":
            self._respond(conn, 200, {"head": self._head_fn()})
            return
        if path == "/checkpoints":
            if self._checkpoints_fn is None:
                self._respond(conn, 404, {"error": "not found", "path": path})
                return
            if self._ready_fn is not None:
                ready, _payload = self._ready_fn()
                if not ready:
                    self._count("not_ready")
                    self._respond(conn, 503, {"error": "not ready"})
                    return
            params = {}
            for pair in query.split("&"):
                name, _, value = pair.partition("=")
                if name:
                    params[name] = value
            try:
                epoch = (
                    int(params["epoch"]) if params.get("epoch") else None
                )
            except ValueError:
                self._respond(conn, 400, {"error": "epoch must be an integer"})
                return
            include_all = params.get("all") in ("1", "true")
            # Lazy-signing checkpointers pay pure-Python G2 work building
            # the payload — that belongs on the pool, not the IO thread.
            conn.inflight = True
            self._pool.submit(self._build_checkpoints, conn, epoch, include_all)
            return
        if path != "/proof":
            self._respond(conn, 404, {"error": "not found", "path": path})
            return
        if self._ready_fn is not None:
            ready, _payload = self._ready_fn()
            if not ready:
                self._count("not_ready")
                self._respond(conn, 503, {"error": "not ready"})
                return
        params = {}
        for pair in query.split("&"):
            name, _, value = pair.partition("=")
            if name:
                params[name] = value
        try:
            checkpoint = int(params.get("checkpoint", ""))
            target_h = (
                int(params["target"]) if params.get("target") else None
            )
        except ValueError:
            self._respond(
                conn,
                400,
                {"error": "checkpoint/target must be integers"},
            )
            return
        # The expensive part leaves the IO thread here.
        conn.inflight = True
        self._pool.submit(self._build_proof, conn, checkpoint, target_h)

    def _build_proof(
        self, conn: _Conn, checkpoint: int, target: Optional[int]
    ) -> None:
        """Worker-pool side: build + encode, then hand bytes back."""
        try:
            with trace.span(
                "node.proof_api", checkpoint=checkpoint, target=target or -1
            ):
                proof = self._proofs.get_proof(checkpoint, target)
            payload = {
                "version": PROOF_WIRE_VERSION,
                "head": self._head_fn(),
                "proof": proof.to_wire(),
            }
            code = 200
            self._count("proofs_served")
        except ProofError as err:
            code, payload = 416, {"error": str(err)}
        except Exception as err:  # noqa: BLE001 - a client must get an
            # answer, and the IO loop must never die for one request
            code, payload = 500, {"error": repr(err)}
        self._done.append((conn, code, payload))
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass

    def _build_checkpoints(
        self, conn: _Conn, epoch: Optional[int], include_all: bool
    ) -> None:
        """Worker-pool side of ``GET /checkpoints`` (ISSUE 20)."""
        try:
            from ..lightsync.checkpoint import CheckpointError
        except Exception:  # pragma: no cover - lightsync always present
            CheckpointError = ValueError  # noqa: N806
        try:
            with trace.span(
                "node.checkpoint_api",
                epoch=-1 if epoch is None else epoch,
                all=int(include_all),
            ):
                payload = self._checkpoints_fn(
                    target_epoch=epoch, include_all=include_all
                )
            payload = dict(payload)
            payload["head"] = self._head_fn()
            code = 200
            self._count("checkpoints_served")
        except CheckpointError as err:
            code, payload = 416, {"error": str(err)}
        except Exception as err:  # noqa: BLE001 - same contract as proofs:
            # the client gets an answer, the IO loop never dies for one
            code, payload = 500, {"error": repr(err)}
        self._done.append((conn, code, payload))
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass

    def _drain_done(self) -> None:
        while self._done:
            conn, code, payload = self._done.popleft()
            conn.inflight = False
            if conn.sock in self._conns:
                self._respond(conn, code, payload)

    def _respond(
        self, conn: _Conn, code: int, payload: dict, *, close: bool = False
    ) -> None:
        reason = {
            200: "OK",
            400: "Bad Request",
            404: "Not Found",
            405: "Method Not Allowed",
            408: "Request Timeout",
            416: "Range Not Satisfiable",
            431: "Request Header Fields Too Large",
            500: "Internal Server Error",
            503: "Service Unavailable",
        }.get(code, "OK")
        body = json.dumps(payload).encode("utf-8")
        close = close or conn.close_after_write
        conn.close_after_write = close
        conn.out += (
            f"HTTP/1.1 {code} {reason}\r\n"
            f"Content-Type: application/json\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: {'close' if close else 'keep-alive'}\r\n"
            f"\r\n"
        ).encode("ascii") + body
        if conn.sock in self._conns:
            self._selector.modify(
                conn.sock,
                selectors.EVENT_READ | selectors.EVENT_WRITE,
                conn,
            )
