"""The deployable validator process (ISSUE 19).

``python -m go_ibft_tpu.node --config node.toml`` boots one validator:
socket-native consensus gossip (:mod:`go_ibft_tpu.net`), WAL-backed
chain (:mod:`go_ibft_tpu.chain`), QoS-tiered verification
(:mod:`go_ibft_tpu.sched`), the proof-API wire transport
(:mod:`go_ibft_tpu.serve` over :mod:`.proof_api`), telemetry with the
liveness/readiness split (:mod:`go_ibft_tpu.obs.httpd`), and graceful
SIGTERM drain.  See docs/DEPLOYMENT.md.
"""

from .config import NodeConfig, NodeConfigError, load_config, parse_toml_subset
from .node import ValidatorNode, build_block_fn
from .proof_api import ProofApiServer

__all__ = [
    "NodeConfig",
    "NodeConfigError",
    "ProofApiServer",
    "ValidatorNode",
    "build_block_fn",
    "load_config",
    "parse_toml_subset",
]
