"""The deployable validator process (ISSUE 19).

:class:`ValidatorNode` composes every layer this repo has grown into ONE
process behind ONE config file:

* consensus   — ``core.IBFT`` + ``crypto.ECDSABackend`` gossiping over
  real TCP sockets (``net.GrpcTransport`` with peer reconnect), ingress
  batched through ``core.BatchingIngress``;
* persistence — ``chain.ChainRunner`` + ``chain.WriteAheadLog`` in
  ``data_dir``; boot always runs ``recover()`` (an empty WAL replays to
  genesis), so a restart resumes mid-round locks instead of
  double-signing;
* QoS         — one ``sched.TenantScheduler`` with the chain on the
  ``consensus`` tier and proof serving on the ``read`` tier, so client
  floods shed before a live round starves;
* serving     — ``serve.ProofServer`` exposed to untrusted clients over
  the :mod:`proof_api` wire transport;
* telemetry   — ``obs.httpd.TelemetryServer`` with /metrics, /healthz
  (liveness), /readyz (readiness: recovered + first height finalized),
  /statusz (scheduler + proof-API stats mounted);
* drain       — SIGTERM/SIGINT runs one graceful shutdown: stop taking
  proof clients, stop the height loop, stop the scheduler, fsync+close
  the WAL, export the per-node trace file, close the gossip listener.
  The trace export is what ``scripts/consensus_timeline.py`` merges
  into the cross-process timeline.

Lifecycle (the __main__ entry drives this)::

    node = ValidatorNode(load_config("node.toml"))
    report = asyncio.run(node.run())   # returns the drain report dict
"""

from __future__ import annotations

import asyncio
import os
import signal
import time
from typing import Optional

from ..chain import ChainRunner, WriteAheadLog
from ..core import IBFT, BatchingIngress
from ..crypto import PrivateKey
from ..crypto.backend import ECDSABackend
from ..net import GrpcTransport
from ..obs import trace
from ..utils import metrics
from ..verify import HostBatchVerifier
from .config import NodeConfig, NodeConfigError
from .proof_api import ProofApiServer

__all__ = ["ValidatorNode", "build_block_fn"]


class _NullLogger:
    def info(self, *a):
        pass

    debug = info

    def error(self, msg, *args):
        import sys

        print(f"[node error] {msg} {args}", file=sys.stderr, flush=True)


def build_block_fn(node_id: int):
    """The node's block builder: deterministic bytes per height.

    Every validator must build the IDENTICAL proposal for a height (the
    reference's ``Backend.BuildProposal`` determinism assumption in this
    payload-free reproduction), so the builder keys on the height alone —
    ``node_id`` rides along only for error messages."""
    del node_id

    def build(view) -> bytes:
        return b"fleet block %d" % view.height

    return build


class ValidatorNode:
    """One validator process: see the module docstring.

    Construction wires everything but opens no sockets; :meth:`run`
    owns the lifecycle.  ``install_signal_handlers=False`` lets tests
    embed a node in a process that keeps its own handlers.
    """

    def __init__(
        self,
        config: NodeConfig,
        *,
        logger=None,
        install_signal_handlers: bool = True,
    ) -> None:
        config.validate()
        self.config = config
        self._log = logger or _NullLogger()
        self._install_signals = install_signal_handlers
        os.makedirs(config.data_dir, exist_ok=True)

        if config.trace.enabled:
            trace.enable(config.trace.ring)

        # -- identity + validator set --------------------------------
        self.key = PrivateKey.from_seed(config.key_seed_bytes)
        powers = config.validator_powers()
        if self.key.address not in powers:
            raise NodeConfigError(
                f"node address {self.key.address.hex()} (from key_seed) is "
                f"not in [validators] — this process would gossip into a "
                f"committee that never counts it"
            )
        self.validators_src = ECDSABackend.static_validators(powers)

        # -- QoS scheduler -------------------------------------------
        self.scheduler = None
        batch_verifier = None
        if config.sched_enabled:
            from ..sched import TenantScheduler

            # Route per config ("host" default).  The auto route's device
            # cutover (>=16 lanes) would park the flush thread inside a
            # first-flush XLA compile — wedging live rounds, the proof
            # API's read tier AND scheduler.stop() during drain — so the
            # device path is opt-in and pre-compiled at boot (below).
            self.scheduler = TenantScheduler(route=config.sched_route)
            batch_verifier = self.scheduler.register(
                f"node{config.node_id}/consensus",
                self.validators_src,
                chain_id=f"node{config.node_id}",
            )
        else:
            batch_verifier = HostBatchVerifier(self.validators_src)

        # -- engine + transport --------------------------------------
        backend = ECDSABackend(
            self.key,
            self.validators_src,
            build_proposal_fn=build_block_fn(config.node_id),
        )
        self.engine = IBFT(
            self._log, backend, None, batch_verifier=batch_verifier
        )
        self.engine.set_base_round_timeout(config.consensus.base_round_timeout_s)
        self.ingress = BatchingIngress(self.engine.add_messages)
        self.transport = GrpcTransport(
            config.consensus.listen,
            config.consensus.peers,
            self.ingress.submit,
            logger=self._log,
            node=self.engine._obs_track,
            reconnect_after=config.consensus.reconnect_after,
        )
        self.engine.transport = self.transport

        # -- chain + WAL ---------------------------------------------
        self.wal_path = os.path.join(config.data_dir, "wal.jsonl")
        self.runner = ChainRunner(
            self.engine,
            WriteAheadLog(self.wal_path),
            overlap=False,  # single-chain node: overlap buys nothing here
        )

        # -- serve plane ---------------------------------------------
        self.proof_api: Optional[ProofApiServer] = None
        self._proof_server = None
        if config.proof_api.listen:
            from ..serve import ProofBuilder, ProofCache, ProofServer

            host, _, port = config.proof_api.listen.rpartition(":")
            self._proof_server = ProofServer(
                ProofBuilder(self.runner, self.runner.validators_for_height),
                ProofCache(),
                scheduler=self.scheduler,
                max_proof_heights=config.proof_api.max_proof_heights,
            )
            self.proof_api = ProofApiServer(
                self._proof_server,
                self.runner.latest_height,
                host=host or "127.0.0.1",
                port=int(port),
                max_connections=config.proof_api.max_connections,
                max_request_bytes=config.proof_api.max_request_bytes,
                header_timeout_s=config.proof_api.header_timeout_s,
                idle_timeout_s=config.proof_api.idle_timeout_s,
                workers=config.proof_api.workers,
                ready_fn=self.runner.telemetry_ready,
            )

        self.telemetry = None
        self._drained = False
        self._started_at = time.monotonic()

    # -- lifecycle ------------------------------------------------------

    async def run(self) -> dict:
        """Boot, serve, run the chain, drain; returns the drain report."""
        cfg = self.config
        loop = asyncio.get_running_loop()
        stop_requested = asyncio.Event()
        if self._install_signals:
            for sig in (signal.SIGTERM, signal.SIGINT):
                loop.add_signal_handler(sig, stop_requested.set)

        if self.scheduler is not None:
            self.scheduler.start()
            if cfg.sched_route != "host":
                # Compile the device kernels NOW, while /readyz is still
                # 503 — never on the first >=cutover flush mid-round.
                self.scheduler.warmup()
        await self.transport.start()
        bound_consensus = self.transport.bound_port

        # Recover BEFORE anything is routable: /readyz stays 503 until
        # this returns (the supervisor contract).
        resumed_at = self.runner.recover()

        if cfg.telemetry.listen:
            host, _, port = cfg.telemetry.listen.rpartition(":")
            extra = {}
            if self.scheduler is not None:
                extra["sched"] = self.scheduler.stats
            if self.proof_api is not None:
                extra["proof_api"] = self.proof_api.stats
            self.telemetry = self.runner.start_telemetry(
                port=int(port),
                host=host or "127.0.0.1",
                wedged_after_s=cfg.telemetry.wedged_after_s or None,
                extra_status=extra,
            )
        if self.proof_api is not None:
            self.proof_api.start()

        self._emit_boot_line(bound_consensus, resumed_at)

        chain_task = asyncio.create_task(
            self.runner.run(
                until_height=cfg.heights if cfg.heights > 0 else None
            ),
            name="node-chain",
        )
        stop_task = asyncio.create_task(
            stop_requested.wait(), name="node-stop"
        )
        try:
            done, _pending = await asyncio.wait(
                {chain_task, stop_task},
                return_when=asyncio.FIRST_COMPLETED,
            )
            if chain_task in done:
                chain_task.result()  # surface a crashed height loop
        finally:
            stop_task.cancel()
            report = await self._drain(chain_task)
        return report

    async def _drain(self, chain_task: Optional[asyncio.Task]) -> dict:
        """Graceful shutdown, in dependency order (see module docstring)."""
        if self._drained:
            return {}
        self._drained = True
        cfg = self.config
        # 1. Stop taking new proof clients (drop the fleet first: nothing
        # downstream depends on them).
        if self.proof_api is not None:
            self.proof_api.stop()
        # 2. Stop the height loop; in-flight WAL appends complete under
        # the WAL lock before close().
        if chain_task is not None and not chain_task.done():
            chain_task.cancel()
            await asyncio.gather(chain_task, return_exceptions=True)
        # 3. Scheduler: drain queued verification, stop the loop thread.
        if self.scheduler is not None:
            self.scheduler.stop()
        if self._proof_server is not None:
            self._proof_server.close()
        # 4. WAL: fsync + close — after this a SIGKILL loses nothing.
        if self.runner.wal is not None:
            self.runner.wal.close()
        # 5. Trace export for the cross-process timeline.
        trace_path = None
        trace_events = 0
        if cfg.trace.enabled:
            trace_path = os.path.join(
                cfg.data_dir, f"trace-node{cfg.node_id}.json"
            )
            try:
                trace_events = self.runner.export_trace(trace_path)
            except Exception as err:  # noqa: BLE001 - drain must finish
                self._log.error("trace export failed", err)
                trace_path = None
        # 6. Close listeners: gossip + telemetry go last so peers see our
        # final COMMITs and a supervisor can scrape the drain.
        await self.transport.stop()
        if self.telemetry is not None:
            self.runner.stop_telemetry()
        self.ingress.close()
        self.engine.messages.close()
        speculator = getattr(self.engine, "speculator", None)
        if speculator is not None:
            speculator.stop()
        report = self._report(trace_path, trace_events)
        return report

    # -- evidence -------------------------------------------------------

    def _emit_boot_line(self, consensus_port, resumed_at: int) -> None:
        """One JSON line on stdout the harness parses for bound ports."""
        import json

        line = {
            "node_boot": self.config.node_id,
            "address": self.key.address.hex(),
            "consensus_port": consensus_port,
            "proof_api_port": (
                self.proof_api.port if self.proof_api is not None else None
            ),
            "telemetry_port": (
                self.telemetry.port if self.telemetry is not None else None
            ),
            "resumed_at_height": resumed_at,
        }
        print(json.dumps(line), flush=True)

    def _report(self, trace_path, trace_events: int) -> dict:
        stats = self.runner.stats()
        return {
            "node": self.config.node_id,
            "address": self.key.address.hex(),
            "chain_height": self.runner.latest_height(),
            "heights_run": stats["heights_run"],
            "wal_path": self.wal_path,
            "trace_path": trace_path,
            "trace_events": trace_events,
            "proof_api": (
                self.proof_api.stats() if self.proof_api is not None else None
            ),
            "sched": (
                self.scheduler.stats() if self.scheduler is not None else None
            ),
            "send_failures": metrics.get_counter(
                ("go-ibft", "transport", "send_failures")
            ),
            "peer_reconnects": metrics.get_counter(
                ("go-ibft", "transport", "peer_reconnects")
            ),
            "uptime_s": round(time.monotonic() - self._started_at, 3),
        }
