"""Node configuration: the ``node.toml`` schema + a dependency-free loader.

``python -m go_ibft_tpu.node --config node.toml`` is the deployable
validator process (ISSUE 19); this module defines what it reads.  The
interpreter this repo pins is 3.10 (no stdlib ``tomllib``) and the repo
posture is zero runtime dependencies, so the loader implements the TOML
subset the schema needs — ``[section]`` / ``[section.sub]`` tables,
``key = value`` pairs with string / int / float / bool / flat-list
values, quoted keys (validator addresses are hex strings), and ``#``
comments.  Anything outside that subset is a :class:`NodeConfigError`,
never a silent misparse.

Schema (all sections optional except ``[node]`` + ``[validators]``)::

    [node]
    id = 0                          # ordinal, used in logs/evidence
    key_seed = "fleet-node-0"       # deterministic key seed (or "hex:..")
    data_dir = "/var/lib/go-ibft/0" # WAL + trace output live here
    heights = 0                     # stop after height N; 0 = run forever

    [consensus]
    listen = "127.0.0.1:7000"       # gRPC consensus gossip bind address
    base_round_timeout_s = 10.0
    reconnect_after = 2             # peer sends that trigger a reconnect

    [consensus.peers]               # name -> target, everyone but self
    node1 = "127.0.0.1:7001"

    [validators]                    # address hex -> voting power
    "ab12..." = 1

    [proof_api]
    listen = "127.0.0.1:8440"       # "" disables the proof API
    max_connections = 1024          # concurrent sockets; excess get 503
    max_request_bytes = 8192        # request line + headers bound
    header_timeout_s = 5.0          # slowloris cutoff (partial request)
    idle_timeout_s = 30.0           # keep-alive idle cutoff
    workers = 2                     # proof-build worker threads
    max_proof_heights = 512         # per-request range clamp

    [telemetry]
    listen = "127.0.0.1:0"          # "" disables /metrics,/healthz,/readyz
    wedged_after_s = 0.0            # 0 = runner default

    [sched]
    enabled = true                  # consensus/read QoS tiers
    route = "host"                  # "host" | "auto" | "device"; non-host
                                    # routes warm the kernels at boot

    [trace]
    enabled = true                  # flight recorder; exported on drain
    ring = 262144

See docs/DEPLOYMENT.md for the operator story.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

__all__ = [
    "NodeConfig",
    "NodeConfigError",
    "load_config",
    "parse_toml_subset",
]


class NodeConfigError(ValueError):
    """Raised on malformed/out-of-subset TOML or invalid settings."""


# ---------------------------------------------------------------------------
# the TOML-subset parser
# ---------------------------------------------------------------------------

_SECTION_RE = re.compile(r"^\[([A-Za-z0-9_.\-]+)\]$")
_BARE_KEY_RE = re.compile(r"^[A-Za-z0-9_\-]+$")


def _strip_comment(line: str) -> str:
    """Cut a ``#`` comment (quote-aware: a ``#`` inside a string stays)."""
    out = []
    in_str = False
    for ch in line:
        if ch == '"':
            in_str = not in_str
        elif ch == "#" and not in_str:
            break
        out.append(ch)
    return "".join(out).strip()


def _parse_scalar(raw: str, where: str):
    raw = raw.strip()
    if not raw:
        raise NodeConfigError(f"{where}: empty value")
    if raw.startswith('"'):
        if not (raw.endswith('"') and len(raw) >= 2):
            raise NodeConfigError(f"{where}: unterminated string {raw!r}")
        body = raw[1:-1]
        if '"' in body:
            raise NodeConfigError(f"{where}: bad string {raw!r}")
        return body
    if raw in ("true", "false"):
        return raw == "true"
    try:
        return int(raw, 10)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        raise NodeConfigError(
            f"{where}: unsupported value {raw!r} (subset: string/int/"
            f"float/bool/list)"
        ) from None


def _parse_value(raw: str, where: str):
    raw = raw.strip()
    if raw.startswith("["):
        if not raw.endswith("]"):
            raise NodeConfigError(f"{where}: unterminated list {raw!r}")
        body = raw[1:-1].strip()
        if not body:
            return []
        return [_parse_scalar(item, where) for item in body.split(",")]
    return _parse_scalar(raw, where)


def _parse_key(raw: str, where: str) -> str:
    raw = raw.strip()
    if raw.startswith('"') and raw.endswith('"') and len(raw) >= 2:
        return raw[1:-1]
    if _BARE_KEY_RE.match(raw):
        return raw
    raise NodeConfigError(f"{where}: bad key {raw!r}")


def parse_toml_subset(text: str) -> Dict[str, dict]:
    """Parse the documented TOML subset into nested dicts.

    Dotted section headers nest (``[consensus.peers]`` lands under
    ``out["consensus"]["peers"]``); key/value pairs before any header
    land at top level.  Raises :class:`NodeConfigError` with the line
    number on anything outside the subset.
    """
    out: Dict[str, dict] = {}
    current = out
    for lineno, raw_line in enumerate(text.splitlines(), start=1):
        line = _strip_comment(raw_line)
        if not line:
            continue
        where = f"line {lineno}"
        m = _SECTION_RE.match(line)
        if m:
            current = out
            for part in m.group(1).split("."):
                if not part:
                    raise NodeConfigError(f"{where}: bad section {line!r}")
                nxt = current.setdefault(part, {})
                if not isinstance(nxt, dict):
                    raise NodeConfigError(
                        f"{where}: section {part!r} collides with a value"
                    )
                current = nxt
            continue
        if "=" not in line:
            raise NodeConfigError(f"{where}: expected key = value, got {line!r}")
        key_raw, _, value_raw = line.partition("=")
        key = _parse_key(key_raw, where)
        if key in current:
            raise NodeConfigError(f"{where}: duplicate key {key!r}")
        current[key] = _parse_value(value_raw, where)
    return out


# ---------------------------------------------------------------------------
# the schema
# ---------------------------------------------------------------------------


def _toml_str(value: str) -> str:
    if '"' in value or "\n" in value:
        raise NodeConfigError(f"unencodable string {value!r}")
    return f'"{value}"'


def _toml_value(value: Union[str, int, float, bool]) -> str:
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, (int, float)):
        return repr(value)
    return _toml_str(value)


@dataclass
class ConsensusConfig:
    listen: str = "127.0.0.1:0"
    peers: Dict[str, str] = field(default_factory=dict)
    base_round_timeout_s: float = 10.0
    reconnect_after: int = 2


@dataclass
class ProofApiConfig:
    listen: str = ""  # "" = disabled
    max_connections: int = 1024
    max_request_bytes: int = 8192
    header_timeout_s: float = 5.0
    idle_timeout_s: float = 30.0
    workers: int = 2
    max_proof_heights: int = 512


@dataclass
class TelemetryConfig:
    listen: str = ""  # "" = disabled
    wedged_after_s: float = 0.0  # 0 = runner default


@dataclass
class TraceConfig:
    enabled: bool = True
    ring: int = 1 << 18


def _proof_api_from(section: dict) -> ProofApiConfig:
    unknown = set(section) - {
        "listen",
        "max_connections",
        "max_request_bytes",
        "header_timeout_s",
        "idle_timeout_s",
        "workers",
        "max_proof_heights",
    }
    if unknown:
        raise NodeConfigError(f"[proof_api] unknown key(s): {sorted(unknown)}")
    return ProofApiConfig(
        listen=str(section.get("listen", "")),
        max_connections=int(section.get("max_connections", 1024)),
        max_request_bytes=int(section.get("max_request_bytes", 8192)),
        header_timeout_s=float(section.get("header_timeout_s", 5.0)),
        idle_timeout_s=float(section.get("idle_timeout_s", 30.0)),
        workers=int(section.get("workers", 2)),
        max_proof_heights=int(section.get("max_proof_heights", 512)),
    )


@dataclass
class NodeConfig:
    node_id: int
    key_seed: str
    data_dir: str
    validators: Dict[str, int]  # address hex -> power
    heights: int = 0  # 0 = run forever
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    proof_api: ProofApiConfig = field(default_factory=ProofApiConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    trace: TraceConfig = field(default_factory=TraceConfig)
    sched_enabled: bool = True
    # "host" by default: a node must never stall a live round (or its
    # SIGTERM drain) on a first-flush XLA compile.  Accelerator hosts opt
    # into "auto"/"device", which triggers a boot-time warmup instead.
    sched_route: str = "host"

    @property
    def key_seed_bytes(self) -> bytes:
        """Seed bytes for :meth:`PrivateKey.from_seed` — ``hex:`` prefix
        for raw bytes, utf-8 otherwise (the fleet harness uses plain
        strings; operators with real key material use hex)."""
        if self.key_seed.startswith("hex:"):
            return bytes.fromhex(self.key_seed[4:])
        return self.key_seed.encode("utf-8")

    def validator_powers(self) -> Dict[bytes, int]:
        return {
            bytes.fromhex(addr): power
            for addr, power in self.validators.items()
        }

    def validate(self) -> "NodeConfig":
        if not self.key_seed:
            raise NodeConfigError("[node] key_seed is required")
        if not self.data_dir:
            raise NodeConfigError("[node] data_dir is required")
        if not self.validators:
            raise NodeConfigError("[validators] must name at least one")
        for addr, power in self.validators.items():
            try:
                raw = bytes.fromhex(addr)
            except ValueError:
                raise NodeConfigError(
                    f"[validators] {addr!r} is not hex"
                ) from None
            if not raw:
                raise NodeConfigError("[validators] empty address")
            if not isinstance(power, int) or power <= 0:
                raise NodeConfigError(
                    f"[validators] {addr}: power must be a positive int"
                )
        for name, listen in (
            ("[consensus] listen", self.consensus.listen),
            ("[proof_api] listen", self.proof_api.listen),
            ("[telemetry] listen", self.telemetry.listen),
        ):
            if listen and ":" not in listen:
                raise NodeConfigError(f"{name}: expected host:port")
        if self.consensus.base_round_timeout_s <= 0:
            raise NodeConfigError("[consensus] base_round_timeout_s must be > 0")
        if self.proof_api.max_connections < 1:
            raise NodeConfigError("[proof_api] max_connections must be >= 1")
        if self.proof_api.max_request_bytes < 64:
            raise NodeConfigError("[proof_api] max_request_bytes must be >= 64")
        if self.heights < 0:
            raise NodeConfigError("[node] heights must be >= 0")
        if self.sched_route not in ("host", "auto", "device"):
            raise NodeConfigError(
                f"[sched] route {self.sched_route!r}: expected "
                f"host | auto | device"
            )
        return self

    # -- wire ------------------------------------------------------------

    @classmethod
    def from_dict(cls, data: Dict[str, dict]) -> "NodeConfig":
        known = {
            "node",
            "consensus",
            "validators",
            "proof_api",
            "telemetry",
            "sched",
            "trace",
        }
        unknown = set(data) - known
        if unknown:
            # Typos must fail loud: a misspelled section silently running
            # defaults is how a node boots without its WAL directory.
            raise NodeConfigError(f"unknown section(s): {sorted(unknown)}")
        node = data.get("node", {})
        consensus = dict(data.get("consensus", {}))
        peers = consensus.pop("peers", {})
        cfg = cls(
            node_id=int(node.get("id", 0)),
            key_seed=str(node.get("key_seed", "")),
            data_dir=str(node.get("data_dir", "")),
            heights=int(node.get("heights", 0)),
            validators={
                str(addr): power
                for addr, power in data.get("validators", {}).items()
            },
            consensus=ConsensusConfig(
                listen=str(consensus.get("listen", "127.0.0.1:0")),
                peers={str(k): str(v) for k, v in peers.items()},
                base_round_timeout_s=float(
                    consensus.get("base_round_timeout_s", 10.0)
                ),
                reconnect_after=int(consensus.get("reconnect_after", 2)),
            ),
            proof_api=_proof_api_from(data.get("proof_api", {})),
            telemetry=TelemetryConfig(
                listen=str(data.get("telemetry", {}).get("listen", "")),
                wedged_after_s=float(
                    data.get("telemetry", {}).get("wedged_after_s", 0.0)
                ),
            ),
            trace=TraceConfig(
                enabled=bool(data.get("trace", {}).get("enabled", True)),
                ring=int(data.get("trace", {}).get("ring", 1 << 18)),
            ),
            sched_enabled=bool(data.get("sched", {}).get("enabled", True)),
            sched_route=str(data.get("sched", {}).get("route", "host")),
        )
        return cfg.validate()

    def to_toml(self) -> str:
        """Render back to the documented schema (the fleet harness writes
        every node's config through this — round-trip pinned in tests)."""
        lines = [
            "[node]",
            f"id = {self.node_id}",
            f"key_seed = {_toml_str(self.key_seed)}",
            f"data_dir = {_toml_str(self.data_dir)}",
            f"heights = {self.heights}",
            "",
            "[consensus]",
            f"listen = {_toml_str(self.consensus.listen)}",
            f"base_round_timeout_s = {_toml_value(self.consensus.base_round_timeout_s)}",
            f"reconnect_after = {self.consensus.reconnect_after}",
            "",
            "[consensus.peers]",
        ]
        for name, target in sorted(self.consensus.peers.items()):
            lines.append(f"{name} = {_toml_str(target)}")
        lines += ["", "[validators]"]
        for addr, power in sorted(self.validators.items()):
            lines.append(f'"{addr}" = {power}')
        p = self.proof_api
        lines += [
            "",
            "[proof_api]",
            f"listen = {_toml_str(p.listen)}",
            f"max_connections = {p.max_connections}",
            f"max_request_bytes = {p.max_request_bytes}",
            f"header_timeout_s = {_toml_value(p.header_timeout_s)}",
            f"idle_timeout_s = {_toml_value(p.idle_timeout_s)}",
            f"workers = {p.workers}",
            f"max_proof_heights = {p.max_proof_heights}",
            "",
            "[telemetry]",
            f"listen = {_toml_str(self.telemetry.listen)}",
            f"wedged_after_s = {_toml_value(self.telemetry.wedged_after_s)}",
            "",
            "[sched]",
            f"enabled = {_toml_value(self.sched_enabled)}",
            f"route = {_toml_str(self.sched_route)}",
            "",
            "[trace]",
            f"enabled = {_toml_value(self.trace.enabled)}",
            f"ring = {self.trace.ring}",
        ]
        return "\n".join(lines) + "\n"


def load_config(path: Union[str, os.PathLike]) -> NodeConfig:
    """Read + parse + validate a ``node.toml``."""
    with open(path, "r", encoding="utf-8") as fh:
        text = fh.read()
    try:
        return NodeConfig.from_dict(parse_toml_subset(text))
    except NodeConfigError as err:
        raise NodeConfigError(f"{path}: {err}") from None
