"""Distributed communication backends (SURVEY.md §5 "distributed
communication backend").

The reference's entire comm layer is the ``Transport.Multicast`` seam
(go-ibft core/transport.go:7-10) with real gossip living in the embedder
(libp2p in Polygon Edge).  This package provides the two production-shaped
backends behind the same seam:

* :class:`GrpcTransport` — asyncio gRPC fire-and-forget multicast between
  hosts over DCN; matches the reference's async-gossip reality.
* :class:`IciLockstepTransport` — the TPU-idiomatic simulation mode: one
  validator per mesh device, "multicast" is an ``all_gather`` of
  fixed-size message tensors over ICI, consensus rounds become lock-step
  collective steps.
* :class:`AggregationTreeGossip` — aggregate-signature COMMIT
  dissemination (ISSUE 7): seals merge up a fan-in tree as partial
  aggregates and ONE quorum certificate broadcasts down, so per-node
  COMMIT wire cost stops scaling with committee size.
"""

from .aggtree import AggregationTreeGossip
from .grpc_transport import GrpcTransport
from .ici import IciLockstepTransport, TickVerdictVerifier, build_tick_program

__all__ = [
    "AggregationTreeGossip",
    "GrpcTransport",
    "IciLockstepTransport",
    "TickVerdictVerifier",
    "build_tick_program",
]
