"""ICI lock-step collective transport (the TPU-idiomatic cluster mode).

One validator per device of a ``jax`` mesh; "multicast" buffers messages
into the local node's fixed-shape outbox tensor, and a periodic collective
step ``all_gather``s every node's outbox across the mesh — over ICI on
real TPU hardware, over host memory on the virtual CPU mesh — then drains
the gathered batch into every engine's batched ingress
(:meth:`IBFT.add_messages`).

This is the high-throughput simulation/benchmark topology promised in
SURVEY.md §5: consensus rounds become lock-step collective steps, and each
step moves ALL in-flight messages of the cluster in one fixed-shape
``(N, M, B)`` uint8 tensor instead of N*M point-to-point sends.

Message slots are length-prefixed (4-byte big-endian) canonical wire
encodings; empty slots are zero (length 0).  Overflowing an outbox drops
the oldest messages with a log line — fire-and-forget semantics, matching
the reference seam (core/transport.go:7-10).
"""

from __future__ import annotations

import asyncio
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..messages.wire import IbftMessage

_LEN_BYTES = 4


class _NodePort:
    """The per-node Transport seam handed to one IBFT engine."""

    def __init__(self, hub: "IciLockstepTransport", index: int) -> None:
        self._hub = hub
        self._index = index

    def multicast(self, message: IbftMessage) -> None:
        self._hub._enqueue(self._index, message)


class IciLockstepTransport:
    """Hub owning the mesh, the outboxes, and the collective step loop."""

    def __init__(
        self,
        n_nodes: int,
        *,
        devices: Optional[Sequence] = None,
        max_msgs: int = 16,
        max_bytes: int = 4096,
        step_interval: float = 0.002,
        logger=None,
    ) -> None:
        if devices is None:
            devices = jax.devices()
        if len(devices) < n_nodes:
            raise ValueError(
                f"ICI transport needs {n_nodes} devices, have {len(devices)}"
            )
        self.mesh = Mesh(np.asarray(devices[:n_nodes]), ("node",))
        self.n_nodes = n_nodes
        self.max_msgs = max_msgs
        self.max_bytes = max_bytes
        self.step_interval = step_interval
        self._log = logger
        self._outboxes: List[List[bytes]] = [[] for _ in range(n_nodes)]
        self._delivers: List[Callable[[Sequence[IbftMessage]], None]] = []
        self._task: Optional[asyncio.Task] = None
        self._sharded = NamedSharding(self.mesh, P("node"))
        self._replicated = NamedSharding(self.mesh, P())
        self._gather = jax.jit(
            lambda x: x, out_shardings=self._replicated
        )

    # -- wiring ---------------------------------------------------------

    def port(self, index: int) -> _NodePort:
        return _NodePort(self, index)

    def register(
        self, deliver_batch: Callable[[Sequence[IbftMessage]], None]
    ) -> _NodePort:
        """Register one node's batched ingress; returns its Transport."""
        index = len(self._delivers)
        if index >= self.n_nodes:
            raise ValueError("all node slots taken")
        self._delivers.append(deliver_batch)
        return self.port(index)

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="ici-lockstep"
            )

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    # -- the collective step --------------------------------------------

    def _enqueue(self, index: int, message: IbftMessage) -> None:
        box = self._outboxes[index]
        payload = message.encode()
        if len(payload) + _LEN_BYTES > self.max_bytes:
            if self._log:
                self._log.error("ici transport: message exceeds slot size")
            return
        box.append(payload)

    def _pack(self) -> Optional[np.ndarray]:
        if not any(self._outboxes):
            return None
        out = np.zeros(
            (self.n_nodes, self.max_msgs, self.max_bytes), dtype=np.uint8
        )
        for n, box in enumerate(self._outboxes):
            if len(box) > self.max_msgs:
                if self._log:
                    self._log.error(
                        "ici transport: outbox overflow, dropping oldest"
                    )
                box = box[-self.max_msgs :]
            for m, payload in enumerate(box):
                out[n, m, :_LEN_BYTES] = np.frombuffer(
                    len(payload).to_bytes(_LEN_BYTES, "big"), np.uint8
                )
                out[n, m, _LEN_BYTES : _LEN_BYTES + len(payload)] = (
                    np.frombuffer(payload, np.uint8)
                )
            self._outboxes[n] = []
        return out

    def step(self) -> None:
        """One lock-step exchange: pack, all_gather over the mesh, drain."""
        packed = self._pack()
        if packed is None:
            return
        sharded = jax.device_put(jnp.asarray(packed), self._sharded)
        gathered = np.asarray(self._gather(sharded))  # (N, M, B) everywhere
        batch: List[IbftMessage] = []
        for n in range(self.n_nodes):
            for m in range(self.max_msgs):
                ln = int.from_bytes(bytes(gathered[n, m, :_LEN_BYTES]), "big")
                if ln == 0:
                    continue
                try:
                    batch.append(
                        IbftMessage.decode(
                            bytes(gathered[n, m, _LEN_BYTES : _LEN_BYTES + ln])
                        )
                    )
                except Exception as err:  # noqa: BLE001
                    if self._log:
                        self._log.error("ici transport: bad slot", err)
        if not batch:
            return
        for deliver in self._delivers:
            deliver(list(batch))

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.step_interval)
            self.step()
