"""ICI lock-step collective transport: one consensus tick = ONE program.

The cluster's whole in-flight message state lives in a single fixed-shape
``(N, M, B)`` uint8 staging tensor — N node outboxes of M length-prefixed
message lanes of B bytes — sharded over the ``("node",)`` mesh axis.  A
tick runs a pinned shard_map program (compile-budget family ``ici_tick``)
that ``all_gather``s every node's outbox shard — over ICI on real TPU
hardware, over host memory on the virtual CPU mesh — and, in the same
program, emits the digest/claimed-address rows the batched verify plane
consumes (:meth:`~go_ibft_tpu.verify.batch.DeviceBatchVerifier
.verify_sender_rows`), so a COMMIT flood drains into the verifier with
zero decode→re-encode→re-pack round trips.  Decoding back to
:class:`IbftMessage` survives only for protocol bookkeeping, fed from the
same gathered buffer.

Data plane is vectorized end to end: packing scatters all payload bytes
into the staging tensor in one fancy-indexed write (no per-slot
``frombuffer`` copies), and unpacking reads every slot's big-endian
length prefix with four whole-tensor shifts (no per-slot
``int.from_bytes``).  A slot that fails to decode is quarantined — counted
and logged, never poisoning the rest of the batch.

Chaos runs as tensor masks on the collective schedule: an object with
``edges(tick) -> (allow, delay)`` (see
:class:`go_ibft_tpu.sim.chaos.ChaosMask`) filters the gathered batch
per receiver edge before drain and defers delayed lanes whole ticks —
seeded, byte-identical per seed, CHAOS-REPLAY compatible.

Drop policy is fire-and-forget, matching the reference seam
(core/transport.go:7-10) — but never silent: oversize payloads and
outbox overflow (drop-oldest, applied at enqueue time) are counted in
``utils.metrics`` counters and surfaced by :meth:`stats`.
"""

from __future__ import annotations

import asyncio
from functools import partial
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..messages.wire import IbftMessage
from ..obs import ledger as cost_ledger
from ..obs import trace
from ..ops import quorum
from ..parallel.mesh import shard_map
from ..utils import metrics

_LEN_BYTES = 4

# Cost-ledger / compile-budget program family for the tick collective.
TICK_PROGRAM = "ici_tick"

_DROP_OVERSIZE = ("go-ibft", "ici", "dropped_oversize")
_DROP_OVERFLOW = ("go-ibft", "ici", "dropped_overflow")
_BAD_SLOT = ("go-ibft", "ici", "bad_slot")


def shard_count(n_nodes: int, n_devices: int) -> int:
    """Largest device count ``d <= n_devices`` with ``n_nodes % d == 0``.

    The staging tensor shards its node axis evenly over ``d`` devices; 1
    means the host passthrough route (no mesh, no collective)."""
    for d in range(min(n_nodes, max(n_devices, 1)), 0, -1):
        if n_nodes % d == 0:
            return d
    return 1


# Module-level program cache: one jit object per (mesh layout, variant).
# jax.jit is shape-polymorphic, so a warmup run at the same cluster shape
# leaves the compiled executable hot for every later hub in the process
# (bench config #15 times a warmed tick, like every other config).
_TICK_PROGRAMS: Dict[Tuple, object] = {}


def build_tick_program(mesh: Mesh, *, rows: bool = False):
    """The pinned tick collective for one cluster shape.

    ``rows=False`` (the simulation fast path): gather the staging tensor —
    in: ``(N, M, B)`` uint8 sharded on ``node``; out: the same tensor
    replicated.  ``rows=True`` (the verify-fused path): additionally
    digest each node's packed sender payloads ON ITS OWN SHARD
    (:func:`go_ibft_tpu.ops.quorum.digest_words`) and gather the
    digest/signature/claimed-address rows alongside the bytes, so the
    sender-validity kernel consumes them with no host-side re-pack.
    Registered as compile-budget family ``ici_tick``
    (:mod:`go_ibft_tpu.boot.registry`)."""
    key = (tuple(mesh.devices.flat), mesh.axis_names, rows)
    cached = _TICK_PROGRAMS.get(key)
    if cached is not None:
        return cached
    node = P("node")

    if not rows:

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(node,),
            out_specs=P(),
            check_vma=False,
        )
        def tick(staging):
            return jax.lax.all_gather(staging, "node", axis=0, tiled=True)

        prog = jax.jit(tick)
        _TICK_PROGRAMS[key] = prog
        return prog

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(node,) * 8,
        out_specs=(P(),) * 7,
        check_vma=False,
    )
    def tick_rows(staging, blocks, counts, r, s, v, senders, live):
        zw = quorum.digest_words(blocks, counts)

        def g(x):
            return jax.lax.all_gather(x, "node", axis=0, tiled=True)

        return (g(staging), g(zw), g(r), g(s), g(v), g(senders), g(live))

    prog = jax.jit(tick_rows)
    _TICK_PROGRAMS[key] = prog
    return prog


class _NodePort:
    """The per-node Transport seam handed to one IBFT engine."""

    def __init__(self, hub: "IciLockstepTransport", index: int) -> None:
        self._hub = hub
        self._index = index

    def multicast(self, message: IbftMessage) -> None:
        self._hub._enqueue(self._index, message)

    def multicast_to(self, message: IbftMessage, targets) -> None:
        """Selective-send: deliver only to the ``targets`` node indices.

        The Byzantine strategy seam (sim/adversary.py): an equivocating
        proposer or COMMIT withholder still rides the SAME staging tensor
        and tick collective — the target set is applied at the per-edge
        fan-out where the chaos masks already cut edges, so targeted
        sends compose with ChaosMask and stay replay-deterministic.
        Honest engines never call this (the Transport protocol is
        ``multicast`` only)."""
        self._hub._enqueue(self._index, message, targets=targets)


class TickVerdictVerifier:
    """BatchVerifier facade that consumes the tick program's verdicts.

    The hub verifies every gathered lane ONCE per tick
    (:meth:`IciLockstepTransport.step`, rows mode) and parks the verdicts
    keyed by message identity; each engine's ingress then resolves
    ``verify_senders`` from that shared map instead of re-packing and
    re-dispatching the same lanes N times.  Misses (locally-built
    messages, trimmed entries) fall through to the wrapped verifier, and
    every other BatchVerifier method delegates unchanged."""

    def __init__(self, hub: "IciLockstepTransport", inner) -> None:
        self._hub = hub
        self._inner = inner

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def verify_senders(self, msgs: Sequence[IbftMessage]) -> np.ndarray:
        verdicts = self._hub._verdicts
        out = np.zeros(len(msgs), dtype=bool)
        miss: List[int] = []
        for i, m in enumerate(msgs):
            hit = verdicts.get(id(m))
            if hit is not None and hit[0] is m:
                out[i] = hit[1]
            else:
                miss.append(i)
        if miss:
            sub = self._inner.verify_senders([msgs[i] for i in miss])
            for j, i in enumerate(miss):
                out[i] = bool(sub[j])
        return out


class IciLockstepTransport:
    """Hub owning the mesh, the staging tensor, and the tick loop.

    ``n_nodes`` no longer needs one device each: the node axis shards
    over the largest ``d | n_nodes`` available devices
    (:func:`shard_count`); ``d == 1`` degrades to a host passthrough
    (same semantics, no collective).  ``verifier`` (a
    :class:`~go_ibft_tpu.verify.batch.DeviceBatchVerifier`) switches the
    tick program to rows mode and enables :meth:`tick_verifier`.
    ``chaos`` is an ``edges(tick) -> (allow, delay)`` mask source applied
    to the gathered batch before drain."""

    def __init__(
        self,
        n_nodes: int,
        *,
        devices: Optional[Sequence] = None,
        max_msgs: int = 16,
        max_bytes: int = 4096,
        step_interval: float = 0.002,
        logger=None,
        verifier=None,
        chaos=None,
    ) -> None:
        if devices is None:
            devices = jax.devices()
        self.n_nodes = n_nodes
        self.max_msgs = max_msgs
        self.max_bytes = max_bytes
        self.step_interval = step_interval
        self._log = logger
        self._verifier = verifier
        self.chaos = chaos
        d = shard_count(n_nodes, len(devices))
        if d > 1:
            self.mesh: Optional[Mesh] = Mesh(
                np.asarray(devices[:d]), ("node",)
            )
            self._sharded = NamedSharding(self.mesh, P("node"))
            self._route = "device"
        else:
            self.mesh = None
            self._sharded = None
            self._route = "host"
        self.devices = d
        # Outboxes hold (message, wire_bytes, targets): encode once at
        # enqueue, decode once per live slot at drain — never per
        # receiver.  ``targets`` is None for honest multicast; a
        # frozenset restricts the fan-out (adversary selective-send).
        self._outboxes: List[
            List[Tuple[IbftMessage, bytes, Optional[frozenset]]]
        ] = [[] for _ in range(n_nodes)]
        self._delivers: List[Callable[[Sequence[IbftMessage]], None]] = []
        self._task: Optional[asyncio.Task] = None
        self._tick = 0
        self._tick_cache: Dict[Tuple, object] = {}
        self._live_entries: List[Tuple[int, IbftMessage]] = []
        # flat slot -> target node set for this tick's targeted sends
        # (populated by _pack alongside _live_entries).
        self._live_targets: Dict[int, frozenset] = {}
        # Delayed chaos lanes: due_tick -> receiver -> [messages].
        self._delayed: Dict[int, Dict[int, List[IbftMessage]]] = {}
        # id(msg) -> (msg, verdict); strong refs pin identity (no GC
        # id reuse), insertion order bounds the trim below.
        self._verdicts: Dict[int, Tuple[IbftMessage, bool]] = {}
        self._stats = {
            "sent": 0,
            "delivered": 0,
            "dropped_oversize": 0,
            "dropped_overflow": 0,
            "dropped_chaos": 0,
            "dropped_targeted": 0,
            "bad_slots": 0,
            "last_live": 0,
        }

    # -- wiring ---------------------------------------------------------

    def port(self, index: int) -> _NodePort:
        return _NodePort(self, index)

    def register(
        self, deliver_batch: Callable[[Sequence[IbftMessage]], None]
    ) -> _NodePort:
        """Register one node's batched ingress; returns its Transport."""
        index = len(self._delivers)
        if index >= self.n_nodes:
            raise ValueError("all node slots taken")
        self._delivers.append(deliver_batch)
        return self.port(index)

    def tick_verifier(self, inner=None) -> TickVerdictVerifier:
        """A per-engine BatchVerifier resolving from the tick's verdicts."""
        return TickVerdictVerifier(self, inner or self._verifier)

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="ici-lockstep"
            )

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def idle(self) -> bool:
        """True when nothing is in flight: no queued outbox lanes and no
        chaos-delayed deliveries pending (the driver's cue to yield real
        wall clock to round timers instead of spinning ticks)."""
        return not any(self._outboxes) and not self._delayed

    def stats(self) -> dict:
        """Tick/traffic/drop accounting (drops also land in
        ``utils.metrics`` counters under ``("go-ibft", "ici", ...)``)."""
        return {
            "ticks": self._tick,
            "nodes": self.n_nodes,
            "devices": self.devices,
            "route": self._route,
            "capacity": self.n_nodes * self.max_msgs,
            **self._stats,
        }

    # -- data plane -----------------------------------------------------

    def _enqueue(
        self, index: int, message: IbftMessage, targets=None
    ) -> None:
        box = self._outboxes[index]
        payload = message.encode()
        if len(payload) + _LEN_BYTES > self.max_bytes:
            self._stats["dropped_oversize"] += 1
            metrics.inc_counter(_DROP_OVERSIZE)
            if self._log:
                self._log.error("ici transport: message exceeds slot size")
            return
        # Drop-oldest AT ENQUEUE time (not silently at pack time): the
        # log line and the counter fire when the loss actually happens.
        while len(box) >= self.max_msgs:
            box.pop(0)
            self._stats["dropped_overflow"] += 1
            metrics.inc_counter(_DROP_OVERFLOW)
            if self._log:
                self._log.error(
                    "ici transport: outbox overflow, dropping oldest"
                )
        box.append(
            (message, payload, None if targets is None else frozenset(targets))
        )
        self._stats["sent"] += 1

    def _pack(self) -> Optional[np.ndarray]:
        """Outboxes -> ``(N, M, B)`` staging tensor (None when idle).

        One fancy-indexed scatter for all payload bytes and one
        vectorized write per length-prefix byte — no per-slot loops.
        Side effect: ``self._live_entries`` records ``(flat_slot,
        message)`` for the drain/rows path; outboxes are cleared."""
        n_nodes, m_slots, b = self.n_nodes, self.max_msgs, self.max_bytes
        flats: List[int] = []
        lens: List[int] = []
        chunks: List[bytes] = []
        entries: List[Tuple[int, IbftMessage]] = []
        targets: Dict[int, frozenset] = {}
        for node, box in enumerate(self._outboxes):
            for slot, (msg, payload, tgt) in enumerate(box):
                flat = node * m_slots + slot
                entries.append((flat, msg))
                if tgt is not None:
                    targets[flat] = tgt
                flats.append(flat)
                lens.append(len(payload))
                chunks.append(payload)
            box.clear()
        self._live_entries = entries
        self._live_targets = targets
        if not entries:
            return None
        staging = np.zeros((n_nodes * m_slots, b), dtype=np.uint8)
        flat_idx = np.asarray(flats, dtype=np.int64)
        lens_a = np.asarray(lens, dtype=np.uint32)
        staging[flat_idx, 0] = (lens_a >> 24).astype(np.uint8)
        staging[flat_idx, 1] = (lens_a >> 16).astype(np.uint8)
        staging[flat_idx, 2] = (lens_a >> 8).astype(np.uint8)
        staging[flat_idx, 3] = lens_a.astype(np.uint8)
        joined = np.frombuffer(b"".join(chunks), dtype=np.uint8)
        starts = np.cumsum(lens_a) - lens_a
        within = np.arange(len(joined), dtype=np.int64) - np.repeat(
            starts.astype(np.int64), lens_a
        )
        staging[
            np.repeat(flat_idx, lens_a), _LEN_BYTES + within
        ] = joined
        return staging.reshape(n_nodes, m_slots, b)

    def _pack_rows(self):
        """Live messages -> slot-aligned sender rows for the tick program.

        Lane ``node * M + slot`` carries that slot's digest inputs so the
        node axis shards identically to the staging tensor; dead lanes
        stay ``live=False``.  Lanes whose payload exceeds the device
        digest ceiling or fails pack validation simply get NO row — the
        engine's fallback verifier covers them."""
        from ..verify.batch import MAX_DEVICE_PAYLOAD, pack_sender_batch

        lanes = self.n_nodes * self.max_msgs
        rowable: List[Tuple[int, IbftMessage, bytes]] = []
        for flat, msg in self._live_entries:
            if len(msg.sender) != 20 or len(msg.signature or b"") != 65:
                continue
            payload = msg.encode(include_signature=False)
            if len(payload) > MAX_DEVICE_PAYLOAD:
                continue
            rowable.append((flat, msg, payload))
        if not rowable:
            return None
        msgs = [m for _, m, _ in rowable]
        payloads = [p for _, _, p in rowable]
        blocks, counts, r, s, v, senders, live = pack_sender_batch(
            msgs, payloads=payloads
        )
        nb = blocks.shape[1]
        idx = np.asarray([f for f, _, _ in rowable])
        n = len(rowable)
        blocks_all = np.zeros((lanes, nb) + blocks.shape[2:], blocks.dtype)
        counts_all = np.ones((lanes,), counts.dtype)
        r_all = np.zeros((lanes,) + r.shape[1:], r.dtype)
        s_all = np.zeros((lanes,) + s.shape[1:], s.dtype)
        v_all = np.zeros((lanes,), v.dtype)
        senders_all = np.zeros((lanes,) + senders.shape[1:], senders.dtype)
        live_all = np.zeros((lanes,), dtype=bool)
        blocks_all[idx] = blocks[:n]
        counts_all[idx] = counts[:n]
        r_all[idx] = r[:n]
        s_all[idx] = s[:n]
        v_all[idx] = v[:n]
        senders_all[idx] = senders[:n]
        live_all[idx] = live[:n]
        arrays = (blocks_all, counts_all, r_all, s_all, v_all, senders_all,
                  live_all)
        return idx, msgs, arrays

    def _tick_program(self, key, rows: bool):
        prog = self._tick_cache.get(key)
        if prog is None:
            prog = build_tick_program(self.mesh, rows=rows)
            self._tick_cache[key] = prog
        return prog

    def _collective(self, staging: np.ndarray, rows):
        """Run ONE tick program: gather (+ digest rows) in one dispatch."""
        n_live = len(self._live_entries)
        padded = self.n_nodes * self.max_msgs
        if self.mesh is None:
            # Host passthrough: same semantics, no collective.  Rows mode
            # still pays its single digest dispatch; accounted to the
            # same family so occupancy stays comparable across routes.
            with cost_ledger.dispatch_span(
                TICK_PROGRAM,
                route=self._route,
                live=n_live,
                padded=padded,
                site="net/ici.py:step",
            ):
                if rows is None:
                    return staging, None
                from ..verify.batch import _digest_kernel

                blocks, counts, r, s, v, senders, live = rows[2]
                zw = np.asarray(
                    _digest_kernel(jnp.asarray(blocks), jnp.asarray(counts))
                )
                return staging, (zw, r, s, v, senders, live)
        key = (staging.shape, None if rows is None else rows[2][0].shape)
        prog = self._tick_program(key, rows is not None)
        with cost_ledger.dispatch_span(
            TICK_PROGRAM,
            route=self._route,
            live=n_live,
            padded=padded,
            kernels=((TICK_PROGRAM, prog),),
            site="net/ici.py:step",
        ):
            put = lambda a: jax.device_put(jnp.asarray(a), self._sharded)
            if rows is None:
                return np.asarray(prog(put(staging))), None
            blocks, counts, r, s, v, senders, live = rows[2]
            out = prog(
                put(staging), put(blocks), put(counts), put(r), put(s),
                put(v), put(senders), put(live),
            )
            gathered = np.asarray(out[0])
            return gathered, tuple(np.asarray(o) for o in out[1:])

    def _drain_rows(self, rows, gathered_rows, decoded) -> None:
        """Per-height sender-validity dispatch over the gathered rows;
        verdicts parked for :class:`TickVerdictVerifier` consumers.

        Verdicts key the DECODED message objects (``decoded``: flat slot
        -> message) — those are what the engines' ingresses will hand
        back to ``verify_senders``."""
        idx, _, _ = rows
        zw, r, s, v, senders, live = gathered_rows
        by_height: Dict[int, List[Tuple[int, IbftMessage]]] = {}
        for lane in idx:
            m = decoded.get(int(lane))
            if m is not None:
                by_height.setdefault(m.view.height, []).append((int(lane), m))
        for height, items in by_height.items():
            lanes = np.asarray([lane for lane, _ in items])
            mask = self._verifier.verify_sender_rows(
                height, zw[lanes], r[lanes], s[lanes], v[lanes],
                senders[lanes], live[lanes],
            )
            for (_, m), ok in zip(items, mask):
                self._verdicts[id(m)] = (m, bool(ok))
        # Trim: verdicts are consumed within a tick or two (the ingress
        # flush is a call_soon away); cap the map so a slow consumer
        # cannot grow it without bound.
        while len(self._verdicts) > 4 * self.n_nodes * self.max_msgs:
            self._verdicts.pop(next(iter(self._verdicts)))

    def _unpack(self, gathered: np.ndarray) -> List[Tuple[int, IbftMessage]]:
        """Gathered tensor -> ``(sender_node, message)`` pairs for the
        live slots (quarantining bad ones).  Length extraction is four
        whole-tensor shifts; only the live slots' payload bytes are
        touched."""
        b = self.max_bytes
        hdr = gathered[:, :, :_LEN_BYTES].astype(np.uint32)
        lens = (
            (hdr[..., 0] << 24) | (hdr[..., 1] << 16)
            | (hdr[..., 2] << 8) | hdr[..., 3]
        )
        live = lens > 0
        batch: List[Tuple[int, IbftMessage]] = []
        for n_i, m_i in zip(*np.nonzero(live)):
            ln = int(lens[n_i, m_i])
            if ln > b - _LEN_BYTES:
                self._quarantine(int(n_i), int(m_i), "bad length")
                continue
            raw = gathered[n_i, m_i, _LEN_BYTES : _LEN_BYTES + ln]
            try:
                flat = int(n_i) * self.max_msgs + int(m_i)
                batch.append((flat, IbftMessage.decode(raw.tobytes())))
            except Exception as err:  # noqa: BLE001
                self._quarantine(int(n_i), int(m_i), err)
        return batch

    def _quarantine(self, node: int, slot: int, err) -> None:
        self._stats["bad_slots"] += 1
        metrics.inc_counter(_BAD_SLOT)
        if self._log:
            self._log.error("ici transport: bad slot", node, slot, err)

    # -- the collective step --------------------------------------------

    def step(self) -> None:
        """One lock-step tick: pack, ONE collective, verify rows, drain."""
        tick = self._tick
        self._tick = tick + 1
        due = self._flush_delayed(tick)
        staging = self._pack()
        if staging is None:
            # Idle tick: no collective (and no ledger dispatch), but
            # chaos-delayed lanes still come due.
            self._deliver(due)
            return
        rows = self._pack_rows() if self._verifier is not None else None
        with trace.span(
            "ici.tick",
            tick=tick,
            live=len(self._live_entries),
            capacity=self.n_nodes * self.max_msgs,
            route=self._route,
        ):
            gathered, gathered_rows = self._collective(staging, rows)
            pairs = self._unpack(np.asarray(gathered))
            if rows is not None and gathered_rows is not None:
                self._drain_rows(rows, gathered_rows, dict(pairs))
        self._stats["last_live"] = len(pairs)
        per_receiver = self._apply_chaos(tick, pairs, due)
        self._deliver(per_receiver)

    def _flush_delayed(self, tick: int) -> Dict[int, List[IbftMessage]]:
        due: Dict[int, List[IbftMessage]] = {}
        for t in sorted(k for k in self._delayed if k <= tick):
            for recv, msgs in self._delayed.pop(t).items():
                due.setdefault(recv, []).extend(msgs)
        return due

    def _apply_chaos(
        self,
        tick: int,
        pairs: List[Tuple[int, IbftMessage]],
        due: Dict[int, List[IbftMessage]],
    ) -> Dict[int, List[IbftMessage]]:
        """Fan the gathered ``(flat_slot, message)`` batch out per
        receiver through the target sets (adversary selective-send) and
        the chaos masks (drop/partition + delay-in-ticks); pass-through
        when neither plane is mounted."""
        n = self.n_nodes
        if self.chaos is None and not self._live_targets:
            if not pairs:
                return due
            msgs = [m for _, m in pairs]
            out = dict(due)
            for j in range(n):
                out[j] = out.get(j, []) + msgs
            return out
        if self.chaos is not None:
            allow, delay = self.chaos.edges(tick)
        else:
            allow = delay = None
        out = dict(due)
        for flat, m in pairs:
            s_i = flat // self.max_msgs
            targets = self._live_targets.get(flat)
            for j in range(n):
                if targets is not None and j not in targets:
                    self._stats["dropped_targeted"] += 1
                    continue
                if allow is not None and not allow[s_i, j]:
                    self._stats["dropped_chaos"] += 1
                    continue
                d = int(delay[s_i, j]) if delay is not None else 0
                if d > 0:
                    self._delayed.setdefault(tick + d, {}).setdefault(
                        j, []
                    ).append(m)
                else:
                    out.setdefault(j, []).append(m)
        return out

    def _deliver(self, per_receiver: Dict[int, List[IbftMessage]]) -> None:
        for j, msgs in per_receiver.items():
            if msgs and j < len(self._delivers):
                self._stats["delivered"] += len(msgs)
                self._delivers[j](list(msgs))

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.step_interval)
            self.step()
