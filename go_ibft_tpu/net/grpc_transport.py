"""gRPC/DCN multicast transport.

Implements the reference's one-method ``Transport`` seam
(go-ibft core/transport.go:7-10) across hosts: ``multicast`` encodes the
message once with the framework's canonical wire codec
(:mod:`go_ibft_tpu.messages.wire`) and fire-and-forgets it to every peer
over async gRPC; inbound bytes decode and land in the local engine's
ingress (``add_message`` or a :class:`~go_ibft_tpu.core.transport.
BatchingIngress` for the batched device path).

No protoc codegen: the service is registered with generic bytes handlers
(the payload already IS a canonical protobuf-compatible encoding, so a
second serialization layer would only add bytes).  Self-delivery is
local (the reference expects nodes to receive their own messages,
core/transport.go:8-9) and never touches the network.

Fire-and-forget semantics match the reference: delivery failures are
logged and dropped — consensus liveness is the protocol's job (round
changes), not the transport's.  Since ISSUE 3 a failed send is retried
with jittered exponential backoff inside a bounded send deadline: a
transiently lossy link recovers without waiting a whole round change,
while the deadline keeps every retry sequence strictly shorter than the
round-0 timeout so the transport can never outlive the round semantics it
serves (``core/ibft.py::get_round_timeout``).
"""

from __future__ import annotations

import asyncio
import random
import time
from typing import Callable, Dict, Optional, Sequence

import grpc

from ..core.ibft import DEFAULT_BASE_ROUND_TIMEOUT
from ..obs import clock, trace
from ..utils import metrics

from ..messages.wire import IbftMessage, decode_traced, encode_traced

_SERVICE = "goibft.Transport"
_METHOD = "Multicast"
_FULL_METHOD = f"/{_SERVICE}/{_METHOD}"

RETRY_KEY = ("go-ibft", "transport", "retries")
SEND_FAILURE_KEY = ("go-ibft", "transport", "send_failures")
PEER_RECONNECT_KEY = ("go-ibft", "transport", "peer_reconnects")


def _identity(b: bytes) -> bytes:
    return b


class GrpcTransport:
    """Asyncio gRPC multicast between validator hosts.

    ``deliver`` receives decoded inbound messages (self-delivered ones
    included).  Call :meth:`start` before use and :meth:`stop` on
    shutdown.  ``peers`` maps peer name -> ``host:port`` target.
    """

    # Retry policy: total budget per (message, peer) send.  The deadline is
    # clamped strictly below the round-0 timeout — a send retried past the
    # round it belongs to is pure waste (the round change already
    # superseded it) and must never keep the event loop busy into the next
    # round's budget.
    MAX_SEND_DEADLINE_S = DEFAULT_BASE_ROUND_TIMEOUT * 0.5

    def __init__(
        self,
        listen_addr: str,
        peers: Dict[str, str],
        deliver: Callable[[IbftMessage], None],
        logger=None,
        *,
        send_deadline_s: float = 3.0,
        base_backoff_s: float = 0.05,
        per_attempt_timeout_s: float = 2.0,
        retry_seed: Optional[int] = None,
        node: Optional[str] = None,
        reconnect_after: int = 2,
    ) -> None:
        # Telemetry identity: the flight-recorder track inbound wire
        # events land on.  Pass the engine's node track (``node-<id>``)
        # for per-node timeline rows that match; without it, wire events
        # land on a ``net-<addr>`` diagnostics track AND the context is
        # left unmarked so the ENGINE still records the canonical
        # ``net.recv`` on its own track — the timeline tool only counts
        # recvs on consensus tracks, so the default never poisons the
        # quorum reconstruction.
        self._node_explicit = node is not None
        self.node = node or f"net-{listen_addr}"
        self._listen_addr = listen_addr
        self._peers = dict(peers)
        self._deliver = deliver
        self._log = logger
        self._server: Optional[grpc.aio.Server] = None
        self._channels: Dict[str, grpc.aio.Channel] = {}
        self._stubs: Dict[str, grpc.aio.UnaryUnaryMultiCallable] = {}
        self._tasks: set = set()
        self.bound_port: Optional[int] = None
        self.send_deadline_s = min(send_deadline_s, self.MAX_SEND_DEADLINE_S)
        self.base_backoff_s = base_backoff_s
        self.per_attempt_timeout_s = per_attempt_timeout_s
        # Jitter stream: seedable so chaos tests replay exact backoff
        # sequences; unseeded production transports de-synchronize
        # naturally.
        self._jitter = random.Random(retry_seed)
        # Peer reconnect (ISSUE 19): a gRPC channel that watched its peer
        # restart can sit in TRANSIENT_FAILURE holding a dead subchannel
        # while the peer is already back on the same address.  After
        # ``reconnect_after`` consecutive exhausted send deadlines to one
        # peer the channel is torn down and recreated, so a restarted
        # validator rejoins the mesh within one send deadline instead of
        # riding gRPC's internal reconnect backoff ladder.
        self.reconnect_after = max(1, reconnect_after)
        self._fail_streak: Dict[str, int] = {}

    # -- lifecycle ------------------------------------------------------

    async def start(self) -> None:
        server = grpc.aio.server()

        async def _handle(request: bytes, context) -> bytes:
            raw, ctx = decode_traced(request)
            try:
                message = IbftMessage.decode(raw)
            except Exception as err:  # noqa: BLE001 - malformed peer input
                if self._log:
                    self._log.error("grpc transport: undecodable message", err)
                return b""
            if ctx is not None:
                # Cross-process delivery: record the recv at the wire
                # boundary (the engine ingress skips contexts marked
                # recorded), attach the context for downstream consumers,
                # and feed the clock-offset estimator — send/recv pairs
                # are the only cross-host clock evidence that exists.
                recv_us = time.perf_counter_ns() // 1000
                clock.observe(ctx.origin, ctx.sent_us, recv_us)
                message.trace_ctx = ctx
                if trace.enabled():
                    trace.instant(
                        "net.recv",
                        track=self.node,
                        origin=ctx.origin,
                        height=ctx.height,
                        round=ctx.round,
                        type=int(message.type),
                        span=ctx.span_id,
                        sent_us=ctx.sent_us,
                        transport="grpc",
                    )
                    # Only suppress the engine's own record when this
                    # transport carries the engine's track: otherwise the
                    # canonical per-node recv would land on a ``net-*``
                    # diagnostics row and the timeline would see no
                    # arrivals at the node.
                    if self._node_explicit:
                        ctx.recorded = True
            self._deliver(message)
            return b""

        handler = grpc.method_handlers_generic_handler(
            _SERVICE,
            {
                _METHOD: grpc.unary_unary_rpc_method_handler(
                    _handle,
                    request_deserializer=_identity,
                    response_serializer=_identity,
                )
            },
        )
        server.add_generic_rpc_handlers((handler,))
        self.bound_port = server.add_insecure_port(self._listen_addr)
        await server.start()
        self._server = server
        for name, target in self._peers.items():
            channel = grpc.aio.insecure_channel(target)
            self._channels[name] = channel
            self._stubs[name] = channel.unary_unary(
                _FULL_METHOD,
                request_serializer=_identity,
                response_deserializer=_identity,
            )

    async def stop(self) -> None:
        for task in list(self._tasks):
            task.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        for channel in self._channels.values():
            await channel.close()
        self._channels.clear()
        self._stubs.clear()
        if self._server is not None:
            await self._server.stop(grace=0.2)
            self._server = None

    def add_peer(self, name: str, target: str) -> None:
        self._peers[name] = target
        channel = grpc.aio.insecure_channel(target)
        self._channels[name] = channel
        self._stubs[name] = channel.unary_unary(
            _FULL_METHOD,
            request_serializer=_identity,
            response_deserializer=_identity,
        )

    def _reconnect_peer(self, name: str) -> None:
        """Tear down and recreate one peer's channel (see ``reconnect_after``).

        The old channel closes asynchronously (its in-flight RPCs were
        already written off by the send deadline); the fresh channel picks
        up the SAME target, so a peer that restarted on its address gets a
        clean TCP connect on the very next multicast.
        """
        target = self._peers.get(name)
        old = self._channels.pop(name, None)
        self._stubs.pop(name, None)
        if old is not None:
            try:
                task = asyncio.get_running_loop().create_task(old.close())
                self._tasks.add(task)
                task.add_done_callback(self._tasks.discard)
            except RuntimeError:  # no running loop (unit tests)
                pass
        if target is None:
            return
        self.add_peer(name, target)
        self._fail_streak[name] = 0
        metrics.inc_counter(PEER_RECONNECT_KEY)
        trace.instant("net.reconnect", peer=name, target=target)
        if self._log:
            self._log.info("grpc transport: reconnected peer", name, target)

    # -- Transport seam -------------------------------------------------

    def multicast(self, message: IbftMessage) -> None:
        """Encode once, self-deliver locally, fan out to all peers."""
        with trace.span(
            "net.multicast", peers=len(self._stubs), type=int(message.type)
        ):
            payload = message.encode()
            # Trace-context frame AROUND the signed bytes (never inside:
            # payload_no_sig must stay byte-identical to the reference).
            ctx = getattr(message, "trace_ctx", None)
            if ctx is not None:
                payload = encode_traced(payload, ctx)
            self._deliver(message)
        for name, stub in self._stubs.items():
            task = asyncio.get_running_loop().create_task(
                self._send(name, stub, payload)
            )
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)

    async def _send(self, name: str, stub, payload: bytes) -> None:
        """One peer send: retry with jittered exponential backoff inside
        ``send_deadline_s``.

        Attempt k sleeps ``base_backoff_s * 2^k * uniform(0.5, 1.5)``
        before retrying; the loop stops as soon as the remaining deadline
        cannot cover the next backoff.  Failures stay fire-and-forget
        (logged + counted, never raised): liveness is the protocol's job,
        the retries only spare it a round change for a transient blip.
        """
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.send_deadline_s
        attempt = 0
        while True:
            remaining = deadline - loop.time()
            if remaining <= 0:
                break
            try:
                with trace.span("net.send", peer=name, attempt=attempt):
                    await stub(
                        payload,
                        timeout=min(self.per_attempt_timeout_s, remaining),
                    )
                self._fail_streak.pop(name, None)
                return
            except asyncio.CancelledError:
                return  # transport stopping: drop silently, never retry
            except (grpc.aio.AioRpcError, grpc.RpcError) as err:
                if self._log:
                    self._log.debug(
                        "grpc multicast attempt failed", name, attempt, err
                    )
            backoff = (
                self.base_backoff_s
                * (2.0**attempt)
                * self._jitter.uniform(0.5, 1.5)
            )
            attempt += 1
            if loop.time() + backoff >= deadline:
                break
            metrics.inc_counter(RETRY_KEY)
            trace.instant("net.retry", peer=name, attempt=attempt)
            await asyncio.sleep(backoff)
        metrics.inc_counter(SEND_FAILURE_KEY)
        trace.instant("net.send_failed", peer=name, attempts=attempt)
        if self._log:
            self._log.debug("grpc multicast gave up", name, attempt)
        # Consecutive exhausted deadlines to one peer: assume the channel
        # went bad (peer restart), not just the link — rebuild it.
        streak = self._fail_streak.get(name, 0) + 1
        self._fail_streak[name] = streak
        if streak >= self.reconnect_after and name in self._peers:
            self._reconnect_peer(name)


def local_cluster_addresses(n: int) -> Sequence[str]:
    """Convenience: n distinct localhost listen addresses (ephemeral)."""
    return ["127.0.0.1:0"] * n
