"""Aggregation-tree gossip: COMMIT dissemination that stops scaling with N.

Full-mesh multicast moves every COMMIT to every node — O(N²) messages per
round, each carrying a full seal — and every node then verifies O(N)
seals.  This module implements the aggregated-signature-gossip alternative
("Scalable BFT Consensus Mechanism Through Aggregated Signature Gossip",
PAPERS.md 1911.04698) over the framework's one-method ``Transport`` seam:

* nodes form a ``fan_in``-ary tree (registration order; node 0 is the
  root);
* a COMMIT no longer floods — the node self-delivers it and buffers its
  BLS seal as a *partial aggregate* (one G2 point + a signer set, exactly
  a certificate-shaped payload);
* dissemination is PERIODIC, the paper's gossip cadence: each
  :meth:`pump` sweep walks nodes children-first, and every node whose
  merged partial grew since its last send pushes ONE partial to its
  parent (every interior node keeps one slot per child; child subtrees
  are disjoint by construction, so merging is plain point addition — no
  double-count bookkeeping).  Children-first order makes a single sweep
  converge: everything buffered anywhere reaches the root in one pump;
* the root watches merged voting power; at quorum it builds ONE
  :class:`~go_ibft_tpu.crypto.quorum_cert.AggregateQuorumCertificate`,
  VERIFIES it (one pairing — the tree merges unverified, so the root
  must never broadcast unchecked; a failing aggregate bisects the slot
  tree to evict the Byzantine contribution while every honest seal
  survives, O(k·fan_in·log N) equations for k bad seals) and broadcasts
  it DOWN the tree, each node forwarding to at most ``fan_in`` children
  and handing the certificate to its engine
  (:meth:`IBFT.add_quorum_certificate` — one pairing to finalize).

Ingest is gated: only COMMITs with a decodable r-torsion BLS seal, a
well-formed 32-byte proposal hash, and a registered-validator sender
enter the aggregate path (everything else floods — the reference path,
where engine-side validation applies); the in-flight key set is bounded
AND attributed (``max_inflight_keys`` globally, ``max_keys_per_sender``
per introducing validator — a spammer's forged keys evict each other,
never honest keys), a COMMIT refused admission floods instead of
dropping (a full window costs efficiency, never liveness), and
relay-state GC is anchored to CERTIFIED progress, so no forged message
can wipe or grow hub state unboundedly.

Per-node wire cost for the COMMIT phase: at most ONE partial of
O(192 + N/8) bytes up per pump sweep per in-flight round plus O(fan_in)
certificate forwards down — a per-node send RATE independent of
committee size (the batching is what the periodic cadence buys over
eager per-seal relay, where interior nodes would forward once per
descendant).  Total traffic is O(N) partials per round in the
everyone-commits-then-pump case and O(N log N) worst case under maximal
interleaving, vs O(N²) full seals for flooding.  The hub counts bytes
and messages per node (:meth:`stats`) so the bench reports the shape
instead of asserting it.

Non-COMMIT messages (and COMMITs whose seal is not a decodable BLS G2
point — an ECDSA cluster can mount this transport unmodified) flood to
every node, the reference posture: the tree mode changes COMMIT
dissemination only.

Like :class:`~go_ibft_tpu.core.transport.LoopbackTransport` and
:class:`~go_ibft_tpu.chain.sync.LoopbackSyncNetwork`, the hub is
in-process (tests, single-host clusters, benches); a DCN implementation
would put one gRPC hop per tree edge behind the same port API.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, FrozenSet, List, Optional, Tuple

from ..crypto import bls as hbls
from ..crypto.quorum_cert import AggregateQuorumCertificate, BLSCertifier
from ..messages.helpers import extract_commit_hash, extract_committed_seal
from ..messages.wire import IbftMessage, MessageType
from ..obs import ledger as cost_ledger
from ..obs import trace
from ..utils import metrics
from ..verify.bls import decode_seal, encode_seal

__all__ = ["AggregationTreeGossip", "TreePort"]

CERTS_BUILT_KEY = ("go-ibft", "aggtree", "certs_built")
PARTIALS_SENT_KEY = ("go-ibft", "aggtree", "partials_sent")
REJECTED_PARTIALS_KEY = ("go-ibft", "aggtree", "rejected_partials")


class TreePort:
    """The per-node ``Transport`` seam handed to one engine."""

    def __init__(self, hub: "AggregationTreeGossip", index: int) -> None:
        self._hub = hub
        self.index = index

    def multicast(self, message: IbftMessage) -> None:
        self._hub._multicast(self.index, message)


@dataclass
class _Node:
    address: bytes
    deliver: Callable[[IbftMessage], None]
    deliver_cert: Optional[Callable[[AggregateQuorumCertificate], None]]
    # (height, round, proposal_hash) -> slot id ("self" or child index) ->
    # (merged G2 point, disjoint signer set)
    slots: Dict[tuple, Dict[object, Tuple[object, FrozenSet[bytes]]]] = field(
        default_factory=dict
    )
    # Keys whose merged partial grew since the last upward send (the pump
    # sweep drains this) and what was last sent per key (dedup).
    dirty: set = field(default_factory=set)
    sent: Dict[tuple, FrozenSet[bytes]] = field(default_factory=dict)
    # wire accounting
    commit_bytes: int = 0
    commit_msgs: int = 0
    flood_bytes: int = 0
    flood_msgs: int = 0


class AggregationTreeGossip:
    """In-process aggregation-tree hub (register → ports → engines)."""

    def __init__(
        self,
        certifier: BLSCertifier,
        *,
        fan_in: int = 2,
        step_interval: float = 0.002,
        auto_pump: bool = True,
        merger=None,
        logger=None,
    ) -> None:
        if fan_in < 1:
            raise ValueError("fan_in must be >= 1")
        self.certifier = certifier
        self.fan_in = fan_in
        self.step_interval = step_interval
        # Optional batched merge seam (ISSUE 12): a
        # :class:`~go_ibft_tpu.verify.aggregate.G2MergeTree` (anything
        # with ``merge_groups``) turns each sweep LEVEL's slot merges
        # into ONE vmapped combine — O(depth) dispatches per sweep
        # instead of per-node-per-key Python g2_adds.  None keeps the
        # host fold (bit-identical; the small-committee default).
        self.merger = merger
        # auto_pump: sweep inline after each ingest while no cadence task
        # runs (synchronous callers converge without an event loop).
        # False = strictly periodic/manual pumping — the batched mode.
        self.auto_pump = auto_pump
        self._log = logger
        self._lock = threading.Lock()
        self._nodes: List[_Node] = []
        # Keys the root has already certified (late partials are no-ops).
        # GC is anchored to CERTIFIED progress, never to a height claimed
        # by an incoming message — a forged high-height COMMIT must not be
        # able to wipe every in-flight partial hub-wide.
        self._certified: set = set()
        self._certified_high = 0
        # Bound on distinct in-flight (height, round, hash) keys: an
        # attacker minting fresh keys (bogus rounds/hashes at plausible
        # heights) grows relay state without it.  Admission is attributed:
        # each key remembers the sender that INTRODUCED it, and one sender
        # holds at most ``max_keys_per_sender`` live introductions (its
        # own lowest-height key evicts first) — so a Byzantine validator
        # forging high-height COMMITs competes with its own spam and can
        # never starve other validators' keys out of the window.  The
        # global cap is a backstop; a key refused admission is not
        # dropped — its COMMIT floods (reference path), so a full window
        # costs efficiency, never liveness.
        self.max_inflight_keys = 64
        self.max_keys_per_sender = 4
        self._live: set = set()
        self._key_introducer: Dict[tuple, bytes] = {}
        self._introduced: Dict[bytes, set] = {}
        self.rejected_partials = 0
        self.certs_built = 0
        self._task = None
        # (node count, depth -> indices) — see _levels().
        self._levels_cache = None

    # -- wiring ----------------------------------------------------------

    def register(
        self,
        address: bytes,
        deliver: Callable[[IbftMessage], None],
        deliver_cert: Optional[
            Callable[[AggregateQuorumCertificate], None]
        ] = None,
    ) -> TreePort:
        """Register one node (tree position = registration order; node 0
        is the root).  ``deliver`` receives flooded messages and the
        node's own self-delivered ones; ``deliver_cert`` receives the
        round's aggregate certificate (wire it to
        ``engine.add_quorum_certificate``)."""
        with self._lock:
            index = len(self._nodes)
            self._nodes.append(_Node(bytes(address), deliver, deliver_cert))
        return TreePort(self, index)

    def _parent(self, i: int) -> Optional[int]:
        return None if i == 0 else (i - 1) // self.fan_in

    def _children(self, i: int) -> List[int]:
        lo = i * self.fan_in + 1
        return [c for c in range(lo, lo + self.fan_in) if c < len(self._nodes)]

    @property
    def depth(self) -> int:
        d, i = 0, len(self._nodes) - 1
        while i > 0:
            i = (i - 1) // self.fan_in
            d += 1
        return d

    # -- the transport seam ----------------------------------------------

    def _multicast(self, origin: int, message: IbftMessage) -> None:
        seal = (
            extract_committed_seal(message)
            if message.type == MessageType.COMMIT
            else None
        )
        point = decode_seal(seal.signature) if seal is not None else None
        phash = extract_commit_hash(message) if seal is not None else None
        view = message.view
        # Tree eligibility: a decodable BLS seal, a well-formed 32-byte
        # proposal hash (anything else would poison the certificate codec
        # in the pump), and a sender that is actually a validator with a
        # registered key at this height (a foreign signer would make
        # every build_from_aggregate for the round fail).  Everything
        # else floods — the reference path, where the engines' own
        # validation applies.
        if (
            point is None
            or phash is None
            or len(phash) != 32
            or view is None
            or not self.certifier.is_member(view.height, message.sender)
        ):
            self._flood(origin, message)
            return
        key = (view.height, view.round, phash)
        with self._lock:
            admitted = self._admit_key(message.sender, key)
            if admitted:
                self._set_slot(
                    origin, key, "self", point, frozenset([message.sender])
                )
        if not admitted:
            # The in-flight window refused the key: degrade to the
            # reference flood path rather than dropping — engines collect
            # a per-seal quorum instead, so a full window (spam or a
            # genuine burst) costs wire efficiency, never liveness.
            self._flood(origin, message)
            return
        # COMMIT with a decodable BLS seal: self-deliver (engines expect
        # their own messages back); the buffered partial rides the next
        # pump sweep.
        self._nodes[origin].deliver(message)
        if self.auto_pump and self._task is None:
            # No cadence task: pump inline so synchronous callers (tests,
            # the bench's dissemination model) converge without an event
            # loop.  With :meth:`start` running (or auto_pump off),
            # ingests BATCH until the next sweep — that cadence is what
            # caps interior nodes at one upward partial per sweep instead
            # of one per descendant.
            self.pump()

    def _flood(self, origin: int, message: IbftMessage) -> None:
        """Reference-path dissemination: every node gets the message."""
        payload_len = len(message.encode())
        nodes = self._nodes
        node = nodes[origin]
        node.flood_bytes += payload_len * max(0, len(nodes) - 1)
        node.flood_msgs += max(0, len(nodes) - 1)
        for peer in nodes:
            peer.deliver(message)

    # -- tree mechanics ---------------------------------------------------

    @staticmethod
    def _slot_parts(slots) -> Tuple[List[object], FrozenSet[bytes]]:
        """One key's slot dict -> (points, merged signer set) — the ONE
        fold shape shared by _merged, the pump's level walk, and the
        root candidate block (so slot semantics can never diverge
        between them)."""
        points: List[object] = []
        signers: FrozenSet[bytes] = frozenset()
        for p, s in slots.values():
            points.append(p)
            signers = signers | s
        return points, signers

    def _merged(self, i: int, key: tuple):
        points, signers = self._slot_parts(self._nodes[i].slots.get(key, {}))
        point = None
        for p in points:
            point = hbls.g2_add(point, p)
        return point, signers

    def _set_slot(self, i: int, key: tuple, slot, point, signers) -> None:
        """Update one slot at node ``i`` (callers hold the lock).  Child
        subtrees are disjoint, so slot replacement is exact; a partial
        that did not grow the signer set is dropped (dedup — re-sends and
        late duplicates mark nothing dirty and cost no wire)."""
        node = self._nodes[i]
        slots = node.slots.setdefault(key, {})
        prev = slots.get(slot)
        if prev is not None and not (signers - prev[1]):
            return  # nothing new from this subtree
        slots[slot] = (point, signers)
        node.dirty.add(key)

    def _depth_of(self, i: int) -> int:
        d = 0
        while i > 0:
            i = (i - 1) // self.fan_in
            d += 1
        return d

    def _levels(self) -> Dict[int, List[int]]:
        """depth -> node indices (root excluded), cached: the topology is
        fixed by registration order, and pump() runs inline after every
        ingest — rebuilding the grouping under the hub lock per COMMIT
        would be O(N log N) of pure overhead (callers hold the lock)."""
        cached = self._levels_cache
        if cached is not None and cached[0] == len(self._nodes):
            return cached[1]
        by_depth: Dict[int, List[int]] = {}
        for i in range(1, len(self._nodes)):
            by_depth.setdefault(self._depth_of(i), []).append(i)
        self._levels_cache = (len(self._nodes), by_depth)
        return by_depth

    def _merge_level(self, work: List[tuple]) -> List[object]:
        """Merge each work item's slot points — one vmapped combine for
        the whole level through :attr:`merger`, or the host fold."""
        groups = [pts for _i, _key, _signers, pts in work]
        if self.merger is not None:
            # route_tag: the merge-tree dispatch this issues records in
            # the cost ledger as ``aggtree/device``, splitting the gossip
            # pump's per-sweep combines from certifier-build merges.
            with cost_ledger.route_tag("aggtree"):
                return self.merger.merge_groups(groups)
        out = []
        for pts in groups:
            point = None
            for p in pts:
                point = hbls.g2_add(point, p)
            out.append(point)
        return out

    def _send_up(self, i: int, key: tuple, merged_point, merged_signers):
        """Push one merged partial to node ``i``'s parent (lock held).

        One certificate-shaped partial up the tree: the 192-byte merged
        point + signer bitmap — size independent of how many seals the
        subtree merged (the bitmap's 1 bit/validator is the only
        N-term).  A merge CAN cancel to infinity (a Byzantine seal equal
        to a sibling's negation — the tree relays unverified); the
        partial still travels, encoded as zeros, and the root's
        quarantine evicts the offending leaf when certification fails.
        """
        node = self._nodes[i]
        node.sent[key] = merged_signers
        height, round_, phash = key
        wire = AggregateQuorumCertificate(
            height=height,
            round=round_,
            proposal_hash=phash,
            agg_seal=(
                encode_seal(merged_point)
                if merged_point is not None
                else b"\x00" * 192
            ),
            bitmap=b"\x00" * ((len(self._nodes) + 7) // 8),
        )
        node.commit_bytes += len(wire.encode())
        node.commit_msgs += 1
        metrics.inc_counter(PARTIALS_SENT_KEY)
        self._set_slot(self._parent(i), key, i, merged_point, merged_signers)

    def pump(self) -> None:
        """One gossip sweep: children-first, each dirty node sends ONE
        merged partial per in-flight key to its parent; the root then
        certifies any key that reached quorum.

        The walk is grouped by tree LEVEL (deepest first — same
        children-first convergence as the node-ordered walk, since a
        parent is always strictly shallower than its children): a single
        sweep fully converges, every node's send rate stays capped at
        one partial per key per sweep, and with a :attr:`merger`
        attached every level's slot merges run as ONE vmapped device
        combine instead of per-child Python g2_adds (ISSUE 12 — O(depth)
        merge dispatches per sweep).  Runs inline after every ingest
        (cheap: nothing dirty = no-op) and from the optional
        :meth:`start` cadence task.
        """
        to_deliver = []
        with self._lock:
            # Level membership is walked deepest-first with the DIRTY
            # check at visit time (not snapshotted): a push from depth
            # d+1 dirties a depth-d parent mid-sweep, and children-first
            # convergence requires that parent to send in THIS sweep.
            by_depth = self._levels()
            for depth in sorted(by_depth, reverse=True):
                work: List[tuple] = []
                for i in by_depth[depth]:
                    node = self._nodes[i]
                    if not node.dirty:
                        continue
                    for key in sorted(node.dirty):
                        points, signers = self._slot_parts(
                            node.slots.get(key, {})
                        )
                        if not (
                            signers - node.sent.get(key, frozenset())
                        ):
                            continue  # nothing new: no merge, no send
                        work.append((i, key, signers, points))
                    node.dirty.clear()
                for (i, key, signers, _pts), point in zip(
                    work, self._merge_level(work)
                ):
                    self._send_up(i, key, point, signers)
            root = self._nodes[0] if self._nodes else None
            candidates = []
            if root is not None and root.dirty:
                rwork = []
                for key in sorted(root.dirty):
                    points, signers = self._slot_parts(
                        root.slots.get(key, {})
                    )
                    rwork.append((0, key, signers, points))
                root.dirty.clear()
                for (_i, key, signers, _pts), point in zip(
                    rwork, self._merge_level(rwork)
                ):
                    candidates.append((key, point, signers))
        # Certification pairs OUTSIDE the lock (a host pairing is ~1 s;
        # holding the hub lock through it would block every node's COMMIT
        # ingest); only the unhappy-path quarantine re-acquires it.
        for key, point, signers in candidates:
            cert = self._certify(key, point, signers)
            if cert is not None:
                to_deliver.append(cert)
        for cert in to_deliver:
            self._broadcast_cert(0, cert)

    async def _run(self) -> None:
        import asyncio

        while True:
            await asyncio.sleep(self.step_interval)
            self.pump()

    def start(self) -> None:
        """Run :meth:`pump` on a periodic asyncio cadence (optional —
        ingest already pumps inline; the cadence only bounds latency for
        partials that raced a sweep)."""
        import asyncio

        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="aggtree-pump"
            )

    async def stop(self) -> None:
        import asyncio

        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def _certify(
        self, key: tuple, point, signers
    ) -> Optional[AggregateQuorumCertificate]:
        """Build AND VERIFY the key's certificate once quorum power
        merged (called WITHOUT the lock — pairings must not block
        ingest; broadcasting happens in the caller).

        The tree merges without verifying (that is what keeps relay
        cheap), so the root must never broadcast unchecked: ONE pairing
        verifies the candidate certificate.  On failure — a Byzantine
        contribution somewhere in the tree — the slot tree is BISECTED
        (:meth:`_quarantine`, under the lock): each bad subtree is
        pairing-checked level by level down to the offending leaf seals,
        which are evicted while every honest contribution survives, and
        certification retries on the cleaned aggregate.  k bad seals
        cost O(k · fan_in · log N) equations; the happy path stays at
        one, computed over a snapshot (commits landing mid-pairing ride
        the next sweep).
        """
        with self._lock:
            if key in self._certified:
                return None
        height, round_, phash = key
        cert = None
        if point is not None:
            cert = self.certifier.build_from_aggregate(
                height, round_, phash, point, list(signers)
            )
            if cert is None:
                return None  # below quorum: keep merging
        if cert is None or not self.certifier.verify(cert):
            # Either the pairing failed, or the merge cancelled to
            # infinity outright (point None with signers present — a
            # Byzantine seal equal to the negation of its siblings' sum).
            # Same disease either way: a bad contribution somewhere in
            # the tree.  Bisect to evict it, then retry on the cleaned
            # aggregate.
            if not signers:
                return None
            with self._lock:
                self._quarantine(0, key, height, phash)
                point, signers = self._merged(0, key)
            cert = (
                self.certifier.build_from_aggregate(
                    height, round_, phash, point, list(signers)
                )
                if point is not None
                else None
            )
            if cert is None or not self.certifier.verify(cert):
                # honest power below quorum after eviction: stay
                # uncertified so late honest commits can still finish
                return None
        with self._lock:
            if key in self._certified:
                return None  # a concurrent sweep won the race
            self._certified.add(key)
            self._certified_high = max(self._certified_high, height)
            self.certs_built += 1
            self._gc()
        metrics.inc_counter(CERTS_BUILT_KEY)
        trace.instant(
            "aggtree.certified", height=height, signers=len(signers)
        )
        return cert

    def _quarantine(self, i: int, key: tuple, height: int, phash) -> None:
        """Bisect node ``i``'s slots for ``key``: pairing-check each, dig
        into bad child subtrees, evict bad leaf seals, and rebuild the
        cleaned merged contributions bottom-up (callers hold the lock).

        In a multi-host deployment this walk is a bisect request down the
        tree; in-process the hub holds every node's slots directly."""
        node = self._nodes[i]
        slots = node.slots.get(key, {})
        for slot_id in list(slots):
            point, signers = slots[slot_id]
            if self.certifier.partial_valid(height, phash, point, signers):
                continue
            if slot_id == "self":
                # the offending leaf seal: evict it (a corrected re-send
                # re-enters through the normal ingest path)
                del slots[slot_id]
                self.rejected_partials += 1
                metrics.inc_counter(REJECTED_PARTIALS_KEY)
                trace.instant(
                    "aggtree.rejected", node=i, height=height
                )
                continue
            self._quarantine(slot_id, key, height, phash)
            child_point, child_signers = self._merged(slot_id, key)
            if child_signers:
                slots[slot_id] = (child_point, child_signers)
                self._nodes[slot_id].sent[key] = child_signers
            else:
                del slots[slot_id]

    def _broadcast_cert(
        self, i: int, cert: AggregateQuorumCertificate
    ) -> None:
        """Root-down dissemination: each node forwards to its children
        (<= fan_in sends) and hands the certificate to its engine."""
        node = self._nodes[i]
        children = self._children(i)
        cert_bytes = len(cert.encode())
        node.commit_bytes += cert_bytes * len(children)
        node.commit_msgs += len(children)
        if node.deliver_cert is not None:
            try:
                node.deliver_cert(cert)
            except Exception as err:  # noqa: BLE001 - one engine's failure
                # must not stop the broadcast reaching its siblings
                if self._log:
                    self._log.error("aggtree cert delivery failed", err)
        for c in children:
            self._broadcast_cert(c, cert)

    def _drop_key(self, key: tuple) -> None:
        for node in self._nodes:
            node.slots.pop(key, None)
            node.sent.pop(key, None)
            node.dirty.discard(key)
        self._live.discard(key)
        introducer = self._key_introducer.pop(key, None)
        if introducer is not None:
            mine = self._introduced.get(introducer)
            if mine is not None:
                mine.discard(key)
                if not mine:
                    del self._introduced[introducer]

    def _admit_key(self, sender: bytes, key: tuple) -> bool:
        """Bound the in-flight key set (callers hold the lock).

        A known key is always admitted.  A fresh key is ATTRIBUTED to the
        sender introducing it, and one sender holds at most
        ``max_keys_per_sender`` live introductions — past that its own
        lowest-height key evicts first, so an attacker minting bogus
        (height, round, hash) keys competes with its own spam and can
        never starve other validators' keys out of the window.  The
        global cap is a backstop (honest rounds share ONE key introduced
        by whoever committed first, so it binds only under pathological
        churn); eviction there is lowest-height-first, newcomers at or
        below the floor refused.  A refusal is not a drop: the caller
        floods the COMMIT instead."""
        if key in self._certified or key in self._live:
            return True
        mine = self._introduced.setdefault(sender, set())
        if len(mine) >= self.max_keys_per_sender:
            self._drop_key(min(mine))
        if len(self._live) >= self.max_inflight_keys:
            oldest = min(self._live, key=lambda k: k[0])
            if key[0] <= oldest[0]:
                if not mine:
                    del self._introduced[sender]
                return False
            self._drop_key(oldest)
        self._live.add(key)
        self._key_introducer[key] = sender
        mine.add(key)
        return True

    def _gc(self) -> None:
        """Drop relay state more than two heights behind CERTIFIED
        progress (callers hold the lock).  Anchoring to certification —
        never to a height claimed by an incoming message — means no
        forged COMMIT can wipe in-flight partials hub-wide."""
        floor = self._certified_high - 2
        if floor <= 0:
            return
        for key in [k for k in self._live if k[0] < floor]:
            self._drop_key(key)
        self._certified = {k for k in self._certified if k[0] >= floor}

    # -- evidence ----------------------------------------------------------

    def stats(self) -> dict:
        """Per-node wire accounting (bench config #9 reads this)."""
        return {
            "nodes": len(self._nodes),
            "fan_in": self.fan_in,
            "depth": self.depth,
            "certs_built": self.certs_built,
            "rejected_partials": self.rejected_partials,
            "commit_bytes_per_node": [n.commit_bytes for n in self._nodes],
            "commit_msgs_per_node": [n.commit_msgs for n in self._nodes],
            "flood_bytes_per_node": [n.flood_bytes for n in self._nodes],
        }
