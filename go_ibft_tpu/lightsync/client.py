"""Checkpoint-anchored cold sync: the light-client side of ISSUE 20.

:class:`CheckpointClient` speaks the proof API's wire surface
(``GET /head``, ``GET /proof``, and the new ``GET /checkpoints`` —
``node/proof_api.py``) over plain stdlib HTTP, trusting NOTHING from
the server:

1. fetch the O(log n) checkpoint skip path and verify every hop through
   :class:`~go_ibft_tpu.lightsync.checkpoint.CheckpointVerifier` — one
   batched pairing dispatch for the whole chain, rotations bridged with
   commitment-enforced finality proofs fetched from the same server;
2. anchor at the verified checkpoint nearest the target height;
3. fetch + verify ONLY the tail ``(anchor, target]`` as an ordinary
   finality proof (``ProofVerifier`` with ``require_commitments`` on, so
   a fabricated rotation diff in the tail dies at the commitment check).

A client checkpointed at genesis of a million-height chain therefore
transfers a handful of ~100-byte certificates plus one short tail proof
instead of a million diff hops — the bench (config #18,
``checkpoint_sync_1m``) measures the ratio and pins the dispatch count.

``fetch`` may be a base URL (``"http://127.0.0.1:9090"``) or any
callable ``path -> (json_payload, wire_bytes)`` (tests and in-process
embedders skip the socket).
"""

from __future__ import annotations

import http.client
import json
import urllib.parse
from dataclasses import dataclass
from typing import Callable, Dict, Mapping, Optional, Tuple

from ..utils import metrics
from .checkpoint import CheckpointAnchor, CheckpointError, CheckpointVerifier

__all__ = [
    "CheckpointClient",
    "ColdSyncReport",
    "http_fetcher",
]

Fetch = Callable[[str], Tuple[dict, int]]


def http_fetcher(base_url: str, *, timeout_s: float = 10.0) -> Fetch:
    """A ``path -> (payload, bytes)`` fetcher over stdlib HTTP/1.1.

    One connection per call (the proof API's keep-alive is an
    optimization, not a contract); non-200 statuses raise
    :class:`CheckpointError` with the status and path.
    """
    parsed = urllib.parse.urlparse(base_url)
    if parsed.scheme not in ("http", ""):
        raise ValueError(f"unsupported scheme {parsed.scheme!r}")
    netloc = parsed.netloc or parsed.path

    def fetch(path: str) -> Tuple[dict, int]:
        conn = http.client.HTTPConnection(netloc, timeout=timeout_s)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                raise CheckpointError(
                    f"GET {path} -> {resp.status} {body[:120]!r}"
                )
            return json.loads(body), len(body)
        finally:
            conn.close()

    return fetch


@dataclass
class ColdSyncReport:
    """What a checkpoint-anchored cold sync cost and verified."""

    head: int
    target: int
    anchor_height: int
    anchor_epoch: int
    spacing: int
    checkpoint_bytes: int
    bridge_bytes: int
    tail_bytes: int
    tail_heights: int
    checkpoint_lanes: int
    pairing_dispatches: int
    powers: Dict[bytes, int]

    @property
    def total_bytes(self) -> int:
        return self.checkpoint_bytes + self.bridge_bytes + self.tail_bytes

    @property
    def heights_skipped(self) -> int:
        return self.anchor_height


class CheckpointClient:
    """Anchors a proof-API client at the nearest verified checkpoint."""

    def __init__(
        self,
        fetch,
        bls_keys_for_height: Optional[Callable[[int], Mapping]] = None,
        *,
        device: bool = False,
        require_commitments: bool = True,
        timeout_s: float = 10.0,
    ) -> None:
        self._fetch: Fetch = (
            http_fetcher(fetch, timeout_s=timeout_s)
            if isinstance(fetch, str)
            else fetch
        )
        self._bls_keys = bls_keys_for_height
        self._device = device
        self._require_commitments = require_commitments

    # -- wire ------------------------------------------------------------

    def head(self) -> Tuple[int, int]:
        payload, n = self._fetch("/head")
        return int(payload["head"]), n

    def fetch_checkpoints(
        self,
        *,
        target_epoch: Optional[int] = None,
        include_all: bool = False,
    ) -> Tuple[dict, int]:
        query = []
        if target_epoch is not None:
            query.append(f"epoch={int(target_epoch)}")
        if include_all:
            query.append("all=1")
        path = "/checkpoints" + ("?" + "&".join(query) if query else "")
        return self._fetch(path)

    def fetch_proof(self, checkpoint: int, target: int) -> Tuple[dict, int]:
        return self._fetch(
            f"/proof?checkpoint={int(checkpoint)}&target={int(target)}"
        )

    # -- verification ----------------------------------------------------

    def _proof_verifier(self):
        from ..serve.server import ProofVerifier

        return ProofVerifier(
            bls_keys_for_height=self._bls_keys,
            require_commitments=self._require_commitments,
        )

    def _verify_tail(
        self,
        checkpoint: int,
        target: int,
        powers: Mapping[bytes, int],
    ) -> Tuple[Dict[bytes, int], int]:
        """Fetch + verify the tail range; returns (powers at ``target``,
        wire bytes).  Also the rotation bridge for checkpoint hops."""
        from ..serve.proof import FinalityProof, walk_sets

        payload, n = self.fetch_proof(checkpoint, target)
        proof = FinalityProof.from_wire(payload["proof"])
        if proof.checkpoint_height != checkpoint or proof.target != target:
            raise CheckpointError(
                f"served proof covers ({proof.checkpoint_height}, "
                f"{proof.target}], requested ({checkpoint}, {target}]"
            )
        self._proof_verifier().verify(proof, powers)
        # The walk is pure dict arithmetic over an already-verified
        # proof; re-running it extracts the derived set at the target.
        sets = walk_sets(
            powers, proof, require_commitments=self._require_commitments
        )
        return dict(sets[target]), n

    def sync(
        self,
        trusted_powers: Mapping[bytes, int],
        *,
        target_epoch: Optional[int] = None,
    ) -> Tuple[CheckpointAnchor, int]:
        """Verify the checkpoint chain to ``target_epoch`` (default:
        latest); returns the anchor + checkpoint wire bytes."""
        payload, n = self.fetch_checkpoints(target_epoch=target_epoch)
        bridge_bytes = 0

        def bridge(from_h, to_h, powers):
            nonlocal bridge_bytes
            new_powers, nb = self._verify_tail(from_h, to_h, powers)
            bridge_bytes += nb
            return new_powers

        verifier = CheckpointVerifier(self._bls_keys, device=self._device)
        anchor = verifier.verify_chain(payload, trusted_powers, bridge=bridge)
        return anchor, n + bridge_bytes

    def cold_sync(
        self,
        trusted_powers: Mapping[bytes, int],
        target: Optional[int] = None,
    ) -> ColdSyncReport:
        """Full cold sync from a genesis trust anchor to ``target``
        (default: the served head): checkpoint skip chain + tail proof,
        every byte verified.  Raises :class:`CheckpointError` /
        ``ProofError`` on any rejection."""
        from ..verify.aggregate import MULTIPAIR_DISPATCHES_KEY

        dispatches0 = metrics.get_counter(MULTIPAIR_DISPATCHES_KEY)
        head, head_bytes = self.head()
        target = head if target is None else int(target)
        if not 1 <= target <= head:
            raise CheckpointError(f"target {target} outside [1, {head}]")

        payload, ckpt_bytes = self.fetch_checkpoints()
        ckpt_bytes += head_bytes
        spacing = int(payload.get("spacing", 0) or 0)
        latest_epoch = int(payload.get("latest_epoch", 0) or 0)
        want_epoch = min(target // spacing, latest_epoch) if spacing else 0
        bridge_bytes = 0
        if want_epoch >= 1:
            if want_epoch != latest_epoch:
                # Re-fetch the skip path ENDING at the epoch we anchor
                # on (the server descends from any epoch ≤ latest).
                payload, n = self.fetch_checkpoints(target_epoch=want_epoch)
                ckpt_bytes += n

            def bridge(from_h, to_h, powers):
                nonlocal bridge_bytes
                new_powers, nb = self._verify_tail(from_h, to_h, powers)
                bridge_bytes += nb
                return new_powers

            verifier = CheckpointVerifier(self._bls_keys, device=self._device)
            anchor = verifier.verify_chain(
                payload, trusted_powers, bridge=bridge
            )
        else:
            anchor = CheckpointAnchor(
                height=0,
                epoch=0,
                powers=dict(trusted_powers),
                spacing=spacing,
                lanes=0,
            )

        tail_bytes = 0
        powers = dict(anchor.powers)
        if target > anchor.height:
            powers, tail_bytes = self._verify_tail(
                anchor.height, target, powers
            )
        return ColdSyncReport(
            head=head,
            target=target,
            anchor_height=anchor.height,
            anchor_epoch=anchor.epoch,
            spacing=spacing,
            checkpoint_bytes=ckpt_bytes,
            bridge_bytes=bridge_bytes,
            tail_bytes=tail_bytes,
            tail_heights=target - anchor.height,
            checkpoint_lanes=anchor.lanes,
            pairing_dispatches=metrics.get_counter(MULTIPAIR_DISPATCHES_KEY)
            - dispatches0,
            powers=powers,
        )
