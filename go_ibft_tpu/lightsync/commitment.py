"""Next-validator-set content commitments (ISSUE 20).

The serve plane's documented fabricated-diff hole (docs/SERVING.md trust
assumption 2, `chain/sync.py`'s height-binding caveat): committed seals
sign only ``(raw_proposal, round)``, so the validator-set diff chain a
proof server hands a light client carries no quorum signature of its
own — a malicious server can invent a rotation to its own keys and seal
every later height itself.  Real chains close this by committing the
NEXT height's validator set inside the block content, so the rotation is
covered by the CURRENT quorum's seals over the proposal bytes.

This module is that commitment as data:

* :func:`set_root` — the canonical 32-byte digest of a voting-power map
  (order-independent: addresses sort first; powers are part of the
  preimage so a power change is a rotation too);
* :func:`embed_next_set` / :func:`extract_next_set` /
  :func:`strip_next_set` — a magic-framed suffix carrying the root on
  the END of the raw proposal bytes.  A suffix (not a prefix) keeps
  every existing consumer of the leading bytes working unchanged —
  ``SimBackend``'s ``b"sim-block-%08d"`` prefix check, the byte-identity
  cluster oracles, and any embedder that parses its own header.

The commitment travels INSIDE the signed bytes (seals cover the whole
``raw_proposal``), which is exactly what makes it enforceable:
``serve/proof.py::walk_sets(..., require_commitments=True)`` checks each
diff hop against the root the PREVIOUS height's quorum sealed, and
``chain/sync.py``-style consumers get the same guarantee through the
embedder's ``is_valid_proposal`` seam (``ECDSABackend`` /
``SimBackend`` with ``commit_next_set=True``).
"""

from __future__ import annotations

from typing import Mapping, Optional

from ..crypto.keccak import keccak256

__all__ = [
    "COMMIT_MAGIC",
    "COMMIT_SUFFIX_BYTES",
    "SET_ROOT_BYTES",
    "embed_next_set",
    "extract_next_set",
    "set_root",
    "strip_next_set",
]

_SET_ROOT_DOMAIN = b"go-ibft-set-root-v1:"
SET_ROOT_BYTES = 32

# Leading NUL keeps the frame from ever being valid UTF-8 text an
# embedder might accidentally produce; the versioned tag makes future
# commitment formats distinguishable without guessing.
COMMIT_MAGIC = b"\x00go-ibft-next-set-v1:"
COMMIT_SUFFIX_BYTES = len(COMMIT_MAGIC) + SET_ROOT_BYTES


def set_root(powers: Mapping[bytes, int]) -> bytes:
    """Canonical digest of a validator voting-power map.

    Deterministic over dict order (addresses sort), length-framed per
    entry (no address/power concatenation ambiguity), and covering the
    POWERS — a stake change with an unchanged member list must produce a
    different root, because it changes every later quorum threshold.
    """
    parts = [_SET_ROOT_DOMAIN]
    for addr in sorted(powers):
        power = powers[addr]
        if not isinstance(power, int) or power <= 0:
            raise ValueError(
                f"set_root over non-positive power {power!r} for "
                f"{bytes(addr).hex()[:16]}"
            )
        a = bytes(addr)
        parts.append(len(a).to_bytes(2, "big"))
        parts.append(a)
        parts.append(power.to_bytes(8, "big"))
    return keccak256(b"".join(parts))


def embed_next_set(raw_proposal: bytes, root: bytes) -> bytes:
    """Append the next-set commitment frame to proposal content."""
    if len(root) != SET_ROOT_BYTES:
        raise ValueError(f"set root must be {SET_ROOT_BYTES} bytes")
    if extract_next_set(raw_proposal) is not None:
        raise ValueError("proposal already carries a next-set commitment")
    return bytes(raw_proposal) + COMMIT_MAGIC + root


def extract_next_set(raw_proposal: bytes) -> Optional[bytes]:
    """The committed next-set root, or None when the frame is absent."""
    raw = bytes(raw_proposal)
    if len(raw) < COMMIT_SUFFIX_BYTES:
        return None
    if raw[-COMMIT_SUFFIX_BYTES:-SET_ROOT_BYTES] != COMMIT_MAGIC:
        return None
    return raw[-SET_ROOT_BYTES:]


def strip_next_set(raw_proposal: bytes) -> bytes:
    """Proposal content without the commitment frame (absent → as-is)."""
    raw = bytes(raw_proposal)
    if extract_next_set(raw) is None:
        return raw
    return raw[:-COMMIT_SUFFIX_BYTES]
