"""Epoch checkpoint certificates with power-of-2 skip links (ISSUE 20).

A light client checkpointed a million heights back should not walk a
million set diffs to resync ("Practical Light Clients for
Committee-Based Blockchains", PAPERS.md 2410.03347).  Instead, at every
epoch boundary ``E`` (``height = E * spacing``) the node builds a
:class:`CheckpointRecord` — a quorum-signed commitment to ("validator
set at the boundary", "chain commitment at the boundary") — and chains
it into a deterministic skip structure: record ``E`` carries the digest
of every record at ``E - 2**j``, so a path from genesis to any epoch is
O(log n) records (:func:`skip_path`) and each hop is bound to its
predecessor by content digest BEFORE any cryptography runs.

The certificate shape is the PR-7 aggregate-quorum-certificate posture
applied to epochs: ONE aggregated BLS G2 seal + an LSB-first signer
bitmap over the SORTED validator set, signed over the record's
:meth:`~CheckpointRecord.digest` under a dedicated domain (a checkpoint
seal can never be confused with a COMMIT seal or a PoP — different
domain, different preimage length).  Verification is the PR-12 batched
plane: the client resolves every record's signing set with cheap exact-
int checks (bitmap membership, quorum power, r-torsion decode — forged
or short-power certificates die here, costing zero pairings), then
verifies ALL hops of the skip chain in ONE
:func:`~go_ibft_tpu.verify.aggregate.multi_aggregate_check` dispatch.

Producer side, :class:`Checkpointer` hooks ``ChainRunner._on_finalize``
(``ChainRunner(checkpointer=...)``), persists records through the WAL
(``kind: "checkpoint"``), and serves the skip path as a wire payload for
``GET /checkpoints`` (``node/proof_api.py``).  Client side,
:class:`CheckpointVerifier` (and the HTTP-speaking
:class:`~go_ibft_tpu.lightsync.client.CheckpointClient`) walks the path
from a trusted genesis set, bridging across rotations with
commitment-enforced finality proofs.
"""

from __future__ import annotations

import struct
import threading
from dataclasses import dataclass, replace
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple

from ..core.validator_manager import calculate_quorum
from ..crypto import bls as hbls
from ..crypto.keccak import keccak256
from ..crypto.quorum_cert import AggregateQuorumCertificate
from ..verify.bls import BLS_SEAL_BYTES, decode_seal, encode_seal
from .commitment import SET_ROOT_BYTES, set_root

__all__ = [
    "CHECKPOINT_WIRE_VERSION",
    "CheckpointAnchor",
    "CheckpointError",
    "CheckpointRecord",
    "CheckpointVerifier",
    "Checkpointer",
    "skip_epochs",
    "skip_path",
]

CHECKPOINT_WIRE_VERSION = 1

_DOMAIN = b"go-ibft-checkpoint-v1:"
# version, epoch, height, link count, bitmap length, seal length
_HEADER = struct.Struct(">BQQBHH")
_DIGEST_HEADER = struct.Struct(">BQQ")


class CheckpointError(Exception):
    """A checkpoint chain failed verification (names the epoch)."""


def skip_epochs(epoch: int) -> List[int]:
    """Ascending exponents ``j`` with ``epoch - 2**j >= 1`` — the skip
    links record ``epoch`` carries (one digest per exponent)."""
    return [j for j in range(max(epoch, 1).bit_length()) if epoch - (1 << j) >= 1]


def skip_path(epoch: int) -> List[int]:
    """The ascending epoch path genesis -> ``epoch`` using the largest
    valid skip at every step: O(log epoch) hops, each a real link."""
    if epoch < 1:
        raise ValueError("epochs start at 1")
    path = [epoch]
    e = epoch
    while e > 1:
        e -= 1 << skip_epochs(e)[-1]
        path.append(e)
    path.reverse()
    return path


@dataclass(frozen=True)
class CheckpointRecord:
    """One epoch boundary's quorum-sealed commitment.

    ``set_root`` commits the validator set IN FORCE at ``height`` (the
    set whose quorum signs this record); ``chain_commitment`` is the
    finalized proposal hash at ``height`` (binding the record to one
    chain); ``skip_digests`` are the digests of the records at
    ``epoch - 2**j`` for every ``j`` in :func:`skip_epochs`, ascending.

    :meth:`digest` covers the BODY only (never the seal/bitmap), so an
    unsigned record's digest — and every later record's skip link to it
    — is stable whether signing happens eagerly or lazily.
    """

    epoch: int
    height: int
    set_root: bytes
    chain_commitment: bytes
    skip_digests: Tuple[bytes, ...] = ()
    agg_seal: bytes = b""
    bitmap: bytes = b""

    def __post_init__(self) -> None:
        if len(self.set_root) != SET_ROOT_BYTES:
            raise ValueError("set_root must be 32 bytes")
        if len(self.chain_commitment) != 32:
            raise ValueError("chain_commitment must be 32 bytes")
        if any(len(d) != 32 for d in self.skip_digests):
            raise ValueError("skip digests must be 32 bytes")

    def _body(self) -> bytes:
        return (
            _DIGEST_HEADER.pack(CHECKPOINT_WIRE_VERSION, self.epoch, self.height)
            + self.set_root
            + self.chain_commitment
            + b"".join(self.skip_digests)
        )

    def digest(self) -> bytes:
        """Signing message AND skip-link target for later records."""
        return keccak256(_DOMAIN + self._body())

    @property
    def signed(self) -> bool:
        return bool(self.agg_seal)

    def encode(self) -> bytes:
        return (
            _HEADER.pack(
                CHECKPOINT_WIRE_VERSION,
                self.epoch,
                self.height,
                len(self.skip_digests),
                len(self.bitmap),
                len(self.agg_seal),
            )
            + self.set_root
            + self.chain_commitment
            + b"".join(self.skip_digests)
            + self.bitmap
            + self.agg_seal
        )

    @classmethod
    def decode(cls, blob: bytes) -> "CheckpointRecord":
        if len(blob) < _HEADER.size:
            raise ValueError("checkpoint record too short")
        version, epoch, height, n_links, bm_len, seal_len = _HEADER.unpack_from(
            blob
        )
        if version != CHECKPOINT_WIRE_VERSION:
            raise ValueError(f"unknown checkpoint record version {version}")
        if seal_len not in (0, BLS_SEAL_BYTES):
            raise ValueError("checkpoint seal length invalid")
        body = blob[_HEADER.size :]
        need = 64 + 32 * n_links + bm_len + seal_len
        if len(body) != need:
            raise ValueError("checkpoint record length mismatch")
        links = tuple(
            body[64 + 32 * i : 96 + 32 * i] for i in range(n_links)
        )
        off = 64 + 32 * n_links
        return cls(
            epoch=epoch,
            height=height,
            set_root=body[:32],
            chain_commitment=body[32:64],
            skip_digests=links,
            bitmap=body[off : off + bm_len],
            agg_seal=body[off + bm_len :],
        )


@dataclass
class CheckpointAnchor:
    """What a verified checkpoint chain buys the client: a trust anchor
    ``(height, powers)`` to hand a ``ProofVerifier`` for the tail."""

    height: int
    epoch: int
    powers: Dict[bytes, int]
    spacing: int
    lanes: int = 0


def _bitmap_signers(
    bitmap: bytes, ordered: Sequence[bytes], epoch: int
) -> List[bytes]:
    """LSB-first bitmap -> signer addresses over the SORTED set (the
    quorum-cert convention); any bit outside the set is a hard error."""
    if len(bitmap) != (len(ordered) + 7) // 8:
        raise CheckpointError(
            f"epoch {epoch}: bitmap length {len(bitmap)} does not match "
            f"a {len(ordered)}-validator set"
        )
    out: List[bytes] = []
    for i in range(len(bitmap) * 8):
        if bitmap[i // 8] >> (i % 8) & 1:
            if i >= len(ordered):
                raise CheckpointError(
                    f"epoch {epoch}: bitmap bit {i} outside the "
                    f"{len(ordered)}-validator set"
                )
            out.append(ordered[i])
    return out


class Checkpointer:
    """Builds, persists, and serves the epoch checkpoint chain.

    ``signers`` maps validator address -> :class:`BLSPrivateKey` for
    every key this process can sign with (a simulation holds the whole
    committee; a production deployment would aggregate partials through
    ``net/aggtree.py`` exactly like COMMIT seals — the record digest is
    just another message).  ``lazy_sign=True`` defers the quorum signing
    to serve time (:meth:`ensure_signed`): record BODIES are cheap
    keccak chains, so a million-height chain only ever pays pure-Python
    G2 signing for the O(log n) records a skip path actually serves.

    Thread-safe: ``on_finalize`` runs on the runner's loop thread while
    ``wire_payload`` serves from the proof-API worker pool.
    """

    def __init__(
        self,
        spacing: int,
        validators_for_height: Callable[[int], Mapping[bytes, int]],
        *,
        signers: Optional[Mapping[bytes, "hbls.BLSPrivateKey"]] = None,
        lazy_sign: bool = False,
    ) -> None:
        if spacing < 1:
            raise ValueError("checkpoint spacing must be >= 1")
        self.spacing = spacing
        self._validators = validators_for_height
        self._signers = dict(signers or {})
        self._lazy = lazy_sign
        self._records: Dict[int, CheckpointRecord] = {}
        self._lock = threading.Lock()

    @property
    def latest_epoch(self) -> int:
        with self._lock:
            return max(self._records, default=0)

    def record(self, epoch: int) -> Optional[CheckpointRecord]:
        with self._lock:
            return self._records.get(epoch)

    # -- build -----------------------------------------------------------

    def on_finalize(
        self, height: int, proposal_hash: bytes
    ) -> Optional[CheckpointRecord]:
        """Runner hook: build (and, unless lazy, quorum-sign) the record
        when ``height`` is an epoch boundary; None otherwise.  Idempotent
        per epoch (recovery replay may re-deliver a boundary)."""
        if height <= 0 or height % self.spacing:
            return None
        epoch = height // self.spacing
        with self._lock:
            if epoch in self._records:
                return None
            links = []
            for j in skip_epochs(epoch):
                prev = self._records.get(epoch - (1 << j))
                if prev is None:
                    raise CheckpointError(
                        f"epoch {epoch}: missing prior record at "
                        f"{epoch - (1 << j)} for skip link 2**{j}"
                    )
                links.append(prev.digest())
            rec = CheckpointRecord(
                epoch=epoch,
                height=height,
                set_root=set_root(self._validators(height)),
                chain_commitment=bytes(proposal_hash),
                skip_digests=tuple(links),
            )
            if self._signers and not self._lazy:
                rec = self._sign(rec)
            self._records[epoch] = rec
            return rec

    def _sign(self, rec: CheckpointRecord) -> CheckpointRecord:
        powers = self._validators(rec.height)
        ordered = sorted(powers)
        quorum = calculate_quorum(sum(powers.values()))
        msg = rec.digest()
        indices: List[int] = []
        points: List["hbls.PointG2"] = []
        got = 0
        for i, addr in enumerate(ordered):
            key = self._signers.get(addr)
            if key is None:
                continue
            points.append(key.sign(msg))
            indices.append(i)
            got += powers[addr]
            if got >= quorum:
                break
        if got < quorum:
            raise CheckpointError(
                f"epoch {rec.epoch}: held signing keys reach {got} of "
                f"quorum {quorum} voting power"
            )
        agg = hbls.aggregate_signatures(points)
        return replace(
            rec,
            agg_seal=encode_seal(agg),
            bitmap=AggregateQuorumCertificate.bitmap_of(indices, len(ordered)),
        )

    def ensure_signed(self, epoch: int) -> CheckpointRecord:
        """The record at ``epoch``, quorum-signed (signing now if it was
        deferred).  Skip-link digests are body-only, so late signing
        never invalidates records already chained on top."""
        with self._lock:
            rec = self._records.get(epoch)
            if rec is None:
                raise CheckpointError(f"no checkpoint record for epoch {epoch}")
            if rec.signed:
                return rec
            rec = self._sign(rec)
            self._records[epoch] = rec
            return rec

    # -- persistence -----------------------------------------------------

    def restore(self, records: Sequence[CheckpointRecord]) -> None:
        """WAL-replay entry: adopt durable records (first write wins,
        matching the WAL's duplicate-finalize posture)."""
        with self._lock:
            for rec in records:
                self._records.setdefault(rec.epoch, rec)

    # -- serving ---------------------------------------------------------

    def wire_payload(
        self,
        *,
        target_epoch: Optional[int] = None,
        include_all: bool = False,
    ) -> Dict[str, object]:
        """The ``GET /checkpoints`` response body: the skip path from
        genesis to ``target_epoch`` (default: latest), every record
        signed.  ``include_all`` serves the full epoch list instead (the
        linear shape — consecutive epochs are gap ``2**0`` hops, so the
        same verifier consumes it; useful as a measured baseline)."""
        latest = self.latest_epoch
        if latest == 0:
            return {
                "version": CHECKPOINT_WIRE_VERSION,
                "spacing": self.spacing,
                "latest_epoch": 0,
                "checkpoints": [],
            }
        epoch = latest if target_epoch is None else int(target_epoch)
        if not 1 <= epoch <= latest:
            raise CheckpointError(
                f"target epoch {epoch} outside [1, {latest}]"
            )
        epochs = list(range(1, epoch + 1)) if include_all else skip_path(epoch)
        return {
            "version": CHECKPOINT_WIRE_VERSION,
            "spacing": self.spacing,
            "latest_epoch": latest,
            "checkpoints": [self.ensure_signed(e).encode().hex() for e in epochs],
        }


class CheckpointVerifier:
    """Client-side skip-chain verification: everything cheap first, then
    ONE batched pairing dispatch over every hop.

    The client trusts a genesis anchor — the validator powers in force
    from height 1.  Walking the served path, each record must (1) chain:
    carry the previous path record's digest in the skip slot matching
    the hop gap (power-of-2 gaps only; genesis record carries no links);
    (2) resolve its signing set: ``set_root`` equal to the current
    trusted set's root — or, on a rotation, a ``bridge`` callback
    produces the new set via a commitment-enforced finality proof and
    the root must match it; (3) pass the exact-int certificate gates
    (bitmap over the SORTED resolved set, quorum voting power, r-torsion
    seal decode, a registered PoP-gated BLS key per signer).  Lanes from
    every hop — bridged or not — then verify in one
    ``multi_aggregate_check``; any failing lane rejects the whole sync.

    A skip link that bypasses a real rotation cannot pass: an honest
    quorum never signed a record with a stale ``set_root``, and a forged
    record fails its pairing lane.
    """

    def __init__(
        self,
        bls_keys_for_height: Callable[[int], Mapping[bytes, "hbls.PointG1"]],
        *,
        device: bool = False,
        multipair=None,
    ) -> None:
        self._keys = bls_keys_for_height
        self._device = device
        self._multipair = multipair

    def build_lanes(
        self,
        payload: Mapping[str, object],
        trusted_powers: Mapping[bytes, int],
        *,
        bridge: Optional[
            Callable[[int, int, Dict[bytes, int]], Mapping[bytes, int]]
        ] = None,
    ):
        """All pre-pairing work: structural path checks, set resolution,
        certificate gates.  Returns ``(lanes, records, anchor)`` with one
        pairing lane per record; exposed so the dispatch-parity tests can
        compare the batched verdicts against the sequential per-record
        oracle on the exact same lanes."""
        if payload.get("version") != CHECKPOINT_WIRE_VERSION:
            raise CheckpointError(
                f"unknown checkpoint payload version {payload.get('version')!r}"
            )
        spacing = payload.get("spacing")
        if not isinstance(spacing, int) or spacing < 1:
            raise CheckpointError(f"invalid checkpoint spacing {spacing!r}")
        raw = payload.get("checkpoints")
        if not isinstance(raw, list) or not raw:
            raise CheckpointError("checkpoint payload carries no records")
        try:
            records = [CheckpointRecord.decode(bytes.fromhex(r)) for r in raw]
        except (TypeError, ValueError) as err:
            raise CheckpointError(f"undecodable checkpoint record: {err}")
        if records[0].epoch != 1:
            raise CheckpointError(
                f"checkpoint chain starts at epoch {records[0].epoch}, "
                "expected the genesis epoch 1"
            )
        cur_powers: Dict[bytes, int] = dict(trusted_powers)
        if not cur_powers:
            raise CheckpointError("trusted genesis powers are empty")
        cur_root = set_root(cur_powers)
        lanes = []
        prev: Optional[CheckpointRecord] = None
        prev_height = 0
        for rec in records:
            e = rec.epoch
            if rec.height != e * spacing:
                raise CheckpointError(
                    f"epoch {e}: height {rec.height} != epoch * spacing "
                    f"{e * spacing}"
                )
            if len(rec.skip_digests) != len(skip_epochs(e)):
                raise CheckpointError(
                    f"epoch {e}: {len(rec.skip_digests)} skip links, "
                    f"expected {len(skip_epochs(e))}"
                )
            if prev is not None:
                gap = e - prev.epoch
                if gap <= 0 or gap & (gap - 1):
                    raise CheckpointError(
                        f"epoch {e}: gap {gap} from {prev.epoch} is not a "
                        "power-of-2 skip"
                    )
                slot = skip_epochs(e).index(gap.bit_length() - 1)
                if rec.skip_digests[slot] != prev.digest():
                    raise CheckpointError(
                        f"epoch {e}: skip link does not bind the verified "
                        f"record at epoch {prev.epoch}"
                    )
            if rec.set_root != cur_root:
                if bridge is None:
                    raise CheckpointError(
                        f"epoch {e}: validator set rotated since height "
                        f"{prev_height} and no bridge source is available"
                    )
                new_powers = dict(bridge(prev_height, rec.height, dict(cur_powers)))
                if set_root(new_powers) != rec.set_root:
                    raise CheckpointError(
                        f"epoch {e}: bridged validator set does not match "
                        "the record's committed set root"
                    )
                cur_powers, cur_root = new_powers, rec.set_root
            if not rec.signed:
                raise CheckpointError(f"epoch {e}: record carries no seal")
            ordered = sorted(cur_powers)
            signers = _bitmap_signers(rec.bitmap, ordered, e)
            got = sum(cur_powers[a] for a in signers)
            quorum = calculate_quorum(sum(cur_powers.values()))
            if got < quorum:
                raise CheckpointError(
                    f"epoch {e}: signer power {got} below quorum {quorum}"
                )
            keys = self._keys(rec.height)
            pubkeys = []
            for addr in signers:
                pk = keys.get(addr)
                if pk is None:
                    raise CheckpointError(
                        f"epoch {e}: signer {addr.hex()[:16]} has no "
                        "registered BLS key (PoP-gated registry required)"
                    )
                pubkeys.append(pk)
            point = decode_seal(rec.agg_seal)
            if point is None:
                raise CheckpointError(
                    f"epoch {e}: aggregate seal does not decode to an "
                    "r-torsion G2 point"
                )
            lanes.append((rec.digest(), [point], pubkeys))
            prev, prev_height = rec, rec.height
        anchor = CheckpointAnchor(
            height=prev_height,
            epoch=prev.epoch,
            powers=dict(cur_powers),
            spacing=spacing,
            lanes=len(lanes),
        )
        return lanes, records, anchor

    def verify_chain(
        self,
        payload: Mapping[str, object],
        trusted_powers: Mapping[bytes, int],
        *,
        bridge: Optional[
            Callable[[int, int, Dict[bytes, int]], Mapping[bytes, int]]
        ] = None,
    ) -> CheckpointAnchor:
        """Verify a served checkpoint payload end to end; returns the
        anchor (height, powers at that height) on success, raises
        :class:`CheckpointError` naming the first failing epoch."""
        lanes, records, anchor = self.build_lanes(
            payload, trusted_powers, bridge=bridge
        )
        if self._multipair is not None:
            mask = self._multipair.check(lanes)
        else:
            from ..verify.aggregate import multi_aggregate_check

            mask = multi_aggregate_check(
                lanes, route="device" if self._device else "host"
            )
        for rec, ok in zip(records, mask):
            if not bool(ok):
                raise CheckpointError(
                    f"epoch {rec.epoch}: aggregate checkpoint seal fails "
                    "the pairing check"
                )
        return anchor
