"""Long-chain light clients (ISSUE 20): quorum-sealed next-set
commitments + epoch checkpoint certificates with O(log n) skip sync.

Three pieces close the serve plane's two documented production blockers
(the fabricated-diff hole and linear cold sync — docs/SERVING.md):

* :mod:`~go_ibft_tpu.lightsync.commitment` — the next validator set's
  root committed INSIDE proposal content (covered by the current
  quorum's seals), enforced per diff hop by ``serve/proof.py::walk_sets``;
* :mod:`~go_ibft_tpu.lightsync.checkpoint` — epoch-boundary aggregate-
  BLS certificates chained with power-of-2 skip links; the whole path
  verifies in ONE batched pairing dispatch;
* :mod:`~go_ibft_tpu.lightsync.client` — the HTTP light client that
  anchors a ``ProofVerifier`` at the nearest verified checkpoint.
"""

from .checkpoint import (
    CHECKPOINT_WIRE_VERSION,
    CheckpointAnchor,
    CheckpointError,
    CheckpointRecord,
    CheckpointVerifier,
    Checkpointer,
    skip_epochs,
    skip_path,
)
from .client import CheckpointClient, ColdSyncReport, http_fetcher
from .commitment import (
    COMMIT_MAGIC,
    COMMIT_SUFFIX_BYTES,
    SET_ROOT_BYTES,
    embed_next_set,
    extract_next_set,
    set_root,
    strip_next_set,
)

__all__ = [
    "CHECKPOINT_WIRE_VERSION",
    "COMMIT_MAGIC",
    "COMMIT_SUFFIX_BYTES",
    "CheckpointAnchor",
    "CheckpointClient",
    "CheckpointError",
    "CheckpointRecord",
    "CheckpointVerifier",
    "Checkpointer",
    "ColdSyncReport",
    "SET_ROOT_BYTES",
    "embed_next_set",
    "extract_next_set",
    "http_fetcher",
    "set_root",
    "skip_epochs",
    "skip_path",
    "strip_next_set",
]
