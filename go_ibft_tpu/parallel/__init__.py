"""Multi-chip parallelism: sharded quorum verification over a device mesh.

The reference's only scaling dimension is validator-set size N — O(N)
sequential signature verifies per phase (SURVEY.md §5 "long-context").
Here that dimension is laid out over a ``jax.sharding.Mesh``:

* ``dp`` — message lanes (the batch axis) sharded across chips;
* ``vp`` — the validator table sharded across chips for very large sets
  (the (B, V) membership/equality matrix is the framework's "attention
  score" analogue — ``dp x vp`` tiles it like 2-D attention sharding).

XLA GSPMD inserts the cross-chip collectives (an all-reduce for the
voting-power sum riding ICI) from sharding annotations alone — no
hand-written NCCL analogue, per the scaling-book recipe.
"""

from .mesh import (
    make_mesh,
    mesh_context,
    mesh_quorum_certify,
    mesh_seal_quorum_certify,
)

__all__ = [
    "make_mesh",
    "mesh_context",
    "mesh_quorum_certify",
    "mesh_seal_quorum_certify",
]
