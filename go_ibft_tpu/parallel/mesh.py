"""Sharded fused quorum kernels over a ``jax.sharding.Mesh`` (shard_map).

Single-program multi-chip via ``shard_map`` with *explicit* collectives —
each chip verifies its slice of the message lanes (``dp`` axis) against its
slice of the validator table (``vp`` axis), then three small ``psum``s
assemble the global answer over ICI:

1. membership: a sender is a validator if *any* table shard matches
   (psum over ``vp``);
2. counted-validators: a validator is counted if *any* lane shard carried
   its valid message (psum over ``dp``);
3. voting power: the exact split-halves sum over table shards
   (psum over ``vp``).

shard_map (not GSPMD auto-partitioning) is deliberate: the 256-step EC
ladder compiles once for the *local* shard shape — partitioning the whole
program would re-run SPMD propagation through the scan and multiply
compile time; the collectives here are three scalar-ish psums, trivially
placed by hand.  This mirrors the scaling-book recipe: pick the mesh,
annotate the data, let the per-shard program stay identical to the
single-chip one.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # moved to jax.shard_map in newer releases
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore

import inspect as _inspect

# The replication-check kwarg was renamed check_rep -> check_vma across
# jax releases; resolve whichever spelling this jax understands so the
# mesh programs build on both (the pinned CI jax still says check_rep).
_CHECK_KW = next(
    (
        kw
        for kw in ("check_vma", "check_rep")
        if kw in _inspect.signature(_shard_map).parameters
    ),
    None,
)


def shard_map(*args, check_vma=False, **kwargs):
    if _CHECK_KW is not None:
        kwargs[_CHECK_KW] = check_vma
    return _shard_map(*args, **kwargs)

from ..ops import quorum

__all__ = [
    "make_mesh",
    "mesh_context",
    "mesh_quorum_certify",
    "mesh_seal_quorum_certify",
]


def make_mesh(
    n_devices: Optional[int] = None, *, vp: int = 1, devices=None
) -> Mesh:
    """A ``(dp, vp)`` mesh over ``n_devices`` devices.

    ``vp`` shards the validator table (for very large sets); the rest of
    the devices go to ``dp`` (message lanes).  When the default platform
    has too few devices (e.g. one tunneled TPU chip), falls back to the
    host-platform CPU devices so multi-chip layouts stay testable
    (``--xla_force_host_platform_device_count``).
    """
    if devices is None:
        devices = jax.devices()
        if n_devices is not None and len(devices) < n_devices:
            devices = jax.devices("cpu")
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(f"need {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    n = len(devices)
    if n % vp:
        raise ValueError(f"{n} devices not divisible by vp={vp}")
    arr = np.asarray(devices).reshape(n // vp, vp)
    return Mesh(arr, ("dp", "vp"))


def mesh_context(
    dp: Optional[int] = None, *, vp: int = 1, devices=None
) -> Optional[Mesh]:
    """Best-effort ``(dp, vp)`` mesh over whatever devices are visible.

    The ONE mesh-construction path shared by
    :class:`~go_ibft_tpu.verify.mesh_batch.MeshBatchVerifier`, the
    ``__graft_entry__`` dryrun, and the test/bench harnesses — so device
    enumeration, the 1-device fallback, and platform pinning can never
    drift between them:

    * **Device enumeration.**  ``devices`` wins when given; otherwise
      ``jax.devices()`` under whatever platform pin is in force
      (``JAX_PLATFORMS`` / ``jax.config.update("jax_platforms", ...)`` —
      this function never overrides the ambient pin).  When the default
      platform shows fewer devices than ``dp * vp`` asks for, the host CPU
      devices are tried (``--xla_force_host_platform_device_count`` makes
      multi-chip layouts testable on any host).
    * **dp selection.**  ``dp=None`` takes every visible device (after
      reserving ``vp``); an explicit ``dp`` is clamped to what exists.
    * **1-device fallback.**  Returns ``None`` when no layout with more
      than one device exists — the signal for callers to degrade to the
      single-device path instead of paying shard_map overhead for a
      1-shard mesh.  A dead backend (``jax.devices()`` raising) also
      returns ``None``: mesh construction must never take a node down.
    """
    want = None if dp is None else dp * vp
    if devices is None:
        try:
            devices = jax.devices()
        except RuntimeError:
            return None
        if want is not None and len(devices) < want:
            try:
                devices = jax.devices("cpu")
            except RuntimeError:
                pass
    n = len(devices) if want is None else min(want, len(devices))
    # Round dp down to what divides cleanly over vp.
    n -= n % max(vp, 1)
    if n // max(vp, 1) < 2:
        return None
    return make_mesh(n, vp=vp, devices=devices[:n])


def _finish(reached_inputs):
    ok, eq, powers_lo, powers_hi, thr_lo, thr_hi = reached_inputs
    # a validator row is counted if any of *this* lane-shard's valid
    # messages matched it; then OR across lane shards.
    counted_local = jnp.any(eq & ok[:, None], axis=0).astype(jnp.int32)
    counted = jax.lax.psum(counted_local, "dp") > 0  # (V_local,)
    lo = jax.lax.psum(jnp.sum(jnp.where(counted, powers_lo, 0)), "vp")
    hi = jax.lax.psum(jnp.sum(jnp.where(counted, powers_hi, 0)), "vp")
    hi = hi + (lo >> 16)
    lo = lo & 0xFFFF
    reached = (hi > thr_hi) | ((hi == thr_hi) & (lo >= thr_lo))
    return reached, lo, hi


def mesh_quorum_certify(mesh: Mesh):
    """Sharded :func:`~go_ibft_tpu.ops.quorum.quorum_certify` (same
    signature/outputs, bit-identical results)."""

    lane = P("dp")
    vrow = P("vp")
    rep = P()

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(lane, lane, lane, lane, lane, lane, vrow, lane, vrow, vrow, rep, rep),
        out_specs=(lane, rep, rep, rep),
        check_vma=False,
    )
    def step(blocks, nblocks, r, s, v, sender_w, table_w, live,
             powers_lo, powers_hi, thr_lo, thr_hi):
        sig_ok = quorum.sender_sig_checks(blocks, nblocks, r, s, v, sender_w, live)
        eq = quorum.membership_eq(sender_w, table_w)  # (B_loc, V_loc)
        member = jax.lax.psum(jnp.any(eq, axis=-1).astype(jnp.int32), "vp") > 0
        ok = sig_ok & member
        reached, lo, hi = _finish((ok, eq, powers_lo, powers_hi, thr_lo, thr_hi))
        return ok, reached, lo, hi

    return jax.jit(step)


def mesh_seal_quorum_certify(mesh: Mesh):
    """Sharded :func:`~go_ibft_tpu.ops.quorum.seal_quorum_certify`."""

    lane = P("dp")
    vrow = P("vp")
    rep = P()

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(lane, lane, lane, lane, lane, vrow, lane, vrow, vrow, rep, rep),
        out_specs=(lane, rep, rep, rep),
        check_vma=False,
    )
    def step(hash_zw, r, s, v, signer_w, table_w, live,
             powers_lo, powers_hi, thr_lo, thr_hi):
        sig_ok = quorum.seal_sig_checks(hash_zw, r, s, v, signer_w, live)
        eq = quorum.membership_eq(signer_w, table_w)
        member = jax.lax.psum(jnp.any(eq, axis=-1).astype(jnp.int32), "vp") > 0
        ok = sig_ok & member
        reached, lo, hi = _finish((ok, eq, powers_lo, powers_hi, thr_lo, thr_hi))
        return ok, reached, lo, hi

    return jax.jit(step)
