"""Keccak-256 (pre-NIST padding, as used by Ethereum) — host implementation.

Pure Python, dependency-free (``hashlib.sha3_256`` is the NIST variant with
different padding and cannot be used).  The device-batched counterpart lives
in :mod:`go_ibft_tpu.ops.keccak`; a native C++ fast path can be registered
via :func:`set_native_impl` (see go_ibft_tpu/native).

Used for the canonical digest of ``payload_no_sig`` bytes (the bytes an
embedder signs — reference messages/proto/helper.go:13-27) and for
pubkey -> 20-byte address derivation.
"""

from __future__ import annotations

from typing import Callable, List, Optional

_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

# Rotation offsets r[x][y] for lane A[x, y].
_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]

_MASK = (1 << 64) - 1
_RATE = 136  # 1088-bit rate for Keccak-256


def _rotl(v: int, n: int) -> int:
    n &= 63
    return ((v << n) | (v >> (64 - n))) & _MASK


def _keccak_f(a: List[int]) -> None:
    """In-place Keccak-f[1600] on a 25-lane state, lane A[x,y] at a[x+5y]."""
    for rc in _RC:
        # theta
        c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x + 5 * y] ^= d[x]
        # rho + pi: B[y, 2x+3y] = rotl(A[x, y], r[x][y])
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl(a[x + 5 * y], _ROT[x][y])
        # chi
        for x in range(5):
            for y in range(5):
                a[x + 5 * y] = b[x + 5 * y] ^ (
                    (~b[(x + 1) % 5 + 5 * y]) & b[(x + 2) % 5 + 5 * y]
                ) & _MASK
        # iota
        a[0] ^= rc


def _keccak256_py(data: bytes) -> bytes:
    state = [0] * 25
    # Multi-rate padding 0x01 .. 0x80 (original Keccak, not NIST SHA-3 0x06).
    padded = bytearray(data)
    pad_len = _RATE - (len(padded) % _RATE)
    if pad_len == 1:
        padded += b"\x81"
    else:
        padded += b"\x01" + b"\x00" * (pad_len - 2) + b"\x80"
    for off in range(0, len(padded), _RATE):
        block = padded[off : off + _RATE]
        for i in range(_RATE // 8):
            state[i] ^= int.from_bytes(block[8 * i : 8 * i + 8], "little")
        _keccak_f(state)
    out = b"".join(state[i].to_bytes(8, "little") for i in range(4))
    return out


_native_impl: Optional[Callable[[bytes], bytes]] = None


def set_native_impl(fn: Optional[Callable[[bytes], bytes]]) -> None:
    """Register a native (C++) keccak256; ``None`` restores pure Python."""
    global _native_impl
    _native_impl = fn


def keccak256(data: bytes) -> bytes:
    """32-byte Keccak-256 digest (Ethereum flavor)."""
    if _native_impl is not None:
        return _native_impl(data)
    return _keccak256_py(data)


def keccak256_many(items: List[bytes]) -> List[bytes]:
    """Bulk host digests: one impl lookup for the whole batch.

    Used by the packing edge for lanes whose digest must come from the host
    (oversize payloads past the largest device block bucket): the per-call
    global lookup and function-call overhead is paid once per batch instead
    of once per message.  Semantically identical to ``[keccak256(x) for x
    in items]``.
    """
    impl = _native_impl if _native_impl is not None else _keccak256_py
    return [impl(data) for data in items]
