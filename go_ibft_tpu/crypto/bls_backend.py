"""Hybrid backend: ECDSA envelopes + BLS12-381 aggregatable committed seals.

The reference's Backend seam leaves seal semantics to the embedder
(core/backend.go:39-41 BuildCommitMessage "must create a committed seal",
:50-55 IsValidCommittedSeal).  This embedder half keeps ECDSA for envelope
sender identity (cheap recovery, address-sized identities) and signs the
COMMIT seal with BLS — so a finalized block ships a quorum certificate
that verifies with ONE pairing equation regardless of validator count
(BASELINE.md config #4).
"""

from __future__ import annotations

from typing import Callable, Mapping, Optional

from ..messages.helpers import CommittedSeal
from ..verify.bls import BLS_SEAL_BYTES, decode_seal, encode_seal
from . import bls as hbls
from . import ecdsa as ec
from .backend import ECDSABackend


class HybridBLSBackend(ECDSABackend):
    """ECDSABackend whose committed seals are BLS G2 signatures.

    ``bls_keys_for_height`` maps height -> {consensus address: G1 pubkey}
    (the BLS analogue of the voting-power map).
    """

    def __init__(
        self,
        key: ec.PrivateKey,
        bls_key: hbls.BLSPrivateKey,
        validators_for_height: Callable[[int], Mapping[bytes, int]],
        bls_keys_for_height: Callable[[int], Mapping[bytes, "hbls.PointG1"]],
        build_proposal_fn=None,
    ):
        super().__init__(key, validators_for_height, build_proposal_fn)
        self.bls_key = bls_key
        self._bls_keys = bls_keys_for_height

    def build_commit_message(self, proposal_hash: bytes, view):
        from ..messages.wire import CommitMessage, IbftMessage, MessageType

        seal = encode_seal(self.bls_key.sign(proposal_hash))
        return self._sign_envelope(
            IbftMessage(
                view=view.copy(),
                sender=self.address,
                type=MessageType.COMMIT,
                commit_data=CommitMessage(
                    proposal_hash=proposal_hash, committed_seal=seal
                ),
            )
        )

    def is_valid_committed_seal(
        self,
        proposal_hash: bytes,
        committed_seal: CommittedSeal,
        height: Optional[int] = None,
    ) -> bool:
        if (
            len(committed_seal.signature) != BLS_SEAL_BYTES
            or len(proposal_hash) != 32
        ):
            return False
        point = decode_seal(committed_seal.signature)
        if point is None:
            return False
        # membership + key lookup in the BLS registry (engine supplies the
        # finalizing height; None means registry of height 0 semantics is
        # undefined, so reject)
        if height is None:
            return False
        pubkey = self._bls_keys(height).get(committed_seal.signer)
        if pubkey is None:
            return False
        return hbls.verify(pubkey, proposal_hash, point)


class HybridBatchVerifier:
    """BatchVerifier composition: device ECDSA envelopes + BLS aggregate
    seals — the engine's batched paths stay identical, only the seal
    math changes."""

    def __init__(self, sender_verifier, seal_verifier):
        self._senders = sender_verifier
        self._seals = seal_verifier

    def verify_senders(self, msgs):
        return self._senders.verify_senders(msgs)

    def verify_committed_seals(self, proposal_hash, seals, height):
        return self._seals.verify_committed_seals(proposal_hash, seals, height)
