"""A complete, real-cryptography embedder backend.

The reference leaves all of this to the embedder (go-ibft
core/backend.go:12-85); this module is the standalone framework's built-in
embedder half: secp256k1 keys, keccak-256 hashing, signed message
construction, and verification predicates that agree bit-for-bit with the
batched device path (:mod:`go_ibft_tpu.verify`).

Conventions (shared with ``verify.batch``):

* envelope signature: 65-byte ``r || s || v`` over
  ``keccak256(payload_no_sig)`` (the reference's canonical signing bytes,
  messages/proto/helper.go:13-27);
* proposal hash: ``keccak256(Proposal.encode())`` — covers both the raw
  proposal and the round, so a round-hijacked proposal re-hash fails;
* committed seal: 65-byte signature over the proposal hash itself;
* proposer selection: round-robin over the sorted validator addresses,
  index ``(height + round) % n`` (the scheme the reference's test clusters
  use, core/helpers_test.go:103-108).
"""

from __future__ import annotations

from typing import Callable, Mapping, Optional, Sequence

import numpy as np

from ..messages.helpers import CommittedSeal
from ..messages.wire import (
    CommitMessage,
    IbftMessage,
    MessageType,
    PreparedCertificate,
    PrePrepareMessage,
    PrepareMessage,
    Proposal,
    RoundChangeCertificate,
    RoundChangeMessage,
    View,
)
from . import ecdsa as ec
from .keccak import keccak256

SIG_BYTES = 65

_native_tried = False


def _try_native_fast_paths() -> None:
    """Best-effort one-time registration of the C++ fast paths.

    Signing each outbound message costs ~90ms of pure Python (one nonce
    scalar-mult) — material against a 2ms round budget; the native path is
    bit-identical (tests/test_native.py) and degrades gracefully when no
    compiler exists."""
    global _native_tried
    if _native_tried:
        return
    _native_tried = True
    try:
        from .. import native

        native.install()
    except Exception:  # noqa: BLE001 - missing toolchain keeps pure Python
        pass


def encode_signature(r: int, s: int, v: int) -> bytes:
    return r.to_bytes(32, "big") + s.to_bytes(32, "big") + bytes([v])


def proposal_hash_of(proposal: Proposal) -> bytes:
    """Canonical proposal hash: keccak over the (raw, round) encoding."""
    return keccak256(proposal.encode())


class ECDSABackend:
    """Backend with real keys; optionally paired with a BatchVerifier.

    ``validators_for_height`` returns the voting-power map per height
    (static single-map clusters can use ``static_validators``).  The
    per-message predicates (``is_valid_validator``,
    ``is_valid_committed_seal``) are the sequential reference path; wiring a
    :class:`go_ibft_tpu.verify.DeviceBatchVerifier` into the engine routes
    the hot phases through the device instead.
    """

    def __init__(
        self,
        key: ec.PrivateKey,
        validators_for_height: Callable[[int], Mapping[bytes, int]],
        build_proposal_fn: Optional[Callable[[View], bytes]] = None,
        *,
        commit_next_set: bool = False,
    ):
        _try_native_fast_paths()
        self.key = key
        self.address = key.address
        self._validators = validators_for_height
        self._build_proposal_fn = build_proposal_fn or (
            lambda view: b"block %d" % view.height
        )
        # ISSUE 20: when on, every built proposal carries a next-set
        # commitment suffix over validators_for_height(height + 1), and
        # is_valid_proposal requires the suffix frame to be present.  The
        # engine seam passes only raw bytes (no height), so this side
        # checks presence; the exact-root check happens where the height
        # is known — serve.proof.walk_sets(require_commitments=True).
        self.commit_next_set = commit_next_set
        self.inserted: list[tuple[Proposal, list[CommittedSeal]]] = []

    @staticmethod
    def static_validators(
        powers: Mapping[bytes, int]
    ) -> Callable[[int], Mapping[bytes, int]]:
        snapshot = dict(powers)
        return lambda _height: snapshot

    # -- signing --------------------------------------------------------

    def _sign_envelope(self, msg: IbftMessage) -> IbftMessage:
        digest = keccak256(msg.encode(include_signature=False))
        msg.signature = encode_signature(*ec.sign(self.key, digest))
        return msg

    # -- MessageConstructor (reference core/backend.go:12-34) -----------

    def build_preprepare_message(
        self,
        raw_proposal: bytes,
        certificate: Optional[RoundChangeCertificate],
        view: View,
    ) -> IbftMessage:
        proposal = Proposal(raw_proposal=raw_proposal, round=view.round)
        return self._sign_envelope(
            IbftMessage(
                view=view.copy(),
                sender=self.address,
                type=MessageType.PREPREPARE,
                preprepare_data=PrePrepareMessage(
                    proposal=proposal,
                    proposal_hash=proposal_hash_of(proposal),
                    certificate=certificate,
                ),
            )
        )

    def build_prepare_message(self, proposal_hash: bytes, view: View) -> IbftMessage:
        return self._sign_envelope(
            IbftMessage(
                view=view.copy(),
                sender=self.address,
                type=MessageType.PREPARE,
                prepare_data=PrepareMessage(proposal_hash=proposal_hash),
            )
        )

    def build_commit_message(self, proposal_hash: bytes, view: View) -> IbftMessage:
        seal = encode_signature(*ec.sign(self.key, proposal_hash))
        return self._sign_envelope(
            IbftMessage(
                view=view.copy(),
                sender=self.address,
                type=MessageType.COMMIT,
                commit_data=CommitMessage(
                    proposal_hash=proposal_hash, committed_seal=seal
                ),
            )
        )

    def build_round_change_message(
        self,
        proposal: Optional[Proposal],
        certificate: Optional[PreparedCertificate],
        view: View,
    ) -> IbftMessage:
        return self._sign_envelope(
            IbftMessage(
                view=view.copy(),
                sender=self.address,
                type=MessageType.ROUND_CHANGE,
                round_change_data=RoundChangeMessage(
                    last_prepared_proposal=proposal,
                    latest_prepared_certificate=certificate,
                ),
            )
        )

    # -- Verifier (reference core/backend.go:37-56) ---------------------

    def is_valid_proposal(self, raw_proposal: bytes) -> bool:
        if not raw_proposal:
            return False
        if self.commit_next_set:
            from ..lightsync.commitment import extract_next_set

            return extract_next_set(raw_proposal) is not None
        return True

    def is_valid_validator(self, msg: IbftMessage) -> bool:
        if msg.view is None or len(msg.signature) != SIG_BYTES:
            return False
        r = int.from_bytes(msg.signature[:32], "big")
        s = int.from_bytes(msg.signature[32:64], "big")
        v = msg.signature[64]
        digest = keccak256(msg.encode(include_signature=False))
        pub = ec.recover(digest, r, s, v)
        if pub is None:
            return False
        return (
            ec.pubkey_to_address(*pub) == msg.sender
            and msg.sender in self._validators(msg.view.height)
        )

    def is_proposer(self, validator_id: bytes, height: int, round_: int) -> bool:
        ordered = sorted(self._validators(height))
        if not ordered:
            return False
        return ordered[(height + round_) % len(ordered)] == validator_id

    def is_valid_proposal_hash(self, proposal: Proposal, hash_: bytes) -> bool:
        return proposal_hash_of(proposal) == hash_

    def is_valid_committed_seal(
        self,
        proposal_hash: bytes,
        committed_seal: CommittedSeal,
        height: Optional[int] = None,
    ) -> bool:
        if len(committed_seal.signature) != SIG_BYTES or len(proposal_hash) != 32:
            return False
        sig = committed_seal.signature
        pub = ec.recover(
            proposal_hash,
            int.from_bytes(sig[:32], "big"),
            int.from_bytes(sig[32:64], "big"),
            sig[64],
        )
        if pub is None:
            return False
        if ec.pubkey_to_address(*pub) != committed_seal.signer:
            return False
        # Membership: same rule as HostBatchVerifier/DeviceBatchVerifier —
        # the signer must belong to the validator set of the height being
        # finalized (the engine always supplies it).
        if height is not None:
            return committed_seal.signer in self._validators(height)
        return True

    # -- ValidatorBackend / Notifier / misc -----------------------------

    def get_voting_powers(self, height: int) -> Mapping[bytes, int]:
        return self._validators(height)

    def round_starts(self, view: View) -> None:  # pragma: no cover - hook
        pass

    def sequence_cancelled(self, view: View) -> None:  # pragma: no cover - hook
        pass

    def build_proposal(self, view: View) -> bytes:
        raw = self._build_proposal_fn(view)
        if self.commit_next_set:
            from ..lightsync.commitment import embed_next_set, set_root

            raw = embed_next_set(raw, set_root(self._validators(view.height + 1)))
        return raw

    def insert_proposal(
        self, proposal: Proposal, committed_seals: Sequence[CommittedSeal]
    ) -> None:
        self.inserted.append((proposal, list(committed_seals)))

    def id(self) -> bytes:
        return self.address
