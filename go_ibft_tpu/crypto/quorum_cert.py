"""Aggregate quorum certificates: O(1)-size proof that a COMMIT quorum sealed.

The engine's per-seal finalization evidence is O(N): one 192-byte BLS (or
65-byte ECDSA) seal per committing validator, re-verified seal-by-seal at
every consumer (WAL replay sanity, block-sync catch-up, light clients).
This module compresses a round's COMMIT quorum into a constant-size
:class:`AggregateQuorumCertificate` — one aggregated G2 point plus a
signer bitmap over the height's sorted validator set — verified with ONE
pairing equation regardless of committee size ("Performance of EdDSA and
BLS Signatures in Committee-Based Consensus", PAPERS.md 2302.00418).

Three consumers share it end to end (ISSUE 7):

* the engine (:meth:`IBFT.add_quorum_certificate`) finalizes a height
  straight from a verified certificate when the aggregation-tree gossip
  transport (:mod:`go_ibft_tpu.net.aggtree`) delivers one;
* the WAL (:mod:`go_ibft_tpu.chain.wal`) persists the certificate instead
  of N seals — finalize records stop scaling with committee size;
* block-sync (:mod:`go_ibft_tpu.chain.sync`) re-verifies a fetched range
  with one certificate equation per height instead of N seal lanes —
  batched: the whole range's equations verify as ONE multi-pairing
  dispatch through :meth:`BLSCertifier.verify_many` (ISSUE 12).

Rogue-key safety: aggregation is only sound over public keys whose
holders have proven possession of the secret scalar (a registered
``pk' = pk_rogue - sum(honest)`` would otherwise let one attacker forge
the whole quorum).  :class:`BLSKeyRegistry` is the enforcement point —
registration REQUIRES a valid proof of possession
(:func:`go_ibft_tpu.crypto.bls.prove_possession`), and the certifier's
key source is expected to be built from one.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Callable, Dict, List, Mapping, Optional, Sequence

from ..core.validator_manager import calculate_quorum
from ..messages.helpers import CommittedSeal
from ..verify.bls import (
    BLS_SEAL_BYTES,
    aggregate_check,
    decode_seal,
    encode_seal,
)
from . import bls as hbls

__all__ = [
    "AGG_CERT_SIGNER",
    "AggregateQuorumCertificate",
    "BLSCertifier",
    "BLSKeyRegistry",
]

_VERSION = 1
_HEADER = struct.Struct(">BQIH")  # version, height, round, bitmap length

# Sentinel signer for the synthetic CommittedSeal an engine records when a
# height finalized from an aggregate certificate rather than individual
# seals (no 20-byte consensus address can be all-0xFF: addresses are
# keccak-derived, and the validator registries never contain it).
AGG_CERT_SIGNER = b"\xff" * 20


@dataclass
class AggregateQuorumCertificate:
    """One round's COMMIT quorum, compressed to O(1).

    ``bitmap`` bit *i* (LSB-first within each byte) marks the *i*-th
    address of the height's SORTED validator set as a signer — the one
    canonical ordering every party can re-derive, so the certificate
    needs no address list.
    """

    height: int
    round: int
    proposal_hash: bytes  # 32 bytes
    agg_seal: bytes  # 192-byte aggregated G2 point
    bitmap: bytes

    # -- codec ----------------------------------------------------------

    def encode(self) -> bytes:
        if len(self.proposal_hash) != 32:
            raise ValueError("proposal hash must be 32 bytes")
        if len(self.agg_seal) != BLS_SEAL_BYTES:
            raise ValueError("aggregated seal must be 192 bytes")
        return (
            _HEADER.pack(_VERSION, self.height, self.round, len(self.bitmap))
            + self.proposal_hash
            + self.agg_seal
            + self.bitmap
        )

    @classmethod
    def decode(cls, blob: bytes) -> "AggregateQuorumCertificate":
        if len(blob) < _HEADER.size + 32 + BLS_SEAL_BYTES:
            raise ValueError("quorum certificate too short")
        version, height, round_, bitmap_len = _HEADER.unpack_from(blob)
        if version != _VERSION:
            raise ValueError(f"unknown quorum certificate version {version}")
        body = blob[_HEADER.size :]
        expected = 32 + BLS_SEAL_BYTES + bitmap_len
        if len(body) != expected:
            raise ValueError(
                f"quorum certificate body {len(body)}B != {expected}B"
            )
        return cls(
            height=height,
            round=round_,
            proposal_hash=body[:32],
            agg_seal=body[32 : 32 + BLS_SEAL_BYTES],
            bitmap=body[32 + BLS_SEAL_BYTES :],
        )

    # -- bitmap helpers --------------------------------------------------

    def signer_indices(self) -> List[int]:
        return [
            byte_i * 8 + bit
            for byte_i, byte in enumerate(self.bitmap)
            for bit in range(8)
            if byte >> bit & 1
        ]

    def signers(self, ordered_validators: Sequence[bytes]) -> List[bytes]:
        """Resolve the bitmap against the height's sorted validator set.

        Raises :class:`ValueError` on out-of-range bits — a certificate
        claiming signers beyond the set is malformed, not merely
        unsatisfied.
        """
        idxs = self.signer_indices()
        if idxs and idxs[-1] >= len(ordered_validators):
            raise ValueError("certificate bitmap exceeds the validator set")
        return [ordered_validators[i] for i in idxs]

    def to_seal(self) -> CommittedSeal:
        """The synthetic seal an engine without a chain layer records."""
        return CommittedSeal(signer=AGG_CERT_SIGNER, signature=self.encode())

    @staticmethod
    def bitmap_of(indices: Sequence[int], n: int) -> bytes:
        out = bytearray((n + 7) // 8)
        for i in indices:
            out[i // 8] |= 1 << (i % 8)
        return bytes(out)


class BLSKeyRegistry:
    """Proof-of-possession-gated BLS pubkey registry for one validator set.

    The ONLY way a key enters the aggregation set is :meth:`register` with
    a valid PoP — the rogue-key defense lives here, not in every verifier.
    The registry is callable with a height (returns the address -> pubkey
    map) so it drops into every ``bls_keys_for_height`` seam unchanged.
    """

    def __init__(self) -> None:
        self._keys: Dict[bytes, "hbls.PointG1"] = {}

    def register(
        self, address: bytes, pubkey: "hbls.PointG1", proof: "hbls.PointG2"
    ) -> None:
        if not hbls.verify_possession(pubkey, proof):
            raise ValueError(
                "BLS pubkey registration rejected: invalid proof of "
                "possession (rogue-key defense)"
            )
        self._keys[bytes(address)] = pubkey

    def register_key(self, address: bytes, key: "hbls.BLSPrivateKey") -> None:
        """Register a locally-held key (derives the PoP itself)."""
        self.register(address, key.pubkey, hbls.prove_possession(key))

    def __call__(self, _height: int) -> Mapping[bytes, "hbls.PointG1"]:
        return self._keys

    def __len__(self) -> int:
        return len(self._keys)


class BLSCertifier:
    """Builds and verifies aggregate quorum certificates for a chain.

    ``validators_for_height`` is the voting-power source (the engine's
    own seam); ``bls_keys_for_height`` maps height -> {address: G1
    pubkey} and MUST be PoP-gated (:class:`BLSKeyRegistry`).  ``device``
    routes the pairing through
    :func:`go_ibft_tpu.ops.bls12_381.aggregate_verify_commit`.
    """

    def __init__(
        self,
        validators_for_height: Callable[[int], Mapping[bytes, int]],
        bls_keys_for_height: Callable[[int], Mapping[bytes, "hbls.PointG1"]],
        *,
        device: bool = False,
        multipair=None,
        aggregator=None,
    ) -> None:
        self._validators = validators_for_height
        self._keys = bls_keys_for_height
        self._device = device
        # Batched verification route (ISSUE 12): ``multipair`` is a
        # :class:`~go_ibft_tpu.verify.aggregate.MultiPairVerifier` (or
        # anything with ``check(lanes)``); :meth:`verify_many` routes a
        # whole certificate batch through ONE batched dispatch.  Default:
        # the functional ``multi_aggregate_check`` on the device or
        # host-batch route per ``device``.
        self._multipair = multipair
        # ``aggregator`` is a :class:`~go_ibft_tpu.verify.aggregate.
        # G2MergeTree` (or anything with ``merge(points)``): ``build``'s
        # seal aggregation then rides the vmapped device merge tree
        # instead of the sequential host g2_add loop (bit-identical
        # results; the host loop is the oracle and the default).
        self._aggregator = aggregator

    # -- build -----------------------------------------------------------

    def build(
        self,
        height: int,
        round_: int,
        proposal_hash: bytes,
        seals: Sequence[CommittedSeal],
    ) -> Optional[AggregateQuorumCertificate]:
        """Compress a seal quorum into a certificate (no pairing: the
        seals were verified when the quorum formed).

        Seals that do not decode, or whose signer is outside the height's
        validator set, are skipped; returns None when the survivors'
        voting power does not reach quorum (a certificate that cannot
        verify is worse than per-seal evidence) or when any input is the
        synthetic aggregate seal (already a certificate).
        """
        members = self._validators(height)
        points: List["hbls.PointG2"] = []
        signers: List[bytes] = []
        for seal in seals:
            if seal.signer == AGG_CERT_SIGNER:
                return None
            if seal.signer not in members or seal.signer in signers:
                continue
            pt = decode_seal(seal.signature)
            if pt is None:
                continue
            points.append(pt)
            signers.append(seal.signer)
        if not points:
            return None
        if self._aggregator is not None:
            # Device merge tree: one dispatch folds the whole quorum
            # (log-depth) instead of len(points) sequential host adds.
            agg = self._aggregator.merge(points)
        else:
            agg = hbls.aggregate_signatures(points)
        if agg is None:
            return None
        return self.build_from_aggregate(
            height, round_, proposal_hash, agg, signers
        )

    def build_from_aggregate(
        self,
        height: int,
        round_: int,
        proposal_hash: bytes,
        agg_point: "hbls.PointG2",
        signers: Sequence[bytes],
    ) -> Optional[AggregateQuorumCertificate]:
        """Certificate from an ALREADY-MERGED aggregate (the aggregation-
        tree root's seam: the tree merged disjoint partials on the way
        up, so the root holds one G2 point + a signer set, never
        individual seals).  Returns None below quorum power or when a
        signer is outside the height's validator set."""
        if agg_point is None:
            return None
        powers = self._validators(height)
        ordered = sorted(powers)
        index_of = {addr: i for i, addr in enumerate(ordered)}
        indices = []
        for addr in set(signers):
            idx = index_of.get(addr)
            if idx is None:
                return None
            indices.append(idx)
        got = sum(powers[ordered[i]] for i in indices)
        if got < calculate_quorum(sum(powers.values())):
            return None
        return AggregateQuorumCertificate(
            height=height,
            round=round_,
            proposal_hash=bytes(proposal_hash),
            agg_seal=encode_seal(agg_point),
            bitmap=AggregateQuorumCertificate.bitmap_of(
                sorted(indices), len(ordered)
            ),
        )

    def is_member(self, height: int, address: bytes) -> bool:
        """Cheap membership gate: is ``address`` a validator at ``height``
        with a registered BLS key?  (The aggregation tree drops non-member
        COMMITs from the aggregate path at ingest — a foreign signer would
        otherwise poison every ``build_from_aggregate`` for the round.)"""
        return (
            address in self._validators(height)
            and self._keys(height).get(address) is not None
        )

    def partial_valid(
        self,
        height: int,
        proposal_hash: bytes,
        point: "hbls.PointG2",
        signers: Sequence[bytes],
    ) -> bool:
        """ONE pairing over a partial aggregate: does ``point`` verify as
        the aggregate seal of exactly ``signers`` over ``proposal_hash``?
        The aggregation tree's quarantine walk uses this to bisect a
        failing root aggregate down to the Byzantine contribution."""
        if point is None or not signers:
            return False
        keys = self._keys(height)
        pubkeys = []
        for addr in signers:
            pk = keys.get(addr)
            if pk is None:
                return False
            pubkeys.append(pk)
        return aggregate_check(
            proposal_hash, [point], pubkeys, device=self._device
        )

    # -- verify ----------------------------------------------------------

    def _lane_of(self, cert: AggregateQuorumCertificate):
        """The certificate's pairing lane ``(proposal_hash, [point],
        pubkeys)`` after every cheap check, or None when a structural
        check already condemns it (no pairing needed).

        Checks, in cost order: structural sanity, bitmap-resolved signers
        exist in BOTH the power map and the PoP-gated key registry,
        combined voting power reaches the height's quorum, and the
        aggregated point is a valid r-torsion G2 element.
        """
        if len(cert.proposal_hash) != 32:
            return None
        powers = self._validators(cert.height)
        if not powers:
            return None
        ordered = sorted(powers)
        try:
            signers = cert.signers(ordered)
        except ValueError:
            return None
        if not signers:
            return None
        quorum = calculate_quorum(sum(powers.values()))
        if sum(powers[a] for a in signers) < quorum:
            return None
        keys = self._keys(cert.height)
        pubkeys = []
        for addr in signers:
            pk = keys.get(addr)
            if pk is None:
                return None
            pubkeys.append(pk)
        point = decode_seal(cert.agg_seal)
        if point is None:
            return None
        return cert.proposal_hash, [point], pubkeys

    def verify(self, cert: AggregateQuorumCertificate) -> bool:
        """ONE pairing equation + exact-int quorum power over the bitmap
        (see :meth:`_lane_of` for the pre-pairing check order)."""
        lane = self._lane_of(cert)
        if lane is None:
            return False
        phash, points, pubkeys = lane
        return aggregate_check(
            phash, points, pubkeys, device=self._device
        )

    def verify_many(self, certs: Sequence[AggregateQuorumCertificate]):
        """MANY certificates through ONE batched multi-pairing dispatch.

        Per-cert verdicts (numpy bool array) bit-identical to
        :meth:`verify` lane-for-lane: structurally-condemned certificates
        are False without costing any pairing work, the survivors verify
        together through the injected
        :class:`~go_ibft_tpu.verify.aggregate.MultiPairVerifier` (or the
        functional batch entry on the device/host route per the
        certifier's ``device`` flag).  This is the block-sync / proof-
        serving seam: a 1000-height certificate range is one call here,
        one batched dispatch below (ISSUE 12 acceptance).
        """
        import numpy as np

        from ..verify.aggregate import multi_aggregate_check

        out = np.zeros(len(certs), dtype=bool)
        lanes = []
        idx = []
        for i, cert in enumerate(certs):
            lane = self._lane_of(cert)
            if lane is None:
                continue
            lanes.append(lane)
            idx.append(i)
        if not lanes:
            return out
        if self._multipair is not None:
            mask = self._multipair.check(lanes)
        else:
            mask = multi_aggregate_check(
                lanes, route="device" if self._device else "host"
            )
        out[np.asarray(idx)] = np.asarray(mask, dtype=bool)
        return out
