"""Host BLS12-381: keys, aggregate commit seals, pairings (pure Python).

The reference injects all cryptography through its Backend seam
(go-ibft core/backend.go:37-56); BASELINE.md config #4 requires the new
build to ALSO support BLS12-381 aggregate COMMIT verification — one
pairing check certifies a whole quorum of seals.  This module is the
exact-arithmetic host oracle: the semantics source of truth the device
path (:mod:`go_ibft_tpu.ops.bls12_381`) must match bit-for-bit, and the
slow-but-sure baseline for the bench denominator.

Scheme (minimal-pubkey-size orientation, eth2-style):

* secret key ``sk`` — scalar mod r;
* public key ``pk = sk * G1`` (48-byte x, on E/Fp: y^2 = x^3 + 4);
* seal over a proposal hash ``m``: ``sigma = sk * H2(m)`` with ``H2`` a
  deterministic try-and-increment hash onto the r-order subgroup of
  E'/Fp2: y^2 = x^3 + 4(u+1) (NOT the RFC 9380 SSWU map — interop with
  other BLS libraries is out of scope, determinism and group-correctness
  are not);
* aggregate verification for one message:
  ``e(G1, sum(sigma_i)) == e(sum(pk_i), H2(m))``.

Everything derivable is DERIVED (cofactors from the curve parameter x,
group orders from the trace) rather than transcribed, so a typo cannot
silently corrupt the math; generators and p/r are the standard published
constants.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from .keccak import keccak256

# -- parameters -------------------------------------------------------------

# Field modulus, subgroup order, curve parameter (standard constants).
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
BLS_X = 0xD201000000010000  # |x|; the BLS12-381 parameter is -x
B1 = 4  # G1 curve: y^2 = x^3 + 4

G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GEN = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)

# Derived: trace t = x + 1 (x negative: t = 1 - BLS_X), group cardinalities,
# cofactors.  #E(Fp) = p + 1 - t.  The G2 twist E'/Fp2 is a SEXTIC twist, so
# its trace is NOT t2 = t^2 - 2p (that is #E(Fp2)) but one of the CM
# variants (t2 +- 3f)/2 with t2^2 - 4p^2 = -3 f^2; the one divisible by r
# (and verified by annihilating actual twist points in the tests) is
# (t2 - 3f)/2.
_T = 1 - BLS_X
H1_COFACTOR = (P + 1 - _T) // R
_T2 = _T * _T - 2 * P


def _isqrt(n: int) -> int:
    import math

    return math.isqrt(n)


_F2 = _isqrt((4 * P * P - _T2 * _T2) // 3)
assert 3 * _F2 * _F2 == 4 * P * P - _T2 * _T2
G2_ORDER_FULL = P * P + 1 - (_T2 - 3 * _F2) // 2
H2_COFACTOR = G2_ORDER_FULL // R
assert (P + 1 - _T) % R == 0 and G2_ORDER_FULL % R == 0

# -- Fp2 / Fp6 / Fp12 tower -------------------------------------------------
# Fp2 = Fp[u]/(u^2+1); Fp6 = Fp2[v]/(v^3 - xi), xi = 1 + u; Fp12 = Fp6[w]/(w^2 - v)

Fp2T = Tuple[int, int]


def f2_add(a: Fp2T, b: Fp2T) -> Fp2T:
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def f2_sub(a: Fp2T, b: Fp2T) -> Fp2T:
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def f2_neg(a: Fp2T) -> Fp2T:
    return (-a[0] % P, -a[1] % P)


def f2_mul(a: Fp2T, b: Fp2T) -> Fp2T:
    t0 = a[0] * b[0]
    t1 = a[1] * b[1]
    t2 = (a[0] + a[1]) * (b[0] + b[1])
    return ((t0 - t1) % P, (t2 - t0 - t1) % P)


def f2_sqr(a: Fp2T) -> Fp2T:
    return f2_mul(a, a)


def f2_muli(a: Fp2T, k: int) -> Fp2T:
    return (a[0] * k % P, a[1] * k % P)


def f2_conj(a: Fp2T) -> Fp2T:
    return (a[0], -a[1] % P)


def f2_inv(a: Fp2T) -> Fp2T:
    norm = (a[0] * a[0] + a[1] * a[1]) % P
    ninv = pow(norm, P - 2, P)
    return (a[0] * ninv % P, -a[1] * ninv % P)


def f2_mul_xi(a: Fp2T) -> Fp2T:
    """Multiply by the Fp6 non-residue xi = 1 + u."""
    return ((a[0] - a[1]) % P, (a[0] + a[1]) % P)


F2_ZERO: Fp2T = (0, 0)
F2_ONE: Fp2T = (1, 0)

Fp6T = Tuple[Fp2T, Fp2T, Fp2T]
F6_ZERO: Fp6T = (F2_ZERO, F2_ZERO, F2_ZERO)
F6_ONE: Fp6T = (F2_ONE, F2_ZERO, F2_ZERO)


def f6_add(a: Fp6T, b: Fp6T) -> Fp6T:
    return (f2_add(a[0], b[0]), f2_add(a[1], b[1]), f2_add(a[2], b[2]))


def f6_sub(a: Fp6T, b: Fp6T) -> Fp6T:
    return (f2_sub(a[0], b[0]), f2_sub(a[1], b[1]), f2_sub(a[2], b[2]))


def f6_neg(a: Fp6T) -> Fp6T:
    return (f2_neg(a[0]), f2_neg(a[1]), f2_neg(a[2]))


def f6_mul(a: Fp6T, b: Fp6T) -> Fp6T:
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0, t1, t2 = f2_mul(a0, b0), f2_mul(a1, b1), f2_mul(a2, b2)
    c0 = f2_add(
        t0,
        f2_mul_xi(
            f2_sub(f2_mul(f2_add(a1, a2), f2_add(b1, b2)), f2_add(t1, t2))
        ),
    )
    c1 = f2_add(
        f2_sub(f2_mul(f2_add(a0, a1), f2_add(b0, b1)), f2_add(t0, t1)),
        f2_mul_xi(t2),
    )
    c2 = f2_add(
        f2_sub(f2_mul(f2_add(a0, a2), f2_add(b0, b2)), f2_add(t0, t2)), t1
    )
    return (c0, c1, c2)


def f6_mul_v(a: Fp6T) -> Fp6T:
    """Multiply by v: (a0, a1, a2) -> (xi*a2, a0, a1)."""
    return (f2_mul_xi(a[2]), a[0], a[1])


def f6_inv(a: Fp6T) -> Fp6T:
    a0, a1, a2 = a
    c0 = f2_sub(f2_sqr(a0), f2_mul_xi(f2_mul(a1, a2)))
    c1 = f2_sub(f2_mul_xi(f2_sqr(a2)), f2_mul(a0, a1))
    c2 = f2_sub(f2_sqr(a1), f2_mul(a0, a2))
    t = f2_add(
        f2_mul(a0, c0),
        f2_mul_xi(f2_add(f2_mul(a1, c2), f2_mul(a2, c1))),
    )
    tinv = f2_inv(t)
    return (f2_mul(c0, tinv), f2_mul(c1, tinv), f2_mul(c2, tinv))


Fp12T = Tuple[Fp6T, Fp6T]
F12_ONE: Fp12T = (F6_ONE, F6_ZERO)


def f12_mul(a: Fp12T, b: Fp12T) -> Fp12T:
    a0, a1 = a
    b0, b1 = b
    t0 = f6_mul(a0, b0)
    t1 = f6_mul(a1, b1)
    c0 = f6_add(t0, f6_mul_v(t1))
    c1 = f6_sub(
        f6_mul(f6_add(a0, a1), f6_add(b0, b1)), f6_add(t0, t1)
    )
    return (c0, c1)


def f12_sqr(a: Fp12T) -> Fp12T:
    return f12_mul(a, a)


def f12_inv(a: Fp12T) -> Fp12T:
    a0, a1 = a
    t = f6_inv(f6_sub(f6_mul(a0, a0), f6_mul_v(f6_mul(a1, a1))))
    return (f6_mul(a0, t), f6_neg(f6_mul(a1, t)))


def f12_pow(a: Fp12T, e: int) -> Fp12T:
    if e < 0:
        return f12_pow(f12_inv(a), -e)
    acc = F12_ONE
    for bit in bin(e)[2:]:
        acc = f12_sqr(acc)
        if bit == "1":
            acc = f12_mul(acc, a)
    return acc


def f12_from_fp(x: int) -> Fp12T:
    return (((x % P, 0), F2_ZERO, F2_ZERO), F6_ZERO)


def f12_from_fp2(x: Fp2T) -> Fp12T:
    return ((x, F2_ZERO, F2_ZERO), F6_ZERO)


# w = (0, 1_Fp6): the Fp12 generator with w^2 = v, w^6 = xi.
F12_W: Fp12T = (F6_ZERO, F6_ONE)
F12_W_INV = f12_inv(F12_W)
_W_INV2 = f12_mul(F12_W_INV, F12_W_INV)
_W_INV3 = f12_mul(_W_INV2, F12_W_INV)

# -- generic affine curve ops over a field given by (mul, add-like) ---------
# Points are None (infinity) or coordinate tuples; two instantiations:
# Fp ints (G1) and Fp2 pairs (G2).

PointG1 = Optional[Tuple[int, int]]
PointG2 = Optional[Tuple[Fp2T, Fp2T]]


def g1_add(a: PointG1, b: PointG1) -> PointG1:
    if a is None:
        return b
    if b is None:
        return a
    x1, y1 = a
    x2, y2 = b
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        m = 3 * x1 * x1 * pow(2 * y1, P - 2, P) % P
    else:
        m = (y2 - y1) * pow(x2 - x1, P - 2, P) % P
    x3 = (m * m - x1 - x2) % P
    return (x3, (m * (x1 - x3) - y1) % P)


class _FieldOps:
    """Tiny field-op record so the Jacobian ladder below serves both Fp
    (G1) and Fp2 (G2) without duplication."""

    def __init__(self, add, sub, mul, inv, muli, zero, one):
        self.add, self.sub, self.mul, self.inv = add, sub, mul, inv
        self.muli, self.zero, self.one = muli, zero, one


_FP_OPS = _FieldOps(
    add=lambda a, b: (a + b) % P,
    sub=lambda a, b: (a - b) % P,
    mul=lambda a, b: a * b % P,
    inv=lambda a: pow(a, P - 2, P),
    muli=lambda a, k: a * k % P,
    zero=0,
    one=1,
)
_FP2_OPS = _FieldOps(f2_add, f2_sub, f2_mul, f2_inv, f2_muli, F2_ZERO, F2_ONE)


def _jac_mul(f: _FieldOps, k: int, pt):
    """Double-and-add in Jacobian coordinates (a = 0 curves): one inversion
    total instead of one per group op — the host workload builders sign
    hundreds of seals, affine ladders would take minutes."""
    if pt is None or k == 0:
        return None
    x0, y0 = pt
    X, Y, Z = None, None, None  # infinity
    ax, ay = x0, y0

    def jdouble(p):
        if p is None:
            return None
        X1, Y1, Z1 = p
        A = f.mul(X1, X1)
        B = f.mul(Y1, Y1)
        C = f.mul(B, B)
        t = f.mul(f.add(X1, B), f.add(X1, B))
        D = f.muli(f.sub(f.sub(t, A), C), 2)
        E = f.muli(A, 3)
        F = f.mul(E, E)
        X3 = f.sub(F, f.muli(D, 2))
        Y3 = f.sub(f.mul(E, f.sub(D, X3)), f.muli(C, 8))
        Z3 = f.muli(f.mul(Y1, Z1), 2)
        return (X3, Y3, Z3)

    def jadd_affine(p):
        """p + (ax, ay), mixed coordinates."""
        if p is None:
            return (ax, ay, f.one)
        X1, Y1, Z1 = p
        Z1Z1 = f.mul(Z1, Z1)
        U2 = f.mul(ax, Z1Z1)
        S2 = f.mul(ay, f.mul(Z1Z1, Z1))
        if U2 == X1:
            if S2 == Y1:
                return jdouble(p)
            return None
        H = f.sub(U2, X1)
        HH = f.mul(H, H)
        HHH = f.mul(HH, H)
        V = f.mul(X1, HH)
        rr = f.sub(S2, Y1)
        X3 = f.sub(f.sub(f.mul(rr, rr), HHH), f.muli(V, 2))
        Y3 = f.sub(f.mul(rr, f.sub(V, X3)), f.mul(Y1, HHH))
        Z3 = f.mul(Z1, H)
        return (X3, Y3, Z3)

    acc = None
    for bit in bin(k)[2:]:
        acc = jdouble(acc)
        if bit == "1":
            acc = jadd_affine(acc)
    if acc is None:
        return None
    X1, Y1, Z1 = acc
    zinv = f.inv(Z1)
    zi2 = f.mul(zinv, zinv)
    return (f.mul(X1, zi2), f.mul(Y1, f.mul(zi2, zinv)))


def g1_mul(k: int, pt: PointG1) -> PointG1:
    return _jac_mul(_FP_OPS, k, pt)


def g1_neg(a: PointG1) -> PointG1:
    return None if a is None else (a[0], -a[1] % P)


def g1_on_curve(pt: PointG1) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - (x * x * x + B1)) % P == 0


B2: Fp2T = f2_mul_xi((B1, 0))  # 4 * (1 + u): M-type twist constant


def g2_add(a: PointG2, b: PointG2) -> PointG2:
    if a is None:
        return b
    if b is None:
        return a
    x1, y1 = a
    x2, y2 = b
    if x1 == x2:
        if f2_add(y1, y2) == F2_ZERO:
            return None
        m = f2_mul(
            f2_muli(f2_sqr(x1), 3), f2_inv(f2_muli(y1, 2))
        )
    else:
        m = f2_mul(f2_sub(y2, y1), f2_inv(f2_sub(x2, x1)))
    x3 = f2_sub(f2_sub(f2_sqr(m), x1), x2)
    return (x3, f2_sub(f2_mul(m, f2_sub(x1, x3)), y1))


def g2_mul(k: int, pt: PointG2) -> PointG2:
    return _jac_mul(_FP2_OPS, k, pt)


def g2_neg(a: PointG2) -> PointG2:
    return None if a is None else (a[0], f2_neg(a[1]))


def g2_on_curve(pt: PointG2) -> bool:
    if pt is None:
        return True
    x, y = pt
    return f2_sub(f2_sqr(y), f2_add(f2_mul(f2_sqr(x), x), B2)) == F2_ZERO


# -- pairing ----------------------------------------------------------------
# Generic-but-slow construction (the oracle property beats speed here):
# untwist G2 into E(Fp12) and run the ate Miller loop with affine line
# functions; final exponentiation as one big f12_pow.

_FE_EXP = (P**12 - 1) // R

Point12 = Optional[Tuple[Fp12T, Fp12T]]


def _untwist(q: PointG2) -> Point12:
    """E'(Fp2) -> E(Fp12) for the M-type twist: (x, y) -> (x/w^2, y/w^3)."""
    if q is None:
        return None
    return (
        f12_mul(f12_from_fp2(q[0]), _W_INV2),
        f12_mul(f12_from_fp2(q[1]), _W_INV3),
    )


def _f12_eq(a: Fp12T, b: Fp12T) -> bool:
    return a == b


def _f12_add(a: Fp12T, b: Fp12T) -> Fp12T:
    return (f6_add(a[0], b[0]), f6_add(a[1], b[1]))


def _f12_sub(a: Fp12T, b: Fp12T) -> Fp12T:
    return (f6_sub(a[0], b[0]), f6_sub(a[1], b[1]))


F12_ZERO: Fp12T = (F6_ZERO, F6_ZERO)


def _slope(p1: Point12, p2: Point12) -> Optional[Fp12T]:
    """Slope of the line through p1, p2 (tangent when equal); None for a
    vertical line (x1 == x2, y1 == -y2)."""
    assert p1 is not None and p2 is not None
    x1, y1 = p1
    x2, y2 = p2
    if _f12_eq(x1, x2):
        if not _f12_eq(y1, y2):
            return None  # vertical
        return f12_mul(
            f12_mul(f12_sqr(x1), f12_from_fp(3)),
            f12_inv(f12_mul(y1, f12_from_fp(2))),
        )
    return f12_mul(_f12_sub(y2, y1), f12_inv(_f12_sub(x2, x1)))


def _p12_add(a: Point12, b: Point12) -> Point12:
    if a is None:
        return b
    if b is None:
        return a
    x1, y1 = a
    x2, y2 = b
    if _f12_eq(x1, x2) and _f12_eq(_f12_add(y1, y2), F12_ZERO):
        return None
    m = _slope(a, b)
    assert m is not None
    x3 = _f12_sub(_f12_sub(f12_sqr(m), x1), x2)
    y3 = _f12_sub(f12_mul(m, _f12_sub(x1, x3)), y1)
    return (x3, y3)


def _line(p1: Point12, p2: Point12, t: Point12) -> Fp12T:
    """Evaluation at ``t`` of the line through p1, p2 (tangent if equal)."""
    assert t is not None
    x1, y1 = p1  # type: ignore[misc]
    xt, yt = t
    m = _slope(p1, p2)
    if m is None:
        return _f12_sub(xt, x1)  # vertical line
    return _f12_sub(f12_mul(m, _f12_sub(xt, x1)), _f12_sub(yt, y1))


def miller_raw(q: PointG2, p: PointG1) -> Fp12T:
    """The UNREDUCED ate Miller value f_{|x|,q}(p) (pre-inversion, pre-
    final-exponentiation).

    Exposed so batch verification can combine many pairings' Miller
    values and pay ONE final exponentiation for the whole product
    (:mod:`go_ibft_tpu.verify.aggregate`) — the final exponentiation is
    ~90% of a host pairing's cost.  ``pairing`` is exactly
    ``f12_pow(f12_inv(miller_raw(q, p)), (p^12 - 1) / r)``.
    """
    if q is None or p is None:
        return F12_ONE
    q12 = _untwist(q)
    p12: Point12 = (f12_from_fp(p[0]), f12_from_fp(p[1]))
    acc = q12
    f = F12_ONE
    for bit in bin(BLS_X)[3:]:
        f = f12_mul(f12_sqr(f), _line(acc, acc, p12))
        acc = _p12_add(acc, acc)
        if bit == "1":
            f = f12_mul(f, _line(acc, q12, p12))
            acc = _p12_add(acc, q12)
    return f


def pairing(q: PointG2, p: PointG1) -> Fp12T:
    """Reduced ate pairing e(q, p); bilinear, non-degenerate on the r-torsion."""
    if q is None or p is None:
        return F12_ONE
    # the BLS12-381 parameter is negative: f_{-n} = 1/f_n up to verticals
    # (killed by the final exponentiation)
    f = f12_inv(miller_raw(q, p))
    return f12_pow(f, _FE_EXP)


def final_exponentiation(f: Fp12T) -> Fp12T:
    """``f^((p^12 - 1) / r)`` — the batch-verification finish step."""
    return f12_pow(f, _FE_EXP)


# -- hashing to G2 ----------------------------------------------------------


def _fp2_sqrt(a: Fp2T) -> Optional[Fp2T]:
    """Tonelli-Shanks in Fp2 (q = p^2, q - 1 = 2^s * m)."""
    if a == F2_ZERO:
        return F2_ZERO
    q1 = P * P - 1
    s = (q1 & -q1).bit_length() - 1
    m = q1 >> s

    def f2_pow(base: Fp2T, e: int) -> Fp2T:
        acc = F2_ONE
        for bit in bin(e)[2:]:
            acc = f2_sqr(acc)
            if bit == "1":
                acc = f2_mul(acc, base)
        return acc

    if f2_pow(a, q1 // 2) != F2_ONE:
        return None
    # find a quadratic non-residue deterministically
    z = (1, 1)
    while f2_pow(z, q1 // 2) == F2_ONE:
        z = (z[0] + 1, z[1])
    c = f2_pow(z, m)
    t = f2_pow(a, m)
    x = f2_pow(a, (m + 1) // 2)
    while t != F2_ONE:
        # find least i with t^(2^i) == 1
        i, t2 = 0, t
        while t2 != F2_ONE:
            t2 = f2_sqr(t2)
            i += 1
        b = c
        for _ in range(s - i - 1):
            b = f2_sqr(b)
        x = f2_mul(x, b)
        c = f2_sqr(b)
        t = f2_mul(t, c)
        s = i
    return x


from functools import lru_cache


@lru_cache(maxsize=64)
def hash_to_g2(message: bytes) -> PointG2:
    """Deterministic try-and-increment map onto the r-order subgroup.

    Draws an Fp2 x-candidate from keccak256 expansions, solves the twist
    equation, clears the cofactor.  Not RFC 9380; see module docstring.
    Cached: one IBFT round hashes the same proposal hash for every seal.
    """
    ctr = 0
    while True:
        seed = message + ctr.to_bytes(4, "big")
        parts = [
            keccak256(seed + bytes([tag])) for tag in range(4)
        ]
        x0 = int.from_bytes(parts[0] + parts[1], "big") % P
        x1 = int.from_bytes(parts[2] + parts[3], "big") % P
        x: Fp2T = (x0, x1)
        y2 = f2_add(f2_mul(f2_sqr(x), x), B2)
        y = _fp2_sqrt(y2)
        if y is not None:
            # canonical parity choice: lexicographically smaller of (y, -y)
            if (y[1], y[0]) > ((P - y[1]) % P, (P - y[0]) % P):
                y = f2_neg(y)
            pt = g2_mul(H2_COFACTOR, (x, y))
            if pt is not None:
                return pt
        ctr += 1


# -- keys / seals -----------------------------------------------------------


class BLSPrivateKey:
    """BLS secret scalar with its G1 public key."""

    def __init__(self, sk: int):
        if not 0 < sk < R:
            raise ValueError("secret key out of range")
        self.sk = sk
        self.pubkey: PointG1 = g1_mul(sk, G1_GEN)

    @classmethod
    def from_seed(cls, seed: bytes) -> "BLSPrivateKey":
        sk = (
            int.from_bytes(
                keccak256(b"bls-keygen-0" + seed)
                + keccak256(b"bls-keygen-1" + seed),
                "big",
            )
            % (R - 1)
            + 1
        )
        return cls(sk)

    def sign(self, message: bytes) -> PointG2:
        return g2_mul(self.sk, hash_to_g2(message))


def aggregate_signatures(sigs: Sequence[PointG2]) -> PointG2:
    acc: PointG2 = None
    for s in sigs:
        acc = g2_add(acc, s)
    return acc


def aggregate_pubkeys(pks: Sequence[PointG1]) -> PointG1:
    acc: PointG1 = None
    for pk in pks:
        acc = g1_add(acc, pk)
    return acc


def aggregate_verify(
    pubkeys: Sequence[PointG1], message: bytes, signature: PointG2
) -> bool:
    """One-message aggregate verification: e(G1, sig) == e(sum(pk), H2(m))."""
    if signature is None or not pubkeys:
        return False
    pk_agg = aggregate_pubkeys(pubkeys)
    if pk_agg is None:
        return False
    lhs = pairing(signature, G1_GEN)
    rhs = pairing(hash_to_g2(message), pk_agg)
    return lhs == rhs


def verify(pubkey: PointG1, message: bytes, signature: PointG2) -> bool:
    return aggregate_verify([pubkey], message, signature)


# -- proof of possession ----------------------------------------------------
# Aggregation over attacker-chosen pubkeys is rogue-key-attackable: an
# attacker registering pk' = pk_rogue - sum(honest pks) can make the
# AGGREGATE verify for a message no honest party signed.  The standard
# defense (Ristenpart-Yilek; the eth2 "possession" scheme) is to accept a
# public key into the aggregation set only with a signature over the key
# itself under a dedicated domain — producible only by someone holding the
# secret scalar, which a maliciously derived pk' by construction is not.

_POP_DOMAIN = b"go-ibft-bls-pop-v1:"


def pubkey_bytes(pubkey: PointG1) -> bytes:
    """Canonical 96-byte uncompressed encoding of a G1 public key."""
    if pubkey is None:
        raise ValueError("cannot encode the point at infinity as a pubkey")
    x, y = pubkey
    return x.to_bytes(48, "big") + y.to_bytes(48, "big")


def possession_message(pubkey: PointG1) -> bytes:
    """The domain-separated bytes a proof of possession signs.

    Domain separation matters twice over: a PoP must never be confusable
    with a committed seal (seals sign 32-byte proposal hashes; this is
    prefix + 96 bytes), and a seal must never double as a PoP."""
    return _POP_DOMAIN + pubkey_bytes(pubkey)


def prove_possession(key: "BLSPrivateKey") -> PointG2:
    """Sign one's own public key under the PoP domain."""
    return key.sign(possession_message(key.pubkey))


def verify_possession(pubkey: PointG1, proof: PointG2) -> bool:
    """Check that ``proof`` demonstrates knowledge of ``pubkey``'s scalar."""
    if pubkey is None or proof is None:
        return False
    if not g1_on_curve(pubkey) or g1_mul(R, pubkey) is not None:
        return False
    return verify(pubkey, possession_message(pubkey), proof)
