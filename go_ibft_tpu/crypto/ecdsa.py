"""secp256k1 ECDSA over Python ints — the host reference implementation.

Three jobs:

1. **Signer** for tests, benchmarks and the standalone crypto backend (the
   reference leaves signing to the embedder, core/backend.go:12-34; this is
   our embedder half).
2. **Bit-for-bit oracle** for the TPU kernels in
   :mod:`go_ibft_tpu.ops.secp256k1` — every device op is tested against
   these ints.
3. **Sequential per-message baseline**: the denominator of BASELINE.md's
   >=30x target is exactly this style of one-at-a-time host verify loop
   (mirroring go-ibft's per-message Verifier calls,
   messages/messages.go:183-198).

Signing uses a deterministic keccak-derived nonce (not RFC 6979, but
collision-free and reproducible — adequate for a consensus-test embedder;
swap in your HSM for production).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import cached_property
from typing import Callable, Optional, Tuple

from .keccak import keccak256

P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8

Point = Optional[Tuple[int, int]]  # None encodes the point at infinity


def _add(a: Point, b: Point) -> Point:
    if a is None:
        return b
    if b is None:
        return a
    x1, y1 = a
    x2, y2 = b
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = (3 * x1 * x1) * pow(2 * y1, P - 2, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, P - 2, P) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def scalar_mul(k: int, pt: Point) -> Point:
    k %= N
    acc: Point = None
    addend = pt
    while k:
        if k & 1:
            acc = _add(acc, addend)
        addend = _add(addend, addend)
        k >>= 1
    return acc


def on_curve(x: int, y: int) -> bool:
    return (y * y - (x * x * x + 7)) % P == 0


def pubkey_to_address(x: int, y: int) -> bytes:
    """Ethereum-style 20-byte address: keccak256(X || Y)[12:]."""
    return keccak256(x.to_bytes(32, "big") + y.to_bytes(32, "big"))[12:]


def digest_to_scalar(digest: bytes) -> int:
    """Map a 32-byte digest to the scalar field (standard truncation mod N)."""
    return int.from_bytes(digest, "big") % N


@dataclass(frozen=True)
class PrivateKey:
    d: int

    @classmethod
    def from_seed(cls, seed: bytes) -> "PrivateKey":
        d = int.from_bytes(keccak256(seed), "big") % N
        return cls(d or 1)

    # cached: a fresh 256-step double-and-add per access made every
    # `key.address` touch cost ~60ms of pure Python (measured via
    # scripts/profile_packing.py); cached_property writes straight into
    # __dict__, which a frozen dataclass permits.
    @cached_property
    def pubkey(self) -> Tuple[int, int]:
        if _native_pubkey is not None:
            out = _native_pubkey(self.d.to_bytes(32, "big"))
            if out is not None:
                return (
                    int.from_bytes(out[:32], "big"),
                    int.from_bytes(out[32:], "big"),
                )
        pt = scalar_mul(self.d, (GX, GY))
        assert pt is not None
        return pt

    @cached_property
    def address(self) -> bytes:
        return pubkey_to_address(*self.pubkey)


# Native (C++) fast paths, registered by go_ibft_tpu.native.install().
# Bit-identical to the Python implementations (differential-tested in
# tests/test_native.py); None falls through to pure Python.
_native_sign: Optional[Callable[[bytes, bytes], Optional[Tuple[int, int, int]]]] = None
_native_pubkey: Optional[Callable[[bytes], Optional[bytes]]] = None
_native_recover: Optional[Callable[[bytes, bytes, int], Optional[bytes]]] = None


def set_native_sign(
    fn: Optional[Callable[[bytes, bytes], Optional[Tuple[int, int, int]]]]
) -> None:
    """Register a native deterministic sign; ``None`` restores pure Python."""
    global _native_sign
    _native_sign = fn


def set_native_pubkey(fn: Optional[Callable[[bytes], Optional[bytes]]]) -> None:
    """Register a native pubkey derivation; ``None`` restores pure Python."""
    global _native_pubkey
    _native_pubkey = fn


def set_native_recover(
    fn: Optional[Callable[[bytes, bytes, int], Optional[bytes]]]
) -> None:
    """Register a native ecrecover; ``None`` restores pure Python."""
    global _native_recover
    _native_recover = fn


def sign(key: PrivateKey, digest: bytes) -> Tuple[int, int, int]:
    """Deterministic ECDSA; returns ``(r, s, v)`` with low-s normalization.

    ``v`` is the recovery id (y-parity of the nonce point, flipped when s is
    negated), so ``recover(digest, r, s, v)`` round-trips to the pubkey.
    """
    if _native_sign is not None:
        out = _native_sign(key.d.to_bytes(32, "big"), digest)
        if out is not None:
            return out
    z = digest_to_scalar(digest)
    counter = 0
    while True:
        k = int.from_bytes(
            keccak256(key.d.to_bytes(32, "big") + digest + bytes([counter])), "big"
        ) % N
        counter += 1
        if k == 0:
            continue
        pt = scalar_mul(k, (GX, GY))
        assert pt is not None
        r = pt[0] % N
        if r == 0:
            continue
        s = pow(k, N - 2, N) * (z + r * key.d) % N
        if s == 0:
            continue
        v = pt[1] & 1
        if s > N // 2:
            s = N - s
            v ^= 1
        return r, s, v


def verify(x: int, y: int, digest: bytes, r: int, s: int) -> bool:
    """Textbook sequential verify — one message at a time (the baseline)."""
    if not (0 < r < N and 0 < s < N):
        return False
    if not on_curve(x, y):
        return False
    z = digest_to_scalar(digest)
    w = pow(s, N - 2, N)
    pt = _add(scalar_mul(z * w % N, (GX, GY)), scalar_mul(r * w % N, (x, y)))
    if pt is None:
        return False
    return pt[0] % N == r % N


def recover(digest: bytes, r: int, s: int, v: int) -> Optional[Tuple[int, int]]:
    """Public-key recovery; ``None`` on any invalid input."""
    if _native_recover is not None:
        if not (0 < r < N and 0 < s < N) or v not in (0, 1):
            return None
        out = _native_recover(
            digest, r.to_bytes(32, "big") + s.to_bytes(32, "big"), v
        )
        return (
            None
            if out is None
            else (int.from_bytes(out[:32], "big"), int.from_bytes(out[32:], "big"))
        )
    return recover_pure(digest, r, s, v)


def recover_pure(digest: bytes, r: int, s: int, v: int) -> Optional[Tuple[int, int]]:
    """Pure-Python recovery, never delegating to the native library.

    The bottom rung of the degraded-mode verify ladder
    (:class:`go_ibft_tpu.verify.ResilientBatchVerifier`): survives a native
    library that has started crashing or returning garbage, at ~90 ms per
    recover.  Bit-identical to :func:`recover` (tests/test_native.py)."""
    if not (0 < r < N and 0 < s < N) or v not in (0, 1):
        return None
    x = r
    y2 = (x * x * x + 7) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        return None
    if (y & 1) != v:
        y = P - y
    z = digest_to_scalar(digest)
    rinv = pow(r, N - 2, N)
    q = _add(
        scalar_mul((-z) % N * rinv % N, (GX, GY)),
        scalar_mul(s * rinv % N, (x, y)),
    )
    return q
