"""Host-side cryptography for go_ibft_tpu.

The reference deliberately contains no cryptography — hashing, signing and
verification are injected by the embedder (go-ibft core/backend.go:37-56,
README.md:6-13).  This package provides a complete embedder-side crypto
stack so the framework is usable standalone:

* :mod:`.keccak` — Keccak-256 (Ethereum flavor), pure Python with an
  optional native C++ fast path (:mod:`go_ibft_tpu.native`).
* :mod:`.ecdsa` — secp256k1 key generation, deterministic signing,
  verification and public-key recovery over Python ints; the host
  reference against which the TPU kernels (:mod:`go_ibft_tpu.ops`) are
  tested bit-for-bit.
"""

from .keccak import keccak256
from .ecdsa import (
    PrivateKey,
    pubkey_to_address,
    sign,
    verify,
    recover,
)

__all__ = [
    "keccak256",
    "PrivateKey",
    "pubkey_to_address",
    "sign",
    "verify",
    "recover",
]
