"""Multi-process fleet harness: N real validator processes + a client fleet.

Everything else in :mod:`go_ibft_tpu.sim` simulates scale inside one
process; this module leaves the process (ISSUE 19, ROADMAP item #1).
:func:`run_fleet` launches ``spec.nodes`` REAL ``python -m
go_ibft_tpu.node`` subprocesses gossiping IBFT over real TCP/gRPC
sockets, waits for every /readyz, then aims a client fleet at the proof
APIs:

* a :class:`ConnectionFleet` — ONE selectors thread holding
  ``spec.connections`` concurrent keep-alive sockets, each pulling
  ``GET /proof`` on a seeded think-time loop (p50/p99 + proofs/s
  evidence comes from here);
* seeded adversaries from the chaos matrix
  (:class:`~go_ibft_tpu.chaos.ChurningClient` connection churn,
  :class:`~go_ibft_tpu.chaos.SlowlorisClient` partial-request
  tricklers — the harness asserts every slowloris socket got cut);

while the chain finalizes underneath.  The run then performs the
cross-process acceptance checks over the WIRE (no process introspection):

* liveness — every node's ``/head`` reaches ``spec.heights`` within the
  window (``missed_heights`` counts the shortfall);
* agreement — the full-range proof fetched from EVERY node is
  byte-identical (``diverged_chains`` counts mismatches): one chain,
  proven through the untrusted-client API itself;
* spot verification — one fetched proof per node is cryptographically
  verified against the genesis validator set.

Finally each node gets SIGTERM (the graceful-drain path: fsync WAL,
export per-node trace, close listeners), the drain reports are parsed
off stdout, and the per-node trace files are merged into ONE
cross-process consensus timeline (:mod:`go_ibft_tpu.obs.timeline` —
the PR-11 tool's intended endgame).  Every knob lives on
:class:`FleetSpec`; the whole run replays from the CHAOS-REPLAY line
(:func:`go_ibft_tpu.chaos.fleet_replay_line`).
"""

from __future__ import annotations

import json
import os
import selectors
import signal
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..chaos.clients import ChurningClient, SlowlorisClient, fleet_replay_line

__all__ = [
    "ConnectionFleet",
    "FleetResult",
    "FleetSpec",
    "alloc_ports",
    "build_fleet_configs",
    "run_fleet",
]

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


@dataclass
class FleetSpec:
    nodes: int = 4
    heights: int = 3  # liveness bound: every node must reach this
    connections: int = 64  # concurrent held client connections
    churn_clients: int = 2
    slowloris_clients: int = 2
    slowloris_conns: int = 4  # sockets per slowloris client
    seed: int = 7
    think_s: float = 0.5  # per-connection gap between proof pulls
    base_round_timeout_s: float = 10.0
    header_timeout_s: float = 1.0  # node-side slowloris cutoff
    max_connections: int = 2048  # node-side connection cap
    boot_timeout_s: float = 120.0
    run_timeout_s: float = 180.0
    drain_timeout_s: float = 60.0
    min_flood_s: float = 2.0  # flood at least this long before checks
    env: Dict[str, str] = field(default_factory=dict)

    def fleet_config(self) -> dict:
        """The CHAOS-REPLAY config blob (shape + digest inputs)."""
        return {
            "nodes": self.nodes,
            "heights": self.heights,
            "connections": self.connections,
            "churn_clients": self.churn_clients,
            "slowloris_clients": self.slowloris_clients,
            "slowloris_conns": self.slowloris_conns,
            "think_s": self.think_s,
        }


@dataclass
class FleetResult:
    missed_heights: int
    diverged_chains: int
    heads: List[int]
    proofs_total: int
    proofs_s: float
    proof_p50_ms: Optional[float]
    proof_p99_ms: Optional[float]
    peak_connections: int
    client_errors: int
    verified_proofs: int
    churn: Dict[str, int]
    slowloris: Dict[str, int]
    reports: List[dict]
    trace_paths: List[str]
    timeline_heights: int
    finalize_p99_ms: Optional[float]
    replay_line: str
    elapsed_s: float

    def summary(self) -> dict:
        return {
            "missed_heights": self.missed_heights,
            "diverged_chains": self.diverged_chains,
            "heads": self.heads,
            "proofs_total": self.proofs_total,
            "proofs_s": round(self.proofs_s, 2),
            "proof_p50_ms": self.proof_p50_ms,
            "proof_p99_ms": self.proof_p99_ms,
            "peak_connections": self.peak_connections,
            "client_errors": self.client_errors,
            "verified_proofs": self.verified_proofs,
            "churn": self.churn,
            "slowloris": self.slowloris,
            "timeline_heights": self.timeline_heights,
            "finalize_p99_ms": self.finalize_p99_ms,
            "elapsed_s": round(self.elapsed_s, 2),
        }


def alloc_ports(n: int) -> List[int]:
    """n distinct free TCP ports: bind-0, read, close.

    The classic small race (another process grabbing a port between
    close and the node's bind) is accepted — the node would fail its
    boot line and the harness reports it; retries belong to the caller.
    All sockets stay open until every port is read so the SAME port is
    never handed out twice.
    """
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports


def build_fleet_configs(
    root: str, spec: FleetSpec
) -> Tuple[List[str], List[dict]]:
    """Write one ``node-<i>.toml`` per validator under ``root``.

    Key material is derived per node (``fleet-node-<i>`` seeds); the
    shared ``[validators]`` table carries every derived address, so the
    processes agree on the committee without any shared state but the
    config files — exactly how a real deployment ships them.
    """
    from ..crypto import PrivateKey
    from ..node.config import (
        ConsensusConfig,
        NodeConfig,
        ProofApiConfig,
        TelemetryConfig,
        TraceConfig,
    )

    n = spec.nodes
    seeds = [f"fleet-node-{i}" for i in range(n)]
    keys = [PrivateKey.from_seed(s.encode()) for s in seeds]
    validators = {k.address.hex(): 1 for k in keys}
    # 3 ports per node: consensus gossip, proof API, telemetry.
    ports = alloc_ports(3 * n)
    infos, paths = [], []
    for i in range(n):
        consensus_port = ports[3 * i]
        proof_port = ports[3 * i + 1]
        telemetry_port = ports[3 * i + 2]
        peers = {
            f"node{j}": f"127.0.0.1:{ports[3 * j]}"
            for j in range(n)
            if j != i
        }
        cfg = NodeConfig(
            node_id=i,
            key_seed=seeds[i],
            data_dir=os.path.join(root, f"node-{i}"),
            validators=validators,
            heights=0,  # run until drained: the harness owns the window
            consensus=ConsensusConfig(
                listen=f"127.0.0.1:{consensus_port}",
                peers=peers,
                base_round_timeout_s=spec.base_round_timeout_s,
            ),
            proof_api=ProofApiConfig(
                listen=f"127.0.0.1:{proof_port}",
                max_connections=spec.max_connections,
                header_timeout_s=spec.header_timeout_s,
                idle_timeout_s=max(30.0, spec.think_s * 4),
            ),
            telemetry=TelemetryConfig(listen=f"127.0.0.1:{telemetry_port}"),
            trace=TraceConfig(enabled=True),
        )
        path = os.path.join(root, f"node-{i}.toml")
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(cfg.to_toml())
        paths.append(path)
        infos.append(
            {
                "node": i,
                "address": keys[i].address.hex(),
                "consensus_port": consensus_port,
                "proof_port": proof_port,
                "telemetry_port": telemetry_port,
                "data_dir": cfg.data_dir,
            }
        )
    return paths, infos


# ---------------------------------------------------------------------------
# the honest client fleet
# ---------------------------------------------------------------------------

_REQUEST = (
    b"GET /proof?checkpoint=0 HTTP/1.1\r\nHost: fleet\r\n"
    b"User-Agent: fleet-client/0.1\r\n\r\n"
)


class _FleetConn:
    __slots__ = (
        "sock",
        "target",
        "buf",
        "sent_at",
        "next_at",
        "need",
        "head_done",
    )

    def __init__(self, sock, target) -> None:
        self.sock = sock
        self.target = target
        self.buf = b""
        self.sent_at: Optional[float] = None
        self.next_at = 0.0
        self.need: Optional[int] = None  # body bytes outstanding
        self.head_done = False


class ConnectionFleet:
    """``connections`` concurrent keep-alive proof pullers, one thread.

    Every connection loops send-request -> read-full-response -> think;
    think times come off a seeded stream so the load is replayable.
    Latency samples cover request-write to last-body-byte.  Connections
    the server closes (idle cutoff, drain) reconnect — sustained
    concurrency is the point, not socket identity.
    """

    def __init__(
        self,
        targets: List[Tuple[str, int]],
        *,
        connections: int,
        think_s: float,
        seed: int,
        request: bytes = _REQUEST,
        request_timeout_s: float = 30.0,
    ) -> None:
        import random

        self.targets = targets
        self.connections = connections
        self.think_s = think_s
        self.request = request
        self.request_timeout_s = request_timeout_s
        self._rng = random.Random(seed ^ 0xF1EE7)
        self.latencies_ms: List[float] = []
        self.proofs = 0
        self.errors = 0
        self.reconnects = 0
        self.peak_open = 0
        self.last_body: Dict[Tuple[str, int], bytes] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="fleet-clients", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None

    def snapshot(self) -> dict:
        with self._lock:
            lat = sorted(self.latencies_ms)
        out = {
            "proofs": self.proofs,
            "errors": self.errors,
            "reconnects": self.reconnects,
            "peak_open": self.peak_open,
            "p50_ms": _pct(lat, 0.50),
            "p99_ms": _pct(lat, 0.99),
        }
        return out

    # -- internals ------------------------------------------------------

    def _connect_one(self, sel, idx: int) -> bool:
        target = self.targets[idx % len(self.targets)]
        try:
            sock = socket.create_connection(target, timeout=5.0)
        except OSError:
            self.errors += 1
            return False
        sock.setblocking(False)
        conn = _FleetConn(sock, target)
        # Stagger first requests so N connections do not fire one
        # synchronized volley per think period.
        conn.next_at = time.monotonic() + self._rng.uniform(
            0.0, max(self.think_s, 0.05)
        )
        sel.register(sock, selectors.EVENT_READ, conn)
        return True

    def _loop(self) -> None:
        sel = selectors.DefaultSelector()
        want = self.connections
        opened = 0
        try:
            while not self._stop.is_set():
                # Build toward the target concurrency in slices — the
                # accept queue sees a ramp, not a SYN avalanche.
                while opened < want and not self._stop.is_set():
                    batch = min(64, want - opened)
                    done = sum(
                        1
                        for k in range(batch)
                        if self._connect_one(sel, opened + k)
                    )
                    opened += batch
                    if done == 0:
                        break
                open_now = len(sel.get_map())
                self.peak_open = max(self.peak_open, open_now)
                now = time.monotonic()
                for key in list(sel.get_map().values()):
                    conn = key.data
                    if not isinstance(conn, _FleetConn):
                        continue
                    if conn.sent_at is None and now >= conn.next_at:
                        try:
                            conn.sock.send(self.request)
                            conn.sent_at = time.monotonic()
                        except OSError:
                            self._recycle(sel, conn)
                    elif (
                        conn.sent_at is not None
                        and now - conn.sent_at > self.request_timeout_s
                    ):
                        self.errors += 1
                        self._recycle(sel, conn)
                for key, _mask in sel.select(timeout=0.05):
                    conn = key.data
                    if isinstance(conn, _FleetConn):
                        self._readable(sel, conn)
        finally:
            for key in list(sel.get_map().values()):
                if isinstance(key.data, _FleetConn):
                    try:
                        key.data.sock.close()
                    except OSError:
                        pass
            sel.close()

    def _recycle(self, sel, conn: _FleetConn) -> None:
        try:
            sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        self.reconnects += 1
        # Reconnect to the same target to hold concurrency steady.
        try:
            sock = socket.create_connection(conn.target, timeout=5.0)
            sock.setblocking(False)
            fresh = _FleetConn(sock, conn.target)
            fresh.next_at = time.monotonic() + self._rng.uniform(
                0.0, max(self.think_s, 0.05)
            )
            sel.register(sock, selectors.EVENT_READ, fresh)
        except OSError:
            self.errors += 1

    def _readable(self, sel, conn: _FleetConn) -> None:
        try:
            chunk = conn.sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._recycle(sel, conn)
            return
        if not chunk:
            self._recycle(sel, conn)
            return
        conn.buf += chunk
        if not conn.head_done:
            head, sep, rest = conn.buf.partition(b"\r\n\r\n")
            if not sep:
                return
            conn.head_done = True
            conn.buf = rest
            conn.need = 0
            for line in head.split(b"\r\n"):
                if line.lower().startswith(b"content-length:"):
                    try:
                        conn.need = int(line.split(b":", 1)[1].strip())
                    except ValueError:
                        pass
            ok = head.startswith(b"HTTP/1.1 200")
            if not ok:
                self.errors += 1
        if conn.need is not None and len(conn.buf) >= conn.need:
            body, conn.buf = conn.buf[: conn.need], conn.buf[conn.need :]
            if conn.sent_at is not None:
                sample = (time.monotonic() - conn.sent_at) * 1e3
                with self._lock:
                    self.latencies_ms.append(sample)
                self.proofs += 1
                self.last_body[conn.target] = body
            conn.sent_at = None
            conn.head_done = False
            conn.need = None
            conn.next_at = time.monotonic() + self.think_s * self._rng.uniform(
                0.5, 1.5
            )


def _pct(sorted_samples: List[float], q: float) -> Optional[float]:
    if not sorted_samples:
        return None
    idx = min(
        len(sorted_samples) - 1, int(round(q * (len(sorted_samples) - 1)))
    )
    return round(sorted_samples[idx], 3)


# ---------------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------------


def _http_get(host: str, port: int, path: str, timeout: float = 5.0):
    """Tiny raw-socket GET -> (status, parsed json | None)."""
    try:
        with socket.create_connection((host, port), timeout=timeout) as s:
            s.settimeout(timeout)
            s.send(
                b"GET %s HTTP/1.1\r\nHost: fleet\r\nConnection: close\r\n\r\n"
                % path.encode()
            )
            data = b""
            while len(data) < (1 << 22):
                chunk = s.recv(65536)
                if not chunk:
                    break
                data += chunk
    except OSError:
        return None, None
    head, sep, body = data.partition(b"\r\n\r\n")
    # The proof API speaks HTTP/1.1; TelemetryServer (stdlib handler)
    # answers HTTP/1.0 — accept both.
    if not sep or not head.startswith(b"HTTP/1."):
        return None, None
    try:
        status = int(head.split(b" ", 2)[1])
    except (ValueError, IndexError):
        return None, None
    try:
        return status, json.loads(body.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return status, None


def launch_fleet(
    config_paths: List[str], run_dir: str, env: Optional[Dict[str, str]] = None
) -> List[subprocess.Popen]:
    """Spawn one ``python -m go_ibft_tpu.node`` per config.

    stdout/stderr land in ``node-<i>.{out,err}.log`` under ``run_dir``
    (the boot line + drain report are parsed off the .out file — the
    ``boot/restart.py`` subprocess idiom)."""
    base_env = dict(os.environ)
    base_env.setdefault("JAX_PLATFORMS", "cpu")
    base_env.update(env or {})
    procs = []
    for i, path in enumerate(config_paths):
        out = open(os.path.join(run_dir, f"node-{i}.out.log"), "wb")
        err = open(os.path.join(run_dir, f"node-{i}.err.log"), "wb")
        procs.append(
            subprocess.Popen(
                [sys.executable, "-m", "go_ibft_tpu.node", "--config", path],
                stdout=out,
                stderr=err,
                cwd=_REPO_ROOT,
                env=base_env,
            )
        )
        out.close()
        err.close()
    return procs


def wait_ready(
    infos: List[dict],
    procs: List[subprocess.Popen],
    timeout_s: float,
) -> None:
    """Block until every node's /readyz is 200 (or raise)."""
    deadline = time.monotonic() + timeout_s
    pending = {info["node"]: info for info in infos}
    while pending:
        for node_id, info in list(pending.items()):
            proc = procs[node_id]
            if proc.poll() is not None:
                raise RuntimeError(
                    f"node {node_id} exited rc={proc.returncode} before ready"
                )
            status, _payload = _http_get(
                "127.0.0.1", info["telemetry_port"], "/readyz", timeout=2.0
            )
            if status == 200:
                del pending[node_id]
        if pending:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"nodes never ready: {sorted(pending)} "
                    f"(boot_timeout_s={timeout_s})"
                )
            time.sleep(0.1)


def _parse_reports(run_dir: str, n: int) -> List[dict]:
    reports = []
    for i in range(n):
        path = os.path.join(run_dir, f"node-{i}.out.log")
        report = {}
        try:
            with open(path, "rb") as fh:
                for raw in fh.read().splitlines():
                    try:
                        line = json.loads(raw.decode("utf-8"))
                    except (ValueError, UnicodeDecodeError):
                        continue
                    if "chain_height" in line:
                        report = line
        except OSError:
            pass
        reports.append(report)
    return reports


def _spot_verify(bodies: Dict[Tuple[str, int], bytes], validators) -> int:
    """Cryptographically verify one fetched proof per node (client side:
    exactly what an untrusted light client runs)."""
    from ..serve import FinalityProof, ProofVerifier

    verified = 0
    verifier = ProofVerifier()
    try:
        for body in bodies.values():
            payload = json.loads(body.decode("utf-8"))
            proof = FinalityProof.from_wire(payload["proof"])
            verifier.verify(proof, validators)  # raises on a bad proof
            verified += 1
    finally:
        verifier.close()
    return verified


def run_fleet(spec: FleetSpec, run_dir: str) -> FleetResult:
    """The whole story; see the module docstring.  Blocking."""
    from ..crypto import PrivateKey
    from ..obs import timeline

    os.makedirs(run_dir, exist_ok=True)
    t0 = time.monotonic()
    config_paths, infos = build_fleet_configs(run_dir, spec)
    procs = launch_fleet(config_paths, run_dir, env=spec.env)
    fleet = None
    adversary_threads: List[threading.Thread] = []
    adversary_stop = threading.Event()
    churn_stats: List[Dict[str, int]] = []
    slow_clients: List[SlowlorisClient] = []
    snap: dict = {}
    flood_elapsed = 0.0
    try:
        wait_ready(infos, procs, spec.boot_timeout_s)

        proof_targets = [
            ("127.0.0.1", info["proof_port"]) for info in infos
        ]
        fleet = ConnectionFleet(
            proof_targets,
            connections=spec.connections,
            think_s=spec.think_s,
            seed=spec.seed,
        )
        flood_t0 = time.monotonic()
        fleet.start()

        # Adversaries: churn + slowloris, round-robin over the nodes.
        def _run_churn(client: ChurningClient):
            churn_stats.append(client.run(adversary_stop))

        for cid in range(spec.churn_clients):
            host, port = proof_targets[cid % len(proof_targets)]
            client = ChurningClient(
                host, port, seed=spec.seed, client_id=cid
            )
            thread = threading.Thread(
                target=_run_churn, args=(client,), daemon=True
            )
            thread.start()
            adversary_threads.append(thread)
        for cid in range(spec.slowloris_clients):
            host, port = proof_targets[cid % len(proof_targets)]
            client = SlowlorisClient(
                host,
                port,
                seed=spec.seed,
                client_id=cid,
                conns=spec.slowloris_conns,
            )
            slow_clients.append(client)
            thread = threading.Thread(
                target=client.run, args=(adversary_stop,), daemon=True
            )
            thread.start()
            adversary_threads.append(thread)

        # Liveness: every head reaches spec.heights under the flood.
        deadline = time.monotonic() + spec.run_timeout_s
        heads = [0] * spec.nodes
        while time.monotonic() < deadline:
            for i, info in enumerate(infos):
                status, payload = _http_get(
                    "127.0.0.1", info["proof_port"], "/head", timeout=2.0
                )
                if status == 200 and payload:
                    heads[i] = max(heads[i], int(payload.get("head", 0)))
            if min(heads) >= spec.heights:
                break
            time.sleep(0.2)
        missed = sum(max(0, spec.heights - h) for h in heads)

        # Keep the flood up long enough to mean something even when the
        # chain finished instantly.
        remaining = spec.min_flood_s - (time.monotonic() - flood_t0)
        if remaining > 0:
            time.sleep(remaining)
        # Throughput evidence closes HERE — the agreement fetches below
        # are the harness's own (serial, long-range) requests and would
        # skew the concurrent fleet's proofs/s window.
        snap = fleet.snapshot()
        flood_elapsed = time.monotonic() - flood_t0

        # Agreement, over the wire: fetch the full height range from
        # EVERY node and compare the per-height PROPOSALS (one chain).
        # Seal lists legitimately differ per node — each stores the
        # commit quorum it observed — so the comparison is proposal
        # bytes, not whole-proof bytes.  (Fetch AFTER liveness so the
        # range exists everywhere.)
        diverged = 0
        canonical = None
        proof_bodies: Dict[Tuple[str, int], bytes] = {}
        if missed == 0:
            for info in infos:
                status, payload = _http_get(
                    "127.0.0.1",
                    info["proof_port"],
                    f"/proof?checkpoint=0&target={spec.heights}",
                    timeout=30.0,
                )
                if status != 200 or not payload:
                    diverged += 1
                    continue
                proposals = [
                    (e["height"], e["proposal"])
                    for e in payload["proof"]["entries"]
                ]
                proof_bodies[
                    ("127.0.0.1", info["proof_port"])
                ] = json.dumps(payload).encode()
                if canonical is None:
                    canonical = proposals
                elif proposals != canonical:
                    diverged += 1
        else:
            diverged = spec.nodes  # liveness failed: agreement unproven

        verified = 0
        if proof_bodies:
            keys = [
                PrivateKey.from_seed(b"fleet-node-%d" % i)
                for i in range(spec.nodes)
            ]
            verified = _spot_verify(
                proof_bodies, {k.address: 1 for k in keys}
            )

        # The server cuts a trickler at header_timeout_s, but the CLIENT
        # only observes the cut on its next trickle iteration — up to
        # ~0.5s of recv timeout per still-open socket, which a loaded
        # box can stretch past the flood window.  Hold the adversaries
        # open until every opened slowloris socket's cut has been
        # observed (sockets are opened once, so uncut only decreases);
        # on deadline, fall through and let the gate report it.
        cut_deadline = time.monotonic() + max(
            10.0, 8.0 * spec.header_timeout_s
        )
        while time.monotonic() < cut_deadline:
            if all(
                c.stats["cut_by_server"] >= c.stats["opened"]
                for c in slow_clients
            ):
                break
            time.sleep(0.2)
    finally:
        adversary_stop.set()
        if fleet is not None:
            fleet.stop()
        for thread in adversary_threads:
            thread.join(timeout=15.0)
        # Graceful drain: SIGTERM, wait, escalate only on a hang.
        for proc in procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        drain_deadline = time.monotonic() + spec.drain_timeout_s
        for proc in procs:
            try:
                proc.wait(timeout=max(0.1, drain_deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10.0)

    reports = _parse_reports(run_dir, spec.nodes)
    trace_paths = [
        r.get("trace_path")
        for r in reports
        if r.get("trace_path") and os.path.exists(r["trace_path"])
    ]

    # One cross-process timeline from N real processes' trace files.
    timeline_heights = 0
    finalize_p99_ms = None
    if trace_paths:
        files = [timeline.load_trace_file(p) for p in trace_paths]
        merged = timeline.merge_events(files)
        timelines = timeline.reconstruct(merged)
        spans = []
        for tl in timelines:
            crit = tl.to_dict().get("critical_path")
            if crit is None:
                continue
            timeline_heights += 1
            # Latency evidence covers the GATED window only: the chain
            # runs until SIGTERM, and heights past spec.heights finalize
            # at whatever pace the flood leaves them — including an
            # in-flight height whose finalize lands during drain.
            if (
                tl.height <= spec.heights
                and crit.get("total_us") is not None
            ):
                spans.append(crit["total_us"] / 1000.0)
        finalize_p99_ms = _pct(sorted(spans), 0.99)

    slow_stats = {
        "opened": sum(c.stats["opened"] for c in slow_clients),
        "cut_by_server": sum(c.stats["cut_by_server"] for c in slow_clients),
        "bytes_sent": sum(c.stats["bytes_sent"] for c in slow_clients),
        "connect_failures": sum(
            c.stats["connect_failures"] for c in slow_clients
        ),
    }
    churn_merged: Dict[str, int] = {}
    for stats in churn_stats:
        for key, value in stats.items():
            churn_merged[key] = churn_merged.get(key, 0) + value

    return FleetResult(
        missed_heights=missed,
        diverged_chains=diverged,
        heads=heads,
        proofs_total=snap.get("proofs", 0),
        proofs_s=(
            snap.get("proofs", 0) / flood_elapsed if flood_elapsed else 0.0
        ),
        proof_p50_ms=snap.get("p50_ms"),
        proof_p99_ms=snap.get("p99_ms"),
        peak_connections=snap.get("peak_open", 0),
        client_errors=snap.get("errors", 0),
        verified_proofs=verified,
        churn=churn_merged,
        slowloris=slow_stats,
        reports=reports,
        trace_paths=trace_paths,
        timeline_heights=timeline_heights,
        finalize_p99_ms=finalize_p99_ms,
        replay_line=fleet_replay_line(spec.seed, spec.fleet_config()),
        elapsed_s=time.monotonic() - t0,
    )
