"""Lock-step cluster driver: N engines, one tick program, one process.

``ClusterSim`` is the 100–1000-validator simulation engine ROADMAP item 3
asked for: every engine multicasts into its
:class:`~go_ibft_tpu.net.ici.IciLockstepTransport` outbox, the driver
runs the tick collective, flushes every engine's
:class:`~go_ibft_tpu.core.transport.BatchingIngress` synchronously
(calibration off — deterministic windows), and yields so the engines
react before the next tick.  Heights run behind a barrier, exactly like
the loopback harness, so the two transports see the same per-height
message population and the finalized chains can be compared byte for
byte.

``LoopbackClusterSim`` is that baseline: per-message gossip fanned into
every engine's ``add_message`` — the tests/harness shape — at matched
cluster size, used both as the chain ORACLE (same
:class:`~go_ibft_tpu.sim.backend.SimBackend` determinism) and as the
timing comparison for bench config #15.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from ..core import IBFT
from ..core.transport import BatchingIngress
from ..net.ici import IciLockstepTransport
from ..obs import gates
from .adversary import AdversaryEngine, CommitWithholder, SelectiveSendPort
from .backend import SimBackend, sim_address
from .invariants import InvariantMonitor


class _NullLogger:
    def info(self, *a):
        pass

    debug = info
    error = info


@dataclass
class ClusterResult:
    """One cluster run's outcome (chains are raw finalized proposals)."""

    transport: str
    nodes: int
    heights: int
    chains: List[List[bytes]]
    elapsed_s: float
    ticks: int = 0
    messages: int = 0
    stats: dict = field(default_factory=dict)

    @property
    def heights_per_s(self) -> float:
        return self.heights / self.elapsed_s if self.elapsed_s > 0 else 0.0

    @property
    def messages_per_tick(self) -> float:
        return self.messages / self.ticks if self.ticks else 0.0

    def missed_heights(self, participants: Optional[Sequence[int]] = None) -> int:
        nodes = range(self.nodes) if participants is None else participants
        return sum(max(0, self.heights - len(self.chains[i])) for i in nodes)

    def diverged_chains(self, participants: Optional[Sequence[int]] = None) -> int:
        """Nodes whose chain is not a prefix-consistent view of the
        longest participant chain (byte comparison, not length)."""
        nodes = list(range(self.nodes) if participants is None else participants)
        if not nodes:
            return 0
        reference = max((self.chains[i] for i in nodes), key=len)
        return sum(
            1
            for i in nodes
            if self.chains[i] != reference[: len(self.chains[i])]
        )

    def slo_records(
        self, participants: Optional[Sequence[int]] = None
    ) -> List[dict]:
        """``missed_heights`` / ``diverged_chains`` records for
        :func:`go_ibft_tpu.obs.gates.gate_slo_records` — a cluster soak
        fails CI exactly like a perf regression."""
        ctx = {"transport": self.transport, "nodes": self.nodes,
               "heights": self.heights}
        return [
            gates.slo_record(
                "missed_heights", self.missed_heights(participants),
                context=ctx,
            ),
            gates.slo_record(
                "diverged_chains", self.diverged_chains(participants),
                context=ctx,
            ),
        ]


class ClusterSim:
    """N engines mounted lock-step on one ICI hub (one-shot: build,
    :meth:`run` once, read the result)."""

    def __init__(
        self,
        n_nodes: int,
        *,
        devices=None,
        max_msgs: int = 8,
        max_bytes: int = 1024,
        round_timeout: float = 0.15,
        chaos=None,
        verifier=None,
        logger=None,
        adversaries=None,
        monitor: bool = False,
        max_rounds: int = 10,
    ) -> None:
        self.n_nodes = n_nodes
        addresses = [sim_address(i) for i in range(n_nodes)]
        self.hub = IciLockstepTransport(
            n_nodes,
            devices=devices,
            max_msgs=max_msgs,
            max_bytes=max_bytes,
            logger=logger,
            verifier=verifier,
            chaos=chaos,
        )
        log = logger or _NullLogger()
        self.adversaries = adversaries  # AdversaryMix or None
        adv_indices = frozenset(adversaries.indices) if adversaries else frozenset()
        self.honest = [i for i in range(n_nodes) if i not in adv_indices]
        self.backends: List[SimBackend] = []
        self.engines: list = []  # IBFT engines and AdversaryEngines
        self.ingresses: List[BatchingIngress] = []
        for i in range(n_nodes):
            strategy = (
                adversaries.build(i, addresses) if i in adv_indices else None
            )
            if strategy is not None and not isinstance(
                strategy, CommitWithholder
            ):
                # Scripted attacker: no IBFT engine at all — the strategy
                # decides every message it sends.  Its backend never
                # finalizes, so the index is excluded from ``honest``.
                engine = AdversaryEngine(strategy, self.hub.port(i))
                self.hub.register(engine.deliver)
                self.backends.append(strategy.backend)
                self.engines.append(engine)
                continue
            port = self.hub.port(i)
            if strategy is not None:
                # Withholder: a REAL engine whose transport selectively
                # delivers COMMITs (Byzantine at the wire, honest above).
                port = SelectiveSendPort(port, strategy)
            backend = SimBackend(i, addresses)
            engine = IBFT(
                log,
                backend,
                port,
                batch_verifier=(
                    self.hub.tick_verifier() if verifier is not None else None
                ),
            )
            engine.set_base_round_timeout(round_timeout)
            ingress = BatchingIngress(engine.add_messages, calibrate=False)
            self.hub.register(self._sink(ingress))
            self.backends.append(backend)
            self.engines.append(engine)
            self.ingresses.append(ingress)
        # The invariant harness quantifies over honest nodes only — a
        # withholder runs an honest engine but is still adversary-owned,
        # so its chain carries no safety obligation.
        self.monitor: Optional[InvariantMonitor] = None
        if monitor or adversaries is not None:
            self.monitor = InvariantMonitor(
                self.backends,
                self.honest,
                max_rounds=max_rounds,
                gst_tick=chaos.heal_tick if chaos is not None else 0,
            )

    @staticmethod
    def _sink(ingress: BatchingIngress):
        def deliver(batch):
            for m in batch:
                ingress.submit(m)

        return deliver

    async def _drive(
        self, tasks, required: Sequence[int], deadline_s: float
    ) -> bool:
        """Tick until every required task finishes (True) or the deadline
        passes (False).  One :meth:`hub.step` + synchronous ingress
        flushes + a few cooperative yields per iteration; idle ticks
        sleep a hair of wall clock so round timers can fire."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + deadline_s
        while not all(tasks[i].done() for i in required):
            if loop.time() > deadline:
                return False
            await asyncio.sleep(0)
            self.hub.step()
            for ingress in self.ingresses:
                ingress.flush()
            for _ in range(4):
                await asyncio.sleep(0)
            if self.monitor is not None:
                self.monitor.scan(self.hub.stats()["ticks"])
            if self.hub.idle():
                await asyncio.sleep(0.0005)
        return True

    async def run(
        self,
        heights: int,
        *,
        participants: Optional[Sequence[int]] = None,
        height_timeout: float = 30.0,
    ) -> ClusterResult:
        if participants is None:
            # Adversary engines never finish a height — require honest
            # nodes only when a mix is mounted.
            participants = (
                self.honest if self.adversaries is not None else
                range(self.n_nodes)
            )
        required = list(participants)
        t0 = time.perf_counter()
        for h in range(heights):
            tasks = [
                asyncio.get_running_loop().create_task(
                    engine.run_sequence(h), name=f"sim-seq-{i}-h{h}"
                )
                for i, engine in enumerate(self.engines)
            ]
            try:
                await self._drive(tasks, required, height_timeout)
            finally:
                for task in tasks:
                    if not task.done():
                        task.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
        elapsed = time.perf_counter() - t0
        for ingress in self.ingresses:
            ingress.close()
        stats = self.hub.stats()
        if self.monitor is not None:
            self.monitor.scan(stats["ticks"])
            stats["invariants"] = self.monitor.summary()
        return ClusterResult(
            transport="lockstep",
            nodes=self.n_nodes,
            heights=heights,
            chains=[b.chain for b in self.backends],
            elapsed_s=elapsed,
            ticks=stats["ticks"],
            messages=stats["delivered"],
            stats=stats,
        )

    def run_sync(self, heights: int, **kw) -> ClusterResult:
        return asyncio.run(self.run(heights, **kw))


class LoopbackClusterSim:
    """The threaded-loopback baseline at matched size: per-message gossip
    into every engine's ``add_message`` (the tests/harness shape)."""

    def __init__(self, n_nodes: int, *, round_timeout: float = 0.15) -> None:
        self.n_nodes = n_nodes
        addresses = [sim_address(i) for i in range(n_nodes)]
        self.backends = [SimBackend(i, addresses) for i in range(n_nodes)]
        self.engines: List[IBFT] = []
        for backend in self.backends:
            engine = IBFT(_NullLogger(), backend, self._port())
            engine.set_base_round_timeout(round_timeout)
            self.engines.append(engine)

    def _port(self):
        sim = self

        class _T:
            def multicast(self, message):
                for engine in sim.engines:
                    engine.add_message(message)

        return _T()

    async def run(
        self, heights: int, *, height_timeout: float = 30.0
    ) -> ClusterResult:
        t0 = time.perf_counter()
        for h in range(heights):
            tasks = [
                asyncio.get_running_loop().create_task(
                    engine.run_sequence(h), name=f"loop-seq-{i}-h{h}"
                )
                for i, engine in enumerate(self.engines)
            ]
            try:
                await asyncio.wait_for(
                    asyncio.gather(*tasks), height_timeout
                )
            finally:
                for task in tasks:
                    if not task.done():
                        task.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
        elapsed = time.perf_counter() - t0
        return ClusterResult(
            transport="loopback",
            nodes=self.n_nodes,
            heights=heights,
            chains=[b.chain for b in self.backends],
            elapsed_s=elapsed,
        )

    def run_sync(self, heights: int, **kw) -> ClusterResult:
        return asyncio.run(self.run(heights, **kw))


def run_matched_pair(
    n_nodes: int,
    heights: int,
    *,
    devices=None,
    max_msgs: int = 8,
    max_bytes: int = 1024,
    round_timeout: float = 0.15,
    height_timeout: float = 60.0,
):
    """Bench config #15's measurement pair: the SAME workload through the
    lock-step engine and the threaded-loopback baseline.  Returns
    ``(lockstep, loopback)`` results; the caller asserts chain identity
    (the oracle gate) before publishing any timing."""
    lock = ClusterSim(
        n_nodes,
        devices=devices,
        max_msgs=max_msgs,
        max_bytes=max_bytes,
        round_timeout=round_timeout,
    ).run_sync(heights, height_timeout=height_timeout)
    loop = LoopbackClusterSim(
        n_nodes, round_timeout=round_timeout
    ).run_sync(heights, height_timeout=height_timeout)
    return lock, loop
