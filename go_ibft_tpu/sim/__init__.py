"""Cluster-scale lock-step simulation (the ICI transport as engine).

``ClusterSim`` mounts N IBFT engines on one
:class:`~go_ibft_tpu.net.ici.IciLockstepTransport` hub and steps them in
lock-step ticks; ``LoopbackClusterSim`` is the matched threaded-loopback
baseline (the tests/harness gossip shape) used as the chain oracle and
the bench comparison point.  ``ChaosMask`` fuses the chaos plane in as
seeded tensor masks on the collective schedule.  See docs/CLUSTER.md.
"""

from .backend import SimBackend, sim_address, sim_block, sim_hash
from .chaos import ChaosMask
from .cluster import (
    ClusterResult,
    ClusterSim,
    LoopbackClusterSim,
    run_matched_pair,
)

__all__ = [
    "ChaosMask",
    "ClusterResult",
    "ClusterSim",
    "LoopbackClusterSim",
    "SimBackend",
    "run_matched_pair",
    "sim_address",
    "sim_block",
    "sim_hash",
]
