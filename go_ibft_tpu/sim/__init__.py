"""Cluster-scale lock-step simulation (the ICI transport as engine).

``ClusterSim`` mounts N IBFT engines on one
:class:`~go_ibft_tpu.net.ici.IciLockstepTransport` hub and steps them in
lock-step ticks; ``LoopbackClusterSim`` is the matched threaded-loopback
baseline (the tests/harness gossip shape) used as the chain oracle and
the bench comparison point.  ``ChaosMask`` fuses the chaos plane in as
seeded tensor masks on the collective schedule; ``AdversaryMix`` mounts
scripted Byzantine strategies on the same seeded schedule, and
``InvariantMonitor`` checks the safety/liveness properties the whole
stack promises.  See docs/CLUSTER.md and docs/ROBUSTNESS.md.
"""

from .adversary import (
    AdversaryEngine,
    AdversaryMix,
    CommitWithholder,
    EquivocatingProposer,
    RoundChangeSpammer,
    StaleHeightReplayer,
    STRATEGIES,
    TreePoisoner,
    cluster_replay_line,
    max_adversaries,
    parse_replay_line,
)
from .backend import SimBackend, sim_address, sim_block, sim_hash
from .chaos import ChaosMask, WAN_PRESETS, wan_mask, wan_regions
from .cluster import (
    ClusterResult,
    ClusterSim,
    LoopbackClusterSim,
    run_matched_pair,
)
from .invariants import InvariantMonitor, Violation

__all__ = [
    "AdversaryEngine",
    "AdversaryMix",
    "ChaosMask",
    "ClusterResult",
    "ClusterSim",
    "CommitWithholder",
    "EquivocatingProposer",
    "InvariantMonitor",
    "LoopbackClusterSim",
    "RoundChangeSpammer",
    "STRATEGIES",
    "SimBackend",
    "StaleHeightReplayer",
    "TreePoisoner",
    "Violation",
    "WAN_PRESETS",
    "cluster_replay_line",
    "max_adversaries",
    "parse_replay_line",
    "run_matched_pair",
    "sim_address",
    "sim_block",
    "sim_hash",
    "wan_mask",
    "wan_regions",
]
