"""Cluster-scale lock-step simulation (the ICI transport as engine).

``ClusterSim`` mounts N IBFT engines on one
:class:`~go_ibft_tpu.net.ici.IciLockstepTransport` hub and steps them in
lock-step ticks; ``LoopbackClusterSim`` is the matched threaded-loopback
baseline (the tests/harness gossip shape) used as the chain oracle and
the bench comparison point.  ``ChaosMask`` fuses the chaos plane in as
seeded tensor masks on the collective schedule; ``AdversaryMix`` mounts
scripted Byzantine strategies on the same seeded schedule, and
``InvariantMonitor`` checks the safety/liveness properties the whole
stack promises.  :mod:`.fleet` leaves the process entirely: N REAL
``python -m go_ibft_tpu.node`` validator subprocesses over TCP sockets
plus a concurrent client fleet against their proof APIs
(:func:`run_fleet`, ISSUE 19).  See docs/CLUSTER.md, docs/ROBUSTNESS.md
and docs/DEPLOYMENT.md.
"""

from .adversary import (
    AdversaryEngine,
    AdversaryMix,
    CommitWithholder,
    EquivocatingProposer,
    RoundChangeSpammer,
    StaleHeightReplayer,
    STRATEGIES,
    TreePoisoner,
    cluster_replay_line,
    max_adversaries,
    parse_replay_line,
)
from .backend import SimBackend, sim_address, sim_block, sim_hash
from .chaos import ChaosMask, WAN_PRESETS, wan_mask, wan_regions
from .cluster import (
    ClusterResult,
    ClusterSim,
    LoopbackClusterSim,
    run_matched_pair,
)
from .fleet import (
    ConnectionFleet,
    FleetResult,
    FleetSpec,
    alloc_ports,
    build_fleet_configs,
    run_fleet,
)
from .invariants import InvariantMonitor, Violation

__all__ = [
    "AdversaryEngine",
    "AdversaryMix",
    "ChaosMask",
    "ClusterResult",
    "ClusterSim",
    "CommitWithholder",
    "ConnectionFleet",
    "FleetResult",
    "FleetSpec",
    "EquivocatingProposer",
    "InvariantMonitor",
    "LoopbackClusterSim",
    "RoundChangeSpammer",
    "STRATEGIES",
    "SimBackend",
    "StaleHeightReplayer",
    "TreePoisoner",
    "Violation",
    "WAN_PRESETS",
    "alloc_ports",
    "build_fleet_configs",
    "cluster_replay_line",
    "max_adversaries",
    "parse_replay_line",
    "run_fleet",
    "run_matched_pair",
    "sim_address",
    "sim_block",
    "sim_hash",
    "wan_mask",
    "wan_regions",
]
