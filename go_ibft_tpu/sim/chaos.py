"""Chaos as tensor masks on the collective schedule.

Where :mod:`go_ibft_tpu.chaos.injector` wraps individual seams with
seeded fault callables, the lock-step cluster fuses faults into the tick
itself: :class:`ChaosMask.edges` is a PURE function of ``(seed, tick)``
returning per-edge ``(allow, delay)`` matrices that
:meth:`~go_ibft_tpu.net.ici.IciLockstepTransport.step` applies to the
gathered tensor before drain.  Byte-identical per seed by construction
(counter-based Philox keyed on ``(seed, tick)`` — no stateful RNG to
drift), so a run replays from nothing but its CHAOS-REPLAY line.

Fault surface:

* **drops** — edges INTO the ``lossy`` receiver set fail with
  ``drop_rate``.  Restricting loss to a named minority keeps the
  connected majority's liveness provable: a dropped PREPREPARE has no
  retransmit, so uniform loss would eventually wedge arbitrary nodes.
* **partition** — one ``(start_tick, end_tick, groups)`` epoch; edges
  crossing group boundaries drop entirely while it lasts.
* **delay** — edges into lossy receivers defer up to ``delay_max`` whole
  ticks (the hub re-delivers when due).

Self-edges are never cut: a node always hears its own multicast, as in
every other transport here.
"""

from __future__ import annotations

import hashlib
import json
from typing import Optional, Sequence, Tuple

import numpy as np


class ChaosMask:
    def __init__(
        self,
        n_nodes: int,
        seed: int = 0,
        *,
        drop_rate: float = 0.0,
        lossy: Sequence[int] = (),
        delay_max: int = 0,
        partition: Optional[Tuple[int, int, Sequence[Sequence[int]]]] = None,
    ) -> None:
        self.n_nodes = n_nodes
        self.seed = int(seed)
        self.drop_rate = float(drop_rate)
        self.lossy = np.asarray(sorted(set(lossy)), dtype=np.int64)
        self.delay_max = int(delay_max)
        self.partition = partition
        if partition is not None:
            start, end, groups = partition
            gid = np.zeros(n_nodes, dtype=np.int64)
            for g, members in enumerate(groups):
                for m in members:
                    gid[m] = g
            self._same_group = gid[:, None] == gid[None, :]
            self._epoch = (int(start), int(end))
        else:
            self._same_group = None
            self._epoch = None

    def _rng(self, tick: int) -> np.random.Generator:
        key = np.array([self.seed, tick], dtype=np.uint64)
        return np.random.Generator(np.random.Philox(key=key))

    def edges(self, tick: int) -> Tuple[np.ndarray, np.ndarray]:
        """``(allow, delay)`` for one tick: ``allow[s, r]`` keeps the
        ``s -> r`` edge, ``delay[s, r]`` defers it that many ticks."""
        n = self.n_nodes
        allow = np.ones((n, n), dtype=bool)
        delay = np.zeros((n, n), dtype=np.int64)
        if self.lossy.size and (self.drop_rate > 0 or self.delay_max > 0):
            rng = self._rng(tick)
            if self.drop_rate > 0:
                keep = rng.random((n, self.lossy.size)) >= self.drop_rate
                allow[:, self.lossy] = keep
            if self.delay_max > 0:
                delay[:, self.lossy] = rng.integers(
                    0, self.delay_max + 1, size=(n, self.lossy.size)
                )
        if self._epoch is not None:
            start, end = self._epoch
            if start <= tick < end:
                allow &= self._same_group
        np.fill_diagonal(allow, True)
        np.fill_diagonal(delay, 0)
        return allow, delay

    # -- replay ---------------------------------------------------------

    def config(self) -> dict:
        return {
            "n_nodes": self.n_nodes,
            "drop_rate": self.drop_rate,
            "lossy": [int(i) for i in self.lossy],
            "delay_max": self.delay_max,
            "partition": (
                None
                if self.partition is None
                else [
                    self._epoch[0],
                    self._epoch[1],
                    [sorted(int(m) for m in g) for g in self.partition[2]],
                ]
            ),
        }

    def schedule_digest(self, ticks: int) -> str:
        """Digest of the full mask schedule over ``[0, ticks)`` — two runs
        with the same seed MUST produce the same digest (the replay
        check's byte-identity witness)."""
        h = hashlib.sha256()
        for t in range(ticks):
            allow, delay = self.edges(t)
            h.update(np.packbits(allow).tobytes())
            h.update(delay.astype(np.int16).tobytes())
        return h.hexdigest()[:16]

    def replay_line(self, ticks: int) -> str:
        """CHAOS-REPLAY line in the injector's format
        (:mod:`go_ibft_tpu.chaos.injector`): everything needed to re-run
        this schedule byte-identically."""
        cfg = json.dumps(
            {"seed": self.seed, **self.config()}, sort_keys=True,
            separators=(",", ":"),
        )
        return (
            f"CHAOS-REPLAY seed={self.seed} "
            f"schedule={self.schedule_digest(ticks)} config={cfg}"
        )
