"""Chaos as tensor masks on the collective schedule.

Where :mod:`go_ibft_tpu.chaos.injector` wraps individual seams with
seeded fault callables, the lock-step cluster fuses faults into the tick
itself: :class:`ChaosMask.edges` is a PURE function of ``(seed, tick)``
returning per-edge ``(allow, delay)`` matrices that
:meth:`~go_ibft_tpu.net.ici.IciLockstepTransport.step` applies to the
gathered tensor before drain.  Byte-identical per seed by construction
(counter-based Philox keyed on ``(seed, tick)`` — no stateful RNG to
drift), so a run replays from nothing but its CHAOS-REPLAY line.

Fault surface:

* **drops** — edges INTO the ``lossy`` receiver set fail with
  ``drop_rate``.  Restricting loss to a named minority keeps the
  connected majority's liveness provable: a dropped PREPREPARE has no
  retransmit, so uniform loss would eventually wedge arbitrary nodes.
* **partition** — ``(start_tick, end_tick, groups)`` epochs (one via
  ``partition=``, any number via ``partitions=[...]``); edges crossing
  group boundaries drop entirely while an epoch lasts.  The largest
  epoch end is :attr:`ChaosMask.heal_tick` — the GST of the classic
  partial-synchrony model, which the invariant harness
  (:mod:`go_ibft_tpu.sim.invariants`) uses to anchor its bounded-rounds
  liveness check.
* **delay** — edges into lossy receivers defer up to ``delay_max`` whole
  ticks (the hub re-delivers when due).
* **WAN geography** — ``regions`` (node groups) plus a ``region_delay``
  matrix give every edge a deterministic base delay in ticks by
  region pair, with ``jitter`` extra seeded ticks on top: the
  multi-region topology presets (:data:`WAN_PRESETS` /
  :func:`wan_mask`) the Byzantine soak runs over.

Self-edges are never cut: a node always hears its own multicast, as in
every other transport here.
"""

from __future__ import annotations

import hashlib
import json
from typing import Optional, Sequence, Tuple

import numpy as np


class ChaosMask:
    def __init__(
        self,
        n_nodes: int,
        seed: int = 0,
        *,
        drop_rate: float = 0.0,
        lossy: Sequence[int] = (),
        delay_max: int = 0,
        partition: Optional[Tuple[int, int, Sequence[Sequence[int]]]] = None,
        partitions: Optional[
            Sequence[Tuple[int, int, Sequence[Sequence[int]]]]
        ] = None,
        regions: Optional[Sequence[Sequence[int]]] = None,
        region_delay: Optional[Sequence[Sequence[int]]] = None,
        jitter: int = 0,
    ) -> None:
        self.n_nodes = n_nodes
        self.seed = int(seed)
        self.drop_rate = float(drop_rate)
        self.lossy = np.asarray(sorted(set(lossy)), dtype=np.int64)
        self.delay_max = int(delay_max)
        self.partition = partition
        epochs = list(partitions or ())
        if partition is not None:
            epochs.insert(0, partition)
        self.partitions: list = []
        self._epoch_masks: list = []
        for start, end, groups in epochs:
            norm = [sorted(int(m) for m in g) for g in groups]
            self.partitions.append((int(start), int(end), norm))
            gid = np.zeros(n_nodes, dtype=np.int64)
            for g, members in enumerate(norm):
                for m in members:
                    gid[m] = g
            self._epoch_masks.append(gid[:, None] == gid[None, :])
        # Back-compat aliases for the single-epoch fields.
        self._same_group = self._epoch_masks[0] if self._epoch_masks else None
        self._epoch = (
            (self.partitions[0][0], self.partitions[0][1])
            if self.partitions
            else None
        )
        # WAN geography: every edge carries a deterministic base delay by
        # region pair; ``jitter`` adds seeded per-(tick, edge) spread.
        self.jitter = int(jitter)
        if regions is not None:
            self.regions = [sorted(int(m) for m in g) for g in regions]
            if region_delay is None:
                raise ValueError("regions without region_delay")
            matrix = np.asarray(region_delay, dtype=np.int64)
            rid = np.zeros(n_nodes, dtype=np.int64)
            for r, members in enumerate(self.regions):
                for m in members:
                    rid[m] = r
            self.region_delay = matrix
            self._base_delay = matrix[rid[:, None], rid[None, :]]
        else:
            self.regions = None
            self.region_delay = None
            self._base_delay = None

    @property
    def heal_tick(self) -> int:
        """First tick with every partition epoch over — the GST anchor
        for bounded-rounds liveness (0 when no partitions)."""
        return max((end for _s, end, _g in self.partitions), default=0)

    def _rng(self, tick: int) -> np.random.Generator:
        key = np.array([self.seed, tick], dtype=np.uint64)
        return np.random.Generator(np.random.Philox(key=key))

    def edges(self, tick: int) -> Tuple[np.ndarray, np.ndarray]:
        """``(allow, delay)`` for one tick: ``allow[s, r]`` keeps the
        ``s -> r`` edge, ``delay[s, r]`` defers it that many ticks."""
        n = self.n_nodes
        allow = np.ones((n, n), dtype=bool)
        delay = np.zeros((n, n), dtype=np.int64)
        rng = None
        if self.lossy.size and (self.drop_rate > 0 or self.delay_max > 0):
            rng = self._rng(tick)
            if self.drop_rate > 0:
                keep = rng.random((n, self.lossy.size)) >= self.drop_rate
                allow[:, self.lossy] = keep
            if self.delay_max > 0:
                delay[:, self.lossy] = rng.integers(
                    0, self.delay_max + 1, size=(n, self.lossy.size)
                )
        if self._base_delay is not None:
            delay = delay + self._base_delay
            if self.jitter > 0:
                if rng is None:
                    rng = self._rng(tick)
                delay = delay + rng.integers(
                    0, self.jitter + 1, size=(n, n)
                )
        for (start, end, _groups), same in zip(
            self.partitions, self._epoch_masks
        ):
            if start <= tick < end:
                allow &= same
        np.fill_diagonal(allow, True)
        np.fill_diagonal(delay, 0)
        return allow, delay

    # -- replay ---------------------------------------------------------

    def config(self) -> dict:
        return {
            "n_nodes": self.n_nodes,
            "drop_rate": self.drop_rate,
            "lossy": [int(i) for i in self.lossy],
            "delay_max": self.delay_max,
            "partition": (
                None
                if self.partition is None
                else [
                    self.partitions[0][0],
                    self.partitions[0][1],
                    self.partitions[0][2],
                ]
            ),
            "partitions": [[s, e, g] for s, e, g in self.partitions],
            "regions": self.regions,
            "region_delay": (
                None
                if self.region_delay is None
                else self.region_delay.tolist()
            ),
            "jitter": self.jitter,
        }

    @classmethod
    def from_config(cls, config: dict, seed: Optional[int] = None) -> "ChaosMask":
        """Rebuild a mask from its :meth:`config` dict (the CHAOS-REPLAY
        round trip — scripts/chaos_replay.py's cluster mode)."""
        return cls(
            int(config["n_nodes"]),
            seed=int(config["seed"] if seed is None else seed),
            drop_rate=float(config.get("drop_rate", 0.0)),
            lossy=config.get("lossy") or (),
            delay_max=int(config.get("delay_max", 0)),
            partitions=[
                (s, e, g) for s, e, g in (config.get("partitions") or ())
            ]
            or (
                [tuple(config["partition"])]
                if config.get("partition")
                else None
            ),
            regions=config.get("regions"),
            region_delay=config.get("region_delay"),
            jitter=int(config.get("jitter", 0)),
        )

    def schedule_digest(self, ticks: int) -> str:
        """Digest of the full mask schedule over ``[0, ticks)`` — two runs
        with the same seed MUST produce the same digest (the replay
        check's byte-identity witness)."""
        h = hashlib.sha256()
        for t in range(ticks):
            allow, delay = self.edges(t)
            h.update(np.packbits(allow).tobytes())
            h.update(delay.astype(np.int16).tobytes())
        return h.hexdigest()[:16]

    def replay_line(self, ticks: int) -> str:
        """CHAOS-REPLAY line in the injector's format
        (:mod:`go_ibft_tpu.chaos.injector`): everything needed to re-run
        this schedule byte-identically."""
        cfg = json.dumps(
            {"seed": self.seed, **self.config()}, sort_keys=True,
            separators=(",", ":"),
        )
        return (
            f"CHAOS-REPLAY seed={self.seed} "
            f"schedule={self.schedule_digest(ticks)} config={cfg}"
        )


# ---------------------------------------------------------------------------
# WAN geo-latency topology presets (the GST model's network half)
# ---------------------------------------------------------------------------

# Delay matrices are in TICKS (the lock-step clock), loosely shaped like
# real inter-region RTT ratios: same-region ~0, same-continent small,
# trans-ocean the worst edge.  ``partition`` names a region to isolate
# for ``[start, end)`` ticks — the pre-GST asynchrony window; after
# ``end`` (== ChaosMask.heal_tick) the bounded-rounds liveness invariant
# is armed.
WAN_PRESETS = {
    # Three regions (us / eu / ap), no partition: pure geography.
    "wan3": {
        "region_delay": [[0, 1, 3], [1, 0, 2], [3, 2, 0]],
        "jitter": 1,
        "partition": None,
    },
    # Three regions with the ap region isolated for one early epoch:
    # the partition/heal schedule the soak's liveness invariant runs
    # against (heal_tick == 18).
    "wan3-partition": {
        "region_delay": [[0, 1, 3], [1, 0, 2], [3, 2, 0]],
        "jitter": 1,
        "partition": (6, 18, 2),
    },
    # Five regions (us-east / us-west / eu / ap / sa), no partition.
    "wan5": {
        "region_delay": [
            [0, 1, 2, 4, 3],
            [1, 0, 3, 3, 4],
            [2, 3, 0, 4, 4],
            [4, 3, 4, 0, 5],
            [3, 4, 4, 5, 0],
        ],
        "jitter": 1,
        "partition": None,
    },
}


def wan_regions(n_nodes: int, n_regions: int) -> list:
    """Contiguous node blocks, one per region (deterministic, balanced:
    region r holds indices ``[r*n//R, (r+1)*n//R)``)."""
    return [
        list(range(r * n_nodes // n_regions, (r + 1) * n_nodes // n_regions))
        for r in range(n_regions)
    ]


def wan_mask(
    preset: str,
    n_nodes: int,
    seed: int = 0,
    *,
    partition_scale: int = 1,
    **overrides,
) -> ChaosMask:
    """Build a :class:`ChaosMask` from a :data:`WAN_PRESETS` entry.

    ``partition_scale`` stretches the preset's partition epoch (tick
    counts are workload-relative); ``overrides`` pass straight through to
    the ChaosMask constructor (e.g. extra ``drop_rate``/``lossy``)."""
    spec = WAN_PRESETS[preset]
    regions = wan_regions(n_nodes, len(spec["region_delay"]))
    partitions = None
    if spec["partition"] is not None:
        start, end, isolate = spec["partition"]
        rest = [
            i
            for r, members in enumerate(regions)
            for i in members
            if r != isolate
        ]
        partitions = [
            (
                start * partition_scale,
                end * partition_scale,
                (regions[isolate], rest),
            )
        ]
    kw = dict(
        regions=regions,
        region_delay=spec["region_delay"],
        jitter=spec["jitter"],
        partitions=partitions,
    )
    kw.update(overrides)
    return ChaosMask(n_nodes, seed=seed, **kw)
