"""Byzantine strategy engine: scripted attackers inside the lock-step tick.

Where :class:`~go_ibft_tpu.sim.chaos.ChaosMask` models *faults* (drops,
delays, partitions — things a crashed disk or a flaky link also do), this
module models *adversaries*: validators that pick their messages.  An
:class:`AdversaryMix` replaces up to ``⌊(N−1)/3⌋`` of a
:class:`~go_ibft_tpu.sim.cluster.ClusterSim`'s engines with scripted
attackers:

* **equivocator** — when it is the round-0 proposer it sends CONFLICTING
  proposals to two disjoint halves of the cluster (selective-send via
  the hub's targeted fan-out).  With the safety guard ON (the default)
  it never supports either variant past the PREPREPARE, so no variant
  can reach quorum while the mix stays within ``f`` — the honest chain
  is provably canonical.  ``guard=False`` (requires
  ``AdversaryMix(unsafe=True)``) additionally COMMITs each variant to
  its half and lets fellow adversaries collude with PREPARE+COMMIT
  support — the classic safety break the invariant harness must catch
  when the mix exceeds tolerance (tests/test_adversary.py).
* **commit_withholder** — a fully honest engine whose transport
  selectively delivers: it signs every COMMIT but only half the cluster
  (seeded per height) ever receives it.
* **rc_spammer** — floods ROUND_CHANGE messages for rounds the cluster
  never voted to leave, including byte-duplicate re-sends of the same
  evidence (the satellite-4 distinct-signer-power regression surface).
* **stale_replayer** — replays finished-height traffic and floods
  future-height messages at the engines' bounded future buffer
  (core/ibft.py ``future_cap_per_sender`` / ``future_cap_total``).
* **aggtree_poisoner** — :class:`TreePoisoner` crafts negated and
  foreign BLS partials for :mod:`go_ibft_tpu.net.aggtree`'s ingest
  gates (used against an aggregation-tree harness; the tree is a
  different transport plane than the lock-step hub).

Every decision a strategy makes — which halves, which receivers, which
rounds — is a pure function of ``(seed, height)`` via counter-based
Philox, exactly like ChaosMask's ``(seed, tick)`` schedule: no stateful
RNG to drift, so one seed replays the whole attack byte-identically and
:func:`cluster_replay_line` emits the same CHAOS-REPLAY contract line
the chaos plane uses (schedule digest covering BOTH the mask schedule
and the adversary scripts).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import re
from collections import deque
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..messages import MessageType, View
from .backend import SimBackend, sim_address, sim_block, sim_hash

__all__ = [
    "AdversaryEngine",
    "AdversaryMix",
    "CommitWithholder",
    "EquivocatingProposer",
    "RoundChangeSpammer",
    "SelectiveSendPort",
    "StaleHeightReplayer",
    "STRATEGIES",
    "TreePoisoner",
    "cluster_replay_line",
    "max_adversaries",
    "parse_replay_line",
]

# (message, targets) — targets None means honest full multicast.
Send = Tuple[object, Optional[frozenset]]


def max_adversaries(n_nodes: int) -> int:
    """The classic BFT bound: ``⌊(N−1)/3⌋`` scripted attackers."""
    return (n_nodes - 1) // 3


def _rng(*key: int) -> np.random.Generator:
    """Counter-based Philox keyed on the given ints — the same
    no-stateful-RNG posture as ChaosMask (replay determinism by
    construction).  Philox keys are 4x64-bit; we fold longer keys."""
    folded = [key[0] & 0xFFFFFFFFFFFFFFFF, 0]
    for extra in key[1:]:
        folded[1] = (folded[1] * 1_000_003 + extra + 1) & 0xFFFFFFFFFFFFFFFF
    return np.random.Generator(
        np.random.Philox(key=np.array(folded, dtype=np.uint64))
    )


class Strategy:
    """One scripted attacker's decision plane (pure in ``(seed, height)``)."""

    name = "strategy"

    def __init__(self, mix: "AdversaryMix", index: int, addresses) -> None:
        self.mix = mix
        self.index = index
        self.n_nodes = mix.n_nodes
        self.seed = mix.seed
        self.backend = SimBackend(index, addresses)

    # -- script hooks ----------------------------------------------------

    def on_height_start(self, height: int) -> List[Send]:
        return []

    def on_message(self, height: int, msg) -> List[Send]:
        return []

    def on_idle(self, height: int, burst: int) -> List[Send]:
        return []

    def script_bytes(self, height: int) -> bytes:
        """Deterministic digest input for this strategy's per-height
        decisions (the adversary half of the replay schedule digest)."""
        return b"%s:%d" % (self.name.encode(), height)


class EquivocatingProposer(Strategy):
    """Conflicting proposals to disjoint halves when it holds round 0."""

    name = "equivocator"

    def __init__(self, mix, index, addresses, *, guard: bool = True) -> None:
        super().__init__(mix, index, addresses)
        if not guard and not mix.unsafe:
            raise ValueError(
                "disabling the equivocation guard needs AdversaryMix("
                "unsafe=True) — it is the deliberate safety break the "
                "invariant harness is tested against"
            )
        self.guard = guard
        self._supported: set = set()

    # Halves are keyed on (seed, height) ONLY — every colluding adversary
    # derives the same split without communicating.
    def halves(self, height: int) -> Tuple[frozenset, frozenset]:
        perm = _rng(self.seed, 0xE9, height).permutation(self.n_nodes)
        cut = self.n_nodes // 2
        advs = set(self.mix.indices)
        half_a = frozenset(int(i) for i in perm[:cut]) | advs
        half_b = frozenset(int(i) for i in perm[cut:]) | advs
        return half_a, half_b

    @staticmethod
    def variants(height: int) -> Tuple[bytes, bytes]:
        # Both pass SimBackend.is_valid_proposal (the "sim-block-"
        # prefix) — a strategic proposer ships VALID conflicting blocks,
        # not garbage the validity gate would reject for free.
        base = sim_block(height)
        return base + b"/equiv-a", base + b"/equiv-b"

    def on_height_start(self, height: int) -> List[Send]:
        if (height % self.n_nodes) != self.index:
            return []  # not the round-0 proposer — lie in wait
        raw_a, raw_b = self.variants(height)
        half_a, half_b = self.halves(height)
        view = View(height=height, round=0)
        sends: List[Send] = [
            (
                self.backend.build_preprepare_message(raw_a, None, view),
                half_a,
            ),
            (
                self.backend.build_preprepare_message(raw_b, None, view),
                half_b,
            ),
        ]
        if not self.guard:
            # Unsafe mode: the proposer also COMMITs each variant to its
            # half (it must NOT send PREPARE — a proposer among the
            # prepare signers voids the quorum, validator_manager.py).
            sends.append(
                (
                    self.backend.build_commit_message(sim_hash(raw_a), view),
                    half_a,
                )
            )
            sends.append(
                (
                    self.backend.build_commit_message(sim_hash(raw_b), view),
                    half_b,
                )
            )
        return sends

    def on_message(self, height: int, msg) -> List[Send]:
        """Collusion (unsafe mode only): support a fellow adversary's
        equivocating proposal with PREPARE+COMMIT into its half."""
        if self.guard:
            return []
        if msg.type != MessageType.PREPREPARE or msg.view is None:
            return []
        if msg.view.height != height or msg.view.round != 0:
            return []
        if msg.sender == self.backend.address:
            return []
        if msg.sender not in self.mix.addresses_of_adversaries:
            return []
        raw = msg.preprepare_data.proposal.raw_proposal
        raw_a, raw_b = self.variants(height)
        if raw not in (raw_a, raw_b):
            return []
        marker = (height, raw)
        if marker in self._supported:
            return []
        self._supported.add(marker)
        half_a, half_b = self.halves(height)
        targets = half_a if raw == raw_a else half_b
        view = View(height=height, round=0)
        phash = sim_hash(raw)
        return [
            (self.backend.build_prepare_message(phash, view), targets),
            (self.backend.build_commit_message(phash, view), targets),
        ]

    def script_bytes(self, height: int) -> bytes:
        half_a, half_b = self.halves(height)
        return b"%s:%d:%d:%r:%r:%d" % (
            self.name.encode(),
            self.index,
            height,
            sorted(half_a),
            sorted(half_b),
            int(self.guard),
        )


class CommitWithholder(Strategy):
    """Honest engine, Byzantine delivery: every COMMIT it signs reaches
    only a seeded half of the cluster (:class:`SelectiveSendPort`)."""

    name = "commit_withholder"

    def commit_targets(self, height: int) -> frozenset:
        perm = _rng(self.seed, 0xC0, height, self.index).permutation(
            self.n_nodes
        )
        cut = self.n_nodes // 2
        return frozenset(int(i) for i in perm[:cut]) | {self.index}

    def script_bytes(self, height: int) -> bytes:
        return b"%s:%d:%d:%r" % (
            self.name.encode(),
            self.index,
            height,
            sorted(self.commit_targets(height)),
        )


class RoundChangeSpammer(Strategy):
    """ROUND_CHANGE floods for rounds nobody voted to leave, with
    byte-duplicate re-sends of the same evidence (satellite 4: quorum
    power must stay distinct-signer no matter how often one signer
    repeats itself)."""

    name = "rc_spammer"

    def __init__(
        self, mix, index, addresses, *, max_round: int = 5,
        dups: int = 2, bursts: int = 3,
    ) -> None:
        super().__init__(mix, index, addresses)
        self.max_round = max_round
        self.dups = dups
        self.bursts = bursts

    def _spam(self, height: int) -> List[Send]:
        sends: List[Send] = []
        for round_ in range(1, self.max_round + 1):
            view = View(height=height, round=round_)
            for _ in range(self.dups):
                sends.append(
                    (
                        self.backend.build_round_change_message(
                            None, None, view
                        ),
                        None,
                    )
                )
        return sends

    def on_height_start(self, height: int) -> List[Send]:
        return self._spam(height)

    def on_idle(self, height: int, burst: int) -> List[Send]:
        return self._spam(height) if burst < self.bursts else []

    def script_bytes(self, height: int) -> bytes:
        return b"%s:%d:%d:%d:%d:%d" % (
            self.name.encode(), self.index, height,
            self.max_round, self.dups, self.bursts,
        )


class StaleHeightReplayer(Strategy):
    """Replays finished heights and floods future ones — the bounded
    future-buffer attack surface (core/ibft.py caps per sender/total)."""

    name = "stale_replayer"

    def __init__(
        self, mix, index, addresses, *, stale_depth: int = 2,
        future_span: int = 6, rounds: int = 2, bursts: int = 3,
    ) -> None:
        super().__init__(mix, index, addresses)
        self.stale_depth = stale_depth
        self.future_span = future_span
        self.rounds = rounds
        self.bursts = bursts

    def _flood(self, height: int) -> List[Send]:
        sends: List[Send] = []
        heights = [
            h for h in range(height - self.stale_depth, height)
            if h >= 0
        ] + list(range(height + 1, height + 1 + self.future_span))
        for h in heights:
            phash = sim_hash(sim_block(h))
            for round_ in range(self.rounds):
                view = View(height=h, round=round_)
                sends.append(
                    (self.backend.build_prepare_message(phash, view), None)
                )
                sends.append(
                    (self.backend.build_commit_message(phash, view), None)
                )
                sends.append(
                    (
                        self.backend.build_round_change_message(
                            None, None, view
                        ),
                        None,
                    )
                )
        return sends

    def on_height_start(self, height: int) -> List[Send]:
        return self._flood(height)

    def on_idle(self, height: int, burst: int) -> List[Send]:
        return self._flood(height) if burst < self.bursts else []

    def script_bytes(self, height: int) -> bytes:
        return b"%s:%d:%d:%d:%d:%d" % (
            self.name.encode(), self.index, height,
            self.stale_depth, self.future_span, self.rounds,
        )


STRATEGIES = {
    cls.name: cls
    for cls in (
        EquivocatingProposer,
        CommitWithholder,
        RoundChangeSpammer,
        StaleHeightReplayer,
    )
}


class SelectiveSendPort:
    """Transport wrapper for the withholder: honest multicast for every
    phase EXCEPT the COMMIT, which only the seeded half receives."""

    def __init__(self, port, strategy: CommitWithholder) -> None:
        self._port = port
        self._strategy = strategy

    def multicast(self, message) -> None:
        if message.type == MessageType.COMMIT and message.view is not None:
            self._port.multicast_to(
                message, self._strategy.commit_targets(message.view.height)
            )
        else:
            self._port.multicast(message)


class AdversaryEngine:
    """Drives one scripted strategy on the ClusterSim height barrier.

    Mirrors the IBFT engine's driver surface (``run_sequence`` coroutine
    + a batched deliver sink) so :class:`ClusterSim` can mount it on a
    hub port without special cases.  It finalizes nothing — its sim
    backend's chain stays empty, which is why adversary indices are
    excluded from the honest participant set.
    """

    # How many cooperative yields between idle bursts: enough that a
    # burst lands roughly once per few ticks without busy-spinning.
    _IDLE_EVERY = 64

    def __init__(self, strategy: Strategy, port) -> None:
        self.strategy = strategy
        self.backend = strategy.backend
        self._port = port
        self._inbox: deque = deque()

    def deliver(self, batch) -> None:
        self._inbox.extend(batch)

    def _send(self, sends: List[Send]) -> None:
        for message, targets in sends:
            if targets is None:
                self._port.multicast(message)
            else:
                self._port.multicast_to(message, targets)

    async def run_sequence(self, height: int) -> None:
        self._inbox.clear()
        self._send(self.strategy.on_height_start(height))
        burst = 0
        spins = 0
        while True:  # cancelled by the driver at height end
            while self._inbox:
                msg = self._inbox.popleft()
                self._send(self.strategy.on_message(height, msg))
            spins += 1
            if spins % self._IDLE_EVERY == 0:
                self._send(self.strategy.on_idle(height, burst))
                burst += 1
            await asyncio.sleep(0)


class AdversaryMix:
    """Which nodes attack, and how.

    ``assignment`` maps node index -> strategy name (see
    :data:`STRATEGIES`).  The classic tolerance bound ``⌊(N−1)/3⌋`` is
    enforced unless ``unsafe=True`` — exceeding it (or disabling the
    equivocation guard) is how the harness's own failure detection is
    tested, never a configuration a soak should pass.
    """

    def __init__(
        self,
        n_nodes: int,
        seed: int,
        assignment: Mapping[int, str],
        *,
        unsafe: bool = False,
        params: Optional[Dict[int, dict]] = None,
    ) -> None:
        self.n_nodes = int(n_nodes)
        self.seed = int(seed)
        self.unsafe = bool(unsafe)
        self.assignment = {int(i): str(s) for i, s in assignment.items()}
        self.params = {int(i): dict(p) for i, p in (params or {}).items()}
        for i, name in self.assignment.items():
            if not 0 <= i < n_nodes:
                raise ValueError(f"adversary index {i} out of range")
            if name not in STRATEGIES:
                raise ValueError(f"unknown strategy {name!r}")
        cap = max_adversaries(n_nodes)
        if len(self.assignment) > cap and not unsafe:
            raise ValueError(
                f"{len(self.assignment)} adversaries exceeds the "
                f"f=(N-1)//3={cap} tolerance bound at N={n_nodes} "
                "(pass unsafe=True only to test the harness itself)"
            )
        self.indices = tuple(sorted(self.assignment))
        self.addresses_of_adversaries = frozenset(
            sim_address(i) for i in self.indices
        )
        self._strategies: Dict[int, Strategy] = {}

    @classmethod
    def seeded(
        cls,
        n_nodes: int,
        seed: int,
        *,
        power: float = 0.3,
        strategies: Sequence[str] = (
            "equivocator",
            "commit_withholder",
            "rc_spammer",
            "stale_replayer",
        ),
    ) -> "AdversaryMix":
        """The bench-config mix: ``power`` of the committee turns
        Byzantine (capped at the tolerance bound), indices drawn and
        strategies dealt round-robin from the seed alone."""
        k = min(int(round(n_nodes * power)), max_adversaries(n_nodes))
        picks = _rng(seed, 0xAD).choice(n_nodes, size=k, replace=False)
        indices = sorted(int(i) for i in picks)
        assignment = {
            i: strategies[j % len(strategies)]
            for j, i in enumerate(indices)
        }
        return cls(n_nodes, seed, assignment)

    # -- construction ----------------------------------------------------

    def build(self, index: int, addresses) -> Strategy:
        """Instantiate (and memoize) the strategy mounted at ``index``."""
        strategy = self._strategies.get(index)
        if strategy is None:
            cls_ = STRATEGIES[self.assignment[index]]
            strategy = cls_(
                self, index, addresses, **self.params.get(index, {})
            )
            self._strategies[index] = strategy
        return strategy

    def honest(self) -> List[int]:
        return [i for i in range(self.n_nodes) if i not in self.assignment]

    # -- replay ----------------------------------------------------------

    def config(self) -> dict:
        return {
            "n_nodes": self.n_nodes,
            "seed": self.seed,
            "unsafe": self.unsafe,
            "adversaries": {
                str(i): self.assignment[i] for i in self.indices
            },
        }

    def schedule_digest(self, heights: int) -> str:
        """Digest over every adversary's per-height script decisions —
        the strategy half of the combined CHAOS-REPLAY digest."""
        addresses = [sim_address(i) for i in range(self.n_nodes)]
        h = hashlib.sha256()
        for index in self.indices:
            strategy = self.build(index, addresses)
            for height in range(heights):
                h.update(strategy.script_bytes(height))
        return h.hexdigest()[:16]


def cluster_replay_line(
    chaos,
    mix: Optional[AdversaryMix],
    ticks: int,
    heights: int,
    *,
    max_msgs: Optional[int] = None,
    max_bytes: Optional[int] = None,
    round_timeout: Optional[float] = None,
) -> str:
    """The lock-step cluster's CHAOS-REPLAY byte-identity line.

    One line carries everything a re-run needs: the chaos mask config,
    the adversary assignment, the tick/height horizon the combined
    schedule digest was computed over (so a replay recomputes the digest
    over the SAME horizon regardless of how many ticks its own run
    takes), and — when given — the transport shape.  The shape matters:
    an undersized ``max_bytes`` silently drops PC-bearing round-change
    messages (hub stat ``dropped_oversize``) and turns a healed
    partition into a permanent wedge, so a replay at different slot
    sizes is a different scenario.  Parsed back by
    :func:`parse_replay_line` / scripts/chaos_replay.py.
    """
    seed = chaos.seed if chaos is not None else (
        mix.seed if mix is not None else 0
    )
    mask_digest = (
        chaos.schedule_digest(ticks) if chaos is not None else "no-chaos"
    )
    adv_digest = (
        mix.schedule_digest(heights) if mix is not None else "no-adversary"
    )
    digest = hashlib.sha256(
        f"{mask_digest}+{adv_digest}".encode()
    ).hexdigest()[:16]
    cfg = {
        "seed": seed,
        "ticks": int(ticks),
        "heights": int(heights),
        "chaos": chaos.config() if chaos is not None else None,
        "adversary": mix.config() if mix is not None else None,
    }
    cluster = {
        k: v
        for k, v in (
            ("max_msgs", max_msgs),
            ("max_bytes", max_bytes),
            ("round_timeout", round_timeout),
        )
        if v is not None
    }
    if cluster:
        cfg["cluster"] = cluster
    blob = json.dumps(cfg, sort_keys=True, separators=(",", ":"))
    return f"CHAOS-REPLAY seed={seed} schedule={digest} config={blob}"


_REPLAY_RE = re.compile(
    r"CHAOS-REPLAY seed=(\d+) schedule=([0-9a-f-]+) config=(\{.*\})\s*$"
)


def parse_replay_line(line: str) -> dict:
    """``CHAOS-REPLAY seed=N schedule=D config={...}`` -> parsed dict
    (raises ValueError on anything else)."""
    m = _REPLAY_RE.search(line.strip())
    if m is None:
        raise ValueError("not a CHAOS-REPLAY line")
    return {
        "seed": int(m.group(1)),
        "schedule": m.group(2),
        "config": json.loads(m.group(3)),
    }


class TreePoisoner:
    """Negated / foreign BLS partials for the aggregation tree's ingest
    gates (:mod:`go_ibft_tpu.net.aggtree`).

    The tree's Byzantine surface is different from the consensus hub's:
    a poisoned PARTIAL that survives ingest cancels honest signatures
    inside an aggregate, so the gates (decodable seal, member sender,
    quarantine-bisect at certify time) are what this strategy probes.
    Imports the BLS backend lazily — sim-crypto cluster runs never pay
    for it.
    """

    def __init__(self, seed: int = 0) -> None:
        self.seed = int(seed)

    @staticmethod
    def negated_commit(bls_key, sender: bytes, proposal_hash: bytes,
                       height: int = 1):
        """A member's COMMIT whose seal is the NEGATION of its honest
        signature: structurally valid, passes every ingest gate, and
        cancels the honest partial inside any aggregate it joins — only
        the certify-time quarantine bisect can evict it."""
        from ..crypto import bls as hbls
        from ..messages.wire import CommitMessage, IbftMessage
        from ..verify.bls import encode_seal

        neg = hbls.g2_neg(bls_key.sign(proposal_hash))
        return IbftMessage(
            view=View(height=height, round=0),
            sender=sender,
            type=MessageType.COMMIT,
            commit_data=CommitMessage(
                proposal_hash=proposal_hash,
                committed_seal=encode_seal(neg),
            ),
        )

    @staticmethod
    def foreign_commit(bls_key, proposal_hash: bytes, height: int = 1):
        """A syntactically perfect COMMIT from an address that is NOT a
        committee member at ``height`` — must die at the membership
        ingest gate, never reach the pump."""
        from ..messages.wire import CommitMessage, IbftMessage
        from ..verify.bls import encode_seal

        return IbftMessage(
            view=View(height=height, round=0),
            sender=b"\xee" * 20,
            type=MessageType.COMMIT,
            commit_data=CommitMessage(
                proposal_hash=proposal_hash,
                committed_seal=encode_seal(bls_key.sign(proposal_hash)),
            ),
        )
