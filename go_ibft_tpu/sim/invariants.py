"""Machine-readable safety/liveness invariants over a lock-step cluster.

The adversary engine (:mod:`go_ibft_tpu.sim.adversary`) makes "the run
finished" an insufficient verdict: a Byzantine mix can leave every
honest node responsive while quietly splitting the chain.  This monitor
checks the three properties the IBFT safety argument actually promises,
incrementally as finalizations land:

* **agreement** — no two honest nodes finalize different proposals at
  the same height (the f<N/3 safety core; the equivocator with its
  guard disabled is the canonical violator, and
  tests/test_adversary.py proves this monitor catches it).
* **validity** — every finalized proposal passes the backend's
  ``is_valid_proposal`` gate (an adversary proposer must not be able to
  finalize garbage).
* **bounded_rounds** — after GST (:attr:`ChaosMask.heal_tick` — the
  largest partition epoch end) every finalization lands within
  ``max_rounds`` rounds: the partial-synchrony liveness claim, made
  falsifiable.

Violations are data (:class:`Violation`), counts surface as SLO records
through :func:`go_ibft_tpu.obs.gates.slo_record` (warn=fail=0 — any
violation is a gate failure), and the offending seed is replayable from
the run's CHAOS-REPLAY line.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["InvariantMonitor", "Violation"]

INVARIANTS = ("agreement", "validity", "bounded_rounds")


@dataclass(frozen=True)
class Violation:
    invariant: str  # one of INVARIANTS
    height: int
    node: int
    tick: int  # hub tick when the scan observed it (-1 outside a run)
    detail: str

    def __str__(self) -> str:  # pragma: no cover - repr convenience
        return (
            f"[{self.invariant}] node={self.node} height={self.height} "
            f"tick={self.tick}: {self.detail}"
        )


class InvariantMonitor:
    """Incremental invariant scanner over honest nodes' finalizations.

    ``backends`` are the per-node SimBackends (finalizations append to
    ``backend.inserted`` in height order — the engines run one
    height-barrier at a time, so position IS height); ``honest`` names
    the indices whose chains the properties quantify over.  ``scan`` is
    cheap and idempotent: each finalization is examined exactly once, so
    the cluster driver calls it every tick and once more at the end.
    """

    def __init__(
        self,
        backends: Sequence,
        honest: Sequence[int],
        *,
        max_rounds: int = 10,
        gst_tick: int = 0,
    ) -> None:
        self.backends = list(backends)
        self.honest = sorted(int(i) for i in honest)
        self.max_rounds = int(max_rounds)
        self.gst_tick = int(gst_tick)
        self.violations: List[Violation] = []
        self.heights_checked = 0
        self.max_finalize_round = 0
        self._seen: Dict[int, int] = {i: 0 for i in self.honest}
        # height -> (first node to finalize it, raw proposal bytes)
        self._canonical: Dict[int, Tuple[int, bytes]] = {}

    def scan(self, tick: int = -1) -> List[Violation]:
        """Examine finalizations that landed since the last scan; returns
        violations found by THIS scan (all-time list in .violations)."""
        found: List[Violation] = []
        for i in self.honest:
            backend = self.backends[i]
            inserted = backend.inserted
            while self._seen[i] < len(inserted):
                height = self._seen[i]
                proposal, _seals = inserted[height]
                self._seen[i] += 1
                self.heights_checked += 1
                found.extend(
                    self._check(i, height, proposal, tick, backend)
                )
        self.violations.extend(found)
        return found

    def _check(self, node, height, proposal, tick, backend):
        raw = proposal.raw_proposal
        round_ = int(proposal.round or 0)
        out: List[Violation] = []
        first = self._canonical.setdefault(height, (node, raw))
        if first[1] != raw:
            out.append(
                Violation(
                    "agreement", height, node, tick,
                    f"finalized {raw!r} but node {first[0]} finalized "
                    f"{first[1]!r}",
                )
            )
        if not backend.is_valid_proposal(raw):
            out.append(
                Violation(
                    "validity", height, node, tick,
                    f"finalized proposal fails is_valid_proposal: {raw!r}",
                )
            )
        self.max_finalize_round = max(self.max_finalize_round, round_)
        # Bounded-rounds is only armed after GST: during a partition
        # epoch a stranded node may legitimately burn rounds.  GST is a
        # TICK bound, so any finalization scanned after heal_tick is in
        # scope (finalizations before it were scanned earlier).
        if (tick < 0 or tick >= self.gst_tick) and round_ > self.max_rounds:
            out.append(
                Violation(
                    "bounded_rounds", height, node, tick,
                    f"finalized at round {round_} > "
                    f"max_rounds={self.max_rounds} after GST",
                )
            )
        return out

    # -- verdict ---------------------------------------------------------

    def count(self, invariant: str) -> int:
        return sum(1 for v in self.violations if v.invariant == invariant)

    @property
    def ok(self) -> bool:
        return not self.violations

    def summary(self) -> dict:
        return {
            "ok": self.ok,
            "heights_checked": self.heights_checked,
            "max_finalize_round": self.max_finalize_round,
            "gst_tick": self.gst_tick,
            "violations": {name: self.count(name) for name in INVARIANTS},
        }

    def slo_records(self, context: Optional[dict] = None) -> list:
        """One SLO record per invariant (warn=fail=0 in the default
        table — any violation fails the gate)."""
        from ..obs import gates

        return [
            gates.slo_record(
                f"invariant_{name}",
                float(self.count(name)),
                context=context,
            )
            for name in INVARIANTS
        ]
