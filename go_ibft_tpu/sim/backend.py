"""Deterministic simulation backend for cluster-scale lock-step runs.

The sim-crypto analogue of the test harness MockBackend, importable from
production drivers (bench.py, scripts/) without reaching into tests/:
proposals are a pure function of HEIGHT (never round), so two runs that
finalize every height produce byte-identical chains even when round
timers jittered differently along the way — the property the cluster
bench's chain-identity oracle and the chaos replay check both lean on.

Sender validity is delegate-checked (``is_valid_validator`` membership),
not signature-checked: every engine validates identically whichever
transport carried the message, which is what makes the lock-step vs
loopback comparison apples-to-apples.  Real-crypto cluster runs use
:class:`~go_ibft_tpu.core.backend.ECDSABackend` plus the tick-fused
verifier instead (tests/test_cluster_sim.py).
"""

from __future__ import annotations

import hashlib
from typing import Callable, List, Mapping, Optional, Sequence

from ..messages import (
    CommitMessage,
    IbftMessage,
    MessageType,
    Proposal,
    PrepareMessage,
    PrePrepareMessage,
    RoundChangeMessage,
    View,
)

_SIM_SIGNATURE = b"\x00" * 65


def sim_address(index: int) -> bytes:
    """Stable per-node address (not 20 bytes — sim crypto never packs)."""
    return b"sim-%05d" % index


def sim_block(height: int) -> bytes:
    """The canonical proposal for ``height`` — round-independent by
    design (see module docstring)."""
    return b"sim-block-%08d" % height


def sim_hash(raw_proposal: bytes) -> bytes:
    return hashlib.sha256(raw_proposal).digest()


class SimBackend:
    """Backend + MessageConstructor + Verifier for one sim node.

    ``commit_next_set`` (ISSUE 20, default off) makes every proposal carry
    a next-set commitment suffix (:mod:`go_ibft_tpu.lightsync.commitment`)
    over the NEXT height's validator set, and makes ``is_valid_proposal``
    require + check it against ``validators_for_height`` — the sim-side
    producer/enforcer pair for commitment-enforced proofs.  Off by
    default so the byte-identity oracles (chain-identity, chaos replay)
    keep their exact historical bytes.
    """

    def __init__(
        self,
        index: int,
        addresses: Sequence[bytes],
        *,
        commit_next_set: bool = False,
        validators_for_height: Optional[
            Callable[[int], Mapping[bytes, int]]
        ] = None,
    ) -> None:
        self.index = index
        self.addresses = list(addresses)
        self.address = self.addresses[index]
        self._members = frozenset(self.addresses)
        self.commit_next_set = commit_next_set
        self._validators_for_height = validators_for_height or (
            lambda _height: {a: 1 for a in self.addresses}
        )
        self.inserted: List[tuple] = []

    # -- MessageConstructor ---------------------------------------------

    def build_preprepare_message(self, raw_proposal, certificate, view: View):
        return IbftMessage(
            view=view.copy(),
            sender=self.address,
            signature=_SIM_SIGNATURE,
            type=MessageType.PREPREPARE,
            preprepare_data=PrePrepareMessage(
                proposal=Proposal(
                    raw_proposal=raw_proposal, round=view.round
                ),
                proposal_hash=sim_hash(raw_proposal),
                certificate=certificate,
            ),
        )

    def build_prepare_message(self, proposal_hash, view: View):
        return IbftMessage(
            view=view.copy(),
            sender=self.address,
            signature=_SIM_SIGNATURE,
            type=MessageType.PREPARE,
            prepare_data=PrepareMessage(proposal_hash=proposal_hash),
        )

    def build_commit_message(self, proposal_hash, view: View):
        return IbftMessage(
            view=view.copy(),
            sender=self.address,
            signature=_SIM_SIGNATURE,
            type=MessageType.COMMIT,
            commit_data=CommitMessage(
                proposal_hash=proposal_hash,
                committed_seal=b"seal:" + self.address,
            ),
        )

    def build_round_change_message(self, proposal, certificate, view: View):
        return IbftMessage(
            view=view.copy(),
            sender=self.address,
            signature=_SIM_SIGNATURE,
            type=MessageType.ROUND_CHANGE,
            round_change_data=RoundChangeMessage(
                last_prepared_proposal=proposal,
                latest_prepared_certificate=certificate,
            ),
        )

    # -- Verifier -------------------------------------------------------

    def is_valid_proposal(self, raw_proposal: bytes) -> bool:
        if not raw_proposal.startswith(b"sim-block-"):
            return False
        if not self.commit_next_set:
            return True
        # Commitment-enforced mode: the proposal must carry a next-set
        # commitment and it must match the set the proposer was obliged
        # to commit to (the height is parseable from the sim prefix, so
        # the sim seam can check the EXACT root, not just presence).
        from ..lightsync.commitment import extract_next_set, set_root, strip_next_set

        committed = extract_next_set(raw_proposal)
        if committed is None:
            return False
        try:
            height = int(strip_next_set(raw_proposal)[len(b"sim-block-"):])
        except ValueError:
            return False
        return committed == set_root(self._validators_for_height(height + 1))

    def is_valid_validator(self, msg: IbftMessage) -> bool:
        return msg.sender in self._members

    def is_proposer(self, validator_id: bytes, height: int, round_: int) -> bool:
        n = len(self.addresses)
        return validator_id == self.addresses[(height + round_) % n]

    def is_valid_proposal_hash(self, proposal: Proposal, hash_: bytes) -> bool:
        return hash_ == sim_hash(proposal.raw_proposal)

    def is_valid_committed_seal(
        self, proposal_hash, committed_seal, height: Optional[int] = None
    ) -> bool:
        return True

    # -- ValidatorBackend -----------------------------------------------

    def get_voting_powers(self, height: int) -> dict:
        return dict(self._validators_for_height(height))

    # -- Backend --------------------------------------------------------

    def build_proposal(self, view: View) -> bytes:
        raw = sim_block(view.height)
        if self.commit_next_set:
            from ..lightsync.commitment import embed_next_set, set_root

            raw = embed_next_set(
                raw, set_root(self._validators_for_height(view.height + 1))
            )
        return raw

    def insert_proposal(self, proposal: Proposal, committed_seals) -> None:
        self.inserted.append((proposal, list(committed_seals)))

    def id(self) -> bytes:
        return self.address

    # -- Notifier -------------------------------------------------------

    def round_starts(self, view: View) -> None:
        return None

    def sequence_cancelled(self, view: View) -> None:
        return None

    # -- results --------------------------------------------------------

    @property
    def chain(self) -> List[bytes]:
        """Finalized raw proposals in insertion order."""
        return [p.raw_proposal for p, _ in self.inserted]
