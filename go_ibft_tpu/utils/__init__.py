"""Utilities: metrics registry, logging adapters."""

from . import metrics

__all__ = ["metrics"]
