"""The ONE default-backend liveness probe (bench + driver entry share it).

The tunneled TPU backend ("axon" PJRT plugin) has three observed failure
modes, and a plain ``jax.devices()`` in-process call survives none of them:

* fail-fast ``RuntimeError`` at init (BENCH_r02.json) — recoverable
  in-process, but only if nothing initialized the backend yet;
* multi-minute HANG at init (BENCH_r04.json: three 120 s probe timeouts)
  — unrecoverable in-process, the call never returns;
* slow-but-live init: the tunnel handshake can take minutes before the
  first ``devices()`` resolves, after which the chip works fine.

So the probe runs ``jax.devices()`` + one tiny matmul in a SUBPROCESS with
a hard timeout, and the parent decides.  Both ``bench.py`` and
``__graft_entry__`` previously carried separate copies of this logic with
different knobs (VERDICT r04 weak #7); this module is now the single
implementation and ``GO_IBFT_PROBE_TIMEOUT`` the single knob.  Callers
that probe repeatedly should go through
``go_ibft_tpu.obs.evidence.probe_fingerprint`` — the TTL'd on-disk cache
over this probe (``~/.cache/go_ibft_tpu/probe.json``), so probe points
within a TTL window cost one file read instead of one timeout each.

The timeout default is 120 s with ONE attempt *per probe point*: blind
retries in a loop are useless (every observed outage is either
instant-fail — which the probe reports in seconds regardless of the
timeout — or hours-long), and a live tunnel initializes well under two
minutes (r03 measured whole device suites within session budgets).  A
dead-but-HANGING tunnel costs the timeout exactly once per call; callers
with their own wall-clock budget clamp via ``timeout_s`` (bench.py passes
half its remaining budget), everyone else shares the single
``GO_IBFT_PROBE_TIMEOUT`` knob.

Single-shot does NOT mean a fallback run gives up on the chip: since PR 1
a CPU-fallback bench re-probes once more near its END
(``go_ibft_tpu/obs/evidence.py::reprobe_and_capture``) and, when the
tunnel woke up mid-run, relaunches the bench in a fresh subprocess to
capture ``evidence_tpu.jsonl`` — two probe points bracketing the run, no
retry loops in between.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional, Tuple

__all__ = ["probe_timeout_s", "probe_default_backend", "ensure_default_backend"]

_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "d = jax.devices();"
    "(jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready();"
    "print('PLATFORM=' + d[0].platform);"
    "print('DEVICES=%d' % len(d))"
)


def _probe_src() -> str:
    """The probe subprocess source; ``GO_IBFT_PROBE_SRC`` overrides it.

    The override exists for the hang-proof contract tests
    (tests/test_obs.py): a stub that sleeps past the deadline simulates
    the observed ``jax.devices()`` hang without needing a dead tunnel, so
    the "bench can never block on the probe" property is pinned in tier-1
    on any host.
    """
    return os.environ.get("GO_IBFT_PROBE_SRC", _PROBE_SRC)


def probe_timeout_s() -> float:
    return float(os.environ.get("GO_IBFT_PROBE_TIMEOUT", "120"))


def probe_default_backend(
    timeout_s: Optional[float] = None,
) -> Tuple[Optional[str], str]:
    """Probe the default JAX backend in a subprocess.

    Returns ``(platform, detail)``: ``platform`` is the live default
    platform name (``"axon"``/``"tpu"``/``"cpu"``/...) or ``None`` when the
    backend is dead, with ``detail`` a one-line reason for the log.
    """
    platform, detail, _devices = probe_default_backend_full(timeout_s)
    return platform, detail


def probe_default_backend_full(
    timeout_s: Optional[float] = None,
) -> Tuple[Optional[str], str, Optional[int]]:
    """:func:`probe_default_backend` plus the probed device count.

    The third element is how many devices the live backend exposed (so
    evidence fingerprints can distinguish dp=1 from dp>1 runs —
    ``--xla_force_host_platform_device_count`` and multi-chip TPU slices
    both show up here), or ``None`` when the backend is dead or the probe
    stub predates the ``DEVICES=`` line (the ``GO_IBFT_PROBE_SRC`` test
    hook).
    """
    if timeout_s is None:
        timeout_s = probe_timeout_s()
    try:
        out = subprocess.run(
            [sys.executable, "-c", _probe_src()],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return None, f"probe timeout after {timeout_s:.0f}s", None
    platform = None
    devices: Optional[int] = None
    for line in out.stdout.splitlines():
        if line.startswith("PLATFORM="):
            platform = line.split("=", 1)[1]
        elif line.startswith("DEVICES="):
            try:
                devices = int(line.split("=", 1)[1])
            except ValueError:
                devices = None
    if platform is not None:
        return platform, "ok", devices
    err = (out.stderr.strip().splitlines() or ["no output"])[-1][:200]
    return None, err, None


_memo: dict = {}


def ensure_default_backend() -> bool:
    """Pin CPU iff the default backend is dead; memoized per process.

    Returns True when the default backend is alive (left untouched).  Only
    effective before the backend initializes in THIS process — backend
    choice is sticky once any array op runs.  NOTE: ``jax_platforms ==
    'cpu'`` already pinned means a caller (dryrun) chose CPU explicitly;
    that is treated as alive-by-construction.
    """
    import jax

    if "alive" in _memo:
        return _memo["alive"]
    if jax.config.jax_platforms == "cpu":
        _memo["alive"] = True
        return True
    platform, _ = probe_default_backend()
    if platform is None:
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass  # backend already up in this process; keep it
        _memo["alive"] = False
    else:
        _memo["alive"] = True
    return _memo["alive"]
