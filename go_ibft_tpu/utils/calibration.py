"""Measured host/device routing constants.

The AdaptiveBatchVerifier's cutover must come from measurement, not
assertion (VERDICT r03 weak #5): the crossover lane count is
``device_dispatch_floor / host_per_verify_cost``, both of which depend on
the actual chip, tunnel, and host CPU.  ``bench.py`` measures both on the
target platform and persists them here; verifier construction reads them.

The file lives next to the persistent XLA cache — same lifecycle: valid
until the hardware or the kernels change, cheap to regenerate (one bench
run), absent on a fresh checkout (the verifier then uses a conservative
static default).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

_DEFAULT_PATH = os.path.expanduser("~/.cache/go_ibft_tpu/calibration.json")

# Conservative static fallback when no measurement exists: past the
# smallest pad bucket the fused dispatch has historically beaten the
# native host loop on a live chip (docs/PERFORMANCE.md); a wrong guess
# here costs latency, never correctness (both routes are differential-
# tested equal).
DEFAULT_CUTOVER_LANES = 16


def _path() -> str:
    return os.environ.get("GO_IBFT_CALIBRATION_FILE", _DEFAULT_PATH)


def load_calibration() -> Optional[dict]:
    """The persisted measurement record, or None."""
    try:
        with open(_path()) as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None


def save_calibration(record: dict) -> None:
    path = _path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        json.dump(record, fh, indent=1)


def derive_cutover(
    device_floor_ms: float, host_per_verify_ms: float, max_lanes: int
) -> int:
    """Crossover lane count: smallest batch where the (latency-bound,
    lane-count-flat) device dispatch beats ``n`` sequential host verifies."""
    if host_per_verify_ms <= 0:
        return DEFAULT_CUTOVER_LANES
    n = int(device_floor_ms / host_per_verify_ms) + 1
    return max(1, min(n, max_lanes))


# Below this many lanes projected to arrive WITHIN the window ceiling,
# waiting the ceiling is not earning batching — flush eagerly instead.
# Half the default adaptive cutover: a sub-cutover gain is host-routed
# one message at a time anyway.
MIN_GAIN_LANES = 8


def calibrated_window(
    rate_per_s: Optional[float],
    pending: int,
    target: int,
    max_window_s: float,
    min_window_s: float = 0.0,
    min_gain_lanes: int = MIN_GAIN_LANES,
) -> float:
    """The ONE window policy shared by the per-stream calibrator and the
    scheduler's aggregate-rate projection:

    * no measured rate: the ceiling (the conservative prior — exactly
      yesterday's fixed window);
    * the remaining batch projects to fill within the ceiling: wait
      exactly the projection (the batch will genuinely fill; waiting is
      earning batching);
    * it will NOT fill within the ceiling, but the ceiling still gains
      at least ``min_gain_lanes``: wait the ceiling — a sustained
      device-sized flood that fills most-but-not-all of the batch must
      keep coalescing, not collapse to per-message flushes (the cliff a
      naive "too slow -> flush now" rule creates);
    * the ceiling would gain almost nothing (a trickle): flush eagerly
      instead of idling out the window for a handful of lanes.
    """
    if rate_per_s is None or rate_per_s <= 0:
        return max_window_s
    remaining = max(0, target - pending)
    projected = remaining / rate_per_s
    if projected <= max_window_s:
        return max(min_window_s, projected)
    if max_window_s * rate_per_s >= min_gain_lanes:
        return max_window_s
    return min_window_s


class ArrivalCalibrator:
    """EWMA inter-arrival model driving per-stream coalescing windows.

    The fixed 2 ms coalescing window (``BatchingIngress.max_delay``,
    ``TenantScheduler.window_s``) charges every batch the same wait
    regardless of how fast its stream actually arrives — a flood fills
    the batch in microseconds and then idles out the window's tail, a
    trickle waits the full window for company that never comes.  This
    model replaces the constant with a measurement: an exponentially
    weighted mean of inter-arrival gaps (per stream/tenant), fed to
    :func:`calibrated_window` (projection when the batch will fill,
    ceiling when a flood merely can't fill ALL of it, eager only when
    the ceiling would gain almost nothing).

    A wrong estimate costs latency, never correctness: the window only
    decides WHEN a flush fires, and an ``idle_reset_s`` gap drops the
    model back to cold so a stale flood-era estimate cannot linger into
    a quiet period.  Thread-safe (ingress observes from transport
    threads; the scheduler thread reads windows).
    """

    def __init__(
        self,
        *,
        alpha: float = 0.2,
        max_window_s: float = 0.002,
        min_window_s: float = 0.0,
        idle_reset_s: float = 0.25,
    ):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = alpha
        self.max_window_s = max_window_s
        self.min_window_s = min_window_s
        self.idle_reset_s = idle_reset_s
        self._lock = threading.Lock()
        self._last: Optional[float] = None
        self._ewma_dt: Optional[float] = None
        self.observed = 0

    def observe(self, n: int = 1, now: Optional[float] = None) -> None:
        """Record an arrival burst of ``n`` lanes at ``now``."""
        if n <= 0:
            return
        if now is None:
            now = time.monotonic()
        with self._lock:
            if self._last is not None:
                gap = now - self._last
                if gap > self.idle_reset_s:
                    # Idle gap: the old rate is history, not evidence.
                    self._ewma_dt = None
                else:
                    dt = max(gap, 0.0) / n
                    self._ewma_dt = (
                        dt
                        if self._ewma_dt is None
                        else self.alpha * dt + (1 - self.alpha) * self._ewma_dt
                    )
            self._last = now
            self.observed += n

    def rate_per_s(self) -> Optional[float]:
        with self._lock:
            if self._ewma_dt is None or self._ewma_dt <= 0:
                return None
            return 1.0 / self._ewma_dt

    def window(self, pending: int, target: int) -> float:
        """Recommended coalescing wait with ``pending`` lanes already
        buffered toward a ``target``-lane batch (policy:
        :func:`calibrated_window`)."""
        return calibrated_window(
            self.rate_per_s(),
            pending,
            target,
            self.max_window_s,
            self.min_window_s,
        )

    def stats(self) -> dict:
        with self._lock:
            dt = self._ewma_dt
        return {
            "observed": self.observed,
            "ewma_inter_arrival_us": None if dt is None else round(dt * 1e6, 3),
            "rate_per_s": None if dt is None or dt <= 0 else round(1.0 / dt, 1),
        }


def measured_cutover() -> Optional[int]:
    """Cutover from the persisted measurement, if one exists.

    Records measured on a non-TPU platform are ignored: a CPU "device
    floor" is enormous and would derive a cutover that silently disables
    the device path on a later live-TPU run sharing the same home dir.
    (bench.py only saves on TPU runs; this is the belt to that suspender —
    checked against the record, not ``jax.default_backend()``, so verifier
    construction never forces backend init, which can HANG on a dead
    tunnel.)
    """
    record = load_calibration()
    if record is None:
        return None
    if record.get("platform") not in ("tpu", "axon"):
        return None
    value = record.get("cutover_lanes")
    return value if isinstance(value, int) and value >= 1 else None
