"""Measured host/device routing constants.

The AdaptiveBatchVerifier's cutover must come from measurement, not
assertion (VERDICT r03 weak #5): the crossover lane count is
``device_dispatch_floor / host_per_verify_cost``, both of which depend on
the actual chip, tunnel, and host CPU.  ``bench.py`` measures both on the
target platform and persists them here; verifier construction reads them.

The file lives next to the persistent XLA cache — same lifecycle: valid
until the hardware or the kernels change, cheap to regenerate (one bench
run), absent on a fresh checkout (the verifier then uses a conservative
static default).
"""

from __future__ import annotations

import json
import os
from typing import Optional

_DEFAULT_PATH = os.path.expanduser("~/.cache/go_ibft_tpu/calibration.json")

# Conservative static fallback when no measurement exists: past the
# smallest pad bucket the fused dispatch has historically beaten the
# native host loop on a live chip (docs/PERFORMANCE.md); a wrong guess
# here costs latency, never correctness (both routes are differential-
# tested equal).
DEFAULT_CUTOVER_LANES = 16


def _path() -> str:
    return os.environ.get("GO_IBFT_CALIBRATION_FILE", _DEFAULT_PATH)


def load_calibration() -> Optional[dict]:
    """The persisted measurement record, or None."""
    try:
        with open(_path()) as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None


def save_calibration(record: dict) -> None:
    path = _path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        json.dump(record, fh, indent=1)


def derive_cutover(
    device_floor_ms: float, host_per_verify_ms: float, max_lanes: int
) -> int:
    """Crossover lane count: smallest batch where the (latency-bound,
    lane-count-flat) device dispatch beats ``n`` sequential host verifies."""
    if host_per_verify_ms <= 0:
        return DEFAULT_CUTOVER_LANES
    n = int(device_floor_ms / host_per_verify_ms) + 1
    return max(1, min(n, max_lanes))


def measured_cutover() -> Optional[int]:
    """Cutover from the persisted measurement, if one exists.

    Records measured on a non-TPU platform are ignored: a CPU "device
    floor" is enormous and would derive a cutover that silently disables
    the device path on a later live-TPU run sharing the same home dir.
    (bench.py only saves on TPU runs; this is the belt to that suspender —
    checked against the record, not ``jax.default_backend()``, so verifier
    construction never forces backend init, which can HANG on a dead
    tunnel.)
    """
    record = load_calibration()
    if record is None:
        return None
    if record.get("platform") not in ("tpu", "axon"):
        return None
    value = record.get("cutover_lanes")
    return value if isinstance(value, int) and value >= 1 else None
