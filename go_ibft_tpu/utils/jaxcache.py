"""Persistent XLA compilation cache management.

The verification kernels are 256-step EC ladders — minutes to compile cold,
milliseconds to load from the persistent cache.  A consensus engine cannot
stall mid-round for a compile (the round timer would expire, SURVEY.md §7
(d)), so anything constructing device verifiers should enable the cache and
pre-warm the hot shapes.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_DEFAULT_DIR = os.path.expanduser("~/.cache/go_ibft_tpu/xla")

_enabled = False


def enable_persistent_cache(path: Optional[str] = None) -> None:
    """Idempotently enable the JAX persistent compilation cache.

    Respects an existing user-configured cache dir; otherwise uses
    ``~/.cache/go_ibft_tpu/xla`` (override with ``path`` or the
    ``JAX_COMPILATION_CACHE_DIR`` env var, which JAX reads natively).
    """
    global _enabled
    if _enabled:
        return
    current = jax.config.jax_compilation_cache_dir
    if current is None:
        target = path or os.environ.get("JAX_COMPILATION_CACHE_DIR") or _DEFAULT_DIR
        os.makedirs(target, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", target)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
    _enabled = True
