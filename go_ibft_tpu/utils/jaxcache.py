"""Persistent XLA compilation cache management.

The verification kernels are 256-step EC ladders — minutes to compile cold,
milliseconds to load from the persistent cache.  A consensus engine cannot
stall mid-round for a compile (the round timer would expire, SURVEY.md §7
(d)), so anything constructing device verifiers should enable the cache and
pre-warm the hot shapes.

The cache directory resolves ``path`` argument > ``GO_IBFT_CACHE_DIR`` >
``JAX_COMPILATION_CACHE_DIR`` (JAX reads the latter natively) > the default
``~/.cache/go_ibft_tpu/xla``.  Growth is bounded by the same posture as the
backend probe cache (obs/evidence.py): entries older than
``GO_IBFT_CACHE_TTL_S`` are dropped, and when the directory exceeds
``GO_IBFT_CACHE_MAX_BYTES`` the oldest entries are evicted first.  JAX's
own cache key covers jax version / backend / XLA flags, so entries written
by an older jax can never be *loaded* as a wrong program — the TTL merely
stops them from squatting on disk after a version bump.
"""

from __future__ import annotations

import os
import time
from typing import Optional, Tuple

import jax

_DEFAULT_DIR = os.path.expanduser("~/.cache/go_ibft_tpu/xla")

# Bounded-growth defaults: generous enough that a full warm_kernels sweep
# (every pinned family, multiple shape buckets, ~tens of MB each) never
# evicts itself, small enough that years of jax bumps cannot fill a disk.
DEFAULT_MAX_BYTES = 4 << 30  # 4 GiB
DEFAULT_TTL_S = 30 * 24 * 3600.0  # 30 days

_enabled = False


def resolve_cache_dir(path: Optional[str] = None) -> str:
    """The cache directory ``enable_persistent_cache`` would select."""
    current = jax.config.jax_compilation_cache_dir
    if current is not None:
        return current
    return (
        path
        or os.environ.get("GO_IBFT_CACHE_DIR")
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or _DEFAULT_DIR
    )


def enable_persistent_cache(path: Optional[str] = None) -> str:
    """Idempotently enable the JAX persistent compilation cache.

    Respects an existing user-configured cache dir; otherwise resolves via
    :func:`resolve_cache_dir`.  Prunes the directory once per process (TTL
    + size bound) before handing it to jax.  Returns the effective dir.
    """
    global _enabled
    target = resolve_cache_dir(path)
    if _enabled:
        return target
    if jax.config.jax_compilation_cache_dir is None:
        os.makedirs(target, exist_ok=True)
        prune_cache(target)
        jax.config.update("jax_compilation_cache_dir", target)
    # Floor below which compiles are not persisted (they cost less than
    # the disk round-trip).  ``GO_IBFT_CACHE_MIN_COMPILE_S=0`` persists
    # everything — the CI boot check uses it so even the sub-second
    # digest program proves a second-boot cache load.
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        float(os.environ.get("GO_IBFT_CACHE_MIN_COMPILE_S", 1)),
    )
    _enabled = True
    return target


def prune_cache(
    path: Optional[str] = None,
    *,
    max_bytes: Optional[int] = None,
    max_age_s: Optional[float] = None,
    now: Optional[float] = None,
) -> Tuple[int, int]:
    """Bound the persistent cache: drop stale entries, evict oldest-first.

    Runs once per process from :func:`enable_persistent_cache` (explicit
    calls always run).  Never raises — a concurrently-pruning sibling
    process or a read-only cache degrades to a no-op, mirroring the probe
    cache's never-fault posture.  Returns ``(files_removed, bytes_removed)``.
    """
    target = path or resolve_cache_dir()
    if max_bytes is None:
        max_bytes = int(
            os.environ.get("GO_IBFT_CACHE_MAX_BYTES", DEFAULT_MAX_BYTES)
        )
    if max_age_s is None:
        max_age_s = float(os.environ.get("GO_IBFT_CACHE_TTL_S", DEFAULT_TTL_S))
    ts = time.time() if now is None else now
    entries = []  # (mtime, size, path)
    try:
        for root, _dirs, files in os.walk(target):
            for name in files:
                p = os.path.join(root, name)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                entries.append((st.st_mtime, st.st_size, p))
    except OSError:
        return (0, 0)
    removed = freed = 0

    def _rm(size: int, p: str) -> None:
        nonlocal removed, freed
        try:
            os.remove(p)
        except OSError:
            return
        removed += 1
        freed += size

    live = []
    for mtime, size, p in entries:
        if max_age_s > 0 and ts - mtime > max_age_s:
            _rm(size, p)
        else:
            live.append((mtime, size, p))
    if max_bytes > 0:
        total = sum(size for _m, size, _p in live)
        for mtime, size, p in sorted(live):  # oldest first
            if total <= max_bytes:
                break
            _rm(size, p)
            total -= size
    return (removed, freed)
