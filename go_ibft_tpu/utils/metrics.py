"""Minimal metrics registry.

The reference emits exactly one gauge family via armon/go-metrics
(core/ibft.go:138-141): ``go-ibft.{sequence|round}.duration``.  This registry
keeps that surface (plus histograms used by the batch verifier for per-batch
device latency) without external dependencies; an embedder can attach a sink
to export to Prometheus or anything else.
"""

from __future__ import annotations

import threading
from collections import defaultdict, deque
from typing import Callable, Optional, Sequence

# Bounded so a long-running validator (many samples per round, forever)
# cannot leak memory; scrapers wanting full fidelity attach a sink.
_HISTOGRAM_WINDOW = 4096

_lock = threading.Lock()
_gauges: dict[tuple[str, ...], float] = {}
_histograms: dict[tuple[str, ...], deque[float]] = defaultdict(
    lambda: deque(maxlen=_HISTOGRAM_WINDOW)
)
_counters: dict[tuple[str, ...], int] = defaultdict(int)
_sink: Optional[Callable[[str, tuple[str, ...], float], None]] = None


def set_sink(sink: Optional[Callable[[str, tuple[str, ...], float], None]]) -> None:
    """Attach a callback receiving (kind, key, value) for every sample."""
    global _sink
    _sink = sink


def set_gauge(key: Sequence[str], value: float) -> None:
    """Set a gauge (reference core/ibft.go:138-141 SetMeasurementTime)."""
    key = tuple(key)
    with _lock:
        _gauges[key] = value
    if _sink is not None:
        _sink("gauge", key, value)


def get_gauge(key: Sequence[str]) -> Optional[float]:
    with _lock:
        return _gauges.get(tuple(key))


def observe(key: Sequence[str], value: float) -> None:
    """Record a histogram sample (e.g. batch-verify kernel latency)."""
    key = tuple(key)
    with _lock:
        _histograms[key].append(value)
    if _sink is not None:
        _sink("histogram", key, value)


def get_histogram(key: Sequence[str]) -> list[float]:
    with _lock:
        return list(_histograms.get(tuple(key), ()))


def inc_counter(key: Sequence[str], n: int = 1) -> int:
    """Increment a monotonic counter (circuit-breaker transitions, quarantined
    lanes, transport retries — the degraded-mode bookkeeping of
    :mod:`go_ibft_tpu.verify` and :mod:`go_ibft_tpu.chaos`).  Returns the new
    value."""
    key = tuple(key)
    with _lock:
        _counters[key] += n
        value = _counters[key]
    if _sink is not None:
        _sink("counter", key, float(value))
    return value


def get_counter(key: Sequence[str]) -> int:
    with _lock:
        return _counters.get(tuple(key), 0)


def counters_snapshot(prefix: Sequence[str] = ()) -> dict[tuple[str, ...], int]:
    """All counters under ``prefix`` (empty prefix = everything)."""
    prefix = tuple(prefix)
    with _lock:
        return {
            k: v for k, v in _counters.items() if k[: len(prefix)] == prefix
        }


def summarize(key: Sequence[str]) -> Optional[dict]:
    """Histogram summary ``{count, p50, mean, max}`` or ``None`` if empty.

    First-class evidence hook for the packing/pipelining attribution keys
    (``verify/pipeline.py::PACK_MS_KEY`` etc.): bench lines and tests read
    one summary dict instead of re-deriving percentiles from raw samples.
    """
    samples = get_histogram(key)
    if not samples:
        return None
    ordered = sorted(samples)
    return {
        "count": len(ordered),
        "p50": ordered[len(ordered) // 2],
        "mean": sum(ordered) / len(ordered),
        "max": ordered[-1],
    }


def reset() -> None:
    """Clear all recorded metrics (test support)."""
    with _lock:
        _gauges.clear()
        _histograms.clear()
        _counters.clear()
