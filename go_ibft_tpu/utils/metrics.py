"""Minimal metrics registry.

The reference emits exactly one gauge family via armon/go-metrics
(core/ibft.go:138-141): ``go-ibft.{sequence|round}.duration``.  This registry
keeps that surface (plus histograms used by the batch verifier for per-batch
device latency) without external dependencies; an embedder can attach a sink
to export to Prometheus or anything else.

**Fixed-bucket latency histograms** (cross-process telemetry plane): the
windowed deques above lose history and cannot be scraped incrementally, so
the live ``/metrics`` endpoint (:mod:`go_ibft_tpu.obs.metrics_export`)
reads a second family — classic Prometheus-style cumulative-bucket
histograms recorded at the hot seams (accept->finalize, verify drains per
route, per-tenant scheduler drains, proof serving, WAL appends).  They are
OFF by default behind one module-global predicate, exactly like the trace
recorder: a disabled ``observe_fixed`` site costs one attribute read and
the bench contract pins the tax under 5% of the config #1 happy path.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from collections import defaultdict, deque
from typing import Callable, Optional, Sequence

# Bounded so a long-running validator (many samples per round, forever)
# cannot leak memory; scrapers wanting full fidelity attach a sink.
_HISTOGRAM_WINDOW = 4096

_lock = threading.Lock()
_gauges: dict[tuple[str, ...], float] = {}
_histograms: dict[tuple[str, ...], deque[float]] = defaultdict(
    lambda: deque(maxlen=_HISTOGRAM_WINDOW)
)
_counters: dict[tuple[str, ...], int] = defaultdict(int)
_sink: Optional[Callable[[str, tuple[str, ...], float], None]] = None


def set_sink(sink: Optional[Callable[[str, tuple[str, ...], float], None]]) -> None:
    """Attach a callback receiving (kind, key, value) for every sample."""
    global _sink
    _sink = sink


def set_gauge(key: Sequence[str], value: float) -> None:
    """Set a gauge (reference core/ibft.go:138-141 SetMeasurementTime)."""
    key = tuple(key)
    with _lock:
        _gauges[key] = value
    if _sink is not None:
        _sink("gauge", key, value)


def get_gauge(key: Sequence[str]) -> Optional[float]:
    with _lock:
        return _gauges.get(tuple(key))


def observe(key: Sequence[str], value: float) -> None:
    """Record a histogram sample (e.g. batch-verify kernel latency)."""
    key = tuple(key)
    with _lock:
        _histograms[key].append(value)
    if _sink is not None:
        _sink("histogram", key, value)


def get_histogram(key: Sequence[str]) -> list[float]:
    with _lock:
        return list(_histograms.get(tuple(key), ()))


def inc_counter(key: Sequence[str], n: int = 1) -> int:
    """Increment a monotonic counter (circuit-breaker transitions, quarantined
    lanes, transport retries — the degraded-mode bookkeeping of
    :mod:`go_ibft_tpu.verify` and :mod:`go_ibft_tpu.chaos`).  Returns the new
    value."""
    key = tuple(key)
    with _lock:
        _counters[key] += n
        value = _counters[key]
    if _sink is not None:
        _sink("counter", key, float(value))
    return value


def get_counter(key: Sequence[str]) -> int:
    with _lock:
        return _counters.get(tuple(key), 0)


def gauges_snapshot() -> dict[tuple[str, ...], float]:
    """All gauges (scrape support for the /metrics exposition)."""
    with _lock:
        return dict(_gauges)


def histograms_snapshot() -> dict[tuple[str, ...], list[float]]:
    """All windowed histograms as lists (scrape support)."""
    with _lock:
        return {k: list(v) for k, v in _histograms.items()}


def counters_snapshot(prefix: Sequence[str] = ()) -> dict[tuple[str, ...], int]:
    """All counters under ``prefix`` (empty prefix = everything)."""
    prefix = tuple(prefix)
    with _lock:
        return {
            k: v for k, v in _counters.items() if k[: len(prefix)] == prefix
        }


def percentile(samples: Sequence[float], q: float) -> Optional[float]:
    """Sorted-index percentile (no interpolation), ``None`` when empty.

    THE percentile definition for this repo's evidence: /metrics summary
    gauges, the SLO soak records, and the smoke scripts all call this so
    a p99 always means the same sample.
    """
    if not samples:
        return None
    ordered = sorted(samples)
    return ordered[min(len(ordered) - 1, int(q * len(ordered)))]


def summarize(key: Sequence[str]) -> Optional[dict]:
    """Histogram summary ``{count, p50, mean, max}`` or ``None`` if empty.

    First-class evidence hook for the packing/pipelining attribution keys
    (``verify/pipeline.py::PACK_MS_KEY`` etc.): bench lines and tests read
    one summary dict instead of re-deriving percentiles from raw samples.
    """
    samples = get_histogram(key)
    if not samples:
        return None
    ordered = sorted(samples)
    return {
        "count": len(ordered),
        "p50": ordered[len(ordered) // 2],
        "mean": sum(ordered) / len(ordered),
        "max": ordered[-1],
    }


def reset() -> None:
    """Clear all recorded metrics (test support)."""
    with _lock:
        _gauges.clear()
        _histograms.clear()
        _counters.clear()
        _fixed.clear()


# ---------------------------------------------------------------------------
# fixed-bucket latency histograms (scrapeable; off unless enabled)
# ---------------------------------------------------------------------------

# Default latency buckets in milliseconds: microsecond WAL appends through
# multi-second degraded drains, roughly x2.5 per step (the Prometheus
# convention), plus the implicit +Inf bucket.
DEFAULT_BUCKETS_MS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
    100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)

# THE predicate: every observe_fixed site checks this one global.
_fixed_enabled = False
_fixed: dict[tuple[str, ...], "_FixedHistogram"] = {}


class _FixedHistogram:
    __slots__ = ("bounds", "counts", "total", "sum")

    def __init__(self, bounds: Sequence[float]) -> None:
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self.total = 0
        self.sum = 0.0


def enable_fixed_histograms() -> None:
    """Turn the fixed-bucket family on (the /metrics mount does this)."""
    global _fixed_enabled
    _fixed_enabled = True


def disable_fixed_histograms() -> None:
    """Back to the no-op path; recorded data stays until :func:`reset`."""
    global _fixed_enabled
    _fixed_enabled = False


def fixed_histograms_enabled() -> bool:
    return _fixed_enabled


def observe_fixed(
    key: Sequence[str],
    value_ms: float,
    bounds: Sequence[float] = DEFAULT_BUCKETS_MS,
) -> None:
    """Record one latency sample into a cumulative-bucket histogram.

    No-op (one global read) unless :func:`enable_fixed_histograms` ran —
    the hot seams call this unconditionally, like ``trace.span``.
    """
    if not _fixed_enabled:
        return
    key = tuple(key)
    with _lock:
        hist = _fixed.get(key)
        if hist is None:
            hist = _fixed[key] = _FixedHistogram(bounds)
        hist.counts[bisect_left(hist.bounds, value_ms)] += 1
        hist.total += 1
        hist.sum += value_ms


def fixed_histograms_snapshot() -> dict[tuple[str, ...], dict]:
    """``{key: {"bounds", "counts", "count", "sum"}}`` — counts are
    per-bucket (not yet cumulative; the Prometheus renderer accumulates)."""
    with _lock:
        return {
            key: {
                "bounds": hist.bounds,
                "counts": tuple(hist.counts),
                "count": hist.total,
                "sum": hist.sum,
            }
            for key, hist in _fixed.items()
        }
