"""Benchmark harness: workload builders + timing for BASELINE.md configs."""

from .workload import (
    RoundWorkload,
    SealLaneWorkload,
    SignedRound,
    build_round_workload,
    build_seal_lane_workload,
    build_signed_round,
)

__all__ = [
    "RoundWorkload",
    "SealLaneWorkload",
    "SignedRound",
    "build_round_workload",
    "build_seal_lane_workload",
    "build_signed_round",
]
