"""Benchmark harness: workload builders + timing for BASELINE.md configs."""

from .workload import RoundWorkload, build_round_workload

__all__ = ["RoundWorkload", "build_round_workload"]
