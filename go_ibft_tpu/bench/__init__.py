"""Benchmark harness: workload builders + timing for BASELINE.md configs."""

from .workload import (
    RoundWorkload,
    SignedRound,
    build_round_workload,
    build_signed_round,
)

__all__ = [
    "RoundWorkload",
    "SignedRound",
    "build_round_workload",
    "build_signed_round",
]
