"""Deterministic signed-round workload builder for benchmarks and dryruns.

Builds what a real IBFT round at height ``h`` produces (BASELINE.md
configs): one PREPARE envelope and one COMMIT seal per validator, all
genuinely ECDSA-signed, packed into the static-shape device arrays the
fused quorum kernels consume.  A ``corrupt_frac`` knob flips signature
bytes on a deterministic subset — the Byzantine-mix config — whose lanes
the kernels must mask out.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

import numpy as np

from ..crypto import PrivateKey
from ..crypto.backend import ECDSABackend, proposal_hash_of
from ..messages.helpers import CommittedSeal, extract_committed_seal
from ..messages.wire import Proposal, View
from ..ops.quorum import split_power
from ..verify.batch import (
    pack_seal_batch,
    pack_sender_batch,
    pack_validator_table,
)

_key_cache: Dict[Tuple[int, int], list] = {}


def _keys(n: int, seed: int) -> list:
    hit = _key_cache.get((n, seed))
    if hit is None:
        hit = [
            PrivateKey.from_seed(b"bench-%d-%d" % (seed, i)) for i in range(n)
        ]
        _key_cache[(n, seed)] = hit
    return hit


@dataclass
class SignedRound:
    """One round's raw signed material, BEFORE device packing.

    The unpacked twin of :class:`RoundWorkload`: the pipelined benchmarks
    pack these per height *inside* the dispatch pipeline (packing is part
    of what they measure/overlap), while :func:`build_round_workload`
    packs eagerly for callers that only time the kernels.
    """

    n_validators: int
    height: int
    prepares: list
    seals: list
    proposal_hash: bytes
    table: np.ndarray  # (V, 5) uint32
    powers_lo: np.ndarray
    powers_hi: np.ndarray
    thr_lo: int
    thr_hi: int
    expected_prepare_mask: np.ndarray
    expected_seal_mask: np.ndarray

    def pack(self, pad_lanes: int = 0) -> "RoundWorkload":
        """Pack PREPARE envelopes + COMMIT seals to device-ready arrays."""
        return RoundWorkload(
            n_validators=self.n_validators,
            height=self.height,
            prepare=pack_sender_batch(self.prepares, pad_lanes),
            seals=pack_seal_batch(self.proposal_hash, self.seals, pad_lanes),
            table=self.table,
            powers_lo=self.powers_lo,
            powers_hi=self.powers_hi,
            thr_lo=self.thr_lo,
            thr_hi=self.thr_hi,
            expected_prepare_mask=self.expected_prepare_mask,
            expected_seal_mask=self.expected_seal_mask,
        )


@dataclass
class RoundWorkload:
    """Device-ready arrays for one round's PREPARE + COMMIT phases."""

    n_validators: int
    height: int
    # prepare phase: (blocks, counts, r, s, v, senders, live)
    prepare: tuple
    # commit-seal phase: (hash_words, r, s, v, signers, live)
    seals: tuple
    table: np.ndarray  # (V, 5) uint32
    powers_lo: np.ndarray
    powers_hi: np.ndarray
    thr_lo: int
    thr_hi: int
    expected_prepare_mask: np.ndarray
    expected_seal_mask: np.ndarray


def build_signed_round(
    n_validators: int,
    *,
    height: int = 1,
    corrupt_frac: float = 0.0,
    seed: int = 0,
) -> SignedRound:
    """Build one signed (unpacked) round: real keys, real ECDSA envelopes
    and seals, deterministic corruption for the Byzantine variants."""
    keys = _keys(n_validators, seed)
    powers = {k.address: 1 for k in keys}
    src = ECDSABackend.static_validators(powers)
    backends = [ECDSABackend(k, src) for k in keys]
    view = View(height=height, round=0)
    proposal = Proposal(raw_proposal=b"bench block %d" % height, round=0)
    phash = proposal_hash_of(proposal)

    prepares = [b.build_prepare_message(phash, view) for b in backends]
    commits = [b.build_commit_message(phash, view) for b in backends]
    seals = [extract_committed_seal(c) for c in commits]

    n_corrupt = int(n_validators * corrupt_frac)
    rng = np.random.default_rng(seed)
    corrupt_idx = rng.choice(n_validators, size=n_corrupt, replace=False)
    expected_prepare = np.ones(n_validators, dtype=bool)
    expected_seal = np.ones(n_validators, dtype=bool)
    for i in corrupt_idx:
        sig = bytearray(prepares[i].signature)
        sig[5] ^= 0xFF  # mangle r -> recovers to a different key
        prepares[i].signature = bytes(sig)
        expected_prepare[i] = False
        seal_sig = bytearray(seals[i].signature)
        seal_sig[5] ^= 0xFF
        seals[i] = CommittedSeal(signer=seals[i].signer, signature=bytes(seal_sig))
        expected_seal[i] = False

    table = pack_validator_table([k.address for k in keys])
    lo_hi = [split_power(powers[k.address]) for k in keys]
    v = table.shape[0]
    powers_lo = np.zeros(v, dtype=np.int32)
    powers_hi = np.zeros(v, dtype=np.int32)
    powers_lo[:n_validators] = [lh[0] for lh in lo_hi]
    powers_hi[:n_validators] = [lh[1] for lh in lo_hi]
    total = sum(powers.values())
    threshold = (2 * total) // 3 + 1
    thr_lo, thr_hi = threshold & 0xFFFF, threshold >> 16

    return SignedRound(
        n_validators=n_validators,
        height=height,
        prepares=prepares,
        seals=seals,
        proposal_hash=phash,
        table=table,
        powers_lo=powers_lo,
        powers_hi=powers_hi,
        thr_lo=thr_lo,
        thr_hi=thr_hi,
        expected_prepare_mask=expected_prepare,
        expected_seal_mask=expected_seal,
    )


def build_round_workload(
    n_validators: int,
    *,
    height: int = 1,
    corrupt_frac: float = 0.0,
    seed: int = 0,
    pad_lanes: int = 0,
) -> RoundWorkload:
    return build_signed_round(
        n_validators, height=height, corrupt_frac=corrupt_frac, seed=seed
    ).pack(pad_lanes)


@dataclass
class SealLaneWorkload:
    """A multi-height committed-seal lane set (the block-sync drain shape).

    ``lanes`` are ``(proposal_hash, seal)`` pairs spanning several heights
    (each height signs its own hash — the per-lane-hash shape
    ``verify_seal_lanes`` drains); ``expected_mask`` is the sequential
    oracle's verdict per lane.  Distinct signatures are bounded by
    ``n_validators x heights`` and TILED out to ``n_lanes`` — duplicated
    lanes cost the verifier exactly the same ladder work as distinct ones
    (no dedup anywhere in the drain), so throughput measurements stay
    honest while host signing stays off the critical path.
    """

    lanes: list  # [(proposal_hash, CommittedSeal), ...]
    height: int  # representative height for the (static) validator table
    validators: object  # ValidatorSource (height -> {address: power})
    expected_mask: np.ndarray


def build_seal_lane_workload(
    n_lanes: int,
    *,
    n_validators: int = 100,
    heights: int = 4,
    corrupt_frac: float = 0.0,
    seed: int = 0,
) -> SealLaneWorkload:
    """Build ``n_lanes`` seal lanes across ``heights`` proposal hashes."""
    keys = _keys(n_validators, seed)
    powers = {k.address: 1 for k in keys}
    src = ECDSABackend.static_validators(powers)
    backends = [ECDSABackend(k, src) for k in keys]
    distinct: list = []
    ok: list = []
    rng = np.random.default_rng(seed)
    for h in range(1, heights + 1):
        proposal = Proposal(raw_proposal=b"mesh bench block %d" % h, round=0)
        phash = proposal_hash_of(proposal)
        view = View(height=h, round=0)
        for b in backends:
            seal = extract_committed_seal(b.build_commit_message(phash, view))
            good = True
            if corrupt_frac and rng.random() < corrupt_frac:
                sig = bytearray(seal.signature)
                sig[5] ^= 0xFF
                seal = CommittedSeal(signer=seal.signer, signature=bytes(sig))
                good = False
            distinct.append((phash, seal))
            ok.append(good)
            if len(distinct) >= n_lanes:
                break
        if len(distinct) >= n_lanes:
            break
    reps = (n_lanes + len(distinct) - 1) // len(distinct)
    lanes = (distinct * reps)[:n_lanes]
    expected = (np.asarray(ok, dtype=bool).tolist() * reps)[:n_lanes]
    return SealLaneWorkload(
        lanes=lanes,
        height=1,
        validators=src,
        expected_mask=np.asarray(expected, dtype=bool),
    )
