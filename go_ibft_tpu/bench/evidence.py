"""Opportunistic TPU evidence capture for CPU-fallback bench runs.

Rounds 1-5 lost every TPU window that opened mid-round: ``bench.py``
probes ONCE at startup (retries burn budget against outages that are
either instant or hours long), so a tunnel that woke up after the probe
contributed nothing.  A fallback run now re-probes near its end and, when
the default backend came alive, relaunches the bench in a FRESH
subprocess — this process pinned ``jax_platforms=cpu`` at startup and can
never use the chip itself — appending the child's JSON lines to
``evidence_tpu.jsonl`` (the same artifact ``scripts/tpu_evidence.sh``
builds).

The child emits the same line schema as the parent, so first-class
packing/pipelining attribution (``pack_ms``, ``pack_lanes_per_s``,
``pipeline_speedup``, ``overlap_efficiency`` on the config #3 line — CPU
and TPU variants alike) is captured here without any extra plumbing.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional, Tuple

from ..utils.probe import probe_default_backend

EVIDENCE_PATH = "evidence_tpu.jsonl"


def reprobe_and_capture(
    remaining_s: float,
    bench_path: str,
    evidence_path: str = EVIDENCE_PATH,
) -> Tuple[Optional[str], str]:
    """Late re-probe; on a live TPU, run ``bench.py`` in a subprocess.

    Returns ``(platform_or_None, detail)``: platform is the live TPU
    platform name when evidence was captured (detail names the artifact),
    else ``None`` with a one-line reason.  Budget discipline mirrors the
    parent: the probe is clamped well under ``remaining_s`` and the child
    gets what is left minus a reserve, so the parent always finishes its
    own report.
    """
    if remaining_s < 240.0:
        return None, f"skipped: {remaining_s:.0f}s of budget left"
    platform, detail = probe_default_backend(min(45.0, remaining_s * 0.15))
    if platform not in ("tpu", "axon"):
        return None, detail if platform is None else f"backend is {platform!r}"
    child_budget = max(120.0, remaining_s - 90.0)
    env = dict(os.environ, GO_IBFT_BENCH_BUDGET_S=str(int(child_budget)))
    env.pop("JAX_PLATFORMS", None)  # the child must see the live default
    try:
        with open(evidence_path, "a") as fh:
            subprocess.run(
                [sys.executable, bench_path],
                stdout=fh,
                stderr=subprocess.DEVNULL,
                timeout=child_budget + 30.0,
                env=env,
                cwd=os.path.dirname(os.path.abspath(bench_path)) or ".",
                check=False,
            )
    except (OSError, subprocess.TimeoutExpired) as err:
        return None, f"evidence run failed: {type(err).__name__}"
    return platform, evidence_path
