"""Superseded by :mod:`go_ibft_tpu.obs.evidence` (ISSUE 4).

The opportunistic TPU capture helper moved into the observability
subsystem alongside the fingerprint cache and the evidence writer; this
module remains as a re-export so older scripts and embedders keep
importing from the historical location.
"""

from ..obs.evidence import EVIDENCE_PATH, reprobe_and_capture

__all__ = ["EVIDENCE_PATH", "reprobe_and_capture"]
