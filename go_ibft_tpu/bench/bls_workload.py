"""BLS aggregate-COMMIT workload builder (BASELINE.md config #4).

Produces the packed device arrays for
:func:`go_ibft_tpu.ops.bls12_381.aggregate_verify_commit` plus a host
baseline timing (the pure-python oracle pairing) for ``vs_baseline``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, Tuple

import numpy as np

from ..crypto import bls as hbls
from ..ops import bls12_381 as dev

_key_cache: Dict[Tuple[int, int], list] = {}


def _bls_keys(n: int, seed: int) -> list:
    hit = _key_cache.get((n, seed))
    if hit is None:
        hit = [
            hbls.BLSPrivateKey.from_seed(b"bls-bench-%d-%d" % (seed, i))
            for i in range(n)
        ]
        _key_cache[(n, seed)] = hit
    return hit


@dataclass
class BLSRoundWorkload:
    n_validators: int
    args: tuple  # positional args for aggregate_verify_commit
    host_ms: float  # host oracle single aggregate-verify wall time


def build_bls_round_workload(
    n_validators: int, *, seed: int = 0, time_host: bool = True
) -> BLSRoundWorkload:
    keys = _bls_keys(n_validators, seed)
    message = b"bls bench proposal hash %d" % seed
    # pad the message to a 32-byte "proposal hash" shape
    message = (message + b"\x00" * 32)[:32]
    sigs = [k.sign(message) for k in keys]
    pubkeys = [k.pubkey for k in keys]

    host_ms = 0.0
    if time_host:
        t0 = time.perf_counter()
        assert hbls.aggregate_verify(
            pubkeys, message, hbls.aggregate_signatures(sigs)
        )
        host_ms = (time.perf_counter() - t0) * 1e3

    v = 1
    while v < n_validators:
        v *= 2
    v = max(v, 2)
    pad = v - n_validators
    pk_x, pk_y = dev.pack_g1_points(pubkeys + [None] * pad)
    sx0, sx1, sy0, sy1 = dev.pack_g2_points(sigs + [None] * pad)
    h = hbls.hash_to_g2(message)
    hx0, hx1, hy0, hy1 = dev.pack_g2_points([h])
    live = np.zeros(v, dtype=bool)
    live[:n_validators] = True
    args = (
        pk_x,
        pk_y,
        sx0,
        sx1,
        sy0,
        sy1,
        hx0[0],
        hx1[0],
        hy0[0],
        hy1[0],
        live,
    )
    return BLSRoundWorkload(
        n_validators=n_validators, args=args, host_ms=host_ms
    )
