"""Multi-tenant consensus: a process-wide verify scheduler (ISSUE 8).

Many independent chains/validator sets multiplexed onto shared hardware:
:class:`TenantScheduler` owns the device (or host-native) verify plane
and coalesces lanes from N concurrent ``ChainRunner``s into shared
batched dispatches, with deficit-round-robin fairness, per-chain
backpressure, and per-tenant latency SLO evidence.  See docs/TENANCY.md.
"""

from .dispatch import CoalescedDispatcher
from .scheduler import (
    COALESCED_REQUESTS_KEY,
    DISPATCHES_KEY,
    DRAIN_MS_KEY,
    FLUSH_FAULTS_KEY,
    PRIORITY_RANK,
    QUEUE_LANES_KEY,
    SHED_LANES_KEY,
    SchedQueueFull,
    TenantScheduler,
    TenantVerifierHandle,
)

__all__ = [
    "CoalescedDispatcher",
    "PRIORITY_RANK",
    "SchedQueueFull",
    "TenantScheduler",
    "TenantVerifierHandle",
    "QUEUE_LANES_KEY",
    "SHED_LANES_KEY",
    "DISPATCHES_KEY",
    "COALESCED_REQUESTS_KEY",
    "DRAIN_MS_KEY",
    "FLUSH_FAULTS_KEY",
]
