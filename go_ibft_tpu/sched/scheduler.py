"""TenantScheduler: process-wide verify scheduling for many chains.

Every ``ChainRunner`` so far owned a private verify ladder, so N
concurrent chains issued N small device dispatches per phase — exactly
the regime where batched signature verification wins (PAPERS.md
2302.00418) and exactly what "many chains, one device" must not do.  This
module lifts the verify data plane to PROCESS scope:

* each chain (tenant) registers once and receives a
  :class:`TenantVerifierHandle` — a drop-in
  :class:`~go_ibft_tpu.core.backend.BatchVerifier` that ``IBFT``,
  ``ChainRunner`` and ``SyncClient`` accept unchanged;
* handles submit verify requests into per-tenant queues; a dedicated
  scheduler thread coalesces queued lanes from ALL tenants into shared
  batched dispatches (:mod:`go_ibft_tpu.sched.dispatch` — the existing
  pinned kernels, one launch for many chains);
* **demand-aware flushing**: a flush fires when the coalesced batch
  reaches a full dispatch (bucket-full) or when the OLDEST queued request
  ages past the coalescing window — an idle tenant contributes nothing
  and therefore never stalls a hot one;
* **deficit-round-robin fairness with a hard starvation bound**: each
  flush serves the globally oldest queued request FIRST (so no request
  waits behind an unbounded stream of younger ones), then fills the
  dispatch by DRR over tenants (per-flush quantum, deficits capped), so a
  hot 100-validator tenant cannot crowd a 4-validator one out of the
  device;
* **priority classes (read-tier QoS, ISSUE 10)**: tenants register as
  ``"consensus"`` (default) or ``"read"``; selection is class-ordered —
  the oldest queued CONSENSUS request always ships first and consensus
  tenants fill the dispatch before any read-tier lane is considered, so
  the proof-serving read plane (:mod:`go_ibft_tpu.serve`) can flood the
  scheduler without ever starving a live round.  Within a class the
  oldest-first + DRR guarantees above hold unchanged; read lanes ride in
  whatever capacity consensus left unused (dispatches are 2048 lanes —
  consensus rounds rarely fill them);
* **per-chain backpressure**: each tenant's queue is bounded in lanes; a
  wedged or flooding tenant sheds load at SUBMIT time — the handle serves
  those verdicts from its local host oracle (exact, slower) — and the
  scheduler thread never blocks on any tenant (results are delivered by
  ``Event.set``, errors are handed back for the CALLER's thread to
  resolve against the oracle);
* **per-tenant observability**: ``sched.coalesce`` / ``sched.dispatch``
  spans, queue-depth gauge, per-tenant drain-latency histograms with
  p50/p99 in :meth:`TenantScheduler.stats` — the latency-SLO evidence
  bench config #10 records.

Cache namespacing (the correctness satellite): per-message packs and seal
verdicts become process-shared state here, so both are namespaced by
tenant — each handle owns a private
:class:`~go_ibft_tpu.verify.pipeline.PackCache` and a private
round-scoped seal-verdict cache, and the engine lifecycle hooks
(``note_round`` / ``reset_pack_cache`` / ``quarantine``) touch ONLY that
tenant's state.  Two chains sharing a proposal hash at the same
height/round can therefore never alias packed lanes or verdicts, and one
tenant's round rotation can never evict another's live round state
(tests/test_sched.py pins both).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from ..messages.helpers import CommittedSeal
from ..messages.wire import IbftMessage
from ..obs import trace
from ..utils import metrics
from ..verify.batch import HostBatchVerifier, _BATCH_BUCKETS
from ..verify.pipeline import PackCache
from .dispatch import (
    CoalescedDispatcher,
    well_formed_seal_lane,
    well_formed_sender,
)

__all__ = [
    "PRIORITY_RANK",
    "SchedQueueFull",
    "TenantScheduler",
    "TenantVerifierHandle",
    "QUEUE_LANES_KEY",
    "SHED_LANES_KEY",
    "DISPATCHES_KEY",
    "COALESCED_REQUESTS_KEY",
    "DRAIN_MS_KEY",
    "FLUSH_FAULTS_KEY",
]

QUEUE_LANES_KEY = ("go-ibft", "sched", "queue_lanes")
SHED_LANES_KEY = ("go-ibft", "sched", "shed_lanes")
DISPATCHES_KEY = ("go-ibft", "sched", "dispatches")
COALESCED_REQUESTS_KEY = ("go-ibft", "sched", "coalesced_requests")
DRAIN_MS_KEY = ("go-ibft", "sched", "drain_ms")
FLUSH_FAULTS_KEY = ("go-ibft", "sched", "flush_faults")
# Fixed-bucket per-tenant drain latency for the /metrics endpoint (the
# tenant chain id renders as the ``tag`` label; off unless
# metrics.enable_fixed_histograms() ran).
SCHED_DRAIN_MS_FIXED_KEY = ("go-ibft", "latency", "sched_drain_ms")


# Tenant QoS classes: lower rank is selected first (ISSUE 10).  Consensus
# traffic (live rounds, chain sync, overlap drains) outranks the
# proof-serving read tier by construction — see _select_locked.
PRIORITY_RANK = {"consensus": 0, "read": 1}


class SchedQueueFull(RuntimeError):
    """A tenant's queue is at its lane cap: the submission is refused so
    the scheduler never buffers unboundedly for a wedged or flooding
    tenant.  The handle resolves the request against its local host
    oracle instead (shed, not dropped — verdicts are never lost)."""


@dataclass
class _Request:
    """One queued verify request (one tenant, one kind, <= dispatch cap)."""

    tenant: "_Tenant"
    kind: str  # "senders" | "seals"
    items: list  # IbftMessage list, or (proposal_hash, seal) lane list
    height: Optional[int]  # membership height for seal lanes
    out: np.ndarray  # caller's full-length verdict array
    out_idxs: List[int]  # positions of ``items`` in ``out``
    lanes: int = 0
    submitted_at: float = 0.0
    done: threading.Event = field(default_factory=threading.Event)
    error: Optional[BaseException] = None
    cancelled: bool = False

    def __post_init__(self) -> None:
        self.lanes = len(self.items)


class _SealVerdictCache:
    """Round-scoped (signer, hash, sig, height) -> verdict, one per tenant.

    The engine keeps its own per-sequence seal-verdict cache; this one
    lives at PROCESS scope (inside the scheduler's tenant state) and is
    therefore namespaced by construction — a verdict stored for chain A
    can never serve chain B, even for byte-identical (signer, proposal
    hash, seal) at the same height/round.  Eviction mirrors the engine's:
    dead rounds go first, the live round evicts FIFO within itself."""

    def __init__(self, cap: int = 4096):
        self._lock = threading.Lock()
        self._by_round: Dict[int, Dict[tuple, bool]] = {}
        self._count = 0
        self._round = 0
        self._cap = cap

    @property
    def cap(self) -> int:
        return self._cap

    def __len__(self) -> int:
        with self._lock:
            return self._count

    def note_round(self, round_: int) -> None:
        with self._lock:
            self._round = round_

    def clear(self) -> None:
        with self._lock:
            self._by_round.clear()
            self._count = 0
            self._round = 0

    def lookup(self, key: tuple) -> Optional[bool]:
        with self._lock:
            for bucket in self._by_round.values():
                if key in bucket:
                    return bucket[key]
            return None

    def store(self, key: tuple, verdict: bool) -> None:
        with self._lock:
            bucket = self._by_round.setdefault(self._round, {})
            if key not in bucket:
                self._count += 1
            bucket[key] = verdict
            while self._count > self._cap and self._by_round:
                oldest = min(self._by_round)
                bucket = self._by_round[oldest]
                if oldest == self._round:
                    bucket.pop(next(iter(bucket)))
                    self._count -= 1
                    if not bucket:
                        del self._by_round[oldest]
                else:
                    self._count -= len(bucket)
                    del self._by_round[oldest]


class _Tenant:
    """Per-registration scheduler state: queue, fairness, caches, stats."""

    def __init__(
        self,
        tid: str,
        chain_id: str,
        validators: Callable[[int], Mapping[bytes, int]],
        calibrator=None,
        priority: str = "consensus",
        max_queue_lanes: Optional[int] = None,
        pack_cache_cap: Optional[int] = None,
        verdict_cache_cap: Optional[int] = None,
    ):
        self.tid = tid
        self.chain_id = chain_id
        self.validators = validators
        self.priority = priority
        self.rank = PRIORITY_RANK[priority]
        # Per-tenant arrival model (ISSUE 9): EWMA inter-arrival rate,
        # summed across active tenants to project how fast the shared
        # dispatch will fill — the calibrated replacement for the fixed
        # coalescing window.
        self.calibrator = calibrator
        self.queue: Deque[_Request] = deque()
        self.queued_lanes = 0
        self.deficit = 0
        # Per-tenant budgets (ISSUE 16): an explicit queue-lane bound
        # overrides the scheduler-wide default, and the cache caps size
        # THIS tenant's slice of process memory — a 4-validator chain can
        # ride along a 100-validator one without inheriting its footprint.
        self.max_queue_lanes = max_queue_lanes
        # ``draining`` marks a tenant mid-removal: new submissions are
        # refused (the handle's host oracle serves them — shed, not
        # dropped) while already-queued work keeps flushing.
        self.draining = False
        # Namespaced caches (satellite: process-shared caches keyed by
        # tenant — lifecycle hooks touch only THIS tenant's state).
        self.pack_cache = (
            PackCache(cap=pack_cache_cap)
            if pack_cache_cap is not None
            else PackCache()
        )
        self.verdicts = (
            _SealVerdictCache(cap=verdict_cache_cap)
            if verdict_cache_cap is not None
            else _SealVerdictCache()
        )
        # SLO evidence.  ``slo_lock`` orders the scheduler thread's
        # sample appends (_complete) against stats() snapshots — a live
        # monitoring scrape must never crash on a mutating deque.
        self.slo_lock = threading.Lock()
        self.drain_ms: Deque[float] = deque(maxlen=4096)
        self.requests = 0
        self.lanes = 0
        self.shed_lanes = 0
        self.sheds = 0


def _percentile(samples: Sequence[float], q: float) -> Optional[float]:
    if not samples:
        return None
    ordered = sorted(samples)
    idx = min(len(ordered) - 1, max(0, int(round(q * (len(ordered) - 1)))))
    return ordered[idx]


class TenantScheduler:
    """Coalesces verify lanes from N tenants into shared dispatches.

    ``window_s`` is the coalescing window (measured from the OLDEST
    queued request — demand-aware, never a periodic tick);
    ``max_dispatch_lanes`` caps one coalesced dispatch (default: the
    largest single-device lane bucket); ``max_queue_lanes`` is the
    per-tenant backpressure bound; ``quantum_lanes`` is the DRR quantum.
    ``route`` feeds the :class:`CoalescedDispatcher` ("auto" routes small
    flushes to the native host path and large ones to the device, like
    the adaptive single-tenant verifier).

    Use as a context manager, or call :meth:`start` / :meth:`stop`.
    """

    def __init__(
        self,
        *,
        window_s: float = 0.002,
        max_dispatch_lanes: int = _BATCH_BUCKETS[-1],
        max_queue_lanes: int = 8192,
        quantum_lanes: int = 256,
        route: str = "auto",
        dispatcher: Optional[CoalescedDispatcher] = None,
        request_timeout_s: float = 30.0,
        calibrate: bool = True,
    ):
        if max_dispatch_lanes < 1 or max_queue_lanes < 1 or quantum_lanes < 1:
            raise ValueError("scheduler bounds must be >= 1")
        self.window_s = window_s
        # Arrival-calibrated windows (ISSUE 9): ``window_s`` becomes the
        # CEILING; the actual wait for the oldest queued request is the
        # projected dispatch-fill time at the measured aggregate arrival
        # rate (per-tenant EWMA models, summed over tenants with queued
        # work).  A stream measured too slow to fill the dispatch inside
        # the ceiling flushes immediately instead of idling out the
        # window.  ``calibrate=False`` restores the fixed window.
        self.calibrate = calibrate
        self.max_dispatch_lanes = min(max_dispatch_lanes, _BATCH_BUCKETS[-1])
        self.max_queue_lanes = max_queue_lanes
        self.quantum_lanes = quantum_lanes
        self.request_timeout_s = request_timeout_s
        self._dispatcher = (
            dispatcher if dispatcher is not None else CoalescedDispatcher(route)
        )
        self._cv = threading.Condition()
        self._tenants: Dict[str, _Tenant] = {}
        self._rr: List[str] = []  # round-robin order (registration order)
        self._rr_next = 0
        self._pending_reqs = 0
        self._pending_lanes = 0
        self._running = False
        self._thread: Optional[threading.Thread] = None
        # Live-reconfiguration state (ISSUE 16): ``_inflight`` counts
        # flushes currently executing outside the lock; ``_paused`` stops
        # the loop from starting new ones while :meth:`reconfigure` drains
        # and swaps the dispatcher.  Submissions stay open throughout —
        # queued work just waits out the (one-flush) pause.
        self._inflight = 0
        self._paused = False
        # Evidence counters (config #10 reads these via stats()).
        self.dispatches = 0
        self.coalesced_requests = 0
        self.coalesced_lanes = 0
        self.flush_faults = 0

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "TenantScheduler":
        with self._cv:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="tenant-sched", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop accepting work; the loop drains everything already queued
        before the thread exits (no request is ever abandoned)."""
        with self._cv:
            self._running = False
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def __enter__(self) -> "TenantScheduler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def running(self) -> bool:
        with self._cv:
            return self._running

    def warmup(self, **kw) -> None:
        """Pre-compile the shared kernels (node startup; never mid-round)."""
        self._dispatcher.warmup(**kw)

    def reconfigure(
        self,
        *,
        dispatcher: Optional[CoalescedDispatcher] = None,
        route: Optional[str] = None,
        dp: Optional[int] = None,
        devices=None,
        warm_lanes: Optional[Sequence[int]] = None,
        table_rows: int = 8,
    ) -> dict:
        """Zero-downtime dispatcher swap / device-mesh resize (ISSUE 16).

        The replacement dispatcher is built — and, with ``warm_lanes``,
        pre-compiled — BEFORE the flush loop pauses, so every tenant keeps
        draining through the old data plane while the new mesh programs
        compile; the swap itself waits only for the single in-flight
        flush.  ``dp`` / ``devices`` re-enter through
        :func:`~go_ibft_tpu.parallel.mesh.mesh_context` (a 1-device
        resolution degrades to the single-device kernels); an explicit
        ``dispatcher`` wins over all shape arguments.  Submissions stay
        open throughout and queued requests survive the swap untouched —
        no tenant misses a height.  Returns ``{"old", "new"}`` dispatcher
        descriptions (the churn-soak evidence)."""
        if dispatcher is None:
            kw = {}
            if dp is not None or devices is not None:
                kw = {"dp": dp, "devices": devices}
            dispatcher = CoalescedDispatcher(
                route if route is not None else self._dispatcher.route, **kw
            )
        if warm_lanes:
            dispatcher.warmup(lanes=warm_lanes, table_rows=table_rows)
        old = self._dispatcher
        with self._cv:
            self._paused = True
            try:
                while self._inflight:
                    self._cv.wait()
                self._dispatcher = dispatcher
            finally:
                self._paused = False
                self._cv.notify_all()
        desc = {"old": old.describe(), "new": dispatcher.describe()}
        trace.instant("sched.reconfigure", **desc["new"])
        return desc

    # -- tenants ---------------------------------------------------------

    def register(
        self,
        tenant_id: str,
        validators_for_height: Callable[[int], Mapping[bytes, int]],
        *,
        chain_id: Optional[str] = None,
        priority: str = "consensus",
        max_queue_lanes: Optional[int] = None,
        pack_cache_cap: Optional[int] = None,
        verdict_cache_cap: Optional[int] = None,
    ) -> "TenantVerifierHandle":
        """Register one tenant (typically one engine of one chain) and
        return its scheduler-backed verifier handle.  ``chain_id`` labels
        the chain for stats aggregation (defaults to the tenant id).
        ``priority`` is the QoS class: ``"consensus"`` (default) for live
        rounds, ``"read"`` for the proof-serving plane — read lanes only
        fill dispatch capacity consensus left unused, so a proof flood
        can never starve a finalizing chain.

        Per-tenant budgets (ISSUE 16): ``max_queue_lanes`` bounds THIS
        tenant's queue (overriding the scheduler-wide default), and
        ``pack_cache_cap`` / ``verdict_cache_cap`` size its private
        caches — all surfaced per tenant in :meth:`stats`."""
        if priority not in PRIORITY_RANK:
            raise ValueError(
                f"unknown priority {priority!r} "
                f"(expected one of {sorted(PRIORITY_RANK)})"
            )
        with self._cv:
            if tenant_id in self._tenants:
                raise ValueError(f"tenant {tenant_id!r} already registered")
            from ..utils.calibration import ArrivalCalibrator

            tenant = _Tenant(
                tenant_id,
                chain_id or tenant_id,
                validators_for_height,
                calibrator=(
                    ArrivalCalibrator(max_window_s=self.window_s)
                    if self.calibrate
                    else None
                ),
                priority=priority,
                max_queue_lanes=max_queue_lanes,
                pack_cache_cap=pack_cache_cap,
                verdict_cache_cap=verdict_cache_cap,
            )
            self._tenants[tenant_id] = tenant
            self._rr.append(tenant_id)
        return TenantVerifierHandle(self, tenant)

    def add_tenant(self, tenant_id, validators_for_height, **kw):
        """Zero-downtime registration (ISSUE 16 naming): identical to
        :meth:`register` — registration has always been safe while the
        flush loop runs (one lock-guarded map insert; the next selection
        pass sees the tenant), so adding a chain to a live scheduler
        costs no pause and no other tenant a height."""
        return self.register(tenant_id, validators_for_height, **kw)

    def remove_tenant(
        self,
        tenant_id: str,
        *,
        drain: bool = True,
        timeout_s: float = 30.0,
    ) -> bool:
        """Zero-downtime removal.  With ``drain`` (default) the tenant
        stops accepting NEW submissions immediately — its handle sheds
        them to the host oracle, so verdicts are never lost — while
        everything already queued keeps flushing through the shared
        dispatch; the tenant is dropped once its queue empties.
        ``drain=False`` (or a drain timeout, or a stopped scheduler)
        refuses the still-queued requests back to their callers' oracles,
        exactly like :meth:`unregister`.  Returns True when the queue
        drained clean.  Survivor tenants never miss a height either way:
        nothing pauses, their queued lanes keep shipping."""
        with self._cv:
            tenant = self._tenants.get(tenant_id)
            if tenant is None:
                return True
            tenant.draining = True
        if drain:
            deadline = time.monotonic() + timeout_s
            with self._cv:
                while tenant.queue and self._running:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    # Flush completions notify; the poll cap bounds the
                    # wait if one slips past between check and wait.
                    self._cv.wait(timeout=min(left, 0.05))
        with self._cv:
            drained = not tenant.queue
        self.unregister(tenant_id)
        trace.instant(
            "sched.remove_tenant", tenant=tenant_id, drained=drained
        )
        return drained

    def unregister(self, tenant_id: str) -> None:
        with self._cv:
            tenant = self._tenants.pop(tenant_id, None)
            if tenant is None:
                return
            self._rr.remove(tenant_id)
            # Outstanding requests are refused back to the handle's oracle
            # rather than silently dropped.
            for req in tenant.queue:
                self._pending_reqs -= 1
                self._pending_lanes -= req.lanes
                req.error = SchedQueueFull("tenant unregistered")
                req.done.set()
            tenant.queue.clear()
            tenant.queued_lanes = 0

    # -- submission (handle-side) ---------------------------------------

    def submit(
        self,
        tenant: _Tenant,
        kind: str,
        items: list,
        height: Optional[int],
        out: np.ndarray,
        out_idxs: List[int],
    ) -> _Request:
        if len(items) > self.max_dispatch_lanes:
            raise ValueError("request exceeds dispatch cap; chunk it first")
        req = _Request(tenant, kind, items, height, out, out_idxs)
        with self._cv:
            if not self._running:
                raise SchedQueueFull("scheduler is not running")
            if tenant.draining or self._tenants.get(tenant.tid) is not tenant:
                # Mid-removal (or an already-removed handle): refuse so
                # the caller's oracle serves the verdict immediately
                # instead of queueing work nothing will ever select.
                raise SchedQueueFull(f"tenant {tenant.tid!r} is draining")
            cap = (
                tenant.max_queue_lanes
                if tenant.max_queue_lanes is not None
                else self.max_queue_lanes
            )
            if tenant.queued_lanes + req.lanes > cap:
                raise SchedQueueFull(
                    f"tenant {tenant.tid!r} queue at {tenant.queued_lanes} "
                    f"lanes (cap {cap})"
                )
            req.submitted_at = time.monotonic()
            if tenant.calibrator is not None:
                tenant.calibrator.observe(req.lanes, now=req.submitted_at)
            tenant.queue.append(req)
            tenant.queued_lanes += req.lanes
            self._pending_reqs += 1
            self._pending_lanes += req.lanes
            metrics.set_gauge(QUEUE_LANES_KEY, float(self._pending_lanes))
            self._cv.notify_all()
        return req

    def note_shed(self, tenant: _Tenant, lanes: int) -> None:
        tenant.shed_lanes += lanes
        tenant.sheds += 1
        metrics.inc_counter(SHED_LANES_KEY, lanes)
        trace.instant("sched.shed", tenant=tenant.tid, lanes=lanes)

    # -- the flush loop --------------------------------------------------

    def _oldest_ts_locked(self) -> Optional[float]:
        ts = [t.queue[0].submitted_at for t in self._tenants.values() if t.queue]
        return min(ts) if ts else None

    def _window_locked(self) -> float:
        """The coalescing window for the current backlog, from the
        measured AGGREGATE arrival rate (per-tenant EWMA models summed
        over tenants with queued work) through the shared
        :func:`~go_ibft_tpu.utils.calibration.calibrated_window` policy:
        the fill projection when the dispatch will fill inside the
        ``window_s`` ceiling, the ceiling when a sustained flood merely
        cannot fill ALL of it, eager (0) only when the ceiling would
        gain almost nothing.  Falls back to the fixed ``window_s`` when
        no rate has been measured yet."""
        if not self.calibrate:
            return self.window_s
        from ..utils.calibration import calibrated_window

        rate = 0.0
        for t in self._tenants.values():
            if t.queue and t.calibrator is not None:
                r = t.calibrator.rate_per_s()
                if r:
                    rate += r
        window = calibrated_window(
            rate if rate > 0 else None,
            self._pending_lanes,
            self.max_dispatch_lanes,
            self.window_s,
        )
        trace.instant(
            "ingress.calibrate",
            scope="sched",
            window_us=round(window * 1e6, 1),
            rate_per_s=round(rate, 1),
            pending=self._pending_lanes,
        )
        return window

    def _loop(self) -> None:
        while True:
            batch: List[_Request] = []
            with self._cv:
                while self._running and (
                    self._pending_reqs == 0 or self._paused
                ):
                    self._cv.wait()
                if self._pending_reqs == 0 and not self._running:
                    return
                # Demand-aware window: flush at bucket-full, or when the
                # oldest queued request ages past the (arrival-calibrated)
                # window.  Idle tenants contribute no requests and thus no
                # delay.
                while self._running and not self._paused:
                    if self._pending_lanes >= self.max_dispatch_lanes:
                        break
                    oldest = self._oldest_ts_locked()
                    if oldest is None:
                        break
                    wait = oldest + self._window_locked() - time.monotonic()
                    if wait <= 0:
                        break
                    self._cv.wait(timeout=wait)
                    if self._pending_reqs == 0:
                        break
                if not (self._paused and self._running):
                    # A running pause (reconfigure draining the dispatcher)
                    # selects nothing; stop() still drains everything.
                    batch = self._select_locked()
                    if batch:
                        self._inflight += 1
            if batch:
                try:
                    self._flush(batch)
                finally:
                    with self._cv:
                        self._inflight -= 1
                        self._cv.notify_all()

    def _select_locked(self) -> List[_Request]:
        """Pick one dispatch's worth of requests.

        Selection is CLASS-ORDERED first (read-tier QoS, ISSUE 10): the
        oldest queued request of the highest-priority class with queued
        work always ships first, and lower classes only fill capacity the
        higher ones left unused — so the consensus starvation bound is
        hard (a proof flood adds at most one in-flight flush of latency,
        never a queueing delay), while read traffic still drains through
        the spare lanes of every dispatch.

        Within a class the prior guarantees hold: the oldest queued
        request is never passed over in favor of younger same-class
        traffic, and the remaining capacity fills by deficit round robin
        — each non-empty tenant earns ``quantum_lanes`` per flush
        (capped at one dispatch) and spends it on whole requests, so
        lane-hungry tenants cannot monopolize consecutive flushes."""
        batch: List[_Request] = []
        lanes = 0
        active = [t for t in self._tenants.values() if t.queue]
        if not active:
            return batch

        def take(tenant: _Tenant) -> _Request:
            nonlocal lanes
            req = tenant.queue.popleft()
            tenant.queued_lanes -= req.lanes
            self._pending_reqs -= 1
            self._pending_lanes -= req.lanes
            lanes += req.lanes
            batch.append(req)
            return req

        top_rank = min(t.rank for t in active)
        oldest_tenant = min(
            (t for t in active if t.rank == top_rank),
            key=lambda t: t.queue[0].submitted_at,
        )
        take(oldest_tenant)
        n = len(self._rr)
        for class_rank in sorted({t.rank for t in self._tenants.values()}):
            for k in range(n):
                tid = self._rr[(self._rr_next + k) % n]
                tenant = self._tenants[tid]
                if tenant.rank != class_rank:
                    continue
                if not tenant.queue:
                    tenant.deficit = 0
                    continue
                tenant.deficit = min(
                    tenant.deficit + self.quantum_lanes, self.max_dispatch_lanes
                )
                while (
                    tenant.queue
                    and lanes + tenant.queue[0].lanes <= self.max_dispatch_lanes
                    and tenant.deficit >= tenant.queue[0].lanes
                ):
                    tenant.deficit -= tenant.queue[0].lanes
                    take(tenant)
                if lanes >= self.max_dispatch_lanes:
                    break
            if lanes >= self.max_dispatch_lanes:
                break
        # Idle tenants reset their deficit even when a full dispatch cut
        # the walk short of visiting them — the documented no-banked-
        # credit invariant must not depend on loop reachability.
        for tenant in self._tenants.values():
            if not tenant.queue:
                tenant.deficit = 0
        if n:
            self._rr_next = (self._rr_next + 1) % n
        metrics.set_gauge(QUEUE_LANES_KEY, float(self._pending_lanes))
        return batch

    def _flush(self, batch: List[_Request]) -> None:
        sender_reqs = [r for r in batch if r.kind == "senders"]
        seal_reqs = [r for r in batch if r.kind == "seals"]
        msgs: List[IbftMessage] = []
        owners: Dict[int, PackCache] = {}
        for req in sender_reqs:
            for m in req.items:
                owners[id(m)] = req.tenant.pack_cache
            msgs.extend(req.items)
        lanes: List[Tuple[bytes, CommittedSeal]] = []
        for req in seal_reqs:
            lanes.extend(req.items)
        with trace.span(
            "sched.coalesce",
            tenants=len({r.tenant.tid for r in batch}),
            requests=len(batch),
            lanes=len(msgs) + len(lanes),
        ):
            try:
                sender_ok, seal_ok = self._dispatcher.dispatch(
                    msgs, lanes, owners
                )
            except Exception as err:  # noqa: BLE001 - hand back, never block
                # The scheduler thread resolves NOTHING itself: each
                # caller's thread falls back to its tenant's host oracle,
                # so one poisoned flush cannot stall every tenant behind
                # a slow sequential re-verify here.
                self.flush_faults += 1
                metrics.inc_counter(FLUSH_FAULTS_KEY)
                for req in batch:
                    req.error = err
                    req.done.set()
                return
        self.dispatches += 1
        self.coalesced_requests += len(batch)
        self.coalesced_lanes += len(msgs) + len(lanes)
        metrics.inc_counter(DISPATCHES_KEY)
        metrics.inc_counter(COALESCED_REQUESTS_KEY, len(batch))
        off = 0
        for req in sender_reqs:
            self._complete(req, sender_ok[off : off + req.lanes])
            off += req.lanes
        off = 0
        for req in seal_reqs:
            self._complete(req, seal_ok[off : off + req.lanes])
            off += req.lanes

    def _complete(self, req: _Request, sig_ok: np.ndarray) -> None:
        """Apply the tenant's membership check and deliver the verdicts."""
        try:
            validators = req.tenant.validators
            mask = np.zeros(req.lanes, dtype=bool)
            powers_by_height: Dict[int, Mapping[bytes, int]] = {}
            for i, item in enumerate(req.items):
                if not sig_ok[i]:
                    continue
                if req.kind == "senders":
                    height, claimed = item.view.height, item.sender
                else:
                    height, claimed = req.height, item[1].signer
                powers = powers_by_height.get(height)
                if powers is None:
                    powers = powers_by_height[height] = validators(height)
                mask[i] = claimed in powers
            if not req.cancelled:
                req.out[np.asarray(req.out_idxs)] = mask
        except Exception as err:  # noqa: BLE001 - caller resolves via oracle
            req.error = err
        finally:
            dt_ms = (time.monotonic() - req.submitted_at) * 1e3
            with req.tenant.slo_lock:
                req.tenant.drain_ms.append(dt_ms)
                req.tenant.requests += 1
                req.tenant.lanes += req.lanes
            metrics.observe(DRAIN_MS_KEY, dt_ms)
            metrics.observe_fixed(
                SCHED_DRAIN_MS_FIXED_KEY + (req.tenant.chain_id,), dt_ms
            )
            req.done.set()

    # -- evidence --------------------------------------------------------

    def stats(self) -> dict:
        """Scheduler + per-tenant SLO snapshot (bench config #10 evidence)."""
        def tenant_row(t: _Tenant) -> dict:
            with t.slo_lock:  # vs the scheduler thread's sample appends
                samples = list(t.drain_ms)
                requests, lanes = t.requests, t.lanes
            return {
                "chain": t.chain_id,
                "priority": t.priority,
                "queue_lanes": t.queued_lanes,
                "requests": requests,
                "lanes": lanes,
                "sheds": t.sheds,
                "shed_lanes": t.shed_lanes,
                "drain_p50_ms": _percentile(samples, 0.50),
                "drain_p99_ms": _percentile(samples, 0.99),
                "arrival": (
                    t.calibrator.stats() if t.calibrator is not None else None
                ),
                "draining": t.draining,
                # Per-tenant memory/queue budgets (ISSUE 16): live
                # occupancy vs cap for each namespaced resource.
                "budgets": {
                    "queue_lanes_cap": (
                        t.max_queue_lanes
                        if t.max_queue_lanes is not None
                        else self.max_queue_lanes
                    ),
                    "pack_entries": len(t.pack_cache),
                    "pack_cap": t.pack_cache.cap,
                    "verdict_entries": len(t.verdicts),
                    "verdict_cap": t.verdicts.cap,
                },
            }

        with self._cv:
            tenants = {
                tid: tenant_row(t) for tid, t in self._tenants.items()
            }
            dispatches = self.dispatches
            requests = self.coalesced_requests
            lanes = self.coalesced_lanes
            faults = self.flush_faults
        return {
            "tenants": tenants,
            "dispatches": dispatches,
            "coalesced_requests": requests,
            "coalesced_lanes": lanes,
            "coalesce_ratio": (
                round(requests / dispatches, 3) if dispatches else None
            ),
            "flush_faults": faults,
            # Tests wrap the dispatcher in doubles without describe();
            # degrade to the class name rather than breaking stats().
            "dispatcher": (
                self._dispatcher.describe()
                if hasattr(self._dispatcher, "describe")
                else {"route": type(self._dispatcher).__name__}
            ),
        }


class TenantVerifierHandle:
    """One tenant's drop-in ``BatchVerifier`` over the shared scheduler.

    Implements the verify surface the engine, the chain runner's overlap
    worker and the sync client use — ``verify_senders``,
    ``verify_committed_seals``, ``verify_seal_lanes`` — plus the engine
    lifecycle hooks (``note_round`` / ``reset_pack_cache`` /
    ``quarantine``), all scoped to THIS tenant.  Every verdict is exact
    against the tenant's own sequential host oracle: membership is
    evaluated over the tenant's validator source, and any shed / faulted
    / timed-out request is resolved by the oracle in the caller's thread
    (degraded latency, never degraded correctness, and never a blocked
    scheduler)."""

    def __init__(self, scheduler: TenantScheduler, tenant: _Tenant):
        self._sched = scheduler
        self._tenant = tenant
        self._oracle = HostBatchVerifier(tenant.validators)

    @property
    def tenant_id(self) -> str:
        return self._tenant.tid

    # -- engine lifecycle hooks (tenant-scoped by construction) ----------

    def note_round(self, round_: int) -> None:
        """Round advance for THIS tenant only: tags this tenant's pack
        and verdict caches; no other tenant's live round state moves."""
        self._tenant.pack_cache.note_round(round_)
        self._tenant.verdicts.note_round(round_)

    def reset_pack_cache(self) -> None:
        """New sequence for THIS tenant only."""
        self._tenant.pack_cache.clear()
        self._tenant.verdicts.clear()

    def quarantine(self, msgs: Sequence[IbftMessage]) -> None:
        for m in msgs:
            self._tenant.pack_cache.evict(m)

    def seed_seal_verdicts(self, entries) -> int:
        """Warm-start hook (ISSUE 16): pre-load seal verdicts replayed
        from the WAL into THIS tenant's verdict cache.  ``entries`` is an
        iterable of ``((signer, proposal_hash, signature, height), bool)``
        pairs — the exact cache key :meth:`verify_committed_seals` uses —
        so a restarted node's first seal drain after recovery is cache
        hits, not device (or oracle) lanes.  Sound because every seeded
        verdict comes from a finalized block the WAL already trusts
        (see go_ibft_tpu/boot/warmstart.py)."""
        n = 0
        verdicts = self._tenant.verdicts
        for key, verdict in entries:
            verdicts.store(tuple(key), bool(verdict))
            n += 1
        return n

    def warmup(self, **kw) -> None:
        self._sched.warmup(**kw)

    # -- BatchVerifier ---------------------------------------------------

    def verify_senders(self, msgs: Sequence[IbftMessage]) -> np.ndarray:
        msgs = list(msgs)
        out = np.zeros(len(msgs), dtype=bool)
        idxs = [i for i, m in enumerate(msgs) if well_formed_sender(m)]
        if idxs:
            self._run("senders", [msgs[i] for i in idxs], None, idxs, out)
        return out

    def verify_committed_seals(
        self, proposal_hash: bytes, seals: Sequence[CommittedSeal], height: int
    ) -> np.ndarray:
        seals = list(seals)
        out = np.zeros(len(seals), dtype=bool)
        if len(proposal_hash) != 32:
            return out
        fresh_idxs: List[int] = []
        fresh_keys: List[tuple] = []
        verdicts = self._tenant.verdicts
        for i, seal in enumerate(seals):
            if not well_formed_seal_lane(proposal_hash, seal):
                continue
            key = (seal.signer, proposal_hash, seal.signature, height)
            hit = verdicts.lookup(key)
            if hit is not None:
                out[i] = hit
            else:
                fresh_idxs.append(i)
                fresh_keys.append(key)
        if fresh_idxs:
            self._run(
                "seals",
                [(proposal_hash, seals[i]) for i in fresh_idxs],
                height,
                fresh_idxs,
                out,
            )
            for i, key in zip(fresh_idxs, fresh_keys):
                verdicts.store(key, bool(out[i]))
        return out

    def verify_seal_lanes(
        self, lanes: Sequence[Tuple[bytes, CommittedSeal]], height: int
    ) -> np.ndarray:
        lanes = list(lanes)
        out = np.zeros(len(lanes), dtype=bool)
        idxs = [
            i
            for i, (proposal_hash, seal) in enumerate(lanes)
            if well_formed_seal_lane(proposal_hash, seal)
        ]
        if idxs:
            self._run("seals", [lanes[i] for i in idxs], height, idxs, out)
        return out

    # -- submission machinery -------------------------------------------

    def _run(
        self,
        kind: str,
        items: list,
        height: Optional[int],
        idxs: List[int],
        out: np.ndarray,
    ) -> None:
        cap = self._sched.max_dispatch_lanes
        pending: List[Tuple[_Request, list, List[int]]] = []
        for start in range(0, len(items), cap):
            chunk = items[start : start + cap]
            chunk_idxs = idxs[start : start + cap]
            try:
                req = self._sched.submit(
                    self._tenant, kind, chunk, height, out, chunk_idxs
                )
            except SchedQueueFull:
                # Backpressure: serve locally, never block or drop.
                self._sched.note_shed(self._tenant, len(chunk))
                self._oracle_fill(kind, chunk, height, chunk_idxs, out)
                continue
            pending.append((req, chunk, chunk_idxs))
        for req, chunk, chunk_idxs in pending:
            if not req.done.wait(self._sched.request_timeout_s):
                # Defensive: a dead scheduler thread must not wedge the
                # consensus loop.  Mark the request so a late flush
                # cannot write into an array the caller already owns.
                req.cancelled = True
                self._sched.note_shed(self._tenant, len(chunk))
                self._oracle_fill(kind, chunk, height, chunk_idxs, out)
            elif req.error is not None:
                self._oracle_fill(kind, chunk, height, chunk_idxs, out)

    def _oracle_fill(
        self,
        kind: str,
        items: list,
        height: Optional[int],
        idxs: List[int],
        out: np.ndarray,
    ) -> None:
        if kind == "senders":
            mask = self._oracle.verify_senders(items)
        else:
            mask = self._oracle.verify_seal_lanes(items, height)
        out[np.asarray(idxs)] = np.asarray(mask, dtype=bool)[: len(idxs)]
