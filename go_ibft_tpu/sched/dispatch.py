"""The multi-tenant coalesced verify data plane.

One flush of the :class:`~go_ibft_tpu.sched.scheduler.TenantScheduler`
carries lanes from MANY chains — different validator sets, different
proposal hashes, different heights — and must still land on the device as
ONE batched dispatch.  The trick that makes cross-tenant coalescing exact
is splitting the verification predicate at the membership check:

* **signature validity is chain-agnostic** — ``recover(digest, sig) ==
  claimed address`` does not mention a validator set, so lanes from any
  number of chains share one recovery-ladder launch;
* **membership is a host dict lookup** — ``claimed in
  tenant.validators(height)`` is exact Python over the tenant's own
  voting-power map, applied per lane after the shared mask returns.

The device dispatch therefore runs the EXISTING pinned programs
(:data:`DIGEST_KERNEL` / :data:`RECOVER_KERNEL` are the very jit objects
``verify/batch.py`` compiled for the single-tenant plane — asserted by
``scripts/compile_budget.py``, so the shared plane can never fork a new
program family) with the membership table packed from the lanes' own
claimed addresses: every live lane's claimed address is trivially a table
member, which reduces the kernel's mask to pure signature validity.  The
per-tenant membership AND happens on host, so the final verdict per lane
is bit-identical to that tenant's sequential
:class:`~go_ibft_tpu.verify.batch.HostBatchVerifier` oracle.

The host route does the same split over the native bulk verifier (one
GIL-releasing C call for the whole coalesced flush) or, without the
native library, the pure-Python recover loop — the scheduler picks per
flush exactly like :class:`~go_ibft_tpu.verify.batch.AdaptiveBatchVerifier`
picks per drain (measured cutover, small flushes stay on host).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..crypto import ecdsa as host_ecdsa
from ..crypto.keccak import keccak256_many
from ..messages.helpers import CommittedSeal
from ..messages.wire import IbftMessage
from ..obs import ledger as cost_ledger
from ..obs import trace
from ..utils import metrics
from ..verify import batch as vbatch
from ..verify.batch import (
    ADDRESS_BYTES,
    SIG_BYTES,
    _BATCH_BUCKETS,
    _bucket,
    pack_seal_lanes,
    pack_validator_table,
)
from ..verify.pipeline import PackCache, SenderPack

__all__ = [
    "CoalescedDispatcher",
    "DIGEST_KERNEL",
    "RECOVER_KERNEL",
    "DISPATCH_LANES_KEY",
    "DISPATCH_MS_KEY",
]

# The shared dispatch MUST reuse the single-tenant plane's compiled
# programs — these are the same jit objects, not re-jitted copies
# (scripts/compile_budget.py asserts the identity so a refactor that
# forks a new program family fails CI, and docs/compile_budget.json
# gains no sched entries).
DIGEST_KERNEL = vbatch._digest_kernel
RECOVER_KERNEL = vbatch._recover_kernel

DISPATCH_LANES_KEY = ("go-ibft", "sched", "dispatch_lanes")
DISPATCH_MS_KEY = ("go-ibft", "sched", "dispatch_ms")

class _RoutingPackCache:
    """Store-side shim routing ``pack_sender_batch`` cache stores to each
    message's OWN tenant cache.

    A coalesced sender pack mixes messages from many tenants, but
    ``pack_sender_batch`` takes one ``cache`` to store fresh packs into.
    Lookups are supplied pre-routed (``cache_hits``); this shim routes the
    stores by message identity so one tenant's packs can never land in —
    or later be served from — another tenant's cache (the namespacing
    contract of docs/TENANCY.md)."""

    def __init__(self, owners: Dict[int, PackCache]):
        self._owners = owners

    def store(self, msg, pack: SenderPack) -> None:
        owner = self._owners.get(id(msg))
        if owner is not None:
            owner.store(msg, pack)


def well_formed_sender(msg: IbftMessage) -> bool:
    """The oracle's sender-lane admission predicate
    (:meth:`HostBatchVerifier.verify_senders` skip conditions)."""
    return (
        msg.view is not None
        and len(msg.sender) == ADDRESS_BYTES
        and len(msg.signature) == SIG_BYTES
    )


def well_formed_seal_lane(proposal_hash: bytes, seal: CommittedSeal) -> bool:
    """The oracle's seal-lane admission predicate (hash + signer + sig)."""
    return (
        len(proposal_hash) == 32
        and len(seal.signer) == ADDRESS_BYTES
        and len(seal.signature) == SIG_BYTES
    )


class CoalescedDispatcher:
    """One shared pack/dispatch engine for mixed-tenant lane batches.

    :meth:`dispatch` takes pre-filtered (well-formed) sender messages and
    ``(proposal_hash, seal)`` lanes from any mix of tenants and returns
    one *signature-validity* mask per kind — ``recover(digest) ==
    claimed``; membership is the caller's (per-tenant, host-exact).

    ``route``:

    * ``"auto"`` — host below the measured adaptive cutover (the same
      calibration the :class:`AdaptiveBatchVerifier` uses: a handful of
      lanes never pays a device dispatch floor), device at or above it;
    * ``"host"`` / ``"device"`` — forced (bench variants, tests).

    ``mesh`` / ``dp`` / ``devices`` (live mesh resize, ISSUE 16): when a
    multi-device mesh resolves (``mesh`` wins; else ``dp``/``devices``
    re-enter through :func:`~go_ibft_tpu.parallel.mesh.mesh_context`),
    the device route dispatches the lane-sharded
    :func:`~go_ibft_tpu.verify.mesh_batch.mesh_verify_mask` program —
    the SAME pinned ``mesh_verify_mask_*_dp*`` family the single-tenant
    sharded verifier compiled — with lanes pinned to ``bucket x dp`` so
    every shard sees an identical local shape (pad lanes are dead).  A
    1-device resolution degrades to the single-device kernels exactly.
    The scheduler swaps whole dispatchers to resize
    (:meth:`TenantScheduler.reconfigure`), so this state is immutable.
    """

    def __init__(
        self,
        route: str = "auto",
        cutover_lanes: Optional[int] = None,
        *,
        mesh=None,
        dp: Optional[int] = None,
        devices=None,
    ):
        if route not in ("auto", "host", "device"):
            raise ValueError(f"unknown route {route!r}")
        self.route = route
        if cutover_lanes is None:
            from ..utils import calibration

            cutover_lanes = (
                calibration.measured_cutover()
                or calibration.DEFAULT_CUTOVER_LANES
            )
        self.cutover = cutover_lanes
        self.mesh = None
        self.dp = 1
        self._mask_kernel = None
        if mesh is None and (dp is not None or devices is not None):
            from ..parallel.mesh import mesh_context

            mesh = mesh_context(dp, devices=devices)
        if mesh is not None and mesh.devices.size >= 2:
            from ..verify.mesh_batch import mesh_verify_mask

            self.mesh = mesh
            self.dp = int(np.prod(mesh.devices.shape))
            self._mask_kernel = mesh_verify_mask(mesh)
        # The recover programs compile per lane bucket; serialize warmup.
        self._warm_lock = threading.Lock()

    def describe(self) -> dict:
        """Shape of this dispatcher (scheduler stats / resize evidence)."""
        return {
            "route": self.route,
            "dp": self.dp,
            "sharded": self.mesh is not None,
            "cutover": self.cutover,
        }

    def _pad_lanes(self, n: int) -> int:
        """Mesh dispatches pin the lane dim to ``bucket(ceil(n/dp)) x dp``
        (every shard gets an identical local shape; pad lanes are dead);
        single-device dispatches keep the pack functions' own bucketing
        (``pad_lanes=0``)."""
        if self.mesh is None or n == 0:
            return 0
        return _bucket((n + self.dp - 1) // self.dp, _BATCH_BUCKETS) * self.dp

    # -- public ----------------------------------------------------------

    def warmup(self, lanes: Sequence[int] = (8,), table_rows: int = 8) -> None:
        """Pre-compile the shared kernels (node startup; never mid-round)."""
        import jax
        import jax.numpy as jnp

        with self._warm_lock:
            for bb in lanes:
                # Warm the kernel the device route will actually launch:
                # the sharded mask program at its dp-aligned global shape
                # when a mesh is attached, the single-device recover
                # ladder otherwise.
                gg = self._pad_lanes(bb) if self.mesh is not None else bb
                kernel = (
                    self._mask_kernel if self.mesh is not None else RECOVER_KERNEL
                )
                program = (
                    "mesh_verify_mask" if self.mesh is not None else "ecdsa_recover"
                )
                with cost_ledger.dispatch_span(
                    program,
                    route="warmup",
                    padded=gg,
                    kernels=((program, kernel),),
                    site="sched/dispatch.py:warmup",
                ):
                    kernel(
                        jnp.zeros((gg, 8), jnp.uint32),
                        jnp.zeros((gg, 20), jnp.int32),
                        jnp.zeros((gg, 20), jnp.int32),
                        jnp.zeros((gg,), jnp.int32),
                        jnp.zeros((gg, 5), jnp.uint32),
                        jnp.zeros((table_rows, 5), jnp.uint32),
                        jnp.zeros((gg,), bool),
                    ).block_until_ready()
                with cost_ledger.dispatch_span(
                    "digest_words",
                    route="warmup",
                    padded=bb,
                    kernels=(("digest_words", DIGEST_KERNEL),),
                    site="sched/dispatch.py:warmup",
                ):
                    jax.block_until_ready(
                        DIGEST_KERNEL(
                            jnp.zeros((bb, 2, 17, 2), jnp.uint32),
                            jnp.ones((bb,), jnp.int32),
                        )
                    )

    def dispatch(
        self,
        sender_msgs: Sequence[IbftMessage],
        seal_lanes: Sequence[Tuple[bytes, CommittedSeal]],
        pack_caches: Optional[Dict[int, PackCache]] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Coalesced signature-validity masks for one flush.

        ``pack_caches`` maps ``id(msg)`` to the owning tenant's
        :class:`PackCache` (lookups AND stores are routed per message).
        Returns ``(sender_sig_ok, seal_sig_ok)``; membership is NOT
        included — the scheduler ANDs each lane with its own tenant's
        validator set.
        """
        total = len(sender_msgs) + len(seal_lanes)
        route = self.route
        if route == "auto":
            route = "device" if total >= self.cutover else "host"
        import time as _time

        t0 = _time.perf_counter()
        with trace.span(
            "sched.dispatch",
            route=route,
            lanes=total,
            senders=len(sender_msgs),
            seals=len(seal_lanes),
        ):
            if route == "device":
                out = self._device(sender_msgs, seal_lanes, pack_caches or {})
            else:
                # Host flushes pad nothing (occupancy 1.0); the device
                # route records per kernel launch inside _device where
                # the padded bucket shapes are known.
                with cost_ledger.dispatch_span(
                    "ecdsa_recover",
                    route="host",
                    live=total,
                    padded=total,
                    site="sched/dispatch.py:dispatch",
                ):
                    out = self._host(
                        sender_msgs, seal_lanes, pack_caches or {}
                    )
        metrics.observe(DISPATCH_MS_KEY, (_time.perf_counter() - t0) * 1e3)
        metrics.observe(DISPATCH_LANES_KEY, float(total))
        return out

    # -- device route ----------------------------------------------------

    def _device(self, msgs, lanes, owners) -> Tuple[np.ndarray, np.ndarray]:
        sender_ok = np.zeros(len(msgs), dtype=bool)
        seal_ok = np.zeros(len(lanes), dtype=bool)
        if msgs:
            # The pack sequence (cache-hit reuse, oversize payloads
            # digested on host) is the single-tenant plane's own
            # implementation — shared, not forked, so a fix there can
            # never miss this route.  Lookups are pre-routed per tenant;
            # stores route back through the owners shim.
            zw, r, s, v, claimed, live = vbatch.pack_sender_digest_rows(
                msgs,
                cache=_RoutingPackCache(owners),
                hits=[
                    (owners[id(m)].lookup(m) if id(m) in owners else None)
                    for m in msgs
                ],
                pad_lanes=self._pad_lanes(len(msgs)),
            )
            # Claimed-address table: every live lane's claimed sender is a
            # member by construction, so the kernel's (sig & member) mask
            # reduces to signature validity — tenant membership stays on
            # host where each chain's own set applies.
            table = pack_validator_table(
                list(dict.fromkeys(m.sender for m in msgs))
            )
            sender_ok = self._sig_mask(zw, r, s, v, claimed, table, live)[
                : len(msgs)
            ]
        if lanes:
            hz, r, s, v, signers, live = pack_seal_lanes(
                list(lanes), pad_lanes=self._pad_lanes(len(lanes))
            )
            table = pack_validator_table(
                list(dict.fromkeys(seal.signer for _h, seal in lanes))
            )
            seal_ok = self._sig_mask(hz, r, s, v, signers, table, live)[
                : len(lanes)
            ]
        return sender_ok, seal_ok

    def _sig_mask(self, zw, r, s, v, claimed, table, live) -> np.ndarray:
        """One signature-validity kernel launch: the sharded mask program
        over an attached mesh, the single-device recover ladder otherwise
        (identical argument layout — mesh_batch kept the sharded program a
        thin shell around the single-chip one)."""
        import jax.numpy as jnp

        sharded = self.mesh is not None
        kernel = self._mask_kernel if sharded else RECOVER_KERNEL
        program = "mesh_verify_mask" if sharded else "ecdsa_recover"
        with cost_ledger.dispatch_span(
            program,
            route="mesh" if sharded else "device",
            live_mask=live,
            kernels=((program, kernel),),
            site="sched/dispatch.py:_device",
        ):
            mask = kernel(
                jnp.asarray(zw),
                jnp.asarray(r),
                jnp.asarray(s),
                jnp.asarray(v),
                jnp.asarray(claimed),
                jnp.asarray(table),
                jnp.asarray(live),
            )
            return np.asarray(mask)

    # -- host route ------------------------------------------------------

    def _host(self, msgs, lanes, owners) -> Tuple[np.ndarray, np.ndarray]:
        digests: List[bytes] = []
        sigs: List[bytes] = []
        claimed: List[bytes] = []
        if msgs:
            payloads = []
            for m in msgs:
                owner = owners.get(id(m))
                hit = owner.lookup(m) if owner is not None else None
                payloads.append(
                    hit.payload
                    if hit is not None
                    else m.encode(include_signature=False)
                )
            digests.extend(keccak256_many(payloads))
            sigs.extend(m.signature for m in msgs)
            claimed.extend(m.sender for m in msgs)
        for proposal_hash, seal in lanes:
            digests.append(proposal_hash)
            sigs.append(seal.signature)
            claimed.append(seal.signer)
        mask = self._host_sig_ok(digests, sigs, claimed)
        return mask[: len(msgs)], mask[len(msgs) :]

    @staticmethod
    def _host_sig_ok(
        digests: List[bytes], sigs: List[bytes], claimed: List[bytes]
    ) -> np.ndarray:
        if not digests:
            return np.zeros(0, dtype=bool)
        from .. import native

        if native.load() is not None:
            # One bulk GIL-releasing call; the claimed-set table makes the
            # native membership check vacuous (recovered == claimed[i]
            # implies membership), leaving exactly signature validity.
            return native.verify_batch_sequential(
                digests, sigs, claimed, list(dict.fromkeys(claimed))
            )
        out = np.zeros(len(digests), dtype=bool)
        for i, (digest, sig, addr) in enumerate(zip(digests, sigs, claimed)):
            r = int.from_bytes(sig[:32], "big")
            s = int.from_bytes(sig[32:64], "big")
            pub = host_ecdsa.recover(digest, r, s, sig[64])
            out[i] = (
                pub is not None and host_ecdsa.pubkey_to_address(*pub) == addr
            )
        return out
