"""Dedup message store with quorum-event signaling.

Re-design of the reference's messages/messages.go:10-323: a per-type store
keyed ``type -> height -> round -> sender`` (one message per sender per view —
the Byzantine spam defense), validity-filtered reads that prune invalid
entries, height GC, and the best-RCC / most-RC queries.

Differences from the reference, by design:

- Thread-safe via per-type ``threading.RLock`` so an embedder may feed
  ``add_message`` from network threads while the asyncio engine drains.
- ``get_valid_messages`` returns messages in deterministic insertion order
  (Python dicts preserve it) instead of Go's random map order, which makes
  batched device verification reproducible.
- An optional *device mirror* hook: the store exposes ``snapshot_view`` which
  hands the batch verifier one contiguous list per (view, type) so quorum
  checks drain a single padded batch (SURVEY.md §2 #5).
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, Optional

from .events import EventManager, Subscription, SubscriptionDetails
from .wire import IbftMessage, MessageType, View

# sender -> message (one message per sender per view)
_SenderMap = dict[bytes, IbftMessage]
# round -> sender map
_RoundMap = dict[int, _SenderMap]
# height -> round map
_HeightMap = dict[int, _RoundMap]


class MessageStore:
    """Height/round/sender-keyed dedup store (reference messages/messages.go:10-22)."""

    def __init__(self) -> None:
        self._event_manager = EventManager()
        self._locks: dict[MessageType, threading.RLock] = {
            t: threading.RLock() for t in MessageType
        }
        self._maps: dict[MessageType, _HeightMap] = {t: {} for t in MessageType}

    # -- subscriptions ------------------------------------------------------

    def subscribe(self, details: SubscriptionDetails) -> Subscription:
        """Create a message-event subscription (reference messages/messages.go:25-27)."""
        return self._event_manager.subscribe(details)

    def unsubscribe(self, sub_id: int) -> None:
        """Cancel a subscription (reference messages/messages.go:30-32)."""
        self._event_manager.cancel_subscription(sub_id)

    def signal_event(self, message_type: MessageType, view: View) -> None:
        """Alert subscribers of a message event (reference messages/messages.go:68-72)."""
        self._event_manager.signal_event(message_type, view.copy())

    def close(self) -> None:
        """Shut down the event manager (reference messages/messages.go:75-77)."""
        self._event_manager.close()

    # -- modifiers ----------------------------------------------------------

    def add_message(self, message: IbftMessage) -> None:
        """Insert, deduplicating by sender (reference messages/messages.go:54-65).

        A later message from the same sender for the same view overwrites the
        earlier one, exactly as the reference's map assignment does.
        """
        if message.view is None:
            return
        if message.type not in self._locks:
            # Unknown open-enum type preserved by the wire codec: not a
            # consensus message, nothing subscribes to it — drop it instead
            # of crashing the embedder's receive path.
            return
        with self._locks[message.type]:
            height_map = self._maps[message.type]
            round_map = height_map.setdefault(message.view.height, {})
            sender_map = round_map.setdefault(message.view.round, {})
            sender_map[message.sender] = message

    def prune_by_height(self, height: int) -> None:
        """Drop all messages below ``height`` (reference messages/messages.go:123-148)."""
        for message_type in MessageType:
            with self._locks[message_type]:
                height_map = self._maps[message_type]
                for stale in [h for h in height_map if h < height]:
                    del height_map[stale]

    # -- fetchers -----------------------------------------------------------

    def num_messages(self, view: View, message_type: MessageType) -> int:
        """Count stored messages for a view (reference messages/messages.go:96-119)."""
        with self._locks[message_type]:
            sender_map = self._maps[message_type].get(view.height, {}).get(view.round)
            return len(sender_map) if sender_map else 0

    def get_valid_messages(
        self,
        view: View,
        message_type: MessageType,
        is_valid: Callable[[IbftMessage], bool],
    ) -> list[IbftMessage]:
        """Fetch messages passing ``is_valid``; prune the ones that fail.

        Mirrors the reference's GetValidMessages
        (messages/messages.go:169-199): invalid messages are removed from the
        store so they are never re-validated (and a Byzantine sender's slot
        frees up only for its own future messages).
        """
        with self._locks[message_type]:
            sender_map = self._maps[message_type].get(view.height, {}).get(view.round)
            if not sender_map:
                return []

            valid: list[IbftMessage] = []
            invalid_senders: list[bytes] = []
            for sender, message in sender_map.items():
                if is_valid(message):
                    valid.append(message)
                else:
                    invalid_senders.append(sender)

            for sender in invalid_senders:
                del sender_map[sender]

            return valid

    def remove_messages(
        self,
        view: View,
        message_type: MessageType,
        invalid: Iterable[IbftMessage],
    ) -> None:
        """Prune specific messages for a view, by identity.

        Batch-verification support: the engine fetches a whole view's messages
        with a trivial filter, verifies them in one device batch, then prunes
        the failures here — observationally equivalent to the reference's
        per-message ``isValid`` pruning inside GetValidMessages.

        Removal compares message identity, not just sender: a sender may have
        replaced its message between the snapshot and this call (the verify
        window holds no store lock), and the replacement must survive.
        """
        with self._locks[message_type]:
            sender_map = self._maps[message_type].get(view.height, {}).get(view.round)
            if not sender_map:
                return
            for message in invalid:
                if sender_map.get(message.sender) is message:
                    del sender_map[message.sender]

    def get_extended_rcc(
        self,
        height: int,
        is_valid_message: Callable[[IbftMessage], bool],
        is_valid_rcc: Callable[[int, list[IbftMessage]], bool],
    ) -> list[IbftMessage]:
        """Best (highest-round) valid round-change certificate for a height.

        Mirrors GetExtendedRCC (reference messages/messages.go:202-245).  The
        reference iterates the round map in Go's random order with a
        ``round <= highestRound`` skip; the fixed point of that loop is "the
        highest round whose valid-message set passes ``is_valid_rcc``" — and
        round 0 can never win (highestRound starts at 0).  We iterate rounds
        in descending order with an early exit, which lands on the same
        result deterministically and never pays the signature-heavy
        ``is_valid_message`` predicate for dominated rounds.
        """
        message_type = MessageType.ROUND_CHANGE
        with self._locks[message_type]:
            round_map = self._maps[message_type].get(height, {})

            # Descending with early exit: only the highest valid round can
            # win, so dominated rounds never pay the (signature-heavy)
            # is_valid_message predicate.
            for round_ in sorted(round_map, reverse=True):
                if round_ <= 0:
                    continue
                valid = [m for m in round_map[round_].values() if is_valid_message(m)]
                if is_valid_rcc(round_, valid):
                    return valid

            return []

    def get_most_round_change_messages(
        self, min_round: int, height: int
    ) -> list[IbftMessage]:
        """Largest round-change message set at or above ``min_round``.

        Mirrors GetMostRoundChangeMessages (reference
        messages/messages.go:249-286), including the quirk that round 0 can
        never be selected (``bestRound == 0`` means "not found").  Ties keep
        the first (lowest) qualifying round, which is deterministic here
        unlike Go's random map order.
        """
        message_type = MessageType.ROUND_CHANGE
        with self._locks[message_type]:
            round_map = self._maps[message_type].get(height, {})

            best_round = 0
            best_count = 0
            for round_ in sorted(round_map):
                if round_ < min_round:
                    continue
                size = len(round_map[round_])
                if size > best_count:
                    best_round = round_
                    best_count = size

            if best_round == 0:
                return []

            return list(round_map[best_round].values())

    # -- batch-verification support ----------------------------------------

    def snapshot_view(
        self, view: View, message_type: MessageType
    ) -> list[IbftMessage]:
        """Contiguous snapshot of a (view, type) cell for batched verification.

        Unlike ``get_valid_messages`` this does not run predicates or prune;
        it exists so the batch verifier can pack (sender, digest, signature)
        arrays in one pass and hand back a boolean mask.
        """
        with self._locks[message_type]:
            sender_map = self._maps[message_type].get(view.height, {}).get(view.round)
            return list(sender_map.values()) if sender_map else []
