"""Event manager: pub/sub signaling for message arrival.

Re-design of the reference's goroutine-per-subscription event plumbing
(messages/event_manager.go:13-129, messages/event_subscription.go:7-84) on
asyncio.  Semantics preserved exactly (SURVEY.md §2 #6):

- **Non-blocking, coalescing notify**: the reference pushes into a buffered
  channel and drops when full (event_subscription.go:72-84); here each
  subscription owns a bounded deque — excess notifications coalesce.  This is
  safe because subscribers always re-check the store after waking (the engine
  re-validates quorum on every wake).
- **Min-round matching**: a subscription either matches its round exactly or
  treats it as a lower bound (event_subscription.go:45-69).
- **Subscribe-then-recheck**: closing the "message arrived before we
  subscribed" race is the *engine's* job (reference core/ibft.go:1286-1298);
  the manager only guarantees no notification is lost-without-wakeup.

The reference spawns one goroutine per subscription to forward notifications;
on asyncio no forwarding task is needed — ``Subscription.wait`` consumes the
deque directly, so there is nothing to leak (goleak parity for free).
"""

from __future__ import annotations

import asyncio
import itertools
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from .wire import MessageType, View


def _running_loop_or_none() -> Optional[asyncio.AbstractEventLoop]:
    try:
        return asyncio.get_running_loop()
    except RuntimeError:
        return None


@dataclass
class SubscriptionDetails:
    """Requested subscription filter (reference messages/event_manager.go:42-58)."""

    message_type: MessageType
    view: View
    # Kept for API parity with the reference; the reference never consults it
    # when matching events (event_subscription.go:45-69).
    min_num_messages: int = 0
    has_min_round: bool = False


@dataclass
class Subscription:
    """A live subscription handle.

    ``wait()`` returns the round number carried by the next matching event, or
    ``None`` once the subscription is closed.  Notifications beyond the buffer
    coalesce (the subscriber re-reads the store on wake anyway).

    Wakeups are thread-safe: an embedder may push messages (and therefore
    signal events) from network threads while the engine's event loop awaits
    ``wait()`` — the owning loop is captured at subscription time and woken
    via ``call_soon_threadsafe`` when signaled from outside it.
    """

    id: int
    details: SubscriptionDetails
    _rounds: deque = field(default_factory=lambda: deque(maxlen=2))
    _wakeup: asyncio.Event = field(default_factory=asyncio.Event)
    _closed: bool = False
    _loop: Optional[asyncio.AbstractEventLoop] = field(
        default_factory=lambda: _running_loop_or_none()
    )

    def _set_wakeup(self) -> None:
        if self._loop is not None and _running_loop_or_none() is not self._loop:
            try:
                self._loop.call_soon_threadsafe(self._wakeup.set)
            except RuntimeError:
                # Owning loop already closed; nobody is waiting.
                pass
        else:
            self._wakeup.set()

    def _event_supported(self, message_type: MessageType, view: View) -> bool:
        """Match filter (reference messages/event_subscription.go:45-69)."""
        if view.height != self.details.view.height:
            return False
        if self.details.has_min_round:
            if view.round < self.details.view.round:
                return False
        else:
            if view.round != self.details.view.round:
                return False
        return message_type == self.details.message_type

    def push_event(self, message_type: MessageType, view: View) -> None:
        """Non-blocking notify (reference messages/event_subscription.go:72-84)."""
        if self._closed or not self._event_supported(message_type, view):
            return
        self._rounds.append(view.round)
        self._set_wakeup()

    def close(self) -> None:
        self._closed = True
        self._set_wakeup()

    def drain_pending(self) -> None:
        """Coalesce queued duplicate wakeups into the drain about to run.

        The engine re-reads the WHOLE store on every wake, so any event
        queued before the store read is already covered by it — processing
        it afterwards would re-run the phase drain for nothing.  Safe
        against lost wakeups: a message that signals after this clear is
        either already in the store (visible to the imminent re-read) or
        its signal lands in the emptied deque and wakes the next ``wait``.
        """
        self._rounds.clear()
        if not self._closed:
            self._wakeup.clear()

    async def wait(self) -> Optional[int]:
        """Await the next matching event's round; ``None`` after close."""
        while True:
            if self._rounds:
                round_ = self._rounds.popleft()
                if not self._rounds and not self._closed:
                    self._wakeup.clear()
                return round_
            if self._closed:
                return None
            await self._wakeup.wait()
            # Re-arm before re-checking: drain_pending may leave the event
            # set with an empty deque (a cross-thread push races the
            # clear); without this the loop would spin on a set event.
            self._wakeup.clear()


class EventManager:
    """Subscription registry (reference messages/event_manager.go:13-129)."""

    def __init__(self) -> None:
        self._subscriptions: dict[int, Subscription] = {}
        self._ids = itertools.count(1)
        # add_message arrives from embedder network threads while the engine
        # loop subscribes/unsubscribes; guard the registry like the store
        # guards its maps (reference relies on Go's per-field mutexes,
        # messages/event_manager.go:16,66,87).
        self._lock = threading.Lock()

    @property
    def num_subscriptions(self) -> int:
        return len(self._subscriptions)

    def subscribe(self, details: SubscriptionDetails) -> Subscription:
        """Register a listener (reference messages/event_manager.go:61-83)."""
        sub = Subscription(id=next(self._ids), details=details)
        with self._lock:
            self._subscriptions[sub.id] = sub
        return sub

    def cancel_subscription(self, sub_id: int) -> None:
        """Stop one subscription (reference messages/event_manager.go:86-95)."""
        with self._lock:
            sub = self._subscriptions.pop(sub_id, None)
        if sub is not None:
            sub.close()

    def close(self) -> None:
        """Cancel all subscriptions (reference messages/event_manager.go:98-107)."""
        with self._lock:
            subs = list(self._subscriptions.values())
            self._subscriptions.clear()
        for sub in subs:
            sub.close()

    def signal_event(self, message_type: MessageType, view: View) -> None:
        """Alert all matching listeners (reference messages/event_manager.go:110-129)."""
        with self._lock:
            subs = list(self._subscriptions.values())
        for sub in subs:
            sub.push_event(message_type, view)
