"""Wire schema for IBFT messages.

Python dataclasses mirroring the protobuf schema of the reference
(/root/reference/messages/proto/messages.proto:1-111) plus a minimal,
dependency-free protobuf wire codec.  Encoding follows proto3 semantics with
fields emitted in field-number order, which makes ``payload_no_sig`` bytes
byte-identical to the Go reference's ``(*IbftMessage).PayloadNoSig()``
(/root/reference/messages/proto/helper.go:13-27), so an embedder can
interoperate on signatures with go-ibft nodes.

Decoding follows proto3 merge semantics so foreign bytes parse exactly as a
protobuf implementation would: duplicated scalar fields keep the last value,
duplicated singular message fields merge, repeated fields append, switching
oneof members clears the previous member, and unknown enum values / fields
are preserved / skipped (enums are open in proto3).

The codec is deliberately tiny: four message types in a oneof envelope, two
certificate containers, ``View`` and ``Proposal``.  No reflection, no
generated code.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional, Union


class MessageType(enum.IntEnum):
    """Message types (reference messages/proto/messages.proto:7-12)."""

    PREPREPARE = 0
    PREPARE = 1
    COMMIT = 2
    ROUND_CHANGE = 3


def _open_enum(value: int) -> Union[MessageType, int]:
    """proto3 enums are open: unknown values are preserved, not rejected."""
    try:
        return MessageType(value)
    except ValueError:
        return value


# ---------------------------------------------------------------------------
# protobuf wire primitives
# ---------------------------------------------------------------------------

_WIRE_VARINT = 0
_WIRE_LEN = 2


def _encode_varint(value: int) -> bytes:
    if value < 0:
        raise ValueError("negative varint")
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _decode_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            if result > 0xFFFFFFFFFFFFFFFF:
                # protobuf varints are at most uint64
                raise ValueError("varint overflows uint64")
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def _tag(field_number: int, wire_type: int) -> bytes:
    return _encode_varint((field_number << 3) | wire_type)


def _emit_uint(out: bytearray, field_number: int, value: int) -> None:
    if value:
        out += _tag(field_number, _WIRE_VARINT)
        out += _encode_varint(value)


def _emit_bytes(out: bytearray, field_number: int, value: Optional[bytes]) -> None:
    # proto3: empty bytes are omitted; None means unset.
    if value:
        out += _tag(field_number, _WIRE_LEN)
        out += _encode_varint(len(value))
        out += value


def _emit_msg(out: bytearray, field_number: int, encoded: Optional[bytes]) -> None:
    # A set-but-empty nested message is emitted as tag + zero length,
    # distinguishable from unset (None) — matching Go pointer semantics.
    if encoded is not None:
        out += _tag(field_number, _WIRE_LEN)
        out += _encode_varint(len(encoded))
        out += encoded


def _read_bytes(buf: bytes, pos: int) -> tuple[bytes, int]:
    length, pos = _decode_varint(buf, pos)
    end = pos + length
    if end > len(buf):
        raise ValueError("truncated length-delimited field")
    return buf[pos:end], end


def _skip_field(buf: bytes, pos: int, wire_type: int) -> int:
    if wire_type == _WIRE_VARINT:
        _, pos = _decode_varint(buf, pos)
        return pos
    if wire_type == _WIRE_LEN:
        _, pos = _read_bytes(buf, pos)
        return pos
    if wire_type == 5:  # 32-bit
        if pos + 4 > len(buf):
            raise ValueError("truncated fixed32 field")
        return pos + 4
    if wire_type == 1:  # 64-bit
        if pos + 8 > len(buf):
            raise ValueError("truncated fixed64 field")
        return pos + 8
    raise ValueError(f"unsupported wire type {wire_type}")


class _Decodable:
    """Mixin providing proto3-merge decoding on top of ``_merge_field``."""

    @classmethod
    def decode(cls, buf: bytes):
        msg = cls()
        msg.merge_from(buf)
        return msg

    def merge_from(self, buf: bytes) -> None:
        """Parse ``buf`` into ``self`` with proto3 merge semantics."""
        pos = 0
        while pos < len(buf):
            key, pos = _decode_varint(buf, pos)
            fnum, wtype = key >> 3, key & 7
            consumed = self._merge_field(fnum, wtype, buf, pos)
            if consumed is None:
                pos = _skip_field(buf, pos, wtype)
            else:
                pos = consumed

    def _merge_field(
        self, fnum: int, wtype: int, buf: bytes, pos: int
    ) -> Optional[int]:
        raise NotImplementedError

    def _merge_nested(self, attr: str, klass, buf: bytes, pos: int) -> int:
        """Merge a length-delimited singular message field into ``attr``."""
        raw, pos = _read_bytes(buf, pos)
        existing = getattr(self, attr)
        if existing is None:
            existing = klass()
            setattr(self, attr, existing)
        existing.merge_from(raw)
        return pos


# ---------------------------------------------------------------------------
# message dataclasses
# ---------------------------------------------------------------------------


@dataclass
class View(_Decodable):
    """(height, round) pair (reference messages/proto/messages.proto:15-21)."""

    height: int = 0
    round: int = 0

    def encode(self) -> bytes:
        out = bytearray()
        _emit_uint(out, 1, self.height)
        _emit_uint(out, 2, self.round)
        return bytes(out)

    def _merge_field(self, fnum, wtype, buf, pos):
        if fnum == 1 and wtype == _WIRE_VARINT:
            self.height, pos = _decode_varint(buf, pos)
            return pos
        if fnum == 2 and wtype == _WIRE_VARINT:
            self.round, pos = _decode_varint(buf, pos)
            return pos
        return None

    def copy(self) -> "View":
        return View(self.height, self.round)


@dataclass
class Proposal(_Decodable):
    """(raw_proposal, round) tuple (reference messages/proto/messages.proto:104-110)."""

    raw_proposal: bytes = b""
    round: int = 0

    def encode(self) -> bytes:
        out = bytearray()
        _emit_bytes(out, 1, self.raw_proposal)
        _emit_uint(out, 2, self.round)
        return bytes(out)

    def _merge_field(self, fnum, wtype, buf, pos):
        if fnum == 1 and wtype == _WIRE_LEN:
            self.raw_proposal, pos = _read_bytes(buf, pos)
            return pos
        if fnum == 2 and wtype == _WIRE_VARINT:
            self.round, pos = _decode_varint(buf, pos)
            return pos
        return None


@dataclass
class PrePrepareMessage(_Decodable):
    """PREPREPARE payload (reference messages/proto/messages.proto:47-57)."""

    proposal: Optional[Proposal] = None
    proposal_hash: bytes = b""
    certificate: Optional["RoundChangeCertificate"] = None

    def encode(self) -> bytes:
        out = bytearray()
        _emit_msg(out, 1, self.proposal.encode() if self.proposal is not None else None)
        _emit_bytes(out, 2, self.proposal_hash)
        _emit_msg(
            out, 3, self.certificate.encode() if self.certificate is not None else None
        )
        return bytes(out)

    def _merge_field(self, fnum, wtype, buf, pos):
        if fnum == 1 and wtype == _WIRE_LEN:
            return self._merge_nested("proposal", Proposal, buf, pos)
        if fnum == 2 and wtype == _WIRE_LEN:
            self.proposal_hash, pos = _read_bytes(buf, pos)
            return pos
        if fnum == 3 and wtype == _WIRE_LEN:
            return self._merge_nested("certificate", RoundChangeCertificate, buf, pos)
        return None


@dataclass
class PrepareMessage(_Decodable):
    """PREPARE payload (reference messages/proto/messages.proto:60-63)."""

    proposal_hash: bytes = b""

    def encode(self) -> bytes:
        out = bytearray()
        _emit_bytes(out, 1, self.proposal_hash)
        return bytes(out)

    def _merge_field(self, fnum, wtype, buf, pos):
        if fnum == 1 and wtype == _WIRE_LEN:
            self.proposal_hash, pos = _read_bytes(buf, pos)
            return pos
        return None


@dataclass
class CommitMessage(_Decodable):
    """COMMIT payload (reference messages/proto/messages.proto:66-72)."""

    proposal_hash: bytes = b""
    committed_seal: bytes = b""

    def encode(self) -> bytes:
        out = bytearray()
        _emit_bytes(out, 1, self.proposal_hash)
        _emit_bytes(out, 2, self.committed_seal)
        return bytes(out)

    def _merge_field(self, fnum, wtype, buf, pos):
        if fnum == 1 and wtype == _WIRE_LEN:
            self.proposal_hash, pos = _read_bytes(buf, pos)
            return pos
        if fnum == 2 and wtype == _WIRE_LEN:
            self.committed_seal, pos = _read_bytes(buf, pos)
            return pos
        return None


@dataclass
class RoundChangeMessage(_Decodable):
    """ROUND_CHANGE payload (reference messages/proto/messages.proto:75-83)."""

    last_prepared_proposal: Optional[Proposal] = None
    latest_prepared_certificate: Optional["PreparedCertificate"] = None

    def encode(self) -> bytes:
        out = bytearray()
        _emit_msg(
            out,
            1,
            self.last_prepared_proposal.encode()
            if self.last_prepared_proposal is not None
            else None,
        )
        _emit_msg(
            out,
            2,
            self.latest_prepared_certificate.encode()
            if self.latest_prepared_certificate is not None
            else None,
        )
        return bytes(out)

    def _merge_field(self, fnum, wtype, buf, pos):
        if fnum == 1 and wtype == _WIRE_LEN:
            return self._merge_nested("last_prepared_proposal", Proposal, buf, pos)
        if fnum == 2 and wtype == _WIRE_LEN:
            return self._merge_nested(
                "latest_prepared_certificate", PreparedCertificate, buf, pos
            )
        return None


@dataclass
class PreparedCertificate(_Decodable):
    """Proposal + quorum-1 PREPAREs (reference messages/proto/messages.proto:87-94)."""

    proposal_message: Optional["IbftMessage"] = None
    prepare_messages: Optional[list["IbftMessage"]] = None

    def encode(self) -> bytes:
        out = bytearray()
        _emit_msg(
            out,
            1,
            self.proposal_message.encode()
            if self.proposal_message is not None
            else None,
        )
        for msg in self.prepare_messages or ():
            _emit_msg(out, 2, msg.encode())
        return bytes(out)

    def _merge_field(self, fnum, wtype, buf, pos):
        if fnum == 1 and wtype == _WIRE_LEN:
            return self._merge_nested("proposal_message", IbftMessage, buf, pos)
        if fnum == 2 and wtype == _WIRE_LEN:
            raw, pos = _read_bytes(buf, pos)
            if self.prepare_messages is None:
                self.prepare_messages = []
            self.prepare_messages.append(IbftMessage.decode(raw))
            return pos
        return None


@dataclass
class RoundChangeCertificate(_Decodable):
    """Quorum of ROUND_CHANGEs (reference messages/proto/messages.proto:98-101)."""

    round_change_messages: list["IbftMessage"] = field(default_factory=list)

    def encode(self) -> bytes:
        out = bytearray()
        for msg in self.round_change_messages:
            _emit_msg(out, 1, msg.encode())
        return bytes(out)

    def _merge_field(self, fnum, wtype, buf, pos):
        if fnum == 1 and wtype == _WIRE_LEN:
            raw, pos = _read_bytes(buf, pos)
            self.round_change_messages.append(IbftMessage.decode(raw))
            return pos
        return None


_PAYLOAD_ATTRS = {
    5: "preprepare_data",
    6: "prepare_data",
    7: "commit_data",
    8: "round_change_data",
}
_PAYLOAD_TYPES = {
    5: PrePrepareMessage,
    6: PrepareMessage,
    7: CommitMessage,
    8: RoundChangeMessage,
}


@dataclass
class IbftMessage(_Decodable):
    """The oneof envelope (reference messages/proto/messages.proto:24-44).

    Exactly one of ``preprepare_data`` / ``prepare_data`` / ``commit_data`` /
    ``round_change_data`` should be set (the oneof payload); setting more than
    one encodes all of them, matching no valid wire message.

    ``type`` is normally a :class:`MessageType` but may be a plain ``int`` for
    unknown values decoded from foreign bytes (proto3 enums are open).
    """

    view: Optional[View] = None
    sender: bytes = b""  # `from` in the .proto; `from` is reserved in Python
    signature: bytes = b""
    type: Union[MessageType, int] = MessageType.PREPREPARE
    preprepare_data: Optional[PrePrepareMessage] = None
    prepare_data: Optional[PrepareMessage] = None
    commit_data: Optional[CommitMessage] = None
    round_change_data: Optional[RoundChangeMessage] = None

    def encode(self, *, include_signature: bool = True) -> bytes:
        out = bytearray()
        _emit_msg(out, 1, self.view.encode() if self.view is not None else None)
        _emit_bytes(out, 2, self.sender)
        if include_signature:
            _emit_bytes(out, 3, self.signature)
        _emit_uint(out, 4, int(self.type))
        _emit_msg(
            out,
            5,
            self.preprepare_data.encode() if self.preprepare_data is not None else None,
        )
        _emit_msg(
            out, 6, self.prepare_data.encode() if self.prepare_data is not None else None
        )
        _emit_msg(
            out, 7, self.commit_data.encode() if self.commit_data is not None else None
        )
        _emit_msg(
            out,
            8,
            self.round_change_data.encode()
            if self.round_change_data is not None
            else None,
        )
        return bytes(out)

    def _merge_field(self, fnum, wtype, buf, pos):
        if fnum == 1 and wtype == _WIRE_LEN:
            return self._merge_nested("view", View, buf, pos)
        if fnum == 2 and wtype == _WIRE_LEN:
            self.sender, pos = _read_bytes(buf, pos)
            return pos
        if fnum == 3 and wtype == _WIRE_LEN:
            self.signature, pos = _read_bytes(buf, pos)
            return pos
        if fnum == 4 and wtype == _WIRE_VARINT:
            raw_type, pos = _decode_varint(buf, pos)
            self.type = _open_enum(raw_type)
            return pos
        if fnum in _PAYLOAD_ATTRS and wtype == _WIRE_LEN:
            # oneof semantics: switching members clears the previous member;
            # re-seeing the active member merges into it.
            for other_fnum, attr in _PAYLOAD_ATTRS.items():
                if other_fnum != fnum:
                    setattr(self, attr, None)
            return self._merge_nested(
                _PAYLOAD_ATTRS[fnum], _PAYLOAD_TYPES[fnum], buf, pos
            )
        return None

    def payload_no_sig(self) -> bytes:
        """Canonical signing bytes: the message with the signature nulled.

        Mirrors the reference's PayloadNoSig
        (/root/reference/messages/proto/helper.go:13-27).  These are the bytes
        an embedder signs and verifies.
        """
        return self.encode(include_signature=False)


# ---------------------------------------------------------------------------
# trace-context propagation (cross-process telemetry plane)
# ---------------------------------------------------------------------------


@dataclass
class TraceContext(_Decodable):
    """Compact per-message trace context carried OUTSIDE the signed bytes.

    The telemetry plane stamps every outbound consensus message with the
    sender's identity and clock so receivers can record causally-linked
    ``net.recv`` events and estimate per-peer clock offsets
    (``go_ibft_tpu.obs.clock``).  The context rides as a framing layer
    AROUND the message (:func:`encode_traced`), never inside
    ``IbftMessage`` — ``payload_no_sig`` and therefore every signature
    stays byte-identical to the reference, traced or not.

    Fields: ``origin`` is the sender's flight-recorder track (one row per
    node), ``height``/``round`` the message's view, ``sent_us`` the
    sender's monotonic ``perf_counter_ns() // 1000`` at multicast time
    (meaningless across processes except as a clock-offset sample), and
    ``span_id`` a per-process send counter linking the sender's
    ``net.send`` instant to every receiver's ``net.recv``.
    """

    origin: str = ""
    height: int = 0
    round: int = 0
    sent_us: int = 0
    span_id: int = 0
    # Delivery-side bookkeeping, never encoded: a transport that already
    # recorded the net.recv for this context (GrpcTransport does, at the
    # wire boundary) sets this so the engine ingress does not record it a
    # second time.  Loopback dispatch leaves it False — the SAME message
    # object reaches every receiver, and each engine records its own recv.
    recorded: bool = False

    def encode(self) -> bytes:
        out = bytearray()
        _emit_bytes(out, 1, self.origin.encode("utf-8"))
        _emit_uint(out, 2, self.height)
        _emit_uint(out, 3, self.round)
        _emit_uint(out, 4, self.sent_us)
        _emit_uint(out, 5, self.span_id)
        return bytes(out)

    def _merge_field(self, fnum, wtype, buf, pos):
        if fnum == 1 and wtype == _WIRE_LEN:
            raw, pos = _read_bytes(buf, pos)
            self.origin = raw.decode("utf-8", "replace")
            return pos
        if fnum == 2 and wtype == _WIRE_VARINT:
            self.height, pos = _decode_varint(buf, pos)
            return pos
        if fnum == 3 and wtype == _WIRE_VARINT:
            self.round, pos = _decode_varint(buf, pos)
            return pos
        if fnum == 4 and wtype == _WIRE_VARINT:
            self.sent_us, pos = _decode_varint(buf, pos)
            return pos
        if fnum == 5 and wtype == _WIRE_VARINT:
            self.span_id, pos = _decode_varint(buf, pos)
            return pos
        return None


# Framing magic for traced payloads.  The first byte decodes as protobuf
# tag (field 26, wire type 7) — wire type 7 does not exist, so no valid
# ``IbftMessage`` encoding can ever start with it: a receiver can always
# tell a traced frame from a bare message without version negotiation.
TRACED_MAGIC = b"\xd7TCX"


def encode_traced(message_bytes: bytes, ctx: TraceContext) -> bytes:
    """Wrap encoded message bytes with a trace-context frame."""
    ctx_bytes = ctx.encode()
    return (
        TRACED_MAGIC + _encode_varint(len(ctx_bytes)) + ctx_bytes + message_bytes
    )


def decode_traced(data: bytes) -> tuple[bytes, Optional[TraceContext]]:
    """Split a payload into (message bytes, trace context or ``None``).

    Bare (untraced) payloads pass through unchanged — the framing is
    strictly additive, and a malformed trace frame from an untrusted peer
    degrades to ``None`` context rather than an error (telemetry must
    never affect message delivery; the message bytes themselves still go
    through the usual decode-and-verify path).
    """
    if not data.startswith(TRACED_MAGIC):
        return data, None
    try:
        length, pos = _decode_varint(data, len(TRACED_MAGIC))
        end = pos + length
        if end > len(data):
            raise ValueError("truncated trace context")
        ctx = TraceContext.decode(data[pos:end])
        return data[end:], ctx
    except ValueError:
        return data[len(TRACED_MAGIC):], None
