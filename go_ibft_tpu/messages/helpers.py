"""Stateless extractors and certificate validators.

Mirrors the behavior of the reference's messages/helpers.go:16-227: payload
extraction out of the oneof envelope and the PreparedCertificate message-set
validity rules.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Optional, Sequence

from .wire import (
    IbftMessage,
    MessageType,
    PreparedCertificate,
    Proposal,
    RoundChangeCertificate,
)


class WrongCommitMessageTypeError(ValueError):
    """A non-COMMIT message was included in COMMIT messages.

    Mirrors ErrWrongCommitMessageType (reference messages/helpers.go:12).
    """


@dataclass
class CommittedSeal:
    """Validator proof of signing a committed proposal.

    Mirrors messages.CommittedSeal (reference messages/helpers.go:16-19).
    """

    signer: bytes
    signature: bytes


def extract_committed_seals(
    commit_messages: Sequence[IbftMessage],
) -> list[CommittedSeal]:
    """Extract committed seals (reference messages/helpers.go:22-35).

    Raises WrongCommitMessageTypeError if a non-COMMIT message sneaks in.
    """
    seals = []
    for msg in commit_messages:
        if msg.type != MessageType.COMMIT:
            raise WrongCommitMessageTypeError(
                "wrong type message is included in COMMIT messages"
            )
        seal = extract_committed_seal(msg)
        if seal is not None:
            seals.append(seal)
    return seals


def extract_committed_seal(commit_message: IbftMessage) -> Optional[CommittedSeal]:
    """Extract one committed seal (reference messages/helpers.go:38-48)."""
    if commit_message.commit_data is None:
        return None
    return CommittedSeal(
        signer=commit_message.sender,
        signature=commit_message.commit_data.committed_seal,
    )


def extract_commit_hash(commit_message: IbftMessage) -> Optional[bytes]:
    """Extract COMMIT proposal hash (reference messages/helpers.go:51-62)."""
    if commit_message.type != MessageType.COMMIT:
        return None
    if commit_message.commit_data is None:
        return None
    return commit_message.commit_data.proposal_hash


def extract_proposal(proposal_message: IbftMessage) -> Optional[Proposal]:
    """Extract the (raw, round) proposal (reference messages/helpers.go:65-76)."""
    if proposal_message.type != MessageType.PREPREPARE:
        return None
    if proposal_message.preprepare_data is None:
        return None
    return proposal_message.preprepare_data.proposal


def extract_proposal_hash(proposal_message: IbftMessage) -> Optional[bytes]:
    """Extract PREPREPARE proposal hash (reference messages/helpers.go:79-90)."""
    if proposal_message.type != MessageType.PREPREPARE:
        return None
    if proposal_message.preprepare_data is None:
        return None
    return proposal_message.preprepare_data.proposal_hash


def extract_round_change_certificate(
    proposal_message: IbftMessage,
) -> Optional[RoundChangeCertificate]:
    """Extract the RCC from a PREPREPARE (reference messages/helpers.go:93-104)."""
    if proposal_message.type != MessageType.PREPREPARE:
        return None
    if proposal_message.preprepare_data is None:
        return None
    return proposal_message.preprepare_data.certificate


def extract_prepare_hash(prepare_message: IbftMessage) -> Optional[bytes]:
    """Extract PREPARE proposal hash (reference messages/helpers.go:107-118)."""
    if prepare_message.type != MessageType.PREPARE:
        return None
    if prepare_message.prepare_data is None:
        return None
    return prepare_message.prepare_data.proposal_hash


def extract_latest_pc(
    round_change_message: IbftMessage,
) -> Optional[PreparedCertificate]:
    """Extract the latest PC (reference messages/helpers.go:121-132)."""
    if round_change_message.type != MessageType.ROUND_CHANGE:
        return None
    if round_change_message.round_change_data is None:
        return None
    return round_change_message.round_change_data.latest_prepared_certificate


def extract_last_prepared_proposal(
    round_change_message: IbftMessage,
) -> Optional[Proposal]:
    """Extract the last prepared proposal (reference messages/helpers.go:135-146)."""
    if round_change_message.type != MessageType.ROUND_CHANGE:
        return None
    if round_change_message.round_change_data is None:
        return None
    return round_change_message.round_change_data.last_prepared_proposal


def has_unique_senders(messages: Iterable[IbftMessage]) -> bool:
    """True iff non-empty and all senders distinct (reference messages/helpers.go:149-166)."""
    seen: set[bytes] = set()
    count = 0
    for msg in messages:
        count += 1
        if msg.sender in seen:
            return False
        seen.add(msg.sender)
    return count > 0


def are_valid_pc_messages(
    messages: Sequence[IbftMessage], height: int, round_limit: int
) -> bool:
    """Validate a PreparedCertificate's message set.

    Mirrors AreValidPCMessages (reference messages/helpers.go:169-213): the set
    must be non-empty; all messages share one height (== ``height``) and one
    round (< ``round_limit``); all carry the same proposal hash (extracted per
    message type — COMMIT/ROUND_CHANGE messages are invalid here); and all
    senders are unique.
    """
    if len(messages) < 1:
        return False

    if messages[0].view is None:
        return False
    round_ = messages[0].view.round
    senders: set[bytes] = set()
    hash_: Optional[bytes] = None

    for msg in messages:
        if msg.view is None or msg.view.height != height:
            return False
        if msg.view.round != round_ or msg.view.round >= round_limit:
            return False

        extracted, ok = _extract_pc_message_hash(msg)
        if hash_ is None:
            # No previous hash for comparison: the first one becomes the
            # reference (stays None while extracted hashes are missing,
            # matching Go's nil-slice semantics where nil == empty).
            hash_ = extracted
        if not ok or (hash_ or b"") != (extracted or b""):
            return False

        if msg.sender in senders:
            return False
        senders.add(msg.sender)

    return True


def _extract_pc_message_hash(message: IbftMessage) -> tuple[Optional[bytes], bool]:
    """Extract the hash a PC member commits to (reference messages/helpers.go:216-227).

    Returns ``(hash, ok)``: ``ok`` is False for message types that cannot be
    part of a PC (COMMIT / ROUND_CHANGE); a PREPREPARE/PREPARE with a missing
    payload yields ``(None, True)``, matching the Go nil-slice semantics.
    """
    if message.type == MessageType.PREPREPARE:
        return extract_proposal_hash(message), True
    if message.type == MessageType.PREPARE:
        return extract_prepare_hash(message), True
    return None, False
