"""Message layer: wire schema, dedup store, events, helpers.

TPU-native re-design of the reference's L1+L2 (messages/ package): see
SURVEY.md §1.  The wire codec produces signing bytes byte-identical to the
reference's protobuf marshaling for interop.
"""

from .events import EventManager, Subscription, SubscriptionDetails
from .helpers import (
    CommittedSeal,
    WrongCommitMessageTypeError,
    are_valid_pc_messages,
    extract_commit_hash,
    extract_committed_seal,
    extract_committed_seals,
    extract_last_prepared_proposal,
    extract_latest_pc,
    extract_prepare_hash,
    extract_proposal,
    extract_proposal_hash,
    extract_round_change_certificate,
    has_unique_senders,
)
from .store import MessageStore
from .wire import (
    CommitMessage,
    IbftMessage,
    MessageType,
    PreparedCertificate,
    PrepareMessage,
    PrePrepareMessage,
    Proposal,
    RoundChangeCertificate,
    RoundChangeMessage,
    View,
)

__all__ = [
    "CommitMessage",
    "CommittedSeal",
    "EventManager",
    "IbftMessage",
    "MessageStore",
    "MessageType",
    "PreparedCertificate",
    "PrepareMessage",
    "PrePrepareMessage",
    "Proposal",
    "RoundChangeCertificate",
    "RoundChangeMessage",
    "Subscription",
    "SubscriptionDetails",
    "View",
    "WrongCommitMessageTypeError",
    "are_valid_pc_messages",
    "extract_commit_hash",
    "extract_committed_seal",
    "extract_committed_seals",
    "extract_last_prepared_proposal",
    "extract_latest_pc",
    "extract_prepare_hash",
    "extract_proposal",
    "extract_proposal_hash",
    "extract_round_change_certificate",
    "has_unique_senders",
]
