"""The production boot layer (ROADMAP item 5: kill the cold boot).

A restarted node historically paid minutes of XLA:CPU compile before its
first round (BENCH_r04: ~3 minutes for ``quorum_certify`` alone) — fatal
for fleet operations where nodes restart constantly.  This package makes
restart cost a cache load instead:

* :mod:`~go_ibft_tpu.boot.registry` — the pinned program registry: one
  buildable ``(lowerable, args)`` per compile-budget family.  This is the
  SAME registry ``scripts/compile_budget.py`` lowers for its trace-size
  ratchet, so the AOT store and the budget guard can never drift apart.
* :mod:`~go_ibft_tpu.boot.aot` — the AOT program store: lowers and
  compiles every pinned family through JAX's persistent compilation
  cache (``GO_IBFT_CACHE_DIR``), classifies each restore cold vs cached
  by measured wall, and records cold compiles to the cost ledger.
* :mod:`~go_ibft_tpu.boot.warmstart` — warm-start: WAL replay +
  verdict-cache seeding + compiled-program restore, all *before* the
  first round opens.
* ``python -m go_ibft_tpu.boot`` — the restart-to-first-finalized
  harness bench config #14 measures (one full boot in a child process).
"""

from .aot import AOTStore, ProgramStatus, fingerprint, load_manifest, write_manifest
from .registry import ProgramUnavailable, program_registry
from .warmstart import WarmStartReport, seed_verdict_caches, warm_start

__all__ = [
    "AOTStore",
    "ProgramStatus",
    "ProgramUnavailable",
    "WarmStartReport",
    "fingerprint",
    "load_manifest",
    "program_registry",
    "seed_verdict_caches",
    "warm_start",
    "write_manifest",
]
