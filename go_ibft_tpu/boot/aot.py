"""The AOT program store: restore compiled programs before the first round.

Artifacts live in two layers under the persistent cache directory
(``GO_IBFT_CACHE_DIR``, resolved by :mod:`go_ibft_tpu.utils.jaxcache`):

* **XLA's persistent compilation cache** — jax keys entries on the HLO
  module + compile options + jax/XLA version + device topology, so a
  stale or cross-backend artifact can never *load* as a wrong program;
  at worst the key misses and the compile runs cold.
* **``<cache_dir>/aot/``** — this store's sidecars: one JSON per pinned
  program recording the :func:`fingerprint` (jax version, backend,
  device count, program family + shape suffix) plus the measured
  lower/compile wall, and optionally the ``jax.export``-serialized
  StableHLO artifact next to it.  The fingerprint gates *reporting and
  skip decisions*: a sidecar minted by a different jax/backend/topology
  marks the program stale, so boot tooling re-compiles it — a recorded
  cold compile, never a trusted stale artifact.

Cold vs cached classification is by measured compile wall against
``cold_threshold_s`` (``GO_IBFT_BOOT_COLD_S``, default 15 s): on this
repo's CPU posture every pinned family compiles cold in ≥ ~50 s and
loads warm in ≤ ~5 s, so the default separates the regimes with margin;
programs below jax's own 1 s persistence floor (the keccak digest pack)
are never classified cold — they cost less than the classification
would.  Cold restores are recorded to the cost ledger
(``compile_ledger.jsonl`` when enabled), which is how the second-boot
zero-cold-compile proof in tests/test_boot.py reads its evidence.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import tempfile
import time
from typing import Dict, Iterable, Optional, Sequence, Tuple

from ..obs import ledger as cost_ledger
from ..utils.jaxcache import enable_persistent_cache, resolve_cache_dir
from .registry import ProgramUnavailable, program_registry

__all__ = [
    "AOTStore",
    "ProgramStatus",
    "family_of",
    "fingerprint",
    "load_manifest",
    "write_manifest",
]

# Shape-suffix stripper shared with scripts/cost_report.py's attribution:
# registry keys are ``<family>_<shape suffix>`` (``_8l``, ``_128v``,
# ``_dp4``); ledger events carry bare family names.
_SHAPE_SUFFIX = re.compile(r"(_dp\d+|_\d+[lv])$")

DEFAULT_COLD_THRESHOLD_S = 15.0


def family_of(program: str) -> str:
    """Strip shape suffixes iteratively (``mesh_verify_mask_8l_dp4`` ->
    ``mesh_verify_mask``)."""
    while True:
        stripped = _SHAPE_SUFFIX.sub("", program)
        if stripped == program:
            return program
        program = stripped


def fingerprint() -> dict:
    """The artifact-validity key: jax version + backend + device count.

    Program family and shape suffix join this per sidecar (the sidecar
    file name IS the registry key), completing the ISSUE-16 key tuple.
    """
    import jax

    try:
        devices = jax.devices()
        backend = devices[0].platform
        count = len(devices)
    except RuntimeError:
        backend, count = "none", 0
    return {
        "jax": jax.__version__,
        "backend": backend,
        "device_count": count,
    }


@dataclasses.dataclass
class ProgramStatus:
    """One program's restore outcome."""

    program: str
    family: str
    status: str  # "cold" | "cached" | "skipped"
    compile_ms: float = 0.0
    lower_ms: float = 0.0
    reason: Optional[str] = None
    exported: bool = False


class AOTStore:
    """Lower + compile pinned program families through the persistent
    cache, with sidecar bookkeeping for skip/report decisions.

    ``cache_dir=None`` resolves through the jaxcache chain (explicit >
    ``GO_IBFT_CACHE_DIR`` > ``JAX_COMPILATION_CACHE_DIR`` > default).
    Note jax pins its compilation cache dir for the process on first
    enable — an explicit ``cache_dir`` differing from an already-enabled
    one affects only the sidecar store, so boot harnesses set
    ``GO_IBFT_CACHE_DIR`` before importing jax-heavy modules.
    """

    def __init__(
        self,
        cache_dir: Optional[str] = None,
        *,
        cold_threshold_s: Optional[float] = None,
        site: str = "boot/aot.py",
    ) -> None:
        self.cache_dir = cache_dir or resolve_cache_dir()
        self.store_dir = os.path.join(self.cache_dir, "aot")
        if cold_threshold_s is None:
            cold_threshold_s = float(
                os.environ.get("GO_IBFT_BOOT_COLD_S", DEFAULT_COLD_THRESHOLD_S)
            )
        self.cold_threshold_s = cold_threshold_s
        self.site = site

    # -- sidecars --------------------------------------------------------

    def _sidecar_path(self, program: str) -> str:
        return os.path.join(self.store_dir, f"{program}.json")

    def read_sidecar(self, program: str) -> Optional[dict]:
        try:
            with open(self._sidecar_path(program)) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def _write_sidecar(self, program: str, payload: dict) -> None:
        """Atomic write, never raising (the probe-cache posture: a
        read-only store degrades to no bookkeeping, not a boot fault)."""
        try:
            os.makedirs(self.store_dir, exist_ok=True)
            fd, tmp = tempfile.mkstemp(
                dir=self.store_dir, prefix=f".{program}.", suffix=".tmp"
            )
            with os.fdopen(fd, "w") as fh:
                json.dump(payload, fh)
            os.replace(tmp, self._sidecar_path(program))
        except OSError:
            pass

    def cached_programs(self) -> set:
        """Registry keys whose sidecar fingerprint matches THIS process —
        programs a prior boot/warm run compiled into the same cache under
        the same jax/backend/topology.  A mismatched sidecar is stale:
        the caller re-compiles (recorded cold), never trusts it."""
        fp = fingerprint()
        out = set()
        for program in program_registry():
            side = self.read_sidecar(program)
            if (
                side is not None
                and side.get("fingerprint") == fp
                and side.get("status") in ("cold", "cached")
            ):
                out.add(program)
        return out

    # -- restore ---------------------------------------------------------

    def pinned_programs(self) -> Tuple[str, ...]:
        return tuple(program_registry())

    def ensure(
        self,
        programs: Optional[Sequence[str]] = None,
        *,
        record: bool = True,
        export: bool = False,
    ) -> Dict[str, ProgramStatus]:
        """Restore ``programs`` (default: every pinned family).

        Each program is lowered at its registry shape and compiled
        through the persistent cache: a warm cache makes ``.compile()``
        a load (measured, classified ``"cached"``); a cold or stale one
        pays the real compile (classified ``"cold"`` past the
        threshold and recorded to the cost ledger when ``record``).
        ``export=True`` additionally serializes the ``jax.export``
        artifact next to the sidecar (provenance/ops tooling; the
        runtime always dispatches its own jit objects).
        """
        enable_persistent_cache()
        out: Dict[str, ProgramStatus] = {}
        for program, build in program_registry(programs).items():
            family = family_of(program)
            try:
                t0 = time.perf_counter()
                fn, args = build()
                lowered = fn.lower(*args)
                t1 = time.perf_counter()
                lowered.compile()
                t2 = time.perf_counter()
            except ProgramUnavailable as exc:
                out[program] = ProgramStatus(
                    program, family, "skipped", reason=str(exc)
                )
                continue
            compile_s = t2 - t1
            status = ProgramStatus(
                program,
                family,
                "cold" if compile_s >= self.cold_threshold_s else "cached",
                compile_ms=compile_s * 1e3,
                lower_ms=(t1 - t0) * 1e3,
            )
            if status.status == "cold" and record:
                cost_ledger.record_compile(
                    family, status.compile_ms, site=self.site
                )
            if export:
                status.exported = self._export(program, fn, args)
            out[program] = status
            self._write_sidecar(
                program,
                {
                    "program": program,
                    "family": family,
                    "fingerprint": fingerprint(),
                    "status": status.status,
                    "compile_ms": round(status.compile_ms, 3),
                    "lower_ms": round(status.lower_ms, 3),
                    "exported": status.exported,
                    "ts": time.time(),
                },
            )
        return out

    def _export(self, program: str, fn, args) -> bool:
        """Serialize the ``jax.export`` artifact (best-effort: programs
        jax.export cannot serialize — shard_map shells on some versions —
        degrade to sidecar-only bookkeeping)."""
        try:
            from jax import export as jax_export

            blob = jax_export.export(fn)(*args).serialize()
            os.makedirs(self.store_dir, exist_ok=True)
            fd, tmp = tempfile.mkstemp(
                dir=self.store_dir, prefix=f".{program}.", suffix=".tmp"
            )
            with os.fdopen(fd, "wb") as fh:
                fh.write(blob)
            os.replace(tmp, os.path.join(self.store_dir, f"{program}.bin"))
            return True
        except Exception:  # noqa: BLE001 - export is provenance, not boot
            return False


# -- the machine-readable AOT manifest (scripts/warm_kernels.py emits,
# -- boot consumes) -----------------------------------------------------


def write_manifest(
    path: str,
    programs: Dict[str, dict],
    *,
    sizes: Iterable[int] = (),
) -> dict:
    """Write the AOT manifest: measured per-family compile cost under a
    fingerprint.  ``programs`` maps family -> ``{"compile_ms": float,
    "events": int}`` (the cost-ledger snapshot's compile table)."""
    doc = {
        "fingerprint": fingerprint(),
        "generated_ts": time.time(),
        "sizes": sorted(int(s) for s in sizes),
        "programs": {
            name: {
                "compile_ms": round(float(acc.get("compile_ms", 0.0)), 3),
                "events": int(acc.get("events", 0)),
            }
            for name, acc in sorted(programs.items())
        },
    }
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".aot_manifest.", suffix=".tmp")
    with os.fdopen(fd, "w") as fh:
        json.dump(doc, fh, indent=1)
        fh.write("\n")
    os.replace(tmp, path)
    return doc


def load_manifest(path: str) -> Optional[dict]:
    """Read a manifest; adds ``"stale"`` (fingerprint mismatch with THIS
    process — consumers must treat every family as a cold candidate)."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return None
    doc["stale"] = doc.get("fingerprint") != fingerprint()
    return doc
