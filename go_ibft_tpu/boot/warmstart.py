"""Warm-start: restore durable state *before* the first round opens.

A restarted node has three kinds of warmth to recover, in cost order:

1. **The compiled program set** — :class:`~go_ibft_tpu.boot.aot.AOTStore`
   restores every requested pinned family through the persistent cache
   (cache loads on a warm cache; recorded cold compiles on a cold or
   stale one).
2. **The WAL** — ``ChainRunner.recover()`` replays the durable chain and
   the in-flight prepared-certificate lock (unchanged; warm-start calls
   it, it does not reimplement it).
3. **Verdict caches** — every committed seal persisted in a finalized
   WAL block was quorum-verified before it was written, so its verdict
   is re-derivable from the WAL alone: :func:`seed_verdict_caches`
   replays ``True`` into the scheduler tenant's seal-verdict cache (the
   ``(signer, proposal_hash, signature, height)`` key) and the serve
   plane's :class:`~go_ibft_tpu.serve.SigVerdictCache` (the
   ``(proposal_hash, signer, signature)`` key).  Blocks carrying an
   aggregate certificate have no per-seal lanes and are skipped.
   :class:`~go_ibft_tpu.verify.pipeline.PackCache` entries are keyed on
   live message *objects* and are deliberately NOT persisted — they
   rebuild on first pack; restoring them cross-process would alias dead
   ids (docs/PERFORMANCE.md "Boot & warm-start").

The second-boot proof rides the cost ledger: enable it with a
``compile_log`` and a warm boot records ZERO cold-compile events for the
restored set (tests/test_boot.py pins this in a subprocess).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, Optional, Sequence

from ..obs import trace
from ..utils.jaxcache import enable_persistent_cache
from .aot import AOTStore, ProgramStatus, family_of, load_manifest

__all__ = ["WarmStartReport", "seed_verdict_caches", "warm_start"]


@dataclasses.dataclass
class WarmStartReport:
    """What one warm start restored, and what each part cost."""

    cache_dir: str
    height: int = 0
    programs: Dict[str, ProgramStatus] = dataclasses.field(default_factory=dict)
    seeded_seal_verdicts: int = 0
    seeded_sig_verdicts: int = 0
    warmup_ms: float = 0.0
    total_ms: float = 0.0

    def by_status(self, status: str) -> list:
        return [p for p in self.programs.values() if p.status == status]

    @property
    def cold(self) -> list:
        return self.by_status("cold")

    @property
    def cached(self) -> list:
        return self.by_status("cached")

    @property
    def skipped(self) -> list:
        return self.by_status("skipped")


def seed_verdict_caches(
    blocks: Sequence,
    *,
    handle=None,
    sig_cache=None,
    max_blocks: int = 1024,
) -> Dict[str, int]:
    """Replay finalized blocks' committed seals into verdict caches.

    Sound because the WAL is already the node's trust root: ``recover()``
    replays these same blocks into the chain unconditionally, and each
    seal in a finalized block passed quorum verification before
    ``append_finalize`` persisted it.  ``handle`` is anything exposing
    ``seed_seal_verdicts(entries)`` (the scheduler's tenant handle);
    ``sig_cache`` anything exposing ``store_batch(keys, verdicts)``.
    """
    from ..crypto.backend import proposal_hash_of

    seal_entries = []
    sig_keys = []
    for block in list(blocks)[-max_blocks:]:
        if block.cert is not None or not block.seals:
            continue  # aggregate-certificate blocks carry no seal lanes
        h = proposal_hash_of(block.proposal)
        for seal in block.seals:
            seal_entries.append(
                ((seal.signer, h, seal.signature, block.height), True)
            )
            sig_keys.append((h, seal.signer, seal.signature))
    out = {"seal_verdicts": 0, "sig_verdicts": 0}
    if handle is not None and seal_entries:
        handle.seed_seal_verdicts(seal_entries)
        out["seal_verdicts"] = len(seal_entries)
    if sig_cache is not None and sig_keys:
        sig_cache.store_batch(sig_keys, [True] * len(sig_keys))
        out["sig_verdicts"] = len(sig_keys)
    return out


def warm_start(
    runner=None,
    *,
    programs: Optional[Sequence[str]] = None,
    manifest: Optional[str] = None,
    store: Optional[AOTStore] = None,
    handle=None,
    sig_cache=None,
    warmups: Sequence[Callable[[], object]] = (),
    record: bool = True,
    export: bool = False,
    seed_blocks: int = 1024,
) -> WarmStartReport:
    """One full warm start; returns what was restored and what it cost.

    Program selection: explicit ``programs`` wins; else a ``manifest``
    path (scripts/warm_kernels.py ``--manifest``) selects the pinned
    programs whose family it measured — unless the manifest is stale
    (fingerprint mismatch) or unreadable, in which case EVERY pinned
    family is a cold candidate (degrade to recorded cold compiles, never
    trust a stale artifact); else every pinned family.

    ``warmups`` are zero-arg callables driven after the program restore
    (e.g. ``verifier.warmup`` / ``dispatcher.warmup``) — they populate
    the *runtime's own* jit objects through the now-warm persistent
    cache, and their seam instrumentation records any true compiles.
    """
    t0 = time.perf_counter()
    cache_dir = enable_persistent_cache()
    store = store or AOTStore(cache_dir)
    if programs is None and manifest is not None:
        doc = load_manifest(manifest)
        if doc is not None and not doc.get("stale"):
            measured = set(doc.get("programs", ()))
            programs = [
                p for p in store.pinned_programs() if family_of(p) in measured
            ]
    statuses = store.ensure(programs, record=record, export=export)

    height = 0
    seeded = {"seal_verdicts": 0, "sig_verdicts": 0}
    if runner is not None:
        height = runner.recover()
        if handle is not None or sig_cache is not None:
            seeded = seed_verdict_caches(
                runner.chain,
                handle=handle,
                sig_cache=sig_cache,
                max_blocks=seed_blocks,
            )

    t_warm = time.perf_counter()
    for fn in warmups:
        fn()
    warmup_ms = (time.perf_counter() - t_warm) * 1e3

    report = WarmStartReport(
        cache_dir=cache_dir,
        height=height,
        programs=statuses,
        seeded_seal_verdicts=seeded["seal_verdicts"],
        seeded_sig_verdicts=seeded["sig_verdicts"],
        warmup_ms=warmup_ms,
        total_ms=(time.perf_counter() - t0) * 1e3,
    )
    trace.instant(
        "boot.warm_start",
        height=height,
        cold=len(report.cold),
        cached=len(report.cached),
        skipped=len(report.skipped),
        seal_verdicts=report.seeded_seal_verdicts,
        sig_verdicts=report.seeded_sig_verdicts,
        total_ms=round(report.total_ms, 1),
    )
    return report
