"""The pinned program registry: every compile-budget family, buildable.

One entry per family in ``docs/compile_budget.json`` — the name IS the
manifest key (``<family>_<shape suffix>``), and the builder returns the
``(lowerable, args)`` pair that reproduces the family's engine-hot shape
exactly as ``scripts/compile_budget.py`` has always lowered it (that
script now consumes THIS registry, so the trace-size ratchet and the AOT
store can never pin different programs).

Builders are lazy: constructing the registry imports nothing heavy, and
each builder does its own imports + argument packing when called, so
restoring one small family (a boot harness on a budget) never pays the
BLS workload build.  A builder whose prerequisites are absent — a mesh
family on a host with fewer devices than its ``dp`` — raises
:class:`ProgramUnavailable`, which the AOT store records as a skip, not
a fault.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Optional, Sequence, Tuple

__all__ = ["ENGINE_LANES", "MESH_DPS", "ProgramUnavailable", "program_registry"]

# The engine-route lane bucket (the acceptance-tracked compile) and the
# dp sweep of the multi-chip pins — both mirrored from the compile-budget
# posture (see scripts/compile_budget.py for the why of each shape).
ENGINE_LANES = 8
MESH_DPS = (2, 4, 8)


class ProgramUnavailable(RuntimeError):
    """A builder's prerequisites are absent on this host (e.g. a mesh
    family needing more devices than exist); degrade to a recorded skip."""


def _engine_shapes() -> dict:
    import jax.numpy as jnp

    from ..ops import secp256k1 as sec

    B = ENGINE_LANES
    L = sec.FIELD.nlimbs
    return {
        "blocks": jnp.zeros((B, 2, 17, 2), jnp.uint32),
        "counts": jnp.ones((B,), jnp.int32),
        "limbs": jnp.zeros((B, L), jnp.int32),
        "v": jnp.zeros((B,), jnp.int32),
        "addr": jnp.zeros((B, 5), jnp.uint32),
        "table": jnp.zeros((8, 5), jnp.uint32),
        "live": jnp.zeros((B,), bool),
        "power": jnp.zeros((8,), jnp.int32),
        "hash_zw": jnp.zeros((B, 8), jnp.uint32),
        "thr": jnp.int32(1),
    }


def _build_bls_aggregate_verify():
    import jax

    from ..bench.bls_workload import build_bls_round_workload
    from ..ops.bls12_381 import aggregate_verify_commit
    import jax.numpy as jnp

    w = build_bls_round_workload(ENGINE_LANES, time_host=False)
    return jax.jit(aggregate_verify_commit), tuple(jnp.asarray(a) for a in w.args)


def _build_g2_merge_tree():
    import jax.numpy as jnp

    from ..ops.bls12_381 import g2_merge_tree

    fe30 = 30  # BLS Fp limb count
    m = jnp.zeros((128, fe30), jnp.int32)
    live = jnp.zeros((128,), bool)
    return g2_merge_tree, (m, m, m, m, live)


def _build_g1_merge_tree():
    import jax.numpy as jnp

    from ..ops.bls12_381 import g1_merge_tree

    fe30 = 30
    m = jnp.zeros((128, fe30), jnp.int32)
    live = jnp.zeros((128,), bool)
    return g1_merge_tree, (m, m, live)


def _build_digest_words():
    import jax

    from ..ops import quorum

    s = _engine_shapes()
    return jax.jit(quorum.digest_words), (s["blocks"], s["counts"])


def _build_multipair_miller():
    import jax.numpy as jnp

    from ..ops.bls12_381 import _multi_miller_stage

    fe30 = 30
    mm = jnp.zeros((2, ENGINE_LANES, fe30), jnp.int32)
    return _multi_miller_stage, (mm, mm, mm, mm, mm, mm)


def _build_quorum_certify():
    import jax

    from ..ops import quorum

    s = _engine_shapes()
    return jax.jit(quorum.quorum_certify), (
        s["blocks"], s["counts"], s["limbs"], s["limbs"], s["v"], s["addr"],
        s["table"], s["live"], s["power"], s["power"], s["thr"], s["thr"],
    )


def _build_round_certify():
    import jax

    from ..ops import quorum

    s = _engine_shapes()
    return jax.jit(quorum.round_certify), (
        s["blocks"], s["counts"], s["limbs"], s["limbs"], s["v"], s["addr"],
        s["live"],
        s["hash_zw"], s["limbs"], s["limbs"], s["v"], s["addr"], s["live"],
        s["table"], s["power"], s["power"], s["thr"], s["thr"],
    )


def _build_ecdsa_recover():
    import jax

    from ..ops import secp256k1 as sec

    s = _engine_shapes()
    return jax.jit(sec.ecdsa_recover), (s["limbs"], s["limbs"], s["limbs"], s["v"])


def _build_ecmul2_base():
    import jax

    from ..ops import secp256k1 as sec

    s = _engine_shapes()
    return jax.jit(sec.ecmul2_base), (s["limbs"], s["limbs"], s["limbs"], s["limbs"])


def _cpu_devices(dp: int):
    import jax

    try:
        cpu = jax.devices("cpu")
    except RuntimeError as exc:
        raise ProgramUnavailable(f"no cpu backend for dp={dp} mesh: {exc}")
    if len(cpu) < dp:
        raise ProgramUnavailable(
            f"mesh family needs {dp} devices, host has {len(cpu)}"
        )
    return cpu[:dp]


def _build_mesh_quorum_certify(dp: int):
    import jax

    from ..parallel import make_mesh, mesh_quorum_certify

    mesh = make_mesh(dp, devices=_cpu_devices(dp))
    s = _engine_shapes()
    return jax.jit(mesh_quorum_certify(mesh)), (
        s["blocks"], s["counts"], s["limbs"], s["limbs"], s["v"], s["addr"],
        s["table"], s["live"], s["power"], s["power"], s["thr"], s["thr"],
    )


def _build_mesh_verify_mask(dp: int):
    import jax.numpy as jnp

    from ..ops import secp256k1 as sec
    from ..parallel import make_mesh
    from ..verify.mesh_batch import mesh_verify_mask

    mesh = make_mesh(dp, devices=_cpu_devices(dp))
    g = ENGINE_LANES * dp  # 8 local lanes per shard
    L = sec.FIELD.nlimbs
    return mesh_verify_mask(mesh), (
        jnp.zeros((g, 8), jnp.uint32),
        jnp.zeros((g, L), jnp.int32),
        jnp.zeros((g, L), jnp.int32),
        jnp.zeros((g,), jnp.int32),
        jnp.zeros((g, 5), jnp.uint32),
        jnp.zeros((8, 5), jnp.uint32),
        jnp.zeros((g,), bool),
    )


def _build_ici_tick(n_nodes: int):
    """The ICI lock-step tick collective (rows variant): all_gather of
    the ``(N, M, B)`` staging tensor + on-shard payload digests + the
    gathered sender rows the verify kernels consume — ONE program per
    consensus tick (net/ici.py).  Pinned at the real-crypto cluster
    shape: ``n_nodes`` nodes on ``n_nodes`` host devices, 8 lanes of
    512-byte slots."""
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from ..net.ici import build_tick_program
    from ..ops import secp256k1 as sec

    mesh = Mesh(np.asarray(_cpu_devices(n_nodes)), ("node",))
    m_slots, b = ENGINE_LANES, 512
    lanes = n_nodes * m_slots
    L = sec.FIELD.nlimbs
    return build_tick_program(mesh, rows=True), (
        jnp.zeros((n_nodes, m_slots, b), jnp.uint8),
        jnp.zeros((lanes, 8, 17, 2), jnp.uint32),
        jnp.ones((lanes,), jnp.int32),
        jnp.zeros((lanes, L), jnp.int32),
        jnp.zeros((lanes, L), jnp.int32),
        jnp.zeros((lanes,), jnp.int32),
        jnp.zeros((lanes, 5), jnp.uint32),
        jnp.zeros((lanes,), bool),
    )


def program_registry(
    programs: Optional[Sequence[str]] = None,
) -> "OrderedDict[str, Callable[[], Tuple[object, tuple]]]":
    """``name -> builder`` for every pinned family (optionally filtered).

    Each builder returns ``(lowerable, args)`` where ``lowerable``
    supports ``.lower(*args)`` (a ``jax.jit`` object).  Unknown names in
    ``programs`` raise ``KeyError`` — a boot manifest naming a family
    this registry does not pin is a configuration error, not a skip.
    """
    defs: "OrderedDict[str, Callable]" = OrderedDict(
        (
            ("bls_aggregate_verify_8v", _build_bls_aggregate_verify),
            ("bls_g2_merge_tree_128v", _build_g2_merge_tree),
            ("bls_g1_merge_tree_128v", _build_g1_merge_tree),
            ("digest_words_8l", _build_digest_words),
            ("bls_multipair_miller_8l", _build_multipair_miller),
            ("quorum_certify_8l", _build_quorum_certify),
            ("round_certify_8l", _build_round_certify),
            ("ecdsa_recover_8l", _build_ecdsa_recover),
            ("ecmul2_base_8l", _build_ecmul2_base),
            ("ici_tick_8n", lambda: _build_ici_tick(8)),
        )
    )
    for dp in MESH_DPS:
        defs[f"mesh_quorum_certify_8l_dp{dp}"] = (
            lambda dp=dp: _build_mesh_quorum_certify(dp)
        )
        defs[f"mesh_verify_mask_8l_dp{dp}"] = (
            lambda dp=dp: _build_mesh_verify_mask(dp)
        )
    if programs is None:
        return defs
    out: "OrderedDict[str, Callable]" = OrderedDict()
    for name in programs:
        out[name] = defs[name]
    return out
