"""Spawn one real boot leg (``python -m go_ibft_tpu.boot``) and parse it.

Bench config #14 measures restart-to-first-finalized by restarting the
node FOR REAL: a fresh interpreter, fresh jax, one shared
``GO_IBFT_CACHE_DIR``.  That process-spawning lives here — in the boot
package that owns the child entrypoint — so ``bench.py`` keeps exactly
one subprocess implementation (the shared backend probe,
``utils/probe.py``).  This module must stay import-light: the PARENT
imports it, and pulling jax in here would distort the very spawn cost
the legs measure.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

__all__ = ["BootLegTimeout", "run_boot_leg"]


class BootLegTimeout(RuntimeError):
    """A boot leg exceeded its wall budget (the child was killed)."""

    def __init__(self, tag: str, timeout_s: float):
        super().__init__(f"boot leg {tag!r} exceeded {timeout_s:.0f}s")
        self.tag = tag
        self.timeout_s = timeout_s


def run_boot_leg(
    tag: str,
    family: str,
    cache_dir: str,
    ledger_path: str,
    *,
    timeout_s: float,
    cwd: str | None = None,
) -> dict:
    """Run one restart leg; return ``{spawn_ms, report, events}``.

    The child keys its persistent cache off ``cache_dir`` alone
    (``JAX_COMPILATION_CACHE_DIR`` is scrubbed — a user-level cache dir
    would leak pre-warmed artifacts into the "cold" leg and fake the
    ratio) and writes its compile ledger to ``ledger_path`` so the
    caller can assert the cached legs recorded ZERO compile events.
    Raises :class:`BootLegTimeout` when the wall budget runs out and
    ``RuntimeError`` on a nonzero child exit.
    """
    env = dict(os.environ)
    env["GO_IBFT_CACHE_DIR"] = cache_dir
    env["GO_IBFT_COMPILE_LEDGER"] = ledger_path
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "go_ibft_tpu.boot", "--programs", family],
            capture_output=True,
            text=True,
            env=env,
            cwd=cwd,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        raise BootLegTimeout(tag, timeout_s) from None
    spawn_ms = (time.perf_counter() - t0) * 1e3
    if proc.returncode != 0:
        raise RuntimeError(
            f"boot leg {tag} rc={proc.returncode}: "
            + proc.stderr.strip()[-300:]
        )
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    events = []
    if os.path.exists(ledger_path):
        with open(ledger_path) as fh:
            events = [json.loads(ln) for ln in fh if ln.strip()]
    return {"spawn_ms": spawn_ms, "report": report, "events": events}
