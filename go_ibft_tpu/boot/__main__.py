"""Restart-to-first-finalized harness: one full boot in one process.

``python -m go_ibft_tpu.boot --programs ecmul2_base_8l`` performs a
production-shaped boot — enable the persistent cache, warm-start the
requested pinned programs (recorded cold compiles on a cold cache, cache
loads on a warm one), then bring up a small real-crypto cluster and
finalize its first height — and prints one JSON line with the measured
milestones.  Bench config #14 runs this as a child process twice against
the same ``GO_IBFT_CACHE_DIR``: the first boot pays the cold compiles,
the second proves the cache (and its compile ledger proves ZERO cold
events).

Timing origin is module entry (``entry_to_first_finalized_ms``): the
interpreter+import tax is reported separately by the parent, which also
measures spawn-to-exit wall.  Set ``GO_IBFT_COMPILE_LEDGER`` to record
cold-compile events to a JSONL file.
"""

import argparse
import json
import os
import sys
import time

_T_ENTRY = time.perf_counter()

# Must match tests/conftest.py BEFORE jax initializes (the device-count
# flag is part of the persistent-cache key).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()


def _first_finalized_ms(nodes: int, heights: int) -> float:
    """Bring up an in-process real-crypto cluster and finalize
    ``heights``; returns the wall from cluster construction to the last
    finalize (host-route verification: no compile rides this path, so
    the measurement isolates what warm-start did or did not restore)."""
    import asyncio

    from ..chain import ChainRunner
    from ..core import IBFT, BatchingIngress
    from ..crypto import PrivateKey
    from ..crypto.backend import ECDSABackend
    from ..verify import HostBatchVerifier

    class _Null:
        def info(self, *a):
            pass

        debug = error = info

    t0 = time.perf_counter()
    keys = [PrivateKey.from_seed(b"boot-harness-%d" % i) for i in range(nodes)]
    src = ECDSABackend.static_validators({k.address: 1 for k in keys})
    cluster = []

    def gossip(message):
        for _core, ingress in cluster:
            ingress.submit(message)

    class _T:
        def multicast(self, message):
            gossip(message)

    runners = []
    for key in keys:
        core = IBFT(
            _Null(),
            ECDSABackend(key, src),
            _T(),
            batch_verifier=HostBatchVerifier(src),
        )
        core.set_base_round_timeout(30.0)
        cluster.append((core, BatchingIngress(core.add_messages)))
        runners.append(ChainRunner(core, overlap=False))

    async def _main():
        await asyncio.wait_for(
            asyncio.gather(*(r.run(until_height=heights) for r in runners)),
            120,
        )

    try:
        asyncio.run(_main())
    finally:
        for core, ingress in cluster:
            ingress.close()
            core.messages.close()
    finalized = min(len(core.backend.inserted) for core, _ in cluster)
    if finalized < heights:
        raise RuntimeError(f"finalized {finalized} < {heights}")
    return (time.perf_counter() - t0) * 1e3


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m go_ibft_tpu.boot")
    p.add_argument(
        "--programs",
        default="",
        help="comma-separated pinned registry keys (default: all)",
    )
    p.add_argument("--manifest", default=None, help="AOT manifest path")
    p.add_argument("--heights", type=int, default=1)
    p.add_argument("--nodes", type=int, default=3)
    p.add_argument(
        "--no-chain",
        action="store_true",
        help="warm-start only (no cluster boot)",
    )
    args = p.parse_args(argv)

    from ..obs import ledger as cost_ledger
    from .warmstart import warm_start

    compile_log = os.environ.get("GO_IBFT_COMPILE_LEDGER")
    if compile_log:
        cost_ledger.enable(compile_log=compile_log)

    programs = [s for s in args.programs.split(",") if s] or None
    report = warm_start(programs=programs, manifest=args.manifest)

    chain_ms = 0.0
    if not args.no_chain:
        chain_ms = _first_finalized_ms(args.nodes, args.heights)
    entry_ms = (time.perf_counter() - _T_ENTRY) * 1e3

    import jax

    out = {
        "entry_to_first_finalized_ms": round(entry_ms, 1),
        "warm_ms": round(report.total_ms, 1),
        "chain_ms": round(chain_ms, 1),
        "cache_dir": report.cache_dir,
        "platform": jax.devices()[0].platform,
        "cold": len(report.cold),
        "cached": len(report.cached),
        "skipped": len(report.skipped),
        "programs": {
            s.program: {
                "status": s.status,
                "compile_ms": round(s.compile_ms, 1),
            }
            for s in report.programs.values()
        },
        "ts": time.time(),
    }
    if compile_log:
        cost_ledger.disable()
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
