"""On-demand device profiling: jax.profiler trace windows for the ledger.

The cost ledger (:mod:`go_ibft_tpu.obs.ledger`) attributes *wall* time
per program; this module captures what the device itself was doing —
a ``jax.profiler`` window whose Chrome-format output
(``*.trace.json.gz``) merges into the PR-11 Perfetto document via
:func:`go_ibft_tpu.obs.timeline.merge_device_trace`, so ONE file shows
consensus phases over host spans over device ops.

Two entry points:

* :func:`capture` — a fixed-length window (the ``/profilez`` endpoint:
  ``GET /profilez?seconds=0.5`` on a live
  :class:`~go_ibft_tpu.obs.httpd.TelemetryServer`);
* :func:`window` — a context manager wrapping a whole run
  (``bench.py --device-trace OUT_DIR``).

Both stamp ``host_anchor_us`` — the flight recorder's monotonic
microsecond clock read immediately after ``start_trace`` — so the merge
can rebase device timestamps (which are relative to the profiler
session) onto the exported host trace's clock.  Alignment is anchor-
based and therefore approximate to within the ``start_trace`` call
overhead (sub-millisecond); the per-track orderings inside either source
stay exact.

The profiler is a process-global singleton in jax: captures serialize on
a module lock, and a second concurrent request reports ``busy`` instead
of corrupting the open session.  Every failure path returns a dict with
``ok: False`` and a reason — a profiling request must never take down a
telemetry endpoint or a bench run.
"""

from __future__ import annotations

import glob
import os
import shutil
import tempfile
import threading
import time
from contextlib import contextmanager
from typing import Optional

__all__ = ["capture", "window", "newest_trace"]

_lock = threading.Lock()

# Anchored capture dir for parameterless captures (the /profilez
# endpoint): ONE per-process directory, pruned before each new window so
# a scraper polling /profilez forever holds at most one trace on disk.
# Callers that pass their own out_dir own its lifecycle.
_default_dir: Optional[str] = None

MIN_SECONDS = 0.05
MAX_SECONDS = 30.0


def _default_capture_dir() -> str:
    global _default_dir
    if _default_dir is None or not os.path.isdir(_default_dir):
        _default_dir = tempfile.mkdtemp(prefix="go-ibft-profilez-")
    else:
        # Keep only the latest window: the profiler nests each run under
        # plugins/profile/<timestamp>/ and never reuses one.
        for entry in os.listdir(_default_dir):
            shutil.rmtree(
                os.path.join(_default_dir, entry), ignore_errors=True
            )
    return _default_dir


def newest_trace(out_dir: str) -> Optional[str]:
    """The most recent ``*.trace.json.gz`` under ``out_dir`` (the
    profiler nests runs under ``plugins/profile/<timestamp>/``)."""
    paths = glob.glob(
        os.path.join(out_dir, "**", "*.trace.json.gz"), recursive=True
    )
    if not paths:
        return None
    return max(paths, key=os.path.getmtime)


def _start(out_dir: str) -> Optional[str]:
    """Start a profiler session; returns an error string or None."""
    try:
        import jax

        jax.profiler.start_trace(out_dir)
    except Exception as err:  # noqa: BLE001 - report, never raise
        return f"{type(err).__name__}: {err}"
    return None


def _stop() -> Optional[str]:
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception as err:  # noqa: BLE001
        return f"{type(err).__name__}: {err}"
    return None


def capture(seconds: float = 0.5, out_dir: Optional[str] = None) -> dict:
    """Capture one fixed-length profiler window.

    Returns ``{"ok", "dir", "path", "host_anchor_us", "seconds"}`` —
    ``path`` is the Chrome-format trace the window produced (None plus an
    ``error`` when the profiler is unavailable, already busy, or wrote
    nothing).

    Without ``out_dir`` the capture lands in one per-process directory
    that is PRUNED before each new window — a scraper polling /profilez
    holds at most one trace on disk, so copy the file before requesting
    another window.  An explicit ``out_dir`` is never pruned.
    """
    seconds = min(MAX_SECONDS, max(MIN_SECONDS, float(seconds)))
    if not _lock.acquire(blocking=False):
        return {"ok": False, "error": "busy: a profiler window is already open"}
    try:
        out_dir = out_dir or _default_capture_dir()
        err = _start(out_dir)
        if err is not None:
            return {"ok": False, "error": err, "dir": out_dir}
        anchor_us = time.perf_counter_ns() // 1000
        time.sleep(seconds)
        err = _stop()
        if err is not None:
            return {"ok": False, "error": err, "dir": out_dir}
        path = newest_trace(out_dir)
        meta = {
            "ok": path is not None,
            "dir": out_dir,
            "path": path,
            "host_anchor_us": anchor_us,
            "seconds": seconds,
        }
        if path is None:
            meta["error"] = "profiler window produced no .trace.json.gz"
        return meta
    finally:
        _lock.release()


@contextmanager
def window(out_dir: str):
    """Profile everything inside the block (``bench.py --device-trace``).

    Yields the capture metadata dict; ``path`` / ``ok`` are filled in
    when the block exits (read them AFTER the with-statement).  A
    profiler that fails to start yields ``ok: False`` and the block runs
    unprofiled — a dead profiler must not kill a bench run.
    """
    meta: dict = {"ok": False, "dir": out_dir, "path": None}
    if not _lock.acquire(blocking=False):
        meta["error"] = "busy: a profiler window is already open"
        yield meta
        return
    started = False
    try:
        try:
            os.makedirs(out_dir, exist_ok=True)
        except OSError as mkdir_err:
            # An unwritable --device-trace target must degrade like a
            # dead profiler: the wrapped run proceeds unprofiled.
            meta["error"] = f"{type(mkdir_err).__name__}: {mkdir_err}"
            yield meta
            return
        err = _start(out_dir)
        if err is None:
            started = True
            meta["host_anchor_us"] = time.perf_counter_ns() // 1000
        else:
            meta["error"] = err
        yield meta
    finally:
        if started:
            err = _stop()
            if err is not None:
                meta["error"] = err
            else:
                meta["path"] = newest_trace(out_dir)
                meta["ok"] = meta["path"] is not None
                if meta["path"] is None:
                    meta["error"] = "profiler window produced no .trace.json.gz"
        _lock.release()
