"""Per-peer clock-offset estimation from (send, recv) timestamp pairs.

Every traced message carries the sender's monotonic microsecond clock
(``TraceContext.sent_us``); the receiver reads its own clock at delivery.
The difference ``recv_local - sent_remote`` equals the true clock offset
plus the one-way network delay, so the MINIMUM over many pairs is the
tightest one-sided offset estimate available without an NTP-style
round-trip — exactly the classic one-way-delay bound.  Loopback clusters
share one process clock, record no samples here, and export offset zero.

The estimator is process-global (like the trace recorder): transports
feed it, exports snapshot it, and the timeline tool
(:mod:`go_ibft_tpu.obs.timeline`) uses the per-origin estimates to rebase
foreign-process timestamps onto the local clock before reconstructing a
cross-node consensus timeline.  Estimates are therefore *upper bounds*
(offset + min one-way delay); the timeline report labels them as such.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["ClockOffsets", "observe", "estimate", "snapshot", "reset"]


class ClockOffsets:
    """Thread-safe per-origin min(recv - send) tracker (bounded)."""

    def __init__(self, max_origins: int = 1024) -> None:
        self._lock = threading.Lock()
        self._min_delta: Dict[str, int] = {}
        self._samples: Dict[str, int] = {}
        self.max_origins = max_origins

    def observe(self, origin: str, sent_us: int, recv_us: int) -> None:
        delta = recv_us - sent_us
        with self._lock:
            if origin not in self._min_delta:
                if len(self._min_delta) >= self.max_origins:
                    return  # bounded: a spammer cannot grow this forever
                self._min_delta[origin] = delta
                self._samples[origin] = 1
            else:
                if delta < self._min_delta[origin]:
                    self._min_delta[origin] = delta
                self._samples[origin] += 1

    def estimate(self, origin: str) -> Optional[int]:
        """Best offset estimate for ``origin`` in µs (``None``: no data)."""
        with self._lock:
            return self._min_delta.get(origin)

    def snapshot(self) -> Dict[str, dict]:
        """``{origin: {"offset_us": est, "samples": n}}`` for exports."""
        with self._lock:
            return {
                origin: {
                    "offset_us": delta,
                    "samples": self._samples[origin],
                }
                for origin, delta in self._min_delta.items()
            }

    def reset(self) -> None:
        with self._lock:
            self._min_delta.clear()
            self._samples.clear()


# Process-global instance (one per node process, like the trace recorder).
_global = ClockOffsets()


def observe(origin: str, sent_us: int, recv_us: int) -> None:
    _global.observe(origin, sent_us, recv_us)


def estimate(origin: str) -> Optional[int]:
    return _global.estimate(origin)


def snapshot() -> Dict[str, dict]:
    return _global.snapshot()


def reset() -> None:
    _global.reset()
