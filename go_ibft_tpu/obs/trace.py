"""Zero-dependency span API — the flight recorder's instrumentation face.

Usage at a hot seam::

    from go_ibft_tpu.obs import trace

    with trace.span("verify.pack", lanes=n):
        ...pack...

    trace.instant("round.timeout", round=r)

Design rules (ISSUE 4 tentpole):

* **Disabled mode is one predicate check.**  ``span()`` and ``instant()``
  read one module global; when no recorder is installed they return a
  shared no-op context manager / return immediately.  No clock reads, no
  contextvar touches, no allocation beyond the caller's kwargs dict.
  The bench contract pins the resulting overhead at < 5% of the config #1
  happy path (``tests/test_bench_contract.py``).
* **Thread-safe.**  The recorder is a lock-guarded ring
  (:class:`~go_ibft_tpu.obs.recorder.RingRecorder`); spans may open and
  close on transport threads, worker pools, and the engine loop
  concurrently.
* **Tracks.**  Every record carries a track name — the timeline row it
  renders on (one per consensus node, plus one per auxiliary thread).
  Resolution order: explicit ``track=`` argument, then the inherited
  track (a ``contextvars.ContextVar`` set by the nearest enclosing span
  that passed ``track=`` — drains instrumented inside the engine inherit
  the node's track automatically, including across ``create_task``
  boundaries), then the current thread name.
"""

from __future__ import annotations

import contextvars
import itertools
import threading
import time
from typing import Optional

from .recorder import DEFAULT_CAPACITY, RingRecorder

__all__ = [
    "enable",
    "disable",
    "enabled",
    "recorder",
    "span",
    "instant",
    "set_track",
    "next_span_id",
]

# THE predicate: every instrumentation site checks this one global.
_recorder: Optional[RingRecorder] = None

_track_var: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "go_ibft_obs_track", default=None
)


def enable(capacity: int = DEFAULT_CAPACITY) -> RingRecorder:
    """Install (and return) a fresh ring recorder; spans start recording."""
    global _recorder
    _recorder = RingRecorder(capacity)
    return _recorder


def disable() -> None:
    """Remove the recorder; every span site reverts to the no-op path."""
    global _recorder
    _recorder = None


def enabled() -> bool:
    return _recorder is not None


def recorder() -> Optional[RingRecorder]:
    return _recorder


_span_id_counter = itertools.count(1)


def next_span_id() -> int:
    """Fresh per-process span id (links a ``net.send`` to its ``net.recv``
    records across nodes; ``itertools.count.__next__`` is atomic under the
    GIL, so transport threads need no lock)."""
    return next(_span_id_counter)


def set_track(name: str) -> contextvars.Token:
    """Set the inherited track for the current context; returns the reset
    token.  Rarely needed directly — passing ``track=`` to the outermost
    span of a scope does the same and resets itself."""
    return _track_var.set(name)


def _resolve_track(explicit: Optional[str]) -> str:
    if explicit is not None:
        return explicit
    inherited = _track_var.get()
    if inherited is not None:
        return inherited
    return threading.current_thread().name


class _NullSpan:
    """Shared no-op context manager returned while tracing is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ("_rec", "name", "track", "args", "_t0", "_tok")

    def __init__(self, rec, name, track, args):
        self._rec = rec
        self.name = name
        self.track = _resolve_track(track)
        self.args = args
        self._tok = _track_var.set(self.track) if track is not None else None
        self._t0 = 0

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        now = time.perf_counter_ns()
        if exc_type is not None:
            # Record the failure on the span itself: a drain that died
            # mid-flight is exactly what a flight recorder must show.
            args = dict(self.args) if self.args else {}
            args["error"] = exc_type.__name__
            self.args = args
        self._rec.append(
            (
                "X",
                self.name,
                self.track,
                self._t0 // 1000,
                (now - self._t0) // 1000,
                self.args or None,
            )
        )
        if self._tok is not None:
            _track_var.reset(self._tok)
        return False


def span(name: str, track: Optional[str] = None, **args):
    """Open a span context manager (no-op unless tracing is enabled).

    ``track`` pins the timeline row and is inherited by spans opened
    within this one (contextvar scope); ``**args`` become the span's
    attributes in the exported trace.
    """
    rec = _recorder
    if rec is None:
        return _NULL
    return _Span(rec, name, track, args)


def instant(name: str, track: Optional[str] = None, **args) -> None:
    """Record a point event (no-op unless tracing is enabled)."""
    rec = _recorder
    if rec is None:
        return
    rec.append(
        (
            "i",
            name,
            _resolve_track(track),
            time.perf_counter_ns() // 1000,
            0,
            args or None,
        )
    )
